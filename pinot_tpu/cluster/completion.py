"""Realtime (LLC) segment management + segment completion protocol.

Controller-side analog of the reference's two FSM owners (SURVEY.md §3.2):

* `PinotLLCRealtimeSegmentManager` (`pinot-controller/.../realtime/
  PinotLLCRealtimeSegmentManager.java`): creates CONSUMING segments per partition
  group, and on commit writes the final metadata, flips ideal state CONSUMING->ONLINE,
  and creates the successor CONSUMING segment from the end offset.
* `SegmentCompletionManager` (`.../realtime/SegmentCompletionManager.java:59,63-71`):
  per-segment FSM electing one committer among replicas; the wire protocol responses
  (HOLD / CATCHUP / COMMIT / DISCARD / KEEP / COMMIT_SUCCESS / FAILED) follow
  `pinot-common/.../protocols/SegmentCompletionProtocol.java:54`.

Committer election: replicas report `segment_consumed(offset)` when they hit end
criteria. The FSM HOLDs until every live replica has reported (or a re-report arrives,
covering lost replicas), then elects the max-offset reporter as committer; laggards get
CATCHUP to the committer's offset, peers at the same offset HOLD until COMMITTED, then
KEEP (use the local build) or DISCARD (download from deep store).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..segment.format import read_json, CREATION_META_FILE, SEGMENT_METADATA_FILE
from ..table import TableConfig
from ..utils.events import emit as emit_event
from .assignment import balanced_assign, compute_counts
from .catalog import (CONSUMING, COLUMN_STATS_KEY, ONLINE, Catalog,
                      SegmentMeta, STATUS_DONE, STATUS_IN_PROGRESS,
                      column_stats_from_meta)
from .deepstore import DeepStoreFS, tar_segment

# protocol responses (reference: SegmentCompletionProtocol.ControllerResponseStatus)
HOLD = "HOLD"
CATCHUP = "CATCHUP"
COMMIT = "COMMIT"
DISCARD = "DISCARD"
KEEP = "KEEP"
COMMIT_SUCCESS = "COMMIT_SUCCESS"
COMMIT_CONTINUE = "COMMIT_CONTINUE"
FAILED = "FAILED"


def llc_segment_name(table: str, partition: int, seq: int) -> str:
    """Reference LLC name format: {table}__{partitionGroup}__{sequence}__{creation}."""
    return f"{table}__{partition}__{seq}__{int(time.time() * 1000)}"


def parse_llc_name(name: str):
    parts = name.split("__")
    return {"table": parts[0], "partition": int(parts[1]), "sequence": int(parts[2])}


@dataclass
class CompletionFSM:
    """Per-segment completion state (reference: SegmentCompletionFSM inner class)."""

    segment: str
    num_replicas: int
    state: str = "HOLDING"     # HOLDING -> COMMITTER_NOTIFIED -> COMMITTING -> COMMITTED
    offsets: Dict[str, int] = field(default_factory=dict)
    reports: Dict[str, int] = field(default_factory=dict)   # server -> report count
    committer: Optional[str] = None
    final_offset: Optional[int] = None
    committer_decided_at: float = 0.0
    commit_timeout_s: float = 120.0
    # set only when _fsm_for rebuilt this FSM after a controller restart: gates
    # the commit-start adoption path so a fresh segment's FSM still requires a
    # real election before any commit
    rebuilt: bool = False
    replica_set: frozenset = frozenset()   # adoption is limited to these servers

    def on_consumed(self, server: str, offset: int) -> Dict[str, object]:
        if self.state == "COMMITTED":
            if offset == self.final_offset:
                return {"status": KEEP, "offset": self.final_offset}
            return {"status": DISCARD, "offset": self.final_offset}

        self.offsets[server] = max(offset, self.offsets.get(server, -1))
        self.reports[server] = self.reports.get(server, 0) + 1

        if self.state == "HOLDING":
            all_reported = len(self.offsets) >= self.num_replicas
            re_reported = any(c > 1 for c in self.reports.values())
            if not (all_reported or re_reported):
                return {"status": HOLD, "offset": offset}
            self._elect()

        if self.state in ("COMMITTER_NOTIFIED", "COMMITTING"):
            if self._committer_stale():
                # re-elect on committer loss (reference: FSM commit time limit).
                # Strike the silent committer's report first — its stale max
                # offset must not win the re-election and wedge the FSM on a
                # dead server; if it is merely slow it re-reports and rejoins.
                if server != self.committer:
                    self.offsets.pop(self.committer, None)
                    self.reports.pop(self.committer, None)
                self._elect()
            target = self.offsets[self.committer]
            if server == self.committer and offset >= target:
                return {"status": COMMIT, "offset": target}
            if offset < target:
                return {"status": CATCHUP, "offset": target}
            return {"status": HOLD, "offset": offset}
        return {"status": HOLD, "offset": offset}

    def _elect(self) -> None:
        self.committer = max(self.offsets, key=lambda s: (self.offsets[s], s))
        self.state = "COMMITTER_NOTIFIED"
        self.committer_decided_at = time.time()

    def _committer_stale(self) -> bool:
        # COMMITTING times out too: a committer that crashed after commitStart
        # (even mid deep-store upload — the upload is atomic-by-rename) must not
        # wedge the segment forever (reference: MAX_COMMIT_TIME in the FSM)
        return (self.state in ("COMMITTER_NOTIFIED", "COMMITTING")
                and time.time() - self.committer_decided_at > self.commit_timeout_s)

    def can_adopt(self, server: str) -> bool:
        """Controller-failover adoption eligibility: this FSM was rebuilt from
        catalog metadata after a restart (so the election that already happened is
        lost) and a replica-set member is claiming the in-flight commit. Exactly
        one server got COMMIT from the previous incarnation (reference:
        lookupOrCreateFsm + committer takeover on failover)."""
        return (self.rebuilt and self.state == "HOLDING" and self.committer is None
                and server in self.replica_set)

    def adopt_committer(self, server: str) -> None:
        self.committer = server
        self.offsets.setdefault(server, -1)
        self.committer_decided_at = time.time()
        self.state = "COMMITTING"

    def on_commit_start(self, server: str) -> str:
        if self.can_adopt(server):
            self.adopt_committer(server)
            return COMMIT_CONTINUE
        if self.state not in ("COMMITTER_NOTIFIED", "COMMITTING") or server != self.committer:
            return FAILED
        self.committer_decided_at = time.time()  # commit clock starts now
        self.state = "COMMITTING"
        return COMMIT_CONTINUE

    def on_commit_end(self, server: str, end_offset: int) -> str:
        if self.state != "COMMITTING" or server != self.committer:
            return FAILED
        self.state = "COMMITTED"
        self.final_offset = end_offset
        return COMMIT_SUCCESS


class LLCSegmentManager:
    """Controller-side realtime lifecycle (one per controller)."""

    def __init__(self, catalog: Catalog, deepstore: DeepStoreFS, work_dir: str):
        self.catalog = catalog
        self.deepstore = deepstore
        self.work_dir = work_dir
        self.fsms: Dict[str, CompletionFSM] = {}
        # one lock across the commit protocol and the validation/repair paths:
        # the protocol is served by HTTP handler threads while the periodic
        # RealtimeSegmentValidationManager runs on the scheduler thread — an
        # unsynchronized repair inside commit_end's DONE->successor window
        # would create a DUPLICATE successor consuming the same records
        # (reference: leadership + per-partition locks guard the same window)
        self._lock = threading.RLock()
        # deep-store quarantine: segments whose upload kept failing past the
        # retry budget ride the peer:// scheme; the commit path stops
        # retrying them, and each validation round probes the blob ONCE
        # (clearing the record on success) — a deep store that poisons a
        # specific blob (auth, quota, size cap) is re-tried at the periodic
        # round's cadence, never in a tight loop. Maps segment ->
        # consecutive upload failures; `clear_quarantine` resets.
        self.quarantined: Dict[str, int] = {}
        os.makedirs(work_dir, exist_ok=True)

    # -- table setup (reference: setUpNewTable) -----------------------------
    def setup_realtime_table(self, cfg: TableConfig, num_partitions: int,
                             start_offsets: Optional[List[int]] = None) -> List[str]:
        table = cfg.table_name_with_type
        names = []
        for p in range(num_partitions):
            off = start_offsets[p] if start_offsets else 0
            names.append(self._create_consuming_segment(table, cfg, p, 0, off))
        return names

    def _create_consuming_segment(self, table: str, cfg: TableConfig, partition: int,
                                  seq: int, start_offset: int) -> str:
        name = llc_segment_name(cfg.name, partition, seq)
        meta = SegmentMeta(name=name, table=table, status=STATUS_IN_PROGRESS,
                           start_offset=str(start_offset), partition_group=partition,
                           sequence_number=seq,
                           creation_time_ms=int(time.time() * 1000))
        self.catalog.put_segment_meta(meta)
        servers = self.catalog.live_servers(cfg.tenant)
        # partition-consistent placement (reference: RealtimeSegmentAssignment —
        # all segments of a partition share one replica set): reuse the
        # predecessor's servers while they are live, so replica-group routing
        # can serve a whole partition from one server (required for upsert
        # valid-doc consistency). Fall back to balanced placement when there is
        # no live predecessor set (first segment, server loss).
        chosen: List[str] = []
        if seq > 0:
            prev_name = next(
                (m.name for m in self.catalog.segments.get(table, {}).values()
                 if m.partition_group == partition
                 and m.sequence_number == seq - 1), None)
            prev = self.catalog.ideal_state.get(table, {}).get(prev_name) \
                if prev_name else None
            if prev:
                inherited = [s for s in sorted(prev) if s in servers]
                if len(inherited) == cfg.replication:
                    chosen = inherited
        if not chosen:
            counts = compute_counts(self.catalog.ideal_state.get(table, {}))
            chosen = balanced_assign(name, servers, cfg.replication, counts)
        self.catalog.update_ideal_state(table, {name: {s: CONSUMING for s in chosen}})
        # graftcheck: ignore[unbounded-keyed-accumulation] -- keyed by LLC
        # segment name: catalog lifecycle objects created by this manager at
        # partition cadence, not query traffic; DONE FSMs are the crash-replay
        # record the completion protocol re-answers duplicate commits from
        self.fsms[name] = CompletionFSM(name, num_replicas=len(chosen))
        emit_event("segment.consuming.created", node="controller", table=table,
                   segment=name, partition=partition, sequence=seq)
        return name

    # -- completion protocol endpoints (reference: LLCSegmentCompletionHandlers) ----
    def _fsm_for(self, segment: str,
                 meta: Optional[SegmentMeta]) -> Optional[CompletionFSM]:
        """Get — or, after a controller restart, rebuild — the segment's FSM.

        FSMs are in-memory; a restarted controller has lost them while segment
        metadata (the durable record, passed in by the caller) says IN_PROGRESS.
        Rebuild an empty HOLDING FSM from the ideal-state replica set so the
        protocol continues instead of FAILING every replica (reference:
        SegmentCompletionManager.lookupOrCreateFsm creating the FSM on first
        message)."""
        fsm = self.fsms.get(segment)
        if fsm is not None:
            return fsm
        if meta is None or meta.status != STATUS_IN_PROGRESS:
            return None
        assignment = self.catalog.ideal_state.get(meta.table, {}).get(segment, {})
        fsm = CompletionFSM(segment, num_replicas=max(len(assignment), 1),
                            rebuilt=True, replica_set=frozenset(assignment))
        self.fsms[segment] = fsm
        return fsm

    def segment_consumed(self, segment: str, server: str, offset: int) -> Dict[str, object]:
        with self._lock:
            meta = self._meta(segment)
            fsm = self._fsm_for(segment, meta)
            if fsm is None:
                if meta is not None and meta.status == STATUS_DONE:
                    final = int(meta.end_offset)
                    return {"status": KEEP if offset == final else DISCARD,
                            "offset": final}
                return {"status": FAILED, "offset": offset}
            return fsm.on_consumed(server, offset)

    def segment_commit_start(self, segment: str, server: str) -> str:
        with self._lock:
            fsm = self._fsm_for(segment, self._meta(segment))
            return fsm.on_commit_start(server) if fsm else FAILED

    def segment_commit_end(self, segment: str, server: str, segment_dir: str,
                           end_offset: int) -> str:
        """Upload + metadata flip + successor creation (reference: commitSegment path in
        PinotLLCRealtimeSegmentManager: commitSegmentFile + commitSegmentMetadata).

        Locking: the metadata flip + FSM transition + successor creation hold
        the manager lock (the validation thread must never observe the
        DONE-without-successor window — it would create a duplicate successor
        consuming the same records), but the DEEP-STORE UPLOAD runs OUTSIDE it:
        one segment's slow tar+upload must not block every other segment's
        HOLD/CATCHUP responses into the commit timeout. During the upload the
        segment is still IN_PROGRESS with a live committer, so neither repair
        path can act on it; eligibility is re-checked after the upload in case
        a timeout re-elected the committer away mid-upload."""
        with self._lock:
            meta = self._meta(segment)
            fsm = self._fsm_for(segment, meta)
            if fsm is not None and fsm.can_adopt(server):
                # controller restarted between this committer's commitStart and
                # its commitEnd (segment build can take seconds): adopt it here
                # too, else the sole replica FAILs into terminal ERROR and the
                # partition wedges
                fsm.adopt_committer(server)
            if fsm is None or fsm.state != "COMMITTING" or server != fsm.committer:
                return FAILED
            table = meta.table
            cfg = self.catalog.table_configs[table]

        # upload the built segment to the deep store (lock NOT held)
        seg_meta_json = read_json(os.path.join(segment_dir, SEGMENT_METADATA_FILE))
        crc = read_json(os.path.join(segment_dir, CREATION_META_FILE))["crc"]
        tar_path = os.path.join(self.work_dir, f"{segment}.tar.gz")
        tar_segment(segment_dir, tar_path)
        uri = f"{table}/{segment}.tar.gz"
        if not self._upload_with_retry(tar_path, uri, segment):
            # deep store unavailable: the commit still succeeds under the PEER
            # download scheme — replicas fetch the committed copy from a
            # serving peer, and the validation round re-uploads to the deep
            # store once it recovers (reference:
            # PeerSchemeSplitSegmentCommitter + peerSegmentDownloadScheme,
            # RealtimeSegmentValidationManager.uploadToDeepStoreIfMissing)
            uri = f"peer://{table}/{segment}"
        size = os.path.getsize(tar_path)
        os.remove(tar_path)

        with self._lock:
            return self._finish_commit(segment, server, fsm, meta, cfg,
                                       seg_meta_json, crc, uri, size,
                                       end_offset)

    def _upload_with_retry(self, local_path: str, uri: str,
                           segment: str) -> bool:
        """Deep-store upload with bounded retries + exponential backoff
        (knobs `deepstore.retry.max` / `deepstore.retry.backoff.ms`). Returns
        True on success (clearing any quarantine record for the segment);
        exhausting the budget quarantines the segment — the caller falls back
        to the peer:// scheme, and `_heal_peer_segments` probes the blob once
        per validation round until an upload lands (or an operator clears
        the record)."""
        from ..utils.metrics import get_registry
        reg = get_registry()
        max_tries = max(1, int(self.catalog.get_property(
            "clusterConfig/deepstore.retry.max", 3)))
        backoff_ms = float(self.catalog.get_property(
            "clusterConfig/deepstore.retry.backoff.ms", 50))
        for attempt in range(max_tries):
            if attempt:
                reg.counter("pinot_controller_deepstore_retries").inc()
                time.sleep(backoff_ms * (2 ** (attempt - 1)) / 1000.0)
            try:
                self.deepstore.upload(local_path, uri)
            # graftcheck: ignore[exception-hygiene] -- each failed attempt is
            # observed: the next iteration counts a deepstore retry, and
            # terminal failure counts + records the quarantine below
            except Exception:
                continue
            with self._lock:
                self.quarantined.pop(segment, None)
            return True
        with self._lock:
            first_time = segment not in self.quarantined
            self.quarantined[segment] = \
                self.quarantined.get(segment, 0) + max_tries
        if first_time:
            reg.counter("pinot_controller_deepstore_quarantined").inc()
            emit_event("deepstore.quarantined", node="controller",
                       segment=segment, attempts=max_tries)
        return False

    def clear_quarantine(self, segment: Optional[str] = None) -> None:
        """Operator escape hatch: reset the failure record for quarantined
        segment(s) (all of them when segment=None) — e.g. after rotating a
        credential that was poisoning specific blobs."""
        with self._lock:
            if segment is None:
                self.quarantined.clear()
            else:
                self.quarantined.pop(segment, None)

    def _finish_commit(self, segment, server, fsm, meta, cfg, seg_meta_json,
                       crc, uri, size, end_offset) -> str:
        if fsm.state != "COMMITTING" or server != fsm.committer:
            return FAILED   # re-elected away during a slow upload
        table = meta.table
        meta.status = STATUS_DONE
        meta.end_offset = str(end_offset)
        meta.num_docs = seg_meta_json["totalDocs"]
        meta.crc = crc
        meta.size_bytes = size
        meta.download_path = uri
        self._fill_time_range(cfg, seg_meta_json, meta)
        col_stats = column_stats_from_meta(seg_meta_json)
        if col_stats:
            meta.custom[COLUMN_STATS_KEY] = col_stats
        self.catalog.put_segment_meta(meta)

        resp = fsm.on_commit_end(server, end_offset)
        if resp != COMMIT_SUCCESS:
            return resp

        # ideal state: this segment CONSUMING -> ONLINE on all its replicas
        assignment = self.catalog.ideal_state.get(table, {}).get(segment, {})
        self.catalog.update_ideal_state(
            table, {segment: {s: ONLINE for s in assignment}})
        emit_event("segment.committed", node="controller", table=table,
                   segment=segment, committer=server, endOffset=end_offset)
        emit_event("segment.online", node="controller", table=table,
                   segment=segment)

        # create the successor CONSUMING segment from the end offset — unless
        # consumption is paused, in which case resume (or the validation
        # manager after resume) recreates successors from committed offsets
        if not self.is_paused(table):
            info = parse_llc_name(segment)
            self._create_consuming_segment(table, cfg, info["partition"],
                                           info["sequence"] + 1, end_offset)
        return COMMIT_SUCCESS

    # -- pause/resume (reference: PinotRealtimeTableResource pauseConsumption /
    # resumeConsumption + PauseStatus in ideal state) -----------------------
    def is_paused(self, table: str) -> bool:
        return self.catalog.get_property(f"pause/{table}") is not None

    def pause_consumption(self, table: str) -> Dict[str, object]:
        """Stop consumption: servers see the pause property and (a) stop
        fetching, (b) force-commit consuming segments that already hold rows.
        Committed segments get NO successor until resume."""
        cfg = self.catalog.table_configs.get(table)
        if cfg is None or cfg.stream is None:
            raise ValueError(f"{table!r} is not a realtime table")
        consuming = [m.name for m in self.catalog.segments.get(table, {}).values()
                     if m.status == STATUS_IN_PROGRESS]
        self.catalog.put_property(f"pause/{table}", "paused")
        return {"paused": True, "consumingSegments": consuming}

    def resume_consumption(self, table: str) -> Dict[str, object]:
        """Clear the pause flag and recreate CONSUMING successors for partitions
        whose latest segment committed while paused (consumption restarts from
        the last committed offsets — the reference's resume semantics)."""
        cfg = self.catalog.table_configs.get(table)
        if cfg is None or cfg.stream is None:
            raise ValueError(f"{table!r} is not a realtime table")
        self.catalog.put_property(f"pause/{table}", None)
        with self._lock:
            created = self._repair_missing_consuming_segments(only_table=table)
        return {"paused": False, "created": created}

    # -- repair (reference: RealtimeSegmentValidationManager) ---------------
    def repair_missing_consuming_segments(self) -> List[str]:
        """Recreate CONSUMING segments for partitions whose latest segment is DONE but
        has no successor (e.g. controller crashed between commit and create)."""
        with self._lock:
            return self._repair_missing_consuming_segments()

    def _repair_missing_consuming_segments(self, only_table: Optional[str] = None
                                           ) -> List[str]:
        created = []
        for table, cfg in list(self.catalog.table_configs.items()):
            if only_table is not None and table != only_table:
                continue
            if cfg.stream is None or self.is_paused(table):
                continue
            if not self.catalog.live_servers(cfg.tenant):
                # creating a successor persists metadata BEFORE assignment;
                # with zero live servers the assignment raises and the orphan
                # IN_PROGRESS meta would wedge the partition forever — wait
                # for servers to come back (next validation round)
                continue
            latest: Dict[int, SegmentMeta] = {}
            for meta in self.catalog.segments.get(table, {}).values():
                if meta.partition_group is None:
                    continue
                cur = latest.get(meta.partition_group)
                if cur is None or meta.sequence_number > cur.sequence_number:
                    latest[meta.partition_group] = meta
            for p, meta in latest.items():
                if meta.status == STATUS_DONE:
                    created.append(self._create_consuming_segment(
                        table, cfg, p, meta.sequence_number + 1, int(meta.end_offset)))
        return created

    def reassign_dead_consuming_segments(self) -> List[str]:
        """Move CONSUMING segments whose every assigned replica is dead onto
        live servers (reference: RealtimeSegmentValidationManager repairing
        consuming segments after server loss). The FSM resets so the new
        replicas run a fresh committer election; they re-consume from the
        segment's durable start offset — at-least-once, no data loss."""
        with self._lock:
            return self._reassign_dead_consuming_segments()

    def _reassign_dead_consuming_segments(self) -> List[str]:
        moved = []
        for table, cfg in list(self.catalog.table_configs.items()):
            if cfg.stream is None:
                continue
            ist = self.catalog.ideal_state.get(table, {})
            live = self.catalog.live_servers(cfg.tenant)
            if not live:
                continue
            counts = compute_counts(ist)
            for seg, assignment in list(ist.items()):
                meta = self.catalog.segments.get(table, {}).get(seg)
                if meta is None or meta.status != STATUS_IN_PROGRESS:
                    continue
                if any(self.catalog.instances.get(s) is not None
                       and self.catalog.instances[s].alive for s in assignment):
                    continue
                chosen = balanced_assign(seg, live, cfg.replication, counts)
                for c in chosen:   # keep counts live: N moved segments SPREAD
                    counts[c] = counts.get(c, 0) + 1
                self.catalog.update_ideal_state(
                    table, {seg: {s: CONSUMING for s in chosen}})
                # fresh election among the new replicas
                self.fsms[seg] = CompletionFSM(seg, num_replicas=len(chosen))
                moved.append(seg)
                emit_event("segment.reassigned", node="controller",
                           table=table, segment=seg, servers=sorted(chosen))
        return moved

    def validate(self) -> Dict[str, List[str]]:
        """One RealtimeSegmentValidationManager round: recreate missing
        successors + move dead-replica consuming segments + heal peer-scheme
        segments into the deep store."""
        with self._lock:
            out = {
                "created": self._repair_missing_consuming_segments(),
                "reassigned": self._reassign_dead_consuming_segments(),
            }
        out["healed"] = self._heal_peer_segments()
        return out

    def _heal_peer_segments(self) -> List[str]:
        """Re-upload peer-scheme committed segments once the deep store is
        reachable again (reference: RealtimeSegmentValidationManager
        .uploadToDeepStoreIfMissing): fetch the tar from a serving peer, put
        it in the deep store, and flip download_path to the durable URI."""
        from .peers import fetch_from_peer
        healed = []
        for table, segs in list(self.catalog.segments.items()):
            for name, meta in list(segs.items()):
                if not (meta.download_path or "").startswith("peer://"):
                    continue
                import uuid as _uuid
                uri = f"{table}/{name}.tar.gz"
                # unique temp per round: POST /validate can run concurrently
                # with the periodic round — a shared name would let one
                # round's truncating open race the other's upload read
                tmp = os.path.join(self.work_dir,
                                   f"heal_{name}_{_uuid.uuid4().hex[:8]}.tar.gz")
                try:
                    fetch_from_peer(self.catalog, table, name, tmp)
                    self.deepstore.upload(tmp, uri)
                except Exception:
                    with self._lock:
                        # the once-per-round probe failed: keep (or extend)
                        # the quarantine record so /debug shows the streak
                        if name in self.quarantined:
                            self.quarantined[name] += 1
                    continue  # still unreachable; next round retries
                finally:
                    if os.path.exists(tmp):
                        os.remove(tmp)
                with self._lock:
                    self.quarantined.pop(name, None)
                    # re-check under the lock: the fetch+upload window is
                    # seconds long — a concurrent table drop (or a racing
                    # heal) must not resurrect the segment's metadata
                    cur = self.catalog.segments.get(table, {}).get(name)
                    if cur is None or not (cur.download_path or ""
                                           ).startswith("peer://"):
                        continue
                    cur.download_path = uri
                    self.catalog.put_segment_meta(cur)
                healed.append(name)
                emit_event("deepstore.healed", node="controller", table=table,
                           segment=name)
        return healed

    def _meta(self, segment: str) -> Optional[SegmentMeta]:
        for table_segs in self.catalog.segments.values():
            if segment in table_segs:
                return table_segs[segment]
        return None

    def _fill_time_range(self, cfg: TableConfig, seg_meta_json, meta: SegmentMeta) -> None:
        if not cfg.time_column:
            return
        col = seg_meta_json["columns"].get(cfg.time_column)
        if col and col.get("minValue") is not None:
            meta.start_time_ms = int(col["minValue"])
            meta.end_time_ms = int(col["maxValue"])
