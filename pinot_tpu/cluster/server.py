"""Server role: segment lifecycle management + query execution.

Analog of the reference's server stack (SURVEY.md §2.6): `BaseServerStarter` boot,
`HelixInstanceDataManager` (add/replace/drop segments on state transitions,
`server/starter/helix/HelixInstanceDataManager.java:78,164`), per-table data managers
with refcounted acquire/release (`BaseTableDataManager`), and the query executor half of
`ServerQueryExecutorV1Impl`. State transitions arrive as catalog ideal-state watch events
instead of Helix messages; the server reconciles desired vs loaded and reports the
external view.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Union

from ..query import stats as qstats
from ..query.aggregates import make_agg
from ..query.context import QueryContext, compile_query
from ..parallel.combine import device_topk_screen
from ..query.executor import ServerQueryExecutor
from ..query.reduce import SegmentResult, merge_segment_results
from ..segment.reader import ImmutableSegment, load_segment
from ..utils.events import emit as emit_event
from ..utils.faults import fault_point
from .catalog import (COLD, CONSUMING, DROPPED, OFFLINE, ONLINE, Catalog,
                      InstanceInfo)
from .deepstore import DeepStoreFS, untar_segment
from .tiering import PRESSURE_INTERVAL_S, TieringManager


class TableDataManager:
    """Per-table loaded segments with refcounting (reference: BaseTableDataManager)."""

    def __init__(self, table: str, data_dir: str):
        self.table = table
        self.data_dir = data_dir
        self._segments: Dict[str, ImmutableSegment] = {}
        self._refcounts: Dict[str, int] = {}
        # segments unloaded while a query still held a ref: their device
        # block + ledger release defers until release() drains the refcount
        self._deferred: Dict[str, ImmutableSegment] = {}
        self._lock = threading.RLock()

    def add_segment(self, name: str, segment: ImmutableSegment) -> None:
        with self._lock:
            # a deferred copy being replaced (reload swap) releases NOW: the
            # fresh reader takes over, and acquired refs point at the old
            # object which stays valid until its holders release it
            old = self._deferred.pop(name, None)
            self._segments[name] = segment
            self._refcounts.setdefault(name, 0)
        if old is not None and old is not segment:
            from ..engine.datablock import release_block
            release_block(old)
        # table attribution for staging sites that only know the segment
        # (engine.datablock): offline segment names carry no table prefix
        from ..utils.memledger import get_ledger
        get_ledger().bind_segment(self.table, name)

    def remove_segment(self, name: str) -> None:
        with self._lock:
            seg = self._segments.pop(name, None)
            if seg is not None and self._refcounts.get(name, 0) > 0:
                # unload-vs-in-flight-query race: a running query acquired
                # this segment — yanking the device block now would fail its
                # kernels mid-flight. Park it; release() frees it when the
                # refcount drains to zero.
                self._deferred[name] = seg
                return
            self._refcounts.pop(name, None)
        if seg is not None:
            # unload = free: drop the cached device block and its ledger
            # entries, not just the host-side reader
            from ..engine.datablock import release_block
            release_block(seg)

    def acquire(self, names: Optional[Sequence[str]] = None) -> List[ImmutableSegment]:
        with self._lock:
            targets = list(self._segments) if names is None else \
                [n for n in names if n in self._segments]
            for n in targets:
                self._refcounts[n] = self._refcounts.get(n, 0) + 1
            return [self._segments[n] for n in targets]

    def release(self, segments: Sequence[ImmutableSegment]) -> None:
        doomed: List[ImmutableSegment] = []
        with self._lock:
            for seg in segments:
                if seg.name in self._refcounts:
                    self._refcounts[seg.name] -= 1
                    if (self._refcounts[seg.name] <= 0
                            and seg.name in self._deferred):
                        # last holder of an unloaded segment: free it now
                        doomed.append(self._deferred.pop(seg.name))
                        self._refcounts.pop(seg.name, None)
        if doomed:
            from ..engine.datablock import release_block
            for seg in doomed:
                release_block(seg)

    def refcount(self, name: str) -> int:
        """In-flight acquisitions of `name` — the tiering eviction loop's
        never-evict-under-a-running-query check."""
        with self._lock:
            return self._refcounts.get(name, 0)

    def get(self, name: str) -> Optional[ImmutableSegment]:
        with self._lock:
            return self._segments.get(name)

    @property
    def segment_names(self) -> List[str]:
        with self._lock:
            return list(self._segments)


class ServerNode:
    """One server instance (reference: HelixServerStarter + ServerInstance)."""

    def __init__(self, instance_id: str, catalog: Catalog, deepstore: DeepStoreFS,
                 data_dir: str, tags: Optional[List[str]] = None, completion=None,
                 scheduler=None, auto_consume: bool = False,
                 device_pipeline=None):
        self.instance_id = instance_id
        self.catalog = catalog
        self.deepstore = deepstore
        self.data_dir = data_dir
        # device bitmap filter indexes default on; operators can force the
        # LUT/interval filter path cluster-wide (e.g. to bisect a wrong-result
        # report) without redeploying servers
        bitmap_on = str(catalog.get_property(
            "clusterConfig/server.index.bitmap.enabled", "true")).lower() != "false"
        # fused single-launch execution: the cluster knob only forces it OFF;
        # when on (default) the calibrated KernelCaps regime decides per shape
        fused_on = str(catalog.get_property(
            "clusterConfig/server.fused.enabled", "true")).lower() != "false"
        fused = None if fused_on else False
        self.executor = ServerQueryExecutor(bitmap_enabled=bitmap_on,
                                            fused_enabled=fused)
        # host-tier executor: never stages device blocks — what unadmitted
        # segments run on when the HBM admission gate rejects them
        self.host_executor = ServerQueryExecutor(use_device=False,
                                                 bitmap_enabled=bitmap_on,
                                                 fused_enabled=fused)
        # HBM capacity override knob (env PINOT_TPU_HBM_CAPACITY_BYTES is the
        # process-level equivalent): lets tests/bench pin a tiny budget
        cap_raw = catalog.get_property(
            "clusterConfig/server.hbm.capacity.bytes", None)
        if cap_raw is not None:
            try:
                from ..utils.memledger import get_ledger
                get_ledger().set_capacity(int(cap_raw))
            except (TypeError, ValueError):
                pass  # malformed knob: keep the probed capacity
        # tiered-storage lifecycle: HBM admission gate + pressure eviction
        self.tiering = TieringManager(catalog, node=instance_id)
        self._pressure_scheduler = None
        # optional admission control (reference: QueryScheduler wrapping the
        # executor; None = direct execution, the single-tenant test default)
        self.scheduler = scheduler
        # device-backed serving: when set, broker-routed partials execute on
        # the TPU through the mesh executor with batched fetches
        # (cluster/device_server.py; reference: ServerInstance owning the
        # engine, ServerInstance.java:55,120-186)
        self.device_pipeline = device_pipeline
        # True in real server processes: realtime managers run their background
        # consume loop (reference: PartitionConsumer threads); False in tests,
        # which drive pump/complete deterministically
        self.auto_consume = auto_consume
        self.tables: Dict[str, TableDataManager] = {}
        # per-table EWMA of bytesFetched per partial: the scheduler's fair
        # queue charges each tenant by predicted bytes so a scan-heavy table
        # consumes its share faster than a cheap-aggregation one
        self._table_bytes_ewma: Dict[str, float] = {}
        self._lock = threading.RLock()
        self._realtime_managers: Dict[str, object] = {}
        self._load_locks: Dict[tuple, threading.Lock] = {}
        self.completion = completion  # LLCSegmentManager handle (in-proc or HTTP proxy)
        # lifecycle: STARTING -> UP -> SHUTTING_DOWN (reference: ServiceStatus +
        # BaseServerStarter's startupServiceStatusCheck gate)
        self.status = "STARTING"
        os.makedirs(data_dir, exist_ok=True)
        catalog.register_instance(InstanceInfo(instance_id, "server", tags=tags
                                               or ["DefaultTenant"]))
        catalog.subscribe(self._on_catalog_event)
        # catch up with pre-existing ideal state (reference: startup reconciliation)
        for table in list(catalog.ideal_state):
            self.reconcile(table)
        self.status = "UP"

    # -- lifecycle ----------------------------------------------------------
    def startup_status(self) -> Dict[str, object]:
        """Readiness: every segment the ideal state assigns to this server is
        actually served/consuming (reference: BaseServerStarter.java:542-549 —
        no queries before all assigned segments are loaded)."""
        assigned = loaded = 0
        # snapshot under the catalog lock: the in-proc Catalog mutates ideal
        # state dicts in place, and a health probe racing update_ideal_state
        # would die with "dictionary changed size during iteration"
        with self.catalog._lock:
            ideal = {t: {s: dict(a) for s, a in ist.items()}
                     for t, ist in self.catalog.ideal_state.items()}
        for table, ist in ideal.items():
            mgr = self.tables.get(table)
            served = set(mgr.segment_names) if mgr else set()
            rt = self._realtime_managers.get(table)
            consuming = set(rt.consumers) if rt is not None else set()
            for seg, assignment in ist.items():
                state = assignment.get(self.instance_id)
                if state in (ONLINE, CONSUMING):
                    assigned += 1
                    if seg in served or seg in consuming:
                        loaded += 1
        ready = self.status == "UP" and loaded == assigned
        return {"status": self.status, "assignedSegments": assigned,
                "loadedSegments": loaded, "ready": ready}

    def shutdown(self) -> None:
        """Graceful stop: deregister from routing, stop consumers/scheduler
        (reference: BaseServerStarter.stop -> shutdownGracefully)."""
        self.status = "SHUTTING_DOWN"
        try:
            self.catalog.set_instance_alive(self.instance_id, False)
        # graftcheck: ignore[exception-hygiene] -- shutdown teardown: the
        # controller being gone already achieves what this call wanted
        except Exception:
            pass  # controller may already be gone during teardown
        for handler in list(self._realtime_managers.values()):
            handler.stop()
        if self.scheduler is not None:
            self.scheduler.stop()
        if self.device_pipeline is not None:
            self.device_pipeline.stop()
        self.stop_pressure_loop()

    def start_pressure_loop(self) -> None:
        """Run the HBM pressure sweep as a background periodic task — called
        by ServerService (real server processes); tests drive
        `tiering.run_pressure_sweep()` directly for determinism."""
        from ..utils.periodic import PeriodicTask, PeriodicTaskScheduler
        if self._pressure_scheduler is not None:
            return
        sched = PeriodicTaskScheduler()
        sched.register(PeriodicTask("HbmPressureLoop", PRESSURE_INTERVAL_S,
                                    self.tiering.run_pressure_sweep))
        sched.start()
        self._pressure_scheduler = sched

    def stop_pressure_loop(self) -> None:
        if self._pressure_scheduler is not None:
            self._pressure_scheduler.stop()
            self._pressure_scheduler = None

    # -- state transitions -------------------------------------------------
    def _on_catalog_event(self, event: str, table: str) -> None:
        if event == "ideal_state":
            self.reconcile(table)
        elif event == "table" and self.catalog.table_configs.get(table) is None:
            # table DROPPED: the final config removal arrives as a 'table'
            # event (ideal-state events already emptied the segments); one
            # last reconcile tears down the realtime manager + its loop
            self.reconcile(table)
        elif event == "property" and table.startswith("pause/"):
            # controller pause/resume consumption (reference: the pause state
            # servers observe in ideal state)
            t = table.split("/", 1)[1]
            rt = self._realtime_managers.get(t)
            if rt is not None:
                rt.set_paused(self.catalog.get_property(table) is not None)
        elif event == "property" and table.startswith("reload/"):
            # controller-triggered segment reload (reference: the Helix RELOAD
            # message driving SegmentPreProcessor on each server). Never let a
            # reload failure propagate: it would kill the catalog watch thread.
            try:
                self.reload_table(table.split("/", 1)[1])
            # graftcheck: ignore[exception-hygiene] -- reload_table already
            # isolates + reports per-segment errors; this guard only keeps
            # the catalog watch thread alive on a wholesale failure
            except Exception:
                pass  # per-segment errors are already isolated + reported below

    def reload_table(self, table: str) -> List[str]:
        """Reconcile every loaded immutable segment's aux indexes with the CURRENT
        table config (reference: HelixInstanceDataManager.reloadSegment ->
        SegmentPreProcessor), swapping in fresh readers so new indexes are used.

        Index REMOVALS are deferred until after the fresh reader is swapped in and
        the old reader's refcount drains, so in-flight queries holding the old
        reader never lazily open a deleted file (the reference likewise destroys
        old index buffers only after segment release)."""
        from ..segment.preprocess import preprocess_segment
        cfg = self.catalog.table_configs.get(table)
        if cfg is None:
            return []
        mgr = self._table_manager(table)
        changes: List[str] = []
        schema = self.catalog.schema_for_table(table)
        segments = mgr.acquire()
        try:
            for seg in segments:
                if getattr(seg, "is_mutable", False) or not getattr(seg, "path", None):
                    continue
                deferred: List[str] = []
                try:
                    ch = preprocess_segment(
                        seg.path, cfg.indexing, defer_removals=deferred,
                        schema=schema)
                except Exception as e:  # one bad segment must not stop the rest
                    changes.append(f"{seg.name}: ERROR {type(e).__name__}: {e}")
                    ch = None
                # reap deferred removals even when a later step failed:
                # preprocess_segment already recorded a CRC that excludes them,
                # so leaving the files on disk would fail CRC verification
                # until some unrelated reload rewrote it
                if deferred:
                    self._remove_after_release(mgr, seg, deferred)
                if ch:
                    mgr.add_segment(seg.name, load_segment(seg.path))
                    changes.extend(f"{seg.name}/{c}" for c in ch)
        finally:
            mgr.release(segments)
        return changes

    def _remove_after_release(self, mgr: TableDataManager, old_seg,
                              paths: List[str]) -> None:
        """Delete superseded index files once the old reader is no longer acquired
        (bounded wait; open mmaps survive unlink on POSIX, so this is belt and
        braces against first-touch-after-delete)."""
        def reap():
            import time as _t
            deadline = _t.time() + 5.0
            while _t.time() < deadline:
                with mgr._lock:
                    # our caller still holds one ref during reload_table
                    if mgr._refcounts.get(old_seg.name, 0) <= 1:
                        break
                _t.sleep(0.05)
            for p in paths:
                try:
                    if os.path.exists(p):
                        os.remove(p)
                except OSError:
                    pass
        # graftcheck: ignore[thread-no-join] -- one-shot reaper bounded by its
        # own 5s deadline; joining would stall reload_table on file cleanup
        threading.Thread(target=reap, daemon=True, name="reload-reap").start()

    def reconcile(self, table: str) -> None:
        """Converge loaded segments to the ideal state (reference: Helix transitions
        OFFLINE->ONLINE / ONLINE->OFFLINE / ->DROPPED in
        SegmentOnlineOfflineStateModelFactory)."""
        ist = self.catalog.ideal_state.get(table, {})
        mgr = self._table_manager(table)
        desired = {seg: assignment[self.instance_id]
                   for seg, assignment in ist.items() if self.instance_id in assignment}

        for seg_name, state in desired.items():
            if state == ONLINE and seg_name not in mgr.segment_names:
                try:
                    # CONSUMING -> ONLINE: adopt the local build when offsets allow,
                    # else download the committed copy (reference:
                    # onBecomeOnlineFromConsuming, CONSUMING->ONLINE transition :91)
                    handler = self._realtime_managers.get(table)
                    local_dir = handler.on_segment_online(seg_name) if handler else None
                    try:
                        if local_dir:
                            mgr.add_segment(seg_name, load_segment(local_dir))
                        else:
                            self._load_online_segment(table, seg_name, mgr)
                    finally:
                        # handoff second half: retire the retained post-commit
                        # consumer whether the load succeeded (immutable now
                        # serves) or failed (ERROR state must not keep a
                        # closed consumer and its buffer alive forever)
                        if handler is not None:
                            handler.retire_consumer(seg_name)
                    self.catalog.report_state(table, seg_name, self.instance_id, ONLINE)
                except Exception:
                    self.catalog.report_state(table, seg_name, self.instance_id, "ERROR")
                    raise
            elif state == CONSUMING and seg_name not in mgr.segment_names:
                handler = self._ensure_realtime_manager(table)
                if handler is not None:
                    handler.start_consuming(seg_name)
                    self.catalog.report_state(table, seg_name, self.instance_id,
                                              CONSUMING)
            elif state == COLD:
                # cold demotion: the deep store holds the bytes; unload the
                # local copy. The segment stays registered + routable — first
                # query lazily re-downloads it (_run_partial cold path).
                # Transition-edge only (external view not yet COLD): a later
                # reconcile must NOT unload a copy the cold path just lazily
                # re-downloaded.
                ev_state = self.catalog.external_view.get(table, {}) \
                    .get(seg_name, {}).get(self.instance_id)
                if ev_state != COLD:
                    if seg_name in mgr.segment_names:
                        busy = mgr.refcount(seg_name) > 0
                        mgr.remove_segment(seg_name)
                        self.tiering.forget(seg_name)
                        if not busy:
                            # an in-flight query may lazily open column files
                            # off its deferred reader — only reclaim disk when
                            # no one holds the segment
                            import shutil
                            shutil.rmtree(os.path.join(self.data_dir, table,
                                                       seg_name),
                                          ignore_errors=True)
                    self.catalog.report_state(table, seg_name,
                                              self.instance_id, COLD)

        for seg_name in list(mgr.segment_names):
            if seg_name not in desired:
                mgr.remove_segment(seg_name)
                self.tiering.forget(seg_name)
                with self._lock:  # prune the load lock with the segment
                    self._load_locks.pop((table, seg_name), None)
                self.catalog.report_state(table, seg_name, self.instance_id, None)

        # CONSUMING segments removed from the ideal state (segment deletion,
        # shrink) must stop consuming too — they live in the realtime manager,
        # not the TableDataManager the loop above sweeps
        rt = self._realtime_managers.get(table)
        if rt is not None:
            for seg_name in list(rt.consumers):
                if seg_name not in desired:
                    consumer = rt.stop_consuming(seg_name)
                    if consumer is not None:
                        consumer.close()
                    self.catalog.report_state(table, seg_name,
                                              self.instance_id, None)

        if self.catalog.table_configs.get(table) is None:
            # table dropped: the realtime manager (and its auto_consume loop)
            # must die with it — a stale handler would keep fetching from the
            # old stream and shadow a recreated table's new config — and the
            # empty TableDataManager entry goes too
            with self._lock:
                handler = self._realtime_managers.pop(table, None)
                self.tables.pop(table, None)
                for key in [k for k in self._load_locks if k[0] == table]:
                    del self._load_locks[key]
            if handler is not None:
                handler.stop()
            # belt-and-braces ledger teardown: any residency still attributed
            # to the dropped table (consuming staging a racing stop missed)
            # must not survive as stale gauges
            from ..utils.memledger import get_ledger
            get_ledger().release(table=table)

        self._refresh_dim_table(table, mgr)

    def _refresh_dim_table(self, table: str, mgr: TableDataManager) -> None:
        """(Re)load a dimension table's PK map after segment changes (reference:
        DimensionTableDataManager rebuilds its map on every segment add/remove)."""
        cfg = self.catalog.table_configs.get(table)
        if cfg is None or not cfg.is_dim_table:
            return
        from ..query.lookup import register_dim_table_from_segments
        schema = self.catalog.schema_for_table(table)
        pk = schema.primary_key_columns if schema else []
        if not pk:
            return
        segments = mgr.acquire()
        try:
            register_dim_table_from_segments(cfg.name, pk, segments)
        finally:
            mgr.release(segments)

    def _ensure_realtime_manager(self, table: str):
        with self._lock:
            handler = self._realtime_managers.get(table)
            if handler is None:
                cfg = self.catalog.table_configs.get(table)
                if cfg is None or cfg.stream is None or self.completion is None:
                    return None
                from ..ingest.realtime import RealtimeTableManager
                handler = RealtimeTableManager(self, table, cfg, self.completion)
                self._realtime_managers[table] = handler
                if self.auto_consume:
                    handler.start_loop()
            return handler

    def realtime_manager(self, table: str):
        return self._realtime_managers.get(table)

    def ingestion_snapshot(self) -> Dict[str, Dict[str, object]]:
        """{table: ingestion rollup} across every realtime manager on this
        server — the payload behind /debug/consuming, and what the
        controller's ingestion status check polls (in-proc clusters register
        this method directly as the poller)."""
        return {table: handler.ingestion_status()
                for table, handler in list(self._realtime_managers.items())}

    def memory_snapshot(self) -> Dict[str, object]:
        """Device-memory residency rollup — the payload behind /debug/memory
        and what the controller's memory status check polls (in-proc clusters
        register this method directly as the poller). The ledger is
        process-global, so in-proc multi-server clusters all report the one
        process view — which is also what jax reports, keeping
        reconciliation honest."""
        from ..utils.memledger import get_ledger
        snap = get_ledger().snapshot()
        snap["instanceId"] = self.instance_id
        snap["tiering"] = self.tiering.snapshot()
        return snap

    def _load_online_segment(self, table: str, seg_name: str, mgr: TableDataManager) -> None:
        # per-segment load lock (reference: SegmentLocks): concurrent
        # reconciles — an ideal-state notify racing a rebalance notify — must
        # not double-download/untar into the same directory (one thread's
        # cleanup deletes the tar under the other, and a racing untar could be
        # read half-written)
        with self._segment_load_lock(table, seg_name):
            meta = self.catalog.segments.get(table, {}).get(seg_name)
            local_dir = os.path.join(self.data_dir, table, seg_name)
            if not os.path.isdir(local_dir):
                if meta is None or not meta.download_path:
                    raise FileNotFoundError(f"no deep-store path for {table}/{seg_name}")
                tar_local = f"{local_dir}.{threading.get_ident()}.tar.gz"
                from .peers import download_segment_tar
                download_segment_tar(self.deepstore, self.catalog, table,
                                     seg_name, tar_local, meta.download_path,
                                     exclude_instance=self.instance_id)
                try:
                    untar_segment(tar_local, os.path.dirname(local_dir))
                finally:
                    if os.path.exists(tar_local):
                        os.remove(tar_local)
            mgr.add_segment(seg_name, load_segment(local_dir))

    def _cold_unloaded(self, table: str,
                       segment_names: Optional[Sequence[str]],
                       mgr: TableDataManager) -> List[str]:
        """Segments the query wants that are assigned COLD to this server
        with no loaded copy — the cold-tier lazy-load set. Snapshot under the
        catalog lock (startup_status idiom: the in-proc catalog mutates its
        dicts in place)."""
        with self.catalog._lock:
            ist = {s: dict(a) for s, a in
                   self.catalog.ideal_state.get(table, {}).items()}
        loaded = set(mgr.segment_names)
        wanted = list(ist) if segment_names is None else list(segment_names)
        return [s for s in wanted
                if s not in loaded
                and ist.get(s, {}).get(self.instance_id) == COLD]

    def local_segment_dir(self, table: str, seg_name: str) -> Optional[str]:
        """On-disk directory of a LOADED segment (peer download serves from
        it); None when this server doesn't serve the segment."""
        mgr = self.tables.get(table)
        if mgr is None:
            return None
        seg = mgr.get(seg_name)
        path = getattr(seg, "path", None)
        return path if path and os.path.isdir(path) else None

    def _segment_load_lock(self, table: str, seg_name: str) -> threading.Lock:
        key = (table, seg_name)
        with self._lock:
            lock = self._load_locks.get(key)
            if lock is None:
                lock = self._load_locks[key] = threading.Lock()
            return lock

    def add_local_segment(self, table: str, segment: ImmutableSegment) -> None:
        """Directly register an already-built local segment (used by realtime commit)."""
        self._table_manager(table).add_segment(segment.name, segment)

    def _table_manager(self, table: str) -> TableDataManager:
        with self._lock:
            if table not in self.tables:
                self.tables[table] = TableDataManager(
                    table, os.path.join(self.data_dir, table))
            return self.tables[table]

    # -- query execution ---------------------------------------------------

    #: minimum remaining deadline budget accepted at submit: below this the
    #: queue hop alone would eat the budget, so the query rejects typed (408
    #: with the stamped deadline) instead of enqueueing doomed work
    MIN_DEADLINE_BUDGET_S = 0.005

    #: EWMA smoothing for the per-table bytesFetched estimate
    _BYTES_EWMA_ALPHA = 0.2

    def execute_partial(self, table: str, ctx: Union[str, QueryContext],
                        segment_names: Optional[Sequence[str]] = None,
                        time_filter: Optional[str] = None) -> SegmentResult:
        """Run the query over this server's copy of `segment_names`, return the merged
        server-level partial (reference: ServerQueryExecutorV1Impl.processQuery returning
        a DataTable).

        `time_filter` is an optional SQL boolean expression ANDed into the WHERE
        clause — the broker's hybrid-table time-boundary split (reference: the
        brokerRequest's timeBoundary attachment in BaseSingleStageBrokerRequestHandler).
        """
        schema = self.catalog.schema_for_table(table)
        if isinstance(ctx, str):
            ctx = compile_query(ctx, schema)
        if time_filter:
            ctx = _apply_time_filter(ctx, time_filter, schema)
        # graftfault: a crash here dies exactly where a killed process would
        # (the broker's taxonomy sees a transport failure and retries on
        # another replica); slow is the straggler the hedging machinery hunts
        fault_point("server.crash")
        fault_point("server.slow")
        # deadline propagation: the broker stamps deadlineEpochMs from its own
        # timeout budget; a partial that arrives after the caller gave up
        # fails typed NOW instead of burning scheduler and device time on an
        # answer nobody is waiting for
        remaining_s = _deadline_remaining_s(ctx)
        if remaining_s is not None and remaining_s <= self.MIN_DEADLINE_BUDGET_S:
            # admission-time rejection: a query whose budget is already spent
            # (or too thin to survive even the queue hop) fails typed NOW with
            # the stamped deadline attached, so the 408 body tells the caller
            # WHICH deadline was missed instead of burning a scheduler slot
            from ..query.scheduler import QueryTimeoutError
            d_ms = ctx.options.get("deadlineEpochMs") if ctx.options else None
            err = QueryTimeoutError(
                f"query deadline budget exhausted ({remaining_s * 1000:.1f}ms "
                f"remaining, floor {self.MIN_DEADLINE_BUDGET_S * 1000:.0f}ms) "
                f"at {self.instance_id}",
                deadline_epoch_ms=float(d_ms) if d_ms is not None else None)
            raise err
        if self.scheduler is not None:
            timeout_s = None
            t_ms = ctx.options.get("timeoutMs") if ctx.options else None
            if t_ms is not None:
                timeout_s = float(t_ms) / 1000.0
            if remaining_s is not None:
                # the tighter of the per-query budget and the broker deadline
                timeout_s = remaining_s if timeout_s is None \
                    else min(timeout_s, remaining_s)
            # the scheduler's worker thread must see the caller's request trace,
            # seeded at the caller's nesting depth so in-proc spans tree up
            # exactly like HTTP-spliced ones; the submit->run gap is admission
            # queueing — recorded as queue_wait so the hop decomposition never
            # goes queued-blind
            from ..utils.trace import current_depth, current_trace
            tr = current_trace()
            depth = current_depth()
            submit_ms = tr.now_ms() if tr is not None else 0.0

            def run():
                if tr is None:
                    return self._execute_partial(table, ctx, segment_names)
                tr.record("queue_wait", submit_ms, tr.now_ms() - submit_ms,
                          depth=depth)
                with tr.activate(depth=depth):
                    return self._execute_partial(table, ctx, segment_names)
            result = self.scheduler.submit(
                table, run, timeout_s=timeout_s,
                cost_bytes=self._predicted_bytes(table))
            self._observe_bytes(table, result)
            return result
        result = self._execute_partial(table, ctx, segment_names)
        self._observe_bytes(table, result)
        return result

    def _predicted_bytes(self, table: str) -> float:
        """The fair scheduler's per-query byte cost for `table`: the EWMA of
        recent partials' bytesFetched (0.0 until the first completes — an
        unknown tenant is charged the 1.0 base cost only)."""
        with self._lock:
            return self._table_bytes_ewma.get(table, 0.0)

    def _observe_bytes(self, table: str, result) -> None:
        stats = getattr(result, "stats", None)
        if not isinstance(stats, dict):
            return
        try:
            b = float(stats.get(qstats.BYTES_FETCHED, 0.0))
        except (TypeError, ValueError):
            return
        with self._lock:
            prev = self._table_bytes_ewma.get(table)
            # graftcheck: ignore[unbounded-keyed-accumulation] -- one float
            # per table this server hosts (topology-bounded key space)
            self._table_bytes_ewma[table] = b if prev is None else \
                prev + self._BYTES_EWMA_ALPHA * (b - prev)

    def _execute_partial(self, table: str, ctx: QueryContext,
                         segment_names: Optional[Sequence[str]]) -> SegmentResult:
        # per-query telemetry record for this server-level partial: executor /
        # kernel hooks on THIS thread publish into it; pipeline-attributed
        # launch stats arrive attached to the device partial and fold in after
        with qstats.collect_stats() as st:
            merged = self._run_partial(table, ctx, segment_names)
        st.merge(merged.stats)
        merged.stats = st.to_wire()
        return merged

    def _run_partial(self, table: str, ctx: QueryContext,
                     segment_names: Optional[Sequence[str]]) -> SegmentResult:
        import time as _t

        from ..utils.metrics import get_registry
        from ..utils.trace import span
        reg = get_registry()
        t0 = _t.perf_counter()
        mgr = self._table_manager(table)
        handler = self._realtime_managers.get(table)
        upsert = getattr(handler, "upsert", None) if handler else None
        segments = mgr.acquire(segment_names)
        admitted: List[ImmutableSegment] = []
        try:
            # cold tier: requested segments assigned COLD to this server with
            # no local copy lazily download NOW, bounded by the propagated
            # deadline — past-budget loads fail typed instead of stalling
            for seg_name in self._cold_unloaded(table, segment_names, mgr):
                remaining_s = _deadline_remaining_s(ctx)
                if (remaining_s is not None
                        and remaining_s <= self.MIN_DEADLINE_BUDGET_S):
                    from ..query.scheduler import QueryTimeoutError
                    d_ms = ctx.options.get("deadlineEpochMs") \
                        if ctx.options else None
                    raise QueryTimeoutError(
                        f"deadline budget exhausted before cold-tier load of "
                        f"{table}/{seg_name} at {self.instance_id}",
                        deadline_epoch_ms=float(d_ms)
                        if d_ms is not None else None)
                t_load = _t.perf_counter()
                with span(f"coldload:{seg_name}"):
                    self._load_online_segment(table, seg_name, mgr)
                segments.extend(mgr.acquire([seg_name]))
                self.tiering.note_cold_load()
                emit_event("segment.cold.loaded", node=self.instance_id,
                           table=table, segment=seg_name)
                qstats.record(qstats.SEGMENTS_COLD_LOADED, 1)
                qstats.record(qstats.COLD_LOAD_MS,
                              (_t.perf_counter() - t_load) * 1000)

            # HBM admission gate: predict each un-staged block's bytes
            # against the tiering target (evicting colder victims first);
            # rejected segments run the host plan instead of OOMing
            from ..engine.datablock import has_block
            host_tier: List[ImmutableSegment] = []
            for seg in segments:
                fresh = not has_block(seg)
                if self.tiering.admit(table, seg, mgr):
                    admitted.append(seg)
                    if fresh:
                        self.tiering.note_promotion()
                        emit_event("tier.promoted", node=self.instance_id,
                                   table=table,
                                   segment=getattr(seg, "name", ""))
                        qstats.record(qstats.TIER_PROMOTIONS, 1)
                else:
                    host_tier.append(seg)
            if host_tier:
                qstats.record(qstats.SEGMENTS_SERVED_HOST_TIER,
                              len(host_tier))

            results = []
            device_partial = None
            if (self.device_pipeline is not None and admitted
                    and upsert is None
                    and (ctx.aggregations or ctx.distinct
                         or device_topk_screen(ctx))):
                # pre-screened on THIS thread: only shapes that CAN plan on
                # device enter the pipeline — everything else goes straight
                # to the host loop instead of waiting out the pipeline's
                # batch-accumulation window for a FALLBACK verdict. DISTINCT
                # rewrites to a group-by, which plans on device; ORDER-BY-
                # limit selections ride the fused top-k kernel when the
                # screen admits them (single-column order, bounded k)
                # device path: ONE server-level partial for the whole set,
                # executed on the mesh with batched fetches; falls back per
                # segment below when the plan can't ride the device (upsert
                # valid masks always take the host path — per-doc visibility
                # is host state)
                from .device_server import DEVICE_FALLBACK
                with span("device"):
                    try:
                        out = self.device_pipeline.execute_partial(ctx,
                                                                   admitted)
                    except Exception:
                        out = DEVICE_FALLBACK  # device fault -> host answers
                if out is not DEVICE_FALLBACK:
                    device_partial = out
                    reg.counter("pinot_server_device_queries",
                                {"table": table}).inc()
            if device_partial is not None:
                results.append(device_partial)
                # the pipeline's threads can't attribute per-query segment
                # counts (they serve many queries per launch) — account the
                # set here, on the query's own thread
                qstats.record(qstats.NUM_SEGMENTS_QUERIED, len(admitted))
                if (device_partial.num_docs_scanned > 0
                        or device_partial.groups or device_partial.rows
                        or device_partial.dense is not None):
                    qstats.record(qstats.NUM_SEGMENTS_MATCHED, len(admitted))
                # unadmitted segments still answer — on the host plan
                for seg in host_tier:
                    with span(f"segment:{seg.name}"):
                        valid = upsert.valid_mask(seg.name, seg.num_docs) \
                            if upsert else None
                        results.append(self.host_executor.execute_segment(
                            ctx, seg, valid))
            else:
                admitted_names = {seg.name for seg in admitted}
                for seg in segments:
                    with span(f"segment:{seg.name}"):
                        valid = upsert.valid_mask(seg.name, seg.num_docs) \
                            if upsert else None
                        ex = self.executor if seg.name in admitted_names \
                            else self.host_executor
                        results.append(ex.execute_segment(ctx, seg, valid))
            # include in-progress realtime docs when a consuming manager exists
            served = [seg.name for seg in segments]
            if handler is not None:
                with span("consuming"):
                    rt_results, rt_served = handler.consuming_results(
                        ctx, segment_names, exclude=set(served))
                results.extend(rt_results)
                served.extend(rt_served)
                if rt_served:
                    # consuming-segment visibility (reference: the broker
                    # response's numConsumingSegmentsQueried +
                    # minConsumingFreshnessTimeMs pair): freshness is the min
                    # across the consuming segments THIS partial touched —
                    # the broker min-merges across servers
                    qstats.record(qstats.NUM_CONSUMING_SEGMENTS_QUERIED,
                                  len(rt_served))
                    fresh = handler.min_freshness_ms(rt_served)
                    if fresh is not None:
                        qstats.record_min(
                            qstats.MIN_CONSUMING_FRESHNESS_TIME_MS, fresh)
        finally:
            # reservations made by THIS query's admissions are settled: a
            # block either staged (the ledger counts it now) or never will
            # until another query re-admits it
            self.tiering.settle([seg.name for seg in admitted])
            mgr.release(segments)
        aggs = [make_agg(f) for f in ctx.aggregations]
        with span("merge"):
            merged = merge_segment_results(results, aggs)
        merged.served = served
        # ServerMeter QUERIES / NUM_DOCS_SCANNED / NUM_SEGMENTS_QUERIED analogs
        reg.counter("pinot_server_queries", {"table": table}).inc()
        reg.counter("pinot_server_docs_scanned").inc(merged.num_docs_scanned)
        reg.counter("pinot_server_segments_queried").inc(len(segments))
        reg.timer("pinot_server_query_latency_ms").update(
            (_t.perf_counter() - t0) * 1000)
        return merged

    def explain_partial(self, table: str, ctx: Union[str, QueryContext],
                        segment_names: Optional[Sequence[str]] = None) -> List[List]:
        """EXPLAIN rows over this server's copy of the segments (reference: v2
        explain asks servers for their operator plans)."""
        from ..query.explain import explain_result
        schema = self.catalog.schema_for_table(table)
        if isinstance(ctx, str):
            ctx = compile_query(ctx, schema)
        mgr = self._table_manager(table)
        segments = mgr.acquire(segment_names)
        try:
            return explain_result(ctx, segments, table=table).rows
        finally:
            mgr.release(segments)

    def segments_served(self, table: str) -> List[str]:
        return self._table_manager(table).segment_names

    @staticmethod
    def apply_time_filter(ctx: QueryContext, time_filter: str, schema) -> QueryContext:
        return _apply_time_filter(ctx, time_filter, schema)


def _deadline_remaining_s(ctx: QueryContext) -> Optional[float]:
    """Seconds left until the broker-stamped absolute deadline
    (`deadlineEpochMs` query option), or None when no deadline rode in.
    Negative means the caller already gave up on this query."""
    d_ms = ctx.options.get("deadlineEpochMs") if ctx.options else None
    if d_ms is None:
        return None
    import time
    return float(d_ms) / 1000.0 - time.time()


def _apply_time_filter(ctx: QueryContext, time_filter: str, schema) -> QueryContext:
    """AND a SQL boolean expression (the broker's hybrid time-boundary predicate)
    into the context's WHERE tree, reusing the normal compile pipeline so the
    predicate is normalized exactly like a user-written one."""
    import dataclasses
    from ..sql.ast import Function
    from ..sql.parser import parse_query
    dummy = parse_query(f"SELECT * FROM t WHERE {time_filter}")
    tf = compile_query(dummy, schema).filter
    new_filter = tf if ctx.filter is None else Function("and", (ctx.filter, tf))
    return dataclasses.replace(ctx, filter=new_filter)
