"""Controller role: table CRUD, segment upload, assignment, retention, rebalance.

Analog of the reference's controller (SURVEY.md §2.7): `PinotHelixResourceManager`
(cluster mutations), `ZKOperator.completeSegmentOperations`
(`pinot-controller/.../api/upload/ZKOperator.java:50,64` — validate, copy to deep store,
write metadata, update ideal state), `RetentionManager` (expiry deletion),
`SegmentDeletionManager`, and `TableRebalancer`'s converge loop. Periodic tasks run on a
`PeriodicTaskScheduler` analog (`pinot_tpu/utils/periodic.py`).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ..schema import Schema
from ..segment.format import read_json, SEGMENT_METADATA_FILE
from ..segment.reader import load_segment
from ..table import TableConfig, TableType
from .assignment import balanced_assign, compute_counts, rebalance_table, replica_group_assign
from .catalog import (Catalog, COLUMN_STATS_KEY, InstanceInfo, ONLINE,
                      SegmentMeta, STATUS_IN_PROGRESS, STATUS_UPLOADED,
                      column_stats_from_meta)
from .deepstore import DeepStoreFS, tar_segment
from .routing import partition_for_value

# deleted segments park in the deep store this long before the retention
# reaper removes them (reference: SegmentDeletionManager's Deleted_Segments
# retention, controller.deleted.segments.retentionInDays default 7)
DELETED_SEGMENTS_RETENTION_DAYS = 7.0


class Controller:
    def __init__(self, instance_id: str, catalog: Catalog, deepstore: DeepStoreFS,
                 work_dir: str):
        self.instance_id = instance_id
        self.catalog = catalog
        self.deepstore = deepstore
        self.work_dir = work_dir
        os.makedirs(work_dir, exist_ok=True)
        from .completion import LLCSegmentManager
        self.llc = LLCSegmentManager(catalog, deepstore,
                                     os.path.join(work_dir, "llc"))
        from ..minion.tasks import PinotTaskManager
        from ..utils.periodic import PeriodicTask, PeriodicTaskScheduler
        self.task_manager = PinotTaskManager(catalog)
        # periodic controller tasks (reference: ControllerPeriodicTask registrations:
        # RetentionManager, PinotTaskManager's generation cron)
        self.scheduler = PeriodicTaskScheduler()
        self._status_tables: set = set()  # tables with exported health gauges
        self.scheduler.register(PeriodicTask("RetentionManager", 300.0,
                                             self.run_retention))
        self.scheduler.register(PeriodicTask("PinotTaskManager", 60.0,
                                             self.task_manager.generate_all))
        self.scheduler.register(PeriodicTask("RealtimeSegmentValidationManager",
                                             60.0, self.llc.validate))
        self.scheduler.register(PeriodicTask("SegmentRelocator", 3600.0,
                                             self.run_segment_relocation))
        self.scheduler.register(PeriodicTask("SegmentStatusChecker", 300.0,
                                             self.run_segment_status_check))
        self.scheduler.register(PeriodicTask("MinionInstancesCleanupTask",
                                             3600.0, self.cleanup_dead_minions))
        self.scheduler.register(PeriodicTask("TaskMetricsEmitter", 300.0,
                                             self.emit_task_metrics))
        # ingestion health plane (reference: the controller's
        # tableIngestionStatus aggregation over server consumingSegmentsInfo)
        self._ingestion_tables: set = set()   # tables with ingestion gauges
        self._ingestion_status: Dict[str, Dict[str, object]] = {}
        # in-proc clusters register ServerNode.ingestion_snapshot directly;
        # OS-process clusters are discovered via advertised instance ports
        self.ingestion_pollers: Dict[str, Callable[[], Dict[str, dict]]] = {}
        self.scheduler.register(PeriodicTask("IngestionStatusChecker", 60.0,
                                             self.run_ingestion_status_check))
        # SLO burn-rate plane: windowed verdicts over the brokers' per-table
        # rollups (slo.latency.p99.ms / slo.error.rate cluster config), the
        # serving-side companion of ingestionStatus
        self._slo_tables: set = set()         # tables with exported SLO gauges
        self._slo_status: Dict[str, Dict[str, object]] = {}
        self._slo_samples: Dict[str, object] = {}   # table -> deque of samples
        # in-proc clusters register Broker.debug_stats directly; OS-process
        # brokers are discovered via advertised instance ports (GET /debug)
        self.slo_pollers: Dict[str, Callable[[], Dict[str, object]]] = {}
        self.scheduler.register(PeriodicTask("SLOStatusChecker", 60.0,
                                             self.run_slo_check))
        # device-memory plane: aggregate every server's HBM residency ledger
        # (/debug/memory) into per-table verdicts — the cluster-level
        # accounting the ROADMAP tiered-storage item needs before any
        # promotion/eviction policy can exist
        self._memory_tables: set = set()      # tables with exported gauges
        self._memory_instances: set = set()   # servers with headroom gauges
        self._memory_status: Dict[str, Dict[str, object]] = {}
        # in-proc clusters register ServerNode.memory_snapshot directly;
        # OS-process servers are discovered via advertised instance ports
        self.memory_pollers: Dict[str, Callable[[], Dict[str, object]]] = {}
        self.scheduler.register(PeriodicTask("MemoryStatusChecker", 60.0,
                                             self.run_memory_check))
        # workload regression sentinel: the per-shape generalization of the
        # SLO plane — windowed burn of each plan fingerprint's overBaseline
        # counter from the brokers' /debug/workload registries
        self._workload_status: Dict[str, object] = {}
        self._workload_samples: Dict[str, object] = {}  # fingerprint -> deque
        # in-proc clusters register Broker.workload.snapshot directly;
        # OS-process brokers are discovered via GET /debug/workload
        self.workload_pollers: Dict[str, Callable[[], Dict[str, object]]] = {}
        self.scheduler.register(PeriodicTask("WorkloadSentinel", 60.0,
                                             self.run_workload_check))
        # event journal plane: cursor-incremental pulls of every node's
        # journal (/debug/events?since=) merged into one bounded cluster
        # timeline; verdict edges trip the flight recorder, which freezes an
        # incident bundle (recent timeline + /debug snapshots + slow-query
        # trace ids) into a bounded incident ring (/debug/incidents)
        self._events_lock = threading.Lock()
        self._timeline: deque = deque()              # merged, arrival order
        self._event_cursors: Dict[str, int] = {}     # source id -> last gseq
        self._events_unreachable: List[str] = []
        self._incidents: deque = deque()             # oldest -> newest
        self._incident_seq = 0
        # in-proc clusters register extra journals here (node -> fn(since));
        # OS-process nodes are discovered via GET /debug/events?since=
        self.event_pollers: Dict[str, Callable[[int], Dict[str, object]]] = {}
        # incident snapshot sources (node -> fn() -> /debug payload); in-proc
        # clusters register Broker.debug_stats, OS-process brokers via HTTP
        self.incident_pollers: Dict[str, Callable[[], Dict[str, object]]] = {}
        # edge-trigger memory for the four verdict planes: previous status
        # per table (per fingerprint for workload), pruned with the plane
        self._verdict_prev: Dict[str, Dict[str, str]] = {
            "ingestion": {}, "slo": {}, "memory": {}, "workload": {}}
        self.scheduler.register(PeriodicTask("EventTimelineCollector", 10.0,
                                             self.run_event_check))
        catalog.register_instance(InstanceInfo(instance_id, "controller"))

    def start_periodic_tasks(self) -> None:
        """Start background schedulers (tests tick with scheduler.run_all_once())."""
        self.scheduler.start()

    def stop_periodic_tasks(self) -> None:
        self.scheduler.stop()

    # -- table CRUD (reference: PinotTableRestletResource + resource manager) ----
    def add_schema(self, schema: Schema) -> None:
        self.catalog.put_schema(schema)

    def add_table(self, config: TableConfig) -> None:
        if config.name not in self.catalog.schemas:
            raise ValueError(f"schema {config.name!r} must be added before the table")
        self._validate_table_config(config)
        self.catalog.put_table_config(config)

    @staticmethod
    def _validate_table_config(config: TableConfig) -> None:
        if config.routing_selector and config.routing_selector.lower().replace(
                "_", "") not in ("balanced", "replicagroup", "strictreplicagroup"):
            # a typo here would silently fall back to balanced and disable the
            # upsert consistency guard — reject at config-write time instead
            raise ValueError(
                f"unknown routingSelector {config.routing_selector!r} "
                "(balanced | replicaGroup | strictReplicaGroup)")

    def add_realtime_table(self, config: TableConfig, num_partitions: int) -> List[str]:
        """Create a realtime table and its initial CONSUMING segments (reference:
        table creation path calling PinotLLCRealtimeSegmentManager.setUpNewTable)."""
        assert config.table_type is TableType.REALTIME and config.stream is not None
        self.add_table(config)
        return self.llc.setup_realtime_table(config, num_partitions)

    def drop_table(self, table: str) -> None:
        for seg in list(self.catalog.segments.get(table, {})):
            self.delete_segment(table, seg)
        # clear per-table operational flags: a table recreated under the same
        # name must not inherit a disabled/paused state from its predecessor
        self.catalog.put_property(f"tableState/{table}", None)
        self.catalog.put_property(f"pause/{table}", None)
        self.catalog.drop_table(table)

    # -- segment upload (reference: ZKOperator.completeSegmentOperations) --------
    def upload_segment(self, table: str, segment_dir: str,
                       custom: Optional[Dict[str, str]] = None) -> SegmentMeta:
        cfg = self.catalog.table_configs.get(table)
        if cfg is None:
            raise ValueError(f"unknown table {table!r}")
        seg_meta_json = read_json(os.path.join(segment_dir, SEGMENT_METADATA_FILE))
        seg_name = seg_meta_json["segmentName"]

        # validate schema compatibility
        schema = self.catalog.schemas.get(cfg.name)
        seg_schema = Schema.from_json(seg_meta_json["schema"])
        for f in schema.fields:
            if not seg_schema.has_column(f.name):
                raise ValueError(f"segment {seg_name} missing column {f.name}")

        # copy to deep store
        tar_path = os.path.join(self.work_dir, f"{seg_name}.tar.gz")
        tar_segment(segment_dir, tar_path)
        uri = f"{table}/{seg_name}.tar.gz"
        self.deepstore.upload(tar_path, uri)
        size = os.path.getsize(tar_path)
        os.remove(tar_path)

        meta = SegmentMeta(
            name=seg_name, table=table, status=STATUS_UPLOADED,
            num_docs=seg_meta_json["totalDocs"],
            crc=read_json(os.path.join(segment_dir, "creation.meta.json"))["crc"],
            size_bytes=size, download_path=uri,
            push_time_ms=int(time.time() * 1000),
            partition_id=self._partition_id(cfg, segment_dir, seg_meta_json),
            custom=dict(custom or {}),
        )
        self._fill_time_range(cfg, seg_meta_json, meta)
        col_stats = column_stats_from_meta(seg_meta_json)
        if col_stats:
            meta.custom[COLUMN_STATS_KEY] = col_stats
        self.catalog.put_segment_meta(meta)
        self._assign_segment(table, cfg, meta)
        from ..utils.metrics import get_registry
        get_registry().counter("pinot_controller_segments_uploaded",
                               {"table": table}).inc()
        return meta

    def _partition_id(self, cfg: TableConfig, segment_dir: str, seg_meta) -> Optional[int]:
        if not cfg.partition:
            return None
        col = seg_meta["columns"].get(cfg.partition.column)
        if col is None:
            return None
        # all rows of a properly partitioned segment map to one partition; derive it
        # from the column min value (builder-side partition check comes with ingest)
        v = col.get("minValue")
        if v is None:
            return None
        return partition_for_value(v, cfg.partition.function, cfg.partition.num_partitions)

    def _fill_time_range(self, cfg: TableConfig, seg_meta, meta: SegmentMeta) -> None:
        if not cfg.time_column:
            return
        col = seg_meta["columns"].get(cfg.time_column)
        if col and col.get("minValue") is not None:
            meta.start_time_ms = int(col["minValue"])
            meta.end_time_ms = int(col["maxValue"])

    def _assign_segment(self, table: str, cfg: TableConfig, meta: SegmentMeta) -> None:
        servers = self.catalog.live_servers(cfg.tenant)
        ist = self.catalog.ideal_state.get(table, {})
        counts = compute_counts(ist)
        if cfg.is_dim_table:
            # dimension tables replicate to EVERY server in the tenant so LOOKUP
            # always resolves locally (reference: DimTableSegmentAssignment)
            chosen = list(servers)
        elif cfg.partition and meta.partition_id is not None:
            chosen = replica_group_assign(meta.name, servers, cfg.replication,
                                          meta.partition_id, counts)
        else:
            chosen = balanced_assign(meta.name, servers, cfg.replication, counts)
        self.catalog.update_ideal_state(table, {meta.name: {s: ONLINE for s in chosen}})

    # -- segment replace w/ lineage (reference: SegmentLineage +
    # startReplaceSegments/endReplaceSegments REST flow) --------------------------
    def replace_segments(self, table: str, old_names: List[str],
                         new_segment_dirs: List[str],
                         custom: Optional[Dict[str, str]] = None) -> List[str]:
        """Atomically (to queries) swap `old_names` for the new segments.

        Routing consults the lineage entries (`cluster/routing.py`): while the entry
        is IN_PROGRESS queries keep hitting the old segments and ignore the new ones;
        after the flip to COMPLETED they see only the new ones. Old segments are then
        physically deleted and the entry removed.
        """
        import uuid as _uuid
        new_names = []
        for d in new_segment_dirs:
            new_names.append(read_json(os.path.join(d, SEGMENT_METADATA_FILE))["segmentName"])
        entry_id = _uuid.uuid4().hex
        key = f"lineage/{table}"

        def add_entry(entries):
            entries = list(entries or [])
            entries.append({"id": entry_id, "from": list(old_names),
                            "to": new_names, "state": "IN_PROGRESS"})
            return entries
        self.catalog.mutate_property(key, add_entry)

        try:
            for d in new_segment_dirs:
                self.upload_segment(table, d, custom=custom)
        except Exception:
            # revert: drop the half-uploaded outputs, queries never saw them
            for name in new_names:
                if name in self.catalog.segments.get(table, {}):
                    self.delete_segment(table, name, permanent=True)
            self.catalog.mutate_property(
                key, lambda es: [e for e in (es or []) if e["id"] != entry_id] or None)
            raise

        def complete(entries):
            return [dict(e, state="COMPLETED") if e["id"] == entry_id else e
                    for e in (entries or [])]
        self.catalog.mutate_property(key, complete)

        for name in old_names:
            self.delete_segment(table, name)
        self.catalog.mutate_property(
            key, lambda es: [e for e in (es or []) if e["id"] != entry_id] or None)
        return new_names

    def reload_table(self, table: str) -> None:
        """Ask every server holding the table to re-run the segment preprocessor
        against the current config (reference: the controller's
        /segments/{table}/reload endpoint sending Helix RELOAD messages).

        A uuid nonce (not a timestamp) guarantees back-to-back reloads each
        produce a distinct property value, so remote snapshot-diff watchers never
        coalesce two reloads into one."""
        import uuid as _uuid
        self.catalog.put_property(f"reload/{table}", _uuid.uuid4().hex)

    def update_table(self, config: TableConfig, reload: bool = True) -> None:
        """Replace a table's config; by default trigger a reload so index changes
        take effect on servers."""
        self._validate_table_config(config)
        self.catalog.put_table_config(config)
        if reload:
            self.reload_table(config.table_name_with_type)

    # -- deletion / retention ---------------------------------------------------
    def delete_segment(self, table: str, segment: str, *,
                       permanent: bool = False,
                       now_ms: Optional[int] = None) -> None:
        """Reference: SegmentDeletionManager — remove from ideal state and
        metadata, and PARK the deep-store copy under Deleted_Segments/ instead
        of deleting it: an accidental drop is recoverable until the retention
        reaper (run_retention) removes parked copies past
        DELETED_SEGMENTS_RETENTION_DAYS.

        `permanent=True` bypasses parking — for internal cleanup of segments
        queries never saw (replace-rollback, minion retry sweeps), where a
        parked copy would just be 7 days of deep-store garbage. `now_ms` is
        the deletion timestamp for the parking note; callers driving a
        simulated clock pass theirs so parking and reaping share one clock."""
        meta = self.catalog.segments.get(table, {}).get(segment)
        self.catalog.update_ideal_state(table, {segment: None})
        self.catalog.drop_segment_meta(table, segment)
        if meta and meta.download_path and self.deepstore.exists(meta.download_path):
            if permanent:
                self.deepstore.delete(meta.download_path)
                return
            parked = f"Deleted_Segments/{table}/{segment}.tar.gz"
            self.deepstore.move(meta.download_path, parked)
            self.catalog.put_property(
                f"deleted/{table}/{segment}",
                {"uri": parked,
                 "deletedAtMs": now_ms or int(time.time() * 1000)})

    def demote_segment_to_cold(self, table: str, segment: str) -> bool:
        """Tiered-storage demotion: flip every replica's ideal-state
        assignment to COLD. Servers unload their local copy (deep store keeps
        the bytes), routing keeps the segment routable, and the first query
        lazily re-downloads it. Returns False when the segment is unknown or
        already fully COLD."""
        from .catalog import COLD
        ist = self.catalog.ideal_state.get(table, {}).get(segment)
        if not ist or all(st == COLD for st in ist.values()):
            return False
        self.catalog.update_ideal_state(
            table, {segment: {srv: COLD for srv in ist}})
        from ..utils.metrics import get_registry
        get_registry().counter("pinot_controller_cold_demotions",
                               {"table": table}).inc()
        from ..utils.events import emit as emit_event
        emit_event("segment.cold.demoted", node=self.instance_id,
                   table=table, segment=segment)
        return True

    def run_retention(self, now_ms: Optional[int] = None) -> List[str]:
        """Reference: RetentionManager periodic task — delete segments past
        retention. With `controller.retention.cold.demote` set true, expiry
        demotes to the cold tier (recoverable, still queryable) instead of
        deleting — time-based tiering riding the same periodic task."""
        now_ms = now_ms or int(time.time() * 1000)
        demote = str(self.catalog.get_property(
            "clusterConfig/controller.retention.cold.demote",
            "false")).lower() == "true"
        deleted = []
        for table, cfg in list(self.catalog.table_configs.items()):
            if not cfg.retention_days or not cfg.time_column:
                continue
            cutoff = now_ms - cfg.retention_days * 24 * 3600 * 1000
            for seg, meta in list(self.catalog.segments.get(table, {}).items()):
                if meta.end_time_ms is not None and meta.end_time_ms < cutoff:
                    if demote:
                        if self.demote_segment_to_cold(table, seg):
                            deleted.append(f"cold:{table}/{seg}")
                        continue
                    self.delete_segment(table, seg, now_ms=now_ms)
                    deleted.append(f"{table}/{seg}")
        # reap parked deep-store copies past the deleted-segment retention
        park_cutoff = now_ms - DELETED_SEGMENTS_RETENTION_DAYS * 86_400_000
        for key, note in list(self.catalog.properties.items()):
            if not key.startswith("deleted/") or not isinstance(note, dict):
                continue
            if note.get("deletedAtMs", 0) < park_cutoff:
                self.deepstore.delete(note["uri"])
                self.catalog.put_property(key, None)
                deleted.append(f"reaped:{note['uri']}")
        return deleted

    # -- periodic health/cleanup tasks --------------------------------------
    def run_segment_status_check(self) -> Dict[str, Dict[str, int]]:
        """Reference: SegmentStatusChecker — per-table segment/replica health
        gauges the metrics endpoint exposes for alerting. Gauges of dropped
        tables are removed, not left exporting stale values."""
        from ..utils.metrics import get_registry
        reg = get_registry()
        out: Dict[str, Dict[str, int]] = {}
        for table in list(self.catalog.ideal_state):
            st = self.table_status(table)
            online = sum(1 for n in st["replicas_online"].values() if n > 0)
            labels = {"table": table}
            reg.gauge("pinot_controller_segments_total", labels).set(st["segments"])
            reg.gauge("pinot_controller_segments_online", labels).set(online)
            reg.gauge("pinot_controller_table_converged", labels).set(
                1 if st["converged"] else 0)
            out[table] = {"segments": st["segments"], "online": online}
        for table in self._status_tables - set(out):
            for g in ("pinot_controller_segments_total",
                      "pinot_controller_segments_online",
                      "pinot_controller_table_converged"):
                reg.remove_gauge(g, {"table": table})
        self._status_tables = set(out)
        return out

    # -- ingestion health (reference: /tables/{t}/ingestionStatus + the
    # RealtimeConsumerMonitor's per-partition lag aggregation) ---------------
    DEFAULT_OFFSET_LAG_THRESHOLD = 10_000.0

    def _cluster_config_float(self, key: str, default: Optional[float]
                              ) -> Optional[float]:
        v = self.catalog.get_property(f"clusterConfig/{key}")
        if v is None:
            return default
        try:
            return float(v)
        except (TypeError, ValueError):
            return default

    def _iter_ingestion_pollers(self):
        """(server_id, poll fn) for every reachable server: explicitly
        registered in-proc pollers first, then instances advertising an HTTP
        port (OS-process servers) — their /debug/consuming route."""
        seen = set()
        for sid, poll in list(self.ingestion_pollers.items()):
            seen.add(sid)
            yield sid, poll
        for info in list(self.catalog.instances.values()):
            if info.role != "server" or not info.port or not info.alive \
                    or info.instance_id in seen:
                continue

            def poll(url=info.url):
                from .http_service import get_json
                return get_json(f"{url}/debug/consuming", timeout=5.0,
                                retries=1).get("tables", {})
            yield info.instance_id, poll

    def ingestion_status(self, table: str) -> Dict[str, object]:
        """Per-table ingestion verdict: HEALTHY / DEGRADED / UNHEALTHY with
        reasons, aggregated live from every server's consuming rollup.
        Thresholds come from cluster config
        (`controller.ingestion.offset.lag.threshold`, default 10k messages;
        `controller.ingestion.freshness.lag.ms.threshold`, unset = freshness
        not judged — event-time clocks are the table's business)."""
        cfg = self.catalog.table_configs.get(table)
        if cfg is None:
            raise ValueError(f"unknown table {table!r}")
        if cfg.stream is None or not cfg.stream.topic:
            return {"table": table, "ingestionState": "HEALTHY", "reasons": [],
                    "paused": False, "numConsumingSegments": 0,
                    "maxOffsetLag": 0, "maxFreshnessLagMs": 0,
                    "totalRowsPerSecond": 0.0, "servers": {},
                    "unreachableServers": [],
                    "message": "offline table: batch ingestion only"}
        paused = bool(self.catalog.get_property(f"pause/{table}"))
        consuming = [m.name for m in self.catalog.segments.get(table, {}).values()
                     if m.status == STATUS_IN_PROGRESS]
        statuses: Dict[str, Dict[str, object]] = {}
        unreachable: List[str] = []
        for sid, poll in self._iter_ingestion_pollers():
            try:
                snap = poll()
            except Exception:
                unreachable.append(sid)
                continue
            st = snap.get(table)
            if st:
                statuses[sid] = st
        attached = {seg for st in statuses.values()
                    for seg in st.get("segments", {})}
        error_segs = sorted({seg for st in statuses.values()
                             for seg in st.get("errorSegments", [])})
        max_offset_lag = max((st.get("maxOffsetLag") or 0
                              for st in statuses.values()), default=0)
        max_fresh_lag = max((st.get("maxFreshnessLagMs") or 0
                             for st in statuses.values()), default=0)
        rows_per_s = round(sum(st.get("totalRowsPerSecond") or 0.0
                               for st in statuses.values()), 3)
        missing = sorted(set(consuming) - attached)

        reasons: List[str] = []
        verdict = "HEALTHY"

        def degrade(to: str, reason: str) -> None:
            nonlocal verdict
            reasons.append(reason)
            order = ("HEALTHY", "DEGRADED", "UNHEALTHY")
            if order.index(to) > order.index(verdict):
                verdict = to

        if error_segs:
            degrade("UNHEALTHY", f"consumers in ERROR state: {error_segs}")
        if missing and not paused:
            degrade("UNHEALTHY",
                    f"consuming segments with no attached consumer: {missing}")
        if consuming and not statuses:
            if unreachable:
                degrade("UNHEALTHY",
                        f"no server reported ingestion status "
                        f"(unreachable: {sorted(unreachable)})")
        elif unreachable:
            degrade("DEGRADED",
                    f"ingestion status poll failed for: {sorted(unreachable)}")
        if paused:
            degrade("DEGRADED", "consumption is paused")
        lag_thr = self._cluster_config_float(
            "controller.ingestion.offset.lag.threshold",
            self.DEFAULT_OFFSET_LAG_THRESHOLD)
        if lag_thr is not None and max_offset_lag > lag_thr:
            degrade("DEGRADED", f"offset lag {max_offset_lag} exceeds "
                                f"threshold {lag_thr:g}")
        fresh_thr = self._cluster_config_float(
            "controller.ingestion.freshness.lag.ms.threshold", None)
        if fresh_thr is not None and max_fresh_lag > fresh_thr:
            degrade("DEGRADED", f"freshness lag {max_fresh_lag}ms exceeds "
                                f"threshold {fresh_thr:g}ms")
        return {"table": table, "ingestionState": verdict, "reasons": reasons,
                "paused": paused, "numConsumingSegments": len(consuming),
                "maxOffsetLag": max_offset_lag,
                "maxFreshnessLagMs": max_fresh_lag,
                "totalRowsPerSecond": rows_per_s,
                "servers": statuses, "unreachableServers": sorted(unreachable)}

    _INGESTION_GAUGES = ("pinot_controller_ingestion_healthy",
                         "pinot_controller_ingestion_offset_lag",
                         "pinot_controller_ingestion_freshness_lag_ms")

    def run_ingestion_status_check(self) -> Dict[str, str]:
        """Periodic rollup: per-realtime-table verdict gauges, stale series
        removed on table drop (same hygiene as run_segment_status_check)."""
        from ..utils.metrics import get_registry
        reg = get_registry()
        out: Dict[str, Dict[str, object]] = {}
        for table, cfg in list(self.catalog.table_configs.items()):
            if cfg.stream is None or not cfg.stream.topic:
                continue
            st = self.ingestion_status(table)
            labels = {"table": table}
            reg.gauge(self._INGESTION_GAUGES[0], labels).set(
                1 if st["ingestionState"] == "HEALTHY" else 0)
            reg.gauge(self._INGESTION_GAUGES[1], labels).set(st["maxOffsetLag"])
            reg.gauge(self._INGESTION_GAUGES[2], labels).set(
                st["maxFreshnessLagMs"])
            out[table] = st
            self._note_verdict("ingestion", table, str(st["ingestionState"]),
                               list(st.get("reasons") or []))
        for table in self._ingestion_tables - set(out):
            for g in self._INGESTION_GAUGES:
                reg.remove_gauge(g, {"table": table})
        self._ingestion_tables = set(out)
        self._ingestion_status = out
        self._prune_verdicts("ingestion", set(out))
        return {t: str(s["ingestionState"]) for t, s in out.items()}

    # -- SLO layer (reference frame: the SRE-workbook multi-window,
    # multi-burn-rate alerting policy applied to per-table query rollups) ----

    _SLO_GAUGES = ("pinot_controller_slo_healthy",
                   "pinot_controller_slo_latency_burn_rate",
                   "pinot_controller_slo_error_burn_rate")
    #: fast-window burn at/above which the verdict is UNHEALTHY: the classic
    #: 1h/14.4x page threshold — burning a 30-day budget in ~2 days
    SLO_PAGE_BURN_RATE = 14.4
    #: a p99 latency target allows 1% of queries over the bound; the latency
    #: burn rate is measured against this violation budget
    SLO_LATENCY_BUDGET = 0.01

    def _iter_slo_pollers(self):
        """(broker_id, poll fn) for every reachable broker: explicitly
        registered in-proc pollers first, then instances advertising an HTTP
        port (OS-process brokers) — their /debug route."""
        seen = set()
        for bid, poll in list(self.slo_pollers.items()):
            seen.add(bid)
            yield bid, poll
        for info in list(self.catalog.instances.values()):
            if info.role != "broker" or not info.port or not info.alive \
                    or info.instance_id in seen:
                continue

            def poll(url=info.url):
                from .http_service import get_json
                return get_json(f"{url}/debug", timeout=5.0, retries=1)
            yield info.instance_id, poll

    def run_slo_check(self, now: Optional[float] = None) -> Dict[str, str]:
        """Periodic SLO evaluation: sample every broker's cumulative per-table
        counters, compute error/latency burn rates over a fast and a slow
        window, and publish a verdict per table (HEALTHY / DEGRADED /
        UNHEALTHY) plus `pinot_controller_slo_*` gauges with stale-series
        removal. `now` is injectable so tests drive synthetic timelines."""
        from collections import deque

        from ..utils.metrics import get_registry
        reg = get_registry()
        now = time.time() if now is None else float(now)
        lat_target = self._cluster_config_float("slo.latency.p99.ms", None)
        err_target = self._cluster_config_float("slo.error.rate", None)
        if err_target is not None and err_target <= 0:
            err_target = None
        if lat_target is None and err_target is None:
            # no SLO configured: tear the whole plane down
            for table in self._slo_tables:
                for g in self._SLO_GAUGES:
                    reg.remove_gauge(g, {"table": table})
            self._slo_tables = set()
            self._slo_status = {}
            self._slo_samples.clear()
            self._prune_verdicts("slo", set())
            return {}
        fast_s = self._cluster_config_float("slo.window.fast.s", 300.0)
        slow_s = self._cluster_config_float("slo.window.slow.s", 3600.0)

        # aggregate cumulative counters across brokers (counters only ever
        # grow, so summing per poll keeps windowed deltas meaningful)
        totals: Dict[str, Dict[str, float]] = {}
        unreachable: List[str] = []
        for bid, poll in self._iter_slo_pollers():
            try:
                snap = poll()
            except Exception:
                unreachable.append(bid)
                continue
            for table, roll in (snap.get("tableStats") or {}).items():
                agg = totals.setdefault(table, {"numQueries": 0.0,
                                                "numErrors": 0.0,
                                                "numOverSlo": 0.0})
                for k in agg:
                    v = roll.get(k)
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        agg[k] += float(v)

        out: Dict[str, Dict[str, object]] = {}
        for table, agg in totals.items():
            samples = self._slo_samples.setdefault(table, deque(maxlen=256))
            samples.append((now, dict(agg)))

            def window_delta(window_s):
                # delta vs the OLDEST sample inside the window (zero when the
                # window holds only the sample just taken — no judgement
                # before a second observation lands)
                cutoff = now - window_s
                for ts, base in samples:
                    if ts >= cutoff:
                        return {k: agg[k] - base[k] for k in agg}
                return {k: 0.0 for k in agg}

            def burns(delta):
                nq = delta["numQueries"]
                if nq <= 0:
                    return 0.0, 0.0   # zero traffic burns no budget
                eb = ((delta["numErrors"] / nq) / err_target
                      if err_target is not None else 0.0)
                lb = ((delta["numOverSlo"] / nq) / self.SLO_LATENCY_BUDGET
                      if lat_target is not None else 0.0)
                return round(eb, 3), round(lb, 3)

            eb_fast, lb_fast = burns(window_delta(fast_s))
            eb_slow, lb_slow = burns(window_delta(slow_s))

            verdict = "HEALTHY"
            reasons: List[str] = []

            def degrade(to: str, reason: str) -> None:
                nonlocal verdict
                reasons.append(reason)
                order = ("HEALTHY", "DEGRADED", "UNHEALTHY")
                if order.index(to) > order.index(verdict):
                    verdict = to

            for dim, bf, bs in (("error", eb_fast, eb_slow),
                                ("latency", lb_fast, lb_slow)):
                if bf >= self.SLO_PAGE_BURN_RATE:
                    degrade("UNHEALTHY", f"{dim} budget burning at {bf:g}x "
                                         f"over the fast window")
                elif bf > 1.0 and bs > 1.0:
                    degrade("DEGRADED", f"{dim} burn rate {bf:g}x fast / "
                                        f"{bs:g}x slow — budget exhausting")
            if unreachable:
                degrade("DEGRADED",
                        f"slo poll failed for brokers: {sorted(unreachable)}")

            labels = {"table": table}
            reg.gauge(self._SLO_GAUGES[0], labels).set(
                1 if verdict == "HEALTHY" else 0)
            reg.gauge(self._SLO_GAUGES[1], labels).set(max(lb_fast, lb_slow))
            reg.gauge(self._SLO_GAUGES[2], labels).set(max(eb_fast, eb_slow))
            out[table] = {
                "table": table, "sloState": verdict, "reasons": reasons,
                "latencyTargetMs": lat_target, "errorRateTarget": err_target,
                "burnRates": {"errorFast": eb_fast, "errorSlow": eb_slow,
                              "latencyFast": lb_fast, "latencySlow": lb_slow},
                "windowsS": {"fast": fast_s, "slow": slow_s},
                "totals": {k: round(v, 3) for k, v in agg.items()},
                "unreachableBrokers": sorted(unreachable),
            }
            self._note_verdict("slo", table, verdict, reasons)
        for table in self._slo_tables - set(out):
            for g in self._SLO_GAUGES:
                reg.remove_gauge(g, {"table": table})
            self._slo_samples.pop(table, None)
        self._slo_tables = set(out)
        self._slo_status = out
        self._prune_verdicts("slo", set(out))
        return {t: str(s["sloState"]) for t, s in out.items()}

    def slo_status(self, table: str) -> Dict[str, object]:
        """Per-table SLO verdict (the /tables/{t}/sloStatus body). Tables the
        check has not judged yet answer with an empty verdict; unknown tables
        raise (-> 404)."""
        st = self._slo_status.get(table)
        if st is None and table.endswith(("_OFFLINE", "_REALTIME")):
            # broker rollups key on the LOGICAL table name; accept nameWithType
            st = self._slo_status.get(table.rsplit("_", 1)[0])
        if st is not None:
            return st
        known = any(name == table or name.rsplit("_", 1)[0] == table
                    for name in self.catalog.table_configs)
        if not known:
            raise ValueError(f"unknown table {table!r}")
        lat = self._cluster_config_float("slo.latency.p99.ms", None)
        err = self._cluster_config_float("slo.error.rate", None)
        configured = lat is not None or (err is not None and err > 0)
        return {"table": table,
                "sloState": "HEALTHY" if configured else "UNCONFIGURED",
                "reasons": [], "latencyTargetMs": lat, "errorRateTarget": err,
                "burnRates": {}, "totals": {},
                "message": ("no query traffic observed yet" if configured else
                            "no SLO targets in cluster config")}

    # -- workload regression sentinel (per-shape SLO burn over plan
    # fingerprints: which query SHAPE regressed, not just which table) ------

    #: per-shape violation budget: a healthy shape is allowed this fraction
    #: of queries over `baselineMs * workload.baseline.multiplier`
    #: (override: `workload.sentinel.budget`; <= 0 disables the sentinel)
    WORKLOAD_SENTINEL_BUDGET = 0.01

    def _iter_workload_pollers(self):
        """(broker_id, poll fn) for every reachable broker's workload
        registry: in-proc pollers first, then advertised HTTP brokers via
        their GET /debug/workload route."""
        seen = set()
        for bid, poll in list(self.workload_pollers.items()):
            seen.add(bid)
            yield bid, poll
        for info in list(self.catalog.instances.values()):
            if info.role != "broker" or not info.port or not info.alive \
                    or info.instance_id in seen:
                continue

            def poll(url=info.url):
                from .http_service import get_json
                return get_json(f"{url}/debug/workload", timeout=5.0,
                                retries=1)
            yield info.instance_id, poll

    def run_workload_check(self, now: Optional[float] = None
                           ) -> Dict[str, str]:
        """Periodic per-shape regression evaluation: sample every broker's
        cumulative per-fingerprint `count` / `overBaseline` counters, burn
        them against the sentinel budget over the shared SLO fast/slow
        windows, and publish a verdict per fingerprint — DEGRADED/UNHEALTHY
        reasons NAME the offending fingerprint so the operator can drill into
        `/debug/workload?fp=`. `now` is injectable for synthetic timelines."""
        from collections import deque

        from ..utils.metrics import get_registry
        reg = get_registry()
        now = time.time() if now is None else float(now)
        budget = self._cluster_config_float(
            "workload.sentinel.budget", self.WORKLOAD_SENTINEL_BUDGET)
        if budget is None or budget <= 0:
            # sentinel disabled: tear the plane down
            reg.remove_gauge("pinot_controller_workload_regressing_shapes")
            self._workload_samples.clear()
            self._workload_status = {}
            self._prune_verdicts("workload", set())
            return {}
        fast_s = self._cluster_config_float("slo.window.fast.s", 300.0)
        slow_s = self._cluster_config_float("slo.window.slow.s", 3600.0)

        # aggregate cumulative per-shape counters across brokers
        totals: Dict[str, Dict[str, object]] = {}
        unreachable: List[str] = []
        for bid, poll in self._iter_workload_pollers():
            try:
                snap = poll()
            except Exception:
                unreachable.append(bid)
                continue
            for shape in (snap.get("shapes") or []):
                fp = shape.get("fingerprint")
                if not fp:
                    continue
                agg = totals.setdefault(fp, {
                    "count": 0.0, "overBaseline": 0.0, "totalTimeMs": 0.0,
                    "baselineMs": 0.0, "canonical": shape.get("canonical"),
                    "tables": shape.get("tables") or []})
                for k in ("count", "overBaseline", "totalTimeMs"):
                    v = shape.get(k)
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        agg[k] += float(v)
                agg["baselineMs"] = max(agg["baselineMs"],
                                        float(shape.get("baselineMs") or 0.0))

        prev = self._workload_status.get("regressions") or {}
        regressions: Dict[str, Dict[str, object]] = {}
        verdicts: Dict[str, str] = {}
        for fp, agg in totals.items():
            samples = self._workload_samples.setdefault(
                fp, deque(maxlen=256))
            samples.append((now, {"count": agg["count"],
                                  "overBaseline": agg["overBaseline"]}))

            def window_delta(window_s):
                # delta vs the OLDEST sample inside the window (zero when
                # only the sample just taken is inside — no judgement before
                # a second observation lands)
                cutoff = now - window_s
                for ts, base in samples:
                    if ts >= cutoff:
                        return {k: agg[k] - base[k] for k in base}
                return {"count": 0.0, "overBaseline": 0.0}

            def burn(delta):
                n = delta["count"]
                if n <= 0:
                    return 0.0   # zero traffic burns no budget
                return round((delta["overBaseline"] / n) / budget, 3)

            bf = burn(window_delta(fast_s))
            bs = burn(window_delta(slow_s))
            verdict = "HEALTHY"
            if bf >= self.SLO_PAGE_BURN_RATE:
                verdict = "UNHEALTHY"
            elif bf > 1.0 and bs > 1.0:
                verdict = "DEGRADED"
            verdicts[fp] = verdict
            if verdict == "HEALTHY":
                continue
            regressions[fp] = {
                "state": verdict,
                "reason": f"shape {fp} over-baseline burn {bf:g}x fast / "
                          f"{bs:g}x slow (baseline "
                          f"{agg['baselineMs']:g}ms)",
                "burnFast": bf, "burnSlow": bs,
                "count": agg["count"], "overBaseline": agg["overBaseline"],
                "baselineMs": agg["baselineMs"],
                "canonical": agg["canonical"], "tables": agg["tables"],
            }
            if fp not in prev:
                # HEALTHY -> regressing transition: one tick per regression
                reg.counter(
                    "pinot_broker_workload_shape_regressions").inc()

        # prune fingerprints no longer reported (evicted/restarted brokers)
        for fp in list(self._workload_samples):
            if fp not in totals:
                self._workload_samples.pop(fp)
        reg.gauge("pinot_controller_workload_regressing_shapes").set(
            len(regressions))
        state = "HEALTHY"
        if any(r["state"] == "UNHEALTHY" for r in regressions.values()):
            state = "UNHEALTHY"
        elif regressions:
            state = "DEGRADED"
        self._workload_status = {
            "state": state,
            "budget": budget,
            "windowsS": {"fast": fast_s, "slow": slow_s},
            "shapesTracked": len(totals),
            "reasons": sorted(r["reason"] for r in regressions.values()),
            "regressions": regressions,
            "unreachableBrokers": sorted(unreachable),
        }
        for fp, v in verdicts.items():
            self._note_verdict(
                "workload", fp, v,
                [regressions[fp]["reason"]] if fp in regressions else [])
        self._prune_verdicts("workload", set(verdicts))
        return verdicts

    def workload_status(self) -> Dict[str, object]:
        """The sentinel's last verdict (surfaced in controller /debug as
        `workloadStatus`); empty until the first check runs."""
        return dict(self._workload_status)

    # -- device-memory plane (the cluster view over per-server HBM ledgers) --

    _MEMORY_TABLE_GAUGES = ("pinot_controller_hbm_healthy",
                            "pinot_controller_hbm_resident_bytes")
    _MEMORY_INSTANCE_GAUGE = "pinot_controller_hbm_headroom_pct"
    #: minimum per-server HBM headroom before a table degrades; a server at or
    #: below a quarter of this (or fully out) is UNHEALTHY
    DEFAULT_MEMORY_HEADROOM_PCT = 20.0

    def _iter_memory_pollers(self):
        """(server_id, poll fn) for every reachable server: explicitly
        registered in-proc pollers first, then instances advertising an HTTP
        port (OS-process servers) — their /debug/memory route."""
        seen = set()
        for sid, poll in list(self.memory_pollers.items()):
            seen.add(sid)
            yield sid, poll
        for info in list(self.catalog.instances.values()):
            if info.role != "server" or not info.port or not info.alive \
                    or info.instance_id in seen:
                continue

            def poll(url=info.url):
                from .http_service import get_json
                return get_json(f"{url}/debug/memory", timeout=5.0, retries=1)
            yield info.instance_id, poll

    def run_memory_check(self) -> Dict[str, str]:
        """Periodic cluster memory rollup: poll every server's residency
        ledger, publish per-server headroom + per-table residency gauges, and
        verdict each table HEALTHY / DEGRADED / UNHEALTHY off the
        `controller.memory.headroom.pct` cluster-config threshold (breach ->
        DEGRADED; at/below a quarter of it, a server fully out of HBM, or no
        server reporting -> UNHEALTHY). Stale series are removed on table
        drop / server departure, same hygiene as the other checkers.

        Per-table bytes sum across servers; in-proc multi-server clusters
        share one process ledger, so there every server reports the same
        process view (the `servers` map makes that visible)."""
        from ..utils.metrics import get_registry
        reg = get_registry()
        thr = self._cluster_config_float("controller.memory.headroom.pct",
                                         self.DEFAULT_MEMORY_HEADROOM_PCT)
        snaps: Dict[str, Dict[str, object]] = {}
        unreachable: List[str] = []
        for sid, poll in self._iter_memory_pollers():
            try:
                snaps[sid] = dict(poll() or {})
            except Exception:
                unreachable.append(sid)

        for sid, snap in snaps.items():
            reg.gauge(self._MEMORY_INSTANCE_GAUGE, {"instance": sid}).set(
                float(snap.get("headroomPct") or 0.0))
        for sid in self._memory_instances - set(snaps):
            reg.remove_gauge(self._MEMORY_INSTANCE_GAUGE, {"instance": sid})
        self._memory_instances = set(snaps)

        breached = {sid: float(snap.get("headroomPct") or 0.0)
                    for sid, snap in snaps.items()
                    if thr is not None
                    and float(snap.get("headroomPct") or 0.0) < thr}
        severe = {sid: h for sid, h in breached.items()
                  if thr is not None and (h <= thr / 4.0 or h <= 0.0)}

        out: Dict[str, Dict[str, object]] = {}
        for table in list(self.catalog.table_configs):
            resident = 0
            per_server: Dict[str, int] = {}
            for sid, snap in snaps.items():
                n = int((snap.get("tables") or {}).get(table, 0) or 0)
                per_server[sid] = n
                resident += n
            verdict = "HEALTHY"
            reasons: List[str] = []

            def degrade(to: str, reason: str) -> None:
                nonlocal verdict
                reasons.append(reason)
                order = ("HEALTHY", "DEGRADED", "UNHEALTHY")
                if order.index(to) > order.index(verdict):
                    verdict = to

            if not snaps:
                degrade("UNHEALTHY",
                        "no server reported memory status"
                        + (f" (unreachable: {sorted(unreachable)})"
                           if unreachable else ""))
            elif unreachable:
                degrade("DEGRADED",
                        f"memory poll failed for: {sorted(unreachable)}")
            for sid, h in sorted(breached.items()):
                if sid in severe:
                    degrade("UNHEALTHY",
                            f"server {sid} HBM headroom {h:g}% critically "
                            f"below threshold {thr:g}%")
                else:
                    degrade("DEGRADED",
                            f"server {sid} HBM headroom {h:g}% below "
                            f"threshold {thr:g}%")

            # tiered-storage rollup: sum each server's lifecycle counters so
            # the verdict shows whether the cluster is riding the admission
            # gate (evictions/rejections climbing) or comfortably hot
            tiering: Dict[str, int] = {}
            for snap in snaps.values():
                t_snap = snap.get("tiering")
                if not isinstance(t_snap, dict):
                    continue
                for k in ("admissions", "rejections", "evictions",
                          "promotions", "coldLoads"):
                    tiering[k] = tiering.get(k, 0) + int(t_snap.get(k, 0) or 0)

            labels = {"table": table}
            reg.gauge(self._MEMORY_TABLE_GAUGES[0], labels).set(
                1 if verdict == "HEALTHY" else 0)
            reg.gauge(self._MEMORY_TABLE_GAUGES[1], labels).set(resident)
            out[table] = {
                "table": table, "memoryState": verdict, "reasons": reasons,
                "residentBytes": resident,
                "headroomThresholdPct": thr,
                "minServerHeadroomPct": min(
                    (float(s.get("headroomPct") or 0.0)
                     for s in snaps.values()), default=None),
                "servers": per_server,
                "unreachableServers": sorted(unreachable),
                "tiering": tiering,
            }
            self._note_verdict("memory", table, verdict, reasons)
        for table in self._memory_tables - set(out):
            for g in self._MEMORY_TABLE_GAUGES:
                reg.remove_gauge(g, {"table": table})
        self._memory_tables = set(out)
        self._memory_status = out
        self._prune_verdicts("memory", set(out))
        return {t: str(s["memoryState"]) for t, s in out.items()}

    def memory_status(self, table: str) -> Dict[str, object]:
        """Per-table memory verdict (the /tables/{t}/memoryStatus body).
        Tables the check has not judged yet answer UNKNOWN; unknown tables
        raise (-> 404)."""
        st = self._memory_status.get(table)
        if st is None and table.endswith(("_OFFLINE", "_REALTIME")):
            # verdicts key on the LOGICAL table name; accept nameWithType
            st = self._memory_status.get(table.rsplit("_", 1)[0])
        if st is not None:
            return st
        known = any(name == table or name.rsplit("_", 1)[0] == table
                    for name in self.catalog.table_configs)
        if not known:
            raise ValueError(f"unknown table {table!r}")
        return {"table": table, "memoryState": "UNKNOWN", "reasons": [],
                "residentBytes": 0, "servers": {},
                "message": "memory check has not run yet"}

    # -- verdict edge-triggering + event timeline + flight recorder ---------

    _VERDICT_KINDS = {"ingestion": "verdict.ingestion", "slo": "verdict.slo",
                      "memory": "verdict.memory",
                      "workload": "verdict.workload"}
    _VERDICT_SEVERITY = {"HEALTHY": "INFO", "DEGRADED": "WARN",
                         "UNHEALTHY": "ERROR"}
    VERDICT_LOGGER = "pinot_tpu.verdicts"

    def _note_verdict(self, plane: str, key: str, status: str,
                      reasons: List[str]) -> None:
        """Edge-trigger one verdict plane's (table-or-shape, status): a no-op
        while the status is unchanged, so repeated DEGRADED ticks emit
        exactly one transition event and one log line. A change counts one
        `pinot_controller_verdict_transitions{kind}` tick; a transition to
        UNHEALTHY (DEGRADED too when `controller.incident.on.degraded` is
        set) trips the flight recorder."""
        prev_map = self._verdict_prev[plane]
        prev = prev_map.get(key, "HEALTHY")
        if status == prev:
            return
        prev_map[key] = status
        from ..utils.events import emit as emit_event
        from ..utils.metrics import get_registry
        get_registry().counter("pinot_controller_verdict_transitions",
                               {"kind": plane}).inc()
        logging.getLogger(self.VERDICT_LOGGER).warning(
            "%s verdict for %s: %s -> %s%s", plane, key, prev, status,
            f" ({'; '.join(map(str, reasons[:3]))})" if reasons else "")
        attrs = {"fromState": prev, "toState": status,
                 "reasons": [str(r) for r in reasons[:3]]}
        if plane == "workload":
            attrs["fingerprint"] = key
            emit_event(self._VERDICT_KINDS[plane], node=self.instance_id,
                       severity=self._VERDICT_SEVERITY.get(status, "WARN"),
                       **attrs)
        else:
            emit_event(self._VERDICT_KINDS[plane], node=self.instance_id,
                       table=key,
                       severity=self._VERDICT_SEVERITY.get(status, "WARN"),
                       **attrs)
        on_degraded = str(self.catalog.get_property(
            "clusterConfig/controller.incident.on.degraded",
            "false")).lower() == "true"
        if status == "UNHEALTHY" or (status == "DEGRADED" and on_degraded):
            self._capture_incident(plane, key, status, reasons)

    def _prune_verdicts(self, plane: str, live_keys) -> None:
        """Drop edge-trigger memory for tables/shapes the plane no longer
        judges (table drop, evicted fingerprint) — the map stays bounded by
        the plane's live key set."""
        prev_map = self._verdict_prev[plane]
        for k in list(prev_map):
            if k not in live_keys:
                prev_map.pop(k)

    def _iter_event_pollers(self):
        """(node id, poll fn taking the since-cursor) for every journal
        source: explicitly registered in-proc pollers first, then instances
        advertising an HTTP port — their GET /debug/events?since= route
        (the memory-checker discovery pattern)."""
        seen = set()
        for nid, poll in list(self.event_pollers.items()):
            seen.add(nid)
            yield nid, poll
        for info in list(self.catalog.instances.values()):
            if info.role not in ("server", "broker") or not info.port \
                    or not info.alive or info.instance_id in seen:
                continue

            def poll(since, url=info.url):
                from .http_service import get_json
                return get_json(f"{url}/debug/events?since={int(since)}",
                                timeout=5.0, retries=1)
            yield info.instance_id, poll

    def run_event_check(self) -> int:
        """Periodic timeline merge: pull every journal source's NEW events
        (cursor-incremental, so a poll ships only what arrived since the
        last tick) into the bounded merged timeline. The controller's own
        process journal is always a source — in-proc clusters share it
        across roles, so it alone carries the whole timeline there. Returns
        the number of events merged this tick."""
        from ..utils.events import get_journal
        cap = max(1, int(self._cluster_config_float(
            "controller.events.ring.size", 1024) or 1024))
        local = get_journal()
        sources = [("local",
                    lambda since: local.events_since(since))]
        sources.extend(self._iter_event_pollers())
        merged = 0
        unreachable: List[str] = []
        seen_ids = set()
        for nid, poll in sources:
            seen_ids.add(nid)
            with self._events_lock:
                since = self._event_cursors.get(nid, 0)
            try:
                payload = poll(since) or {}
            except Exception:
                unreachable.append(nid)   # cursor unchanged; next tick re-pulls
                continue
            rows = payload.get("events") or []
            cursor = payload.get("cursor")
            with self._events_lock:
                for ev in rows:
                    if isinstance(ev, dict):
                        self._timeline.append(dict(ev))
                        merged += 1
                if isinstance(cursor, (int, float)):
                    self._event_cursors[nid] = int(cursor)
                while len(self._timeline) > cap:
                    self._timeline.popleft()
        with self._events_lock:
            # cursors of departed sources are dropped with the source
            for nid in list(self._event_cursors):
                if nid not in seen_ids:
                    self._event_cursors.pop(nid)
            self._events_unreachable = sorted(unreachable)
        return merged

    def timeline(self, kind: Optional[str] = None, table: Optional[str] = None,
                 severity: Optional[str] = None, since: Optional[float] = None,
                 limit: Optional[int] = None) -> List[Dict[str, object]]:
        """The merged cluster timeline in causal order — sorted on
        (tsMs, node, seq), the deterministic tiebreak — with the
        /debug/timeline filters: exact kind/table match, `severity` admitting
        its level and everything worse, `since` an epoch-ms lower bound, and
        `limit` keeping the newest N after filtering."""
        from ..utils.events import SEVERITIES
        with self._events_lock:
            rows = list(self._timeline)
        rows.sort(key=lambda e: (e.get("tsMs", 0), str(e.get("node", "")),
                                 e.get("seq", 0)))
        if kind:
            rows = [e for e in rows if e.get("kind") == kind]
        if table:
            rows = [e for e in rows if e.get("table") == table]
        if severity and severity in SEVERITIES:
            floor = SEVERITIES.index(severity)
            rows = [e for e in rows
                    if e.get("severity") in SEVERITIES
                    and SEVERITIES.index(e["severity"]) >= floor]
        if since is not None:
            rows = [e for e in rows if e.get("tsMs", 0) >= float(since)]
        if limit is not None:
            rows = rows[-max(0, int(limit)):]
        return rows

    def _iter_incident_pollers(self):
        """(node id, poll fn) for incident snapshot sources: registered
        in-proc pollers (Broker.debug_stats) first, then HTTP brokers via
        their GET /debug route."""
        seen = set()
        for nid, poll in list(self.incident_pollers.items()):
            seen.add(nid)
            yield nid, poll
        for info in list(self.catalog.instances.values()):
            if info.role != "broker" or not info.port or not info.alive \
                    or info.instance_id in seen:
                continue

            def poll(url=info.url):
                from .http_service import get_json
                return get_json(f"{url}/debug", timeout=5.0, retries=1)
            yield info.instance_id, poll

    def _capture_incident(self, plane: str, key: str, status: str,
                          reasons: List[str]) -> Dict[str, object]:
        """Flight recorder: freeze one incident bundle — the freshest N
        timeline events, the controller's verdict-plane snapshots, every
        incident poller's /debug payload (admission, detector, workload,
        recent slow queries), and the slow-query trace ids those payloads
        carry — into the bounded incident ring. Called on verdict edges
        only, so one UNHEALTHY episode captures exactly one bundle."""
        from ..utils.events import emit as emit_event
        from ..utils.metrics import get_registry
        n_events = max(1, int(self._cluster_config_float(
            "controller.incident.events", 100) or 100))
        ring_cap = max(1, int(self._cluster_config_float(
            "controller.incident.ring.size", 8) or 8))
        # pull journals NOW: the bundle must include the very transitions
        # that tripped the verdict, not wait out the collector's cadence
        self.run_event_check()
        nodes: Dict[str, object] = {}
        slow_trace_ids: List[str] = []
        for nid, poll in self._iter_incident_pollers():
            try:
                snap = poll()
            except Exception:
                nodes[nid] = {"unreachable": True}
                continue
            nodes[nid] = snap
            if isinstance(snap, dict):
                for q in snap.get("recentSlowQueries") or []:
                    tid = (q.get("stats") or {}).get("traceId") \
                        if isinstance(q, dict) else None
                    if tid and tid not in slow_trace_ids:
                        slow_trace_ids.append(tid)
        snapshots = {
            "ingestionStatus": {t: {k: v for k, v in s.items()
                                    if k != "servers"}
                                for t, s in self._ingestion_status.items()},
            "sloStatus": dict(self._slo_status),
            "memoryStatus": dict(self._memory_status),
            "workloadStatus": dict(self._workload_status),
            "nodes": nodes,
        }
        with self._events_lock:
            events = list(self._timeline)[-n_events:]
            self._incident_seq += 1
            bundle = {
                "id": self._incident_seq,
                "tsMs": int(time.time() * 1000),
                "plane": plane,
                "key": key,
                "status": status,
                "reasons": [str(r) for r in reasons],
                "events": events,
                "snapshots": snapshots,
                "slowTraceIds": slow_trace_ids,
            }
            self._incidents.append(bundle)
            while len(self._incidents) > ring_cap:
                self._incidents.popleft()
        get_registry().counter("pinot_controller_incidents_captured").inc()
        emit_event("incident.captured", node=self.instance_id,
                   plane=plane, key=key, status=status)
        return bundle

    def incidents(self, limit: Optional[int] = None
                  ) -> List[Dict[str, object]]:
        """Newest-first retained incident bundles (the /debug/incidents
        body)."""
        with self._events_lock:
            rows = list(self._incidents)
        rows.reverse()
        return rows[:limit] if limit is not None else rows

    def debug_stats(self) -> Dict[str, object]:
        """Controller /debug rollup: periodic task health (a silently-failing
        task is a climbing errorCount + stale lastRunMs), the last ingestion
        and SLO verdicts, and the controller-scoped metric snapshot + gauge
        rings."""
        from ..utils.metrics import get_registry
        reg = get_registry()
        return {
            "instance": self.instance_id,
            "periodicTasks": self.scheduler.stats(),
            "ingestionStatus": {t: {k: v for k, v in s.items()
                                    if k != "servers"}
                                for t, s in self._ingestion_status.items()},
            "sloStatus": dict(self._slo_status),
            "memoryStatus": dict(self._memory_status),
            "workloadStatus": dict(self._workload_status),
            "controllerMetrics": {k: v for k, v in reg.snapshot().items()
                                  if k.startswith(("pinot_controller",
                                                   "pinot_periodic"))},
            "gaugeHistories": reg.gauge_histories("pinot_controller"),
            "events": self.events_summary(),
        }

    def events_summary(self) -> Dict[str, object]:
        """Light timeline rollup for /debug (the full data lives behind the
        /debug/timeline and /debug/incidents routes)."""
        with self._events_lock:
            return {
                "timelineEvents": len(self._timeline),
                "cursors": dict(self._event_cursors),
                "unreachable": list(self._events_unreachable),
                "incidents": len(self._incidents),
                "incidentsCaptured": self._incident_seq,
            }

    def cleanup_dead_minions(self) -> List[str]:
        """Reference: MinionInstancesCleanupTask — drop dead minion instances
        from the catalog so they stop counting toward capacity. Liveness is
        re-checked under the catalog lock: a minion that came back between the
        scan and the removal must survive."""
        dead = [iid for iid, info in list(self.catalog.instances.items())
                if info.role == "minion" and not info.alive]
        return [iid for iid in dead if self.catalog.remove_instance(
            iid, only_if=lambda i: i.role == "minion" and not i.alive)]

    def emit_task_metrics(self) -> Dict[str, int]:
        """Reference: TaskMetricsEmitter — minion task queue depth by state.
        Every known state is written each tick (including zeros), so a drained
        queue doesn't leave a stale nonzero gauge alerting forever."""
        from ..minion.tasks import COMPLETED, ERROR, GENERATED, RUNNING
        from ..utils.metrics import get_registry
        reg = get_registry()
        counts: Dict[str, int] = {}
        for t in self.task_manager.queue.tasks():
            counts[t.state] = counts.get(t.state, 0) + 1
        for state in (GENERATED, RUNNING, COMPLETED, ERROR):
            reg.gauge("pinot_controller_minion_tasks", {"state": state}).set(
                counts.get(state, 0))
        return counts

    def set_table_state(self, table: str, enabled: bool) -> None:
        """Reference: ChangeTableState / table enable-disable REST op — a
        disabled table keeps its segments loaded but brokers refuse queries
        until it is re-enabled."""
        if table not in self.catalog.table_configs:
            raise ValueError(f"unknown table {table!r}")
        self.catalog.put_property(f"tableState/{table}",
                                  None if enabled else "disabled")

    # -- tenants (reference: PinotTenantRestletResource + tag-based instance
    # assignment: a tenant IS a tag on server instances) --------------------
    def update_instance_tags(self, instance_id: str, tags: List[str]) -> None:
        """Re-tag an instance (reference: updateInstanceTags). Tables assigned
        to a tenant tag pick up the change on the next assignment/rebalance/
        relocation — existing ideal state is not rewritten here."""
        self.catalog.update_instance_tags(instance_id, tags)

    def list_tenants(self) -> Dict[str, List[str]]:
        """tenant tag -> live server instances carrying it."""
        out: Dict[str, List[str]] = {}
        with self.catalog._lock:
            for info in self.catalog.instances.values():
                if info.role != "server" or not info.alive:
                    continue
                for tag in info.tags:
                    out.setdefault(tag, []).append(info.instance_id)
        return {t: sorted(v) for t, v in sorted(out.items())}

    def pause_consumption(self, table: str) -> Dict[str, object]:
        """Reference: PinotRealtimeTableResource.pauseConsumption."""
        return self.llc.pause_consumption(table)

    def resume_consumption(self, table: str) -> Dict[str, object]:
        return self.llc.resume_consumption(table)

    def _tier_pool(self, cfg: TableConfig, meta: SegmentMeta,
                   now_ms: int):
        """(tier_name, pool_tag) a segment belongs on: the matching TierConfig
        with the LARGEST age threshold wins (oldest tier first); age is
        measured from the segment's data end-time, falling back to push time
        for time-column-less tables. Consuming segments (no push time yet)
        and un-aged segments stay on the tenant pool."""
        basis = meta.end_time_ms if meta.end_time_ms is not None \
            else meta.push_time_ms
        if cfg.tiers and basis:
            age_days = (now_ms - basis) / 86_400_000.0
            for t in sorted(cfg.tiers, key=lambda t: -t.segment_age_days):
                if age_days >= t.segment_age_days:
                    return t.name, t.server_tag
        return None, cfg.tenant

    def run_segment_relocation(self, now_ms: Optional[int] = None) -> List[str]:
        """Reference: SegmentRelocator periodic task — move segments whose age
        crossed a tier threshold onto that tier's tagged server pool.

        Moves converge through the same add-first/drop-when-live loop as
        rebalance (never below one online replica), so queries keep working
        mid-move: the tier server downloads from the deep store and reports
        ONLINE before the old replica is dropped. Partitioned tables keep
        their replica-group placement inside the new pool."""
        now_ms = now_ms or int(time.time() * 1000)
        moved: List[str] = []
        for table, cfg in list(self.catalog.table_configs.items()):
            if not cfg.tiers:
                continue
            target: Dict[str, Dict[str, str]] = {}
            ist = self.catalog.ideal_state.get(table, {})
            # per-pool load counts, computed once and incremented as segments
            # are placed — otherwise every segment in one pass picks the same
            # least-loaded server and dogpiles it
            pool_counts: Dict[str, Dict[str, int]] = {}
            for seg, meta in list(self.catalog.segments.get(table, {}).items()):
                if meta.status == STATUS_IN_PROGRESS:
                    continue  # consuming segments are not relocatable — they
                    # have no deep-store copy; the completed successor will be
                    # placed by tier on a later pass (reference: SegmentRelocator
                    # only moves completed segments)
                tier_name, pool_tag = self._tier_pool(cfg, meta, now_ms)
                pool = self.catalog.live_servers(pool_tag)
                if not pool:  # never strand a segment on an empty tier pool
                    continue
                current = set(ist.get(seg, {}))
                if current and current <= set(pool):
                    continue  # already fully inside the desired pool
                counts = pool_counts.get(pool_tag)
                if counts is None:
                    counts = pool_counts[pool_tag] = compute_counts({
                        s: a for s, a in ist.items() if set(a) <= set(pool)})
                if cfg.partition and meta.partition_id is not None:
                    chosen = replica_group_assign(seg, pool, cfg.replication,
                                                  meta.partition_id, counts)
                else:
                    chosen = balanced_assign(seg, pool, cfg.replication, counts)
                for s in chosen:
                    counts[s] = counts.get(s, 0) + 1
                target[seg] = {s: ONLINE for s in chosen}
                moved.append(f"{table}/{seg}->{tier_name or cfg.tenant}")
            if target:
                self._converge_ideal_state(table, target, cfg.replication)
        return moved

    # -- rebalance (reference: TableRebalancer.java:114,277) ---------------------
    def rebalance(self, table: str, min_available_replicas: int = 1) -> Dict[str, Dict[str, str]]:
        """Compute a balanced target and converge incrementally, never dropping a
        segment below `min_available_replicas` currently-online copies."""
        cfg = self.catalog.table_configs[table]
        current = {s: dict(a) for s, a in self.catalog.ideal_state.get(table, {}).items()}

        # tier-aware: rebalance each storage pool separately, so tiered
        # segments stay on their tier servers instead of being pulled back
        # onto the tenant pool (and ping-ponging with the SegmentRelocator)
        now_ms = int(time.time() * 1000)
        metas = self.catalog.segments.get(table, {})
        by_pool: Dict[str, Dict[str, Dict[str, str]]] = {}
        for seg, assignment in current.items():
            meta = metas.get(seg)
            pool_tag = cfg.tenant if meta is None \
                else self._tier_pool(cfg, meta, now_ms)[1]
            by_pool.setdefault(pool_tag, {})[seg] = assignment
        target: Dict[str, Dict[str, str]] = {}
        for pool_tag, segs in by_pool.items():
            pool = self.catalog.live_servers(pool_tag)
            if not pool:  # empty pool: leave those segments untouched
                target.update(segs)
                continue
            target.update(rebalance_table(segs, pool, cfg.replication))
        return self._converge_ideal_state(table, target, cfg.replication,
                                          min_available_replicas)

    def _converge_ideal_state(self, table: str, target: Dict[str, Dict[str, str]],
                              replication: int, min_available_replicas: int = 1
                              ) -> Dict[str, Dict[str, str]]:
        """Incrementally walk ideal state toward `target`, adding a replica
        before dropping one and never dropping below `min_available_replicas`
        currently-online target copies (reference: TableRebalancer.java:277-298).
        Segments absent from `target` are left untouched."""
        current = {s: dict(a) for s, a in
                   self.catalog.ideal_state.get(table, {}).items()
                   if s in target}
        max_rounds = len(target) * (replication + 1) + 4
        for _ in range(max_rounds):
            if current == target:
                break
            updates = {}
            for seg, want in target.items():
                have = current.get(seg, {})
                if have == want:
                    continue
                ev = self.catalog.external_view.get(table, {}).get(seg, {})
                online_now = [s for s, st in ev.items() if st == ONLINE]
                step = dict(have)
                added = False
                for s in want:
                    if s not in step:
                        step[s] = ONLINE  # add first ...
                        added = True
                        break
                if not added:
                    removable = [s for s in step if s not in want]
                    for s in removable:
                        # ... drop only when enough target replicas are live
                        live_targets = [t for t in online_now if t in want]
                        if len(live_targets) >= min_available_replicas:
                            step.pop(s)
                            break
                if step != have:
                    updates[seg] = step
                    current[seg] = step
            if updates:
                self.catalog.update_ideal_state(table, updates)
            else:
                break
        return current

    # -- status (reference: SegmentStatusChecker) --------------------------------
    def table_status(self, table: str) -> Dict[str, object]:
        ist = self.catalog.ideal_state.get(table, {})
        ev = self.catalog.external_view.get(table, {})
        converged = all(ev.get(seg, {}) == assignment for seg, assignment in ist.items())
        return {
            "segments": len(ist),
            "converged": converged,
            "replicas_online": {seg: sum(1 for st in ev.get(seg, {}).values()
                                         if st == ONLINE) for seg in ist},
        }
