"""Remote proxies: HTTP clients that let server/broker processes join a cluster.

The reference keeps all cluster state in ZooKeeper and every role watches it via Helix;
here the controller process is the authoritative metadata owner (catalog.py) and
remote roles mirror it through `RemoteCatalog` — a version-stamped snapshot poll with
long-poll watches (the ZK-watch analog). Mutations initiated by remote roles
(instance registration, external-view reports) are POSTed to the controller, then
reflected locally on the next snapshot.

Also here: `RemoteCompletion` (the server's HTTP client for the segment completion
protocol — reference: `ServerSegmentCompletionProtocolHandler` POSTing to
`LLCSegmentCompletionHandlers`), `RemoteServerHandle` (the broker's query dispatch to
a server over HTTP — reference: `QueryRouter.submitQuery` over Netty), and
`ControllerDeepStore` (segment fetch by URL through the controller — reference:
`SegmentFetcherFactory` http scheme).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import urllib.parse
from typing import Dict, Optional, Sequence

from ..schema import Schema
from ..table import TableConfig
from ..utils.faults import fault_point
from .catalog import Catalog, InstanceInfo, SegmentMeta
from .deepstore import DeepStoreFS, tar_segment, untar_segment
from .http_service import HttpError, get_json, http_call, post_json
from .wire import decode_segment_result, encode_query_request


class RemoteCatalog(Catalog):
    """Catalog mirror for a remote role process.

    Reads are served from the local mirror (refreshed by a watch thread); the
    mutations a remote role performs are forwarded to the controller. Watch events
    fire exactly like the in-proc catalog's, driven by snapshot diffs.
    """

    def __init__(self, controller_url: str, poll_timeout_s: float = 10.0):
        super().__init__()
        self.controller_url = controller_url.rstrip("/")
        self._version = -1
        self._poll_timeout_s = poll_timeout_s
        self._stop = threading.Event()
        self._refresh()  # initial sync before any subscriber exists
        self._thread = threading.Thread(target=self._watch_loop,
                                        name="catalog-watch", daemon=True)
        self._thread.start()

    # -- remote-forwarded mutations ----------------------------------------
    def register_instance(self, info: InstanceInfo) -> None:
        post_json(f"{self.controller_url}/catalog/instances", info.to_json(),
                  retries=2)
        super().register_instance(info)

    def report_state(self, table: str, segment: str, server: str, state) -> None:
        post_json(f"{self.controller_url}/catalog/externalView",
                  {"table": table, "segment": segment, "server": server,
                   "state": state}, retries=2)
        super().report_state(table, segment, server, state)

    def set_instance_alive(self, instance_id: str, alive: bool) -> None:
        post_json(f"{self.controller_url}/catalog/instances",
                  {"instance_id": instance_id, "alive": alive}, retries=2)
        super().set_instance_alive(instance_id, alive)

    def put_property(self, key: str, value) -> None:
        post_json(f"{self.controller_url}/catalog/property",
                  {"key": key, "value": value}, retries=2)
        super().put_property(key, value)

    def mutate_property(self, key: str, fn):
        # A remote read-modify-write needs a controller-side CAS endpoint; silently
        # mutating only the mirror would be clobbered by the next snapshot poll
        # (e.g. two minions double-claiming a task). Fail loudly until that exists.
        raise NotImplementedError(
            "mutate_property is not supported on RemoteCatalog; run task claiming "
            "(TaskQueue) against the controller's in-proc catalog")

    # -- watch loop ----------------------------------------------------------
    def close(self) -> None:
        self._stop.set()
        # best-effort reap: an idle watcher exits immediately; one blocked in
        # the long poll is a daemon and dies at its poll boundary — teardown
        # must not wait out an in-flight controller hold
        self._thread.join(timeout=1.0)

    def _watch_loop(self) -> None:
        while not self._stop.is_set():
            try:
                resp = get_json(f"{self.controller_url}/catalog/watch"
                                f"?since={self._version}"
                                f"&timeoutSec={self._poll_timeout_s}",
                                timeout=self._poll_timeout_s + 10)
                if resp.get("version", -1) != self._version:
                    self._refresh()
            except (ConnectionError, HttpError):
                if self._stop.wait(0.5):
                    return
            except Exception as e:
                # a subscriber callback blowing up (transient consumer-create
                # failure, reconcile error) must NOT kill the watch thread —
                # that would permanently blind this node to catalog changes
                import sys
                print(f"[pinot-tpu] catalog watch error: {type(e).__name__}: {e}",
                      file=sys.stderr)
                if self._stop.wait(0.5):
                    return

    def _refresh(self) -> None:
        snap = get_json(f"{self.controller_url}/catalog/snapshot", retries=2)
        with self._lock:
            old_ideal = self.ideal_state
            old_ev = self.external_view
            # content-sensitive: a config VALUE change (quota, indexing) must fire
            # a 'table' event too, not just key add/remove
            old_tables = {k: json.dumps(v.to_json(), sort_keys=True)
                          for k, v in self.table_configs.items()}
            old_instances = {k: (v.alive, v.port) for k, v in self.instances.items()}
            old_properties = dict(self.properties)

            self.schemas = {k: Schema.from_json(v)
                            for k, v in snap["schemas"].items()}
            self.table_configs = {k: TableConfig.from_json(v)
                                  for k, v in snap["tableConfigs"].items()}
            self.segments = {t: {s: SegmentMeta.from_json(m)
                                 for s, m in segs.items()}
                             for t, segs in snap["segments"].items()}
            self.ideal_state = snap["idealState"]
            self.external_view = snap["externalView"]
            self.instances = {k: InstanceInfo.from_json(v)
                              for k, v in snap["instances"].items()}
            self.properties = snap.get("properties", {})
            self._version = snap["version"]

            ideal_changed = [t for t in set(old_ideal) | set(self.ideal_state)
                             if old_ideal.get(t) != self.ideal_state.get(t)]
            ev_changed = [t for t in set(old_ev) | set(self.external_view)
                          if old_ev.get(t) != self.external_view.get(t)]
            new_tables = {k: json.dumps(v.to_json(), sort_keys=True)
                          for k, v in self.table_configs.items()}
            table_changed = [k for k in set(old_tables) | set(new_tables)
                             if old_tables.get(k) != new_tables.get(k)]
            inst_changed = [
                k for k, v in self.instances.items()
                if old_instances.get(k) != (v.alive, v.port)
            ] + [k for k in old_instances if k not in self.instances]
            prop_changed = [k for k in set(old_properties) | set(self.properties)
                            if old_properties.get(k) != self.properties.get(k)]

        for t in table_changed:
            self._notify("table", t)
        for t in ideal_changed:
            self._notify("ideal_state", t)
        for t in ev_changed:
            self._notify("external_view", t)
        for i in inst_changed:
            self._notify("instance", i)
        for k in prop_changed:
            self._notify("property", k)


class RemoteTaskQueue:
    """Minion-side task claim/finish against the controller's atomic queue
    (reference: Helix task framework claims; `POST /tasks/claim` runs under the
    controller catalog's lock, so N minions never double-claim)."""

    def __init__(self, controller_url: str):
        self.controller_url = controller_url.rstrip("/")

    def claim(self, worker_id: str, task_types):
        from ..minion.tasks import TaskSpec
        resp = post_json(f"{self.controller_url}/tasks/claim",
                         {"worker": worker_id, "taskTypes": list(task_types)})
        return TaskSpec.from_json(resp["task"]) if resp.get("task") else None

    def finish(self, task_id: str, error: str = "",
               worker_id: Optional[str] = None) -> bool:
        resp = post_json(f"{self.controller_url}/tasks/finish",
                         {"taskId": task_id, "error": error,
                          "worker": worker_id}, retries=2)
        return bool(resp.get("applied"))


class RemoteController:
    """The controller API surface a remote MinionWorker needs — upload,
    atomic replace (staged through the deep-store proxy), delete — over REST
    (reference: minion executors talking to the controller's segment upload /
    startReplaceSegments / endReplaceSegments resources)."""

    def __init__(self, controller_url: str, token: Optional[str] = None):
        self.controller_url = controller_url.rstrip("/")
        self.token = token

    def _tar_bytes(self, segment_dir: str) -> tuple:
        name = os.path.basename(segment_dir.rstrip("/"))
        with tempfile.TemporaryDirectory() as tmp:
            tar_path = os.path.join(tmp, f"{name}.tar.gz")
            tar_segment(segment_dir, tar_path)
            with open(tar_path, "rb") as f:
                return name, f.read()

    def upload_segment(self, table: str, segment_dir: str,
                       custom: Optional[Dict[str, str]] = None) -> None:
        name, payload = self._tar_bytes(segment_dir)
        q = urllib.parse.urlencode(
            {"name": name, **({"custom": json.dumps(custom)} if custom else {})})
        http_call("POST", f"{self.controller_url}/segments/{table}?{q}", payload,
                  content_type="application/octet-stream", timeout=120.0,
                  token=self.token)

    def replace_segments(self, table: str, old_names, new_segment_dirs,
                         custom: Optional[Dict[str, str]] = None) -> None:
        import uuid as _uuid
        staged = []
        for d in new_segment_dirs:
            name, payload = self._tar_bytes(d)
            uri = f"staging/{_uuid.uuid4().hex[:12]}/{name}.tar.gz"
            http_call("POST", f"{self.controller_url}/deepstore/{uri}", payload,
                      content_type="application/octet-stream", timeout=120.0,
                      token=self.token)
            staged.append(uri)
        post_json(f"{self.controller_url}/replaceSegments/{table}",
                  {"from": list(old_names), "stagedTars": staged,
                   "custom": custom}, timeout=120.0, token=self.token)

    def delete_segment(self, table: str, segment: str, *,
                       permanent: bool = False) -> None:
        q = "?permanent=true" if permanent else ""
        http_call("DELETE", f"{self.controller_url}/segments/{table}/{segment}{q}",
                  token=self.token)


class RemoteCompletion:
    """Server-side HTTP client for the segment completion protocol (reference:
    `ServerSegmentCompletionProtocolHandler` — segmentConsumed / segmentCommitStart /
    segmentCommit with file upload, against `LLCSegmentCompletionHandlers`)."""

    def __init__(self, controller_url: str):
        self.controller_url = controller_url.rstrip("/")

    def segment_consumed(self, segment: str, server: str, offset: int):
        return post_json(f"{self.controller_url}/segmentConsumed",
                         {"segment": segment, "server": server, "offset": offset},
                         retries=2)

    def segment_commit_start(self, segment: str, server: str) -> str:
        return post_json(f"{self.controller_url}/segmentCommitStart",
                         {"segment": segment, "server": server}, retries=2)["status"]

    def segment_commit_end(self, segment: str, server: str, segment_dir: str,
                           end_offset: int) -> str:
        """Tar the locally built segment and upload it with the commit-end call
        (reference: commitSegment = segmentCommitEndWithMetadata + file upload)."""
        with tempfile.TemporaryDirectory() as tmp:
            tar_path = os.path.join(tmp, f"{segment}.tar.gz")
            tar_segment(segment_dir, tar_path)
            with open(tar_path, "rb") as f:
                payload = f.read()
        q = urllib.parse.urlencode({"segment": segment, "server": server,
                                    "offset": end_offset})
        resp = http_call("POST", f"{self.controller_url}/segmentCommitEnd?{q}",
                         payload, content_type="application/octet-stream",
                         timeout=120.0)
        return json.loads(resp.decode())["status"]


class RemoteServerHandle:
    """Broker -> server query dispatch over HTTP; matches the in-proc
    `ServerHandle` signature (reference: QueryRouter.submitQuery + DataTable
    deserialize on response).

    Two transports: `submit_async` multiplexes tagged queries over the mux
    stream (`cluster/mux.py`) and returns a Future WITHOUT holding a thread
    for the round trip — the broker's scatter prefers it; `__call__` blocks
    (riding the mux future when available, else the legacy one-exchange-per-
    query POST /query). `use_mux=False` pins the legacy transport (the
    differential tests dispatch both ways and compare)."""

    def __init__(self, server_url: str, timeout_s: float = 60.0,
                 token: Optional[str] = None, use_mux: bool = True,
                 mux_streams: int = 1):
        self.server_url = server_url.rstrip("/")
        self.timeout_s = timeout_s
        # explicit per-handle token (external connector processes have no
        # process-global default token); None falls back to the default
        self.token = token
        self.use_mux = use_mux
        self._mux_streams = max(1, int(mux_streams))
        self._mux = None               # lazily opened MuxClient
        self._mux_unsupported = False  # old peer without /mux: legacy forever
        self._mux_down_until = 0.0     # transient legacy window after backoff
        self._mux_lock = threading.Lock()

    #: how long dispatch rides the legacy transport after the mux client
    #: exhausts its reconnect backoff; afterwards mux is retried (the peer may
    #: have restarted) rather than being pinned to legacy forever.
    MUX_COOLDOWN_S = 1.0

    def _mux_client(self):
        from .mux import MuxClient
        with self._mux_lock:
            if self._mux is None:
                from .http_service import _DEFAULT_TOKEN
                token = self.token if self.token is not None \
                    else _DEFAULT_TOKEN
                self._mux = MuxClient(self.server_url, token=token,
                                      streams=self._mux_streams,
                                      timeout_s=self.timeout_s)
            return self._mux

    def close(self) -> None:
        with self._mux_lock:
            mux, self._mux = self._mux, None
        if mux is not None:
            mux.close()

    def submit_async(self, table: str, ctx, segment_names: Sequence[str],
                     time_filter: Optional[str] = None,
                     span_name: Optional[str] = None):
        """Mux dispatch: a Future resolving to the decoded SegmentResult
        (tracing spliced, frame-queue stats folded in — same observable
        surface as `__call__`). Returns None when mux is disabled or the
        peer predates /mux; the caller falls back to the legacy transport."""
        if not self.use_mux or self._mux_unsupported:
            return None
        if time.time() < self._mux_down_until:
            return None  # inside the post-backoff cooldown: ride legacy
        # graftfault: a crashed peer looks like a dispatch that dies before
        # any response — FaultInjected IS a ConnectionError, so the broker's
        # taxonomy marks the server unhealthy and retries on another replica
        fault_point("server.crash")
        from ..utils.metrics import get_registry
        from ..utils.trace import current_depth, current_trace
        sql = ctx if isinstance(ctx, str) else ctx.sql
        if not sql:
            raise ValueError("remote dispatch requires the query SQL text")
        tr = current_trace()
        depth = current_depth() if tr is not None else 0
        dispatch_ms = tr.elapsed_ms() if tr is not None else 0.0
        t0 = time.perf_counter()
        body = encode_query_request(
            table, sql, segment_names, time_filter,
            trace=tr is not None,
            trace_id=tr.trace_id if tr is not None else "",
            sampled=bool(tr.sampled) if tr is not None else False)
        if tr is not None:
            tr.record("serialize", dispatch_ms,
                      (time.perf_counter() - t0) * 1000, depth + 1)
        try:
            return self._mux_client().submit(
                body, trace=tr, depth=depth, dispatch_ms=dispatch_ms,
                span_name=span_name)
        except HttpError as e:
            if e.status in (404, 405, 501):
                # peer without a /mux route: remember and use legacy for good
                self._mux_unsupported = True
                get_registry().counter("pinot_broker_mux_fallbacks").inc()
                return None
            raise
        except ConnectionError:
            # the mux client already burned its jittered-backoff budget
            # (MuxClient.submit retries internally); answer by retrying this
            # request over the legacy per-request transport, and keep riding
            # it for a short cooldown so a dead peer isn't re-probed through
            # the full backoff ladder on every scatter
            self._mux_down_until = time.time() + self.MUX_COOLDOWN_S
            get_registry().counter("pinot_broker_mux_fallbacks").inc()
            return None

    #: longest Retry-After deferral honored before the single bounded retry
    #: (server hints can be large under saturation; a dispatch thread must
    #: not sleep seconds inside a scatter)
    RETRY_AFTER_CAP_S = 0.1

    def __call__(self, table: str, ctx, segment_names: Sequence[str],
                 time_filter: Optional[str] = None):
        try:
            return self._call_once(table, ctx, segment_names, time_filter)
        except HttpError as e:
            # overload-aware retry: a 429 carrying the server's Retry-After
            # hint (drain-rate estimate from its scheduler) gets exactly ONE
            # deferred retry after honoring the hint — bounded, so backoff
            # never amplifies into the blind hammering the hint exists to stop
            if e.status != 429:
                raise
            hint_ms = getattr(e, "retry_after_ms", None)
            if hint_ms is None:
                # legacy transport: the hint rides the JSON error body, which
                # http_call folds into the exception message
                s = str(e)
                try:
                    hint_ms = json.loads(s[s.index("{"):]).get("retryAfterMs")
                except (ValueError, AttributeError):
                    hint_ms = None
            if hint_ms is None:
                raise
            time.sleep(min(float(hint_ms) / 1000.0, self.RETRY_AFTER_CAP_S))
            return self._call_once(table, ctx, segment_names, time_filter)

    def _call_once(self, table: str, ctx, segment_names: Sequence[str],
                   time_filter: Optional[str] = None):
        from concurrent.futures import TimeoutError as _FutureTimeout

        from ..utils.trace import current_depth, current_trace, span
        fut = self.submit_async(table, ctx, segment_names, time_filter)
        if fut is not None:
            try:
                return fut.result(timeout=self.timeout_s)
            except _FutureTimeout:
                # the stream's stale-reap fails the wedged connection on the
                # next submit; classify this as a transport failure now
                raise ConnectionError(
                    f"mux response from {self.server_url} timed out "
                    f"after {self.timeout_s}s") from None
        sql = ctx if isinstance(ctx, str) else ctx.sql
        if not sql:
            raise ValueError("remote dispatch requires the query SQL text")
        tr = current_trace()
        dispatch_ms = tr.elapsed_ms() if tr is not None else 0.0
        # wire-level spans decompose the broker<->server hop: serialize the
        # request, the on-the-wire round trip (send), deserialize the result —
        # the server's own queue_wait/deserialize/exec spans splice in below
        with span("serialize"):
            body = encode_query_request(
                table, sql, segment_names, time_filter,
                trace=tr is not None,
                trace_id=tr.trace_id if tr is not None else "",
                sampled=bool(tr.sampled) if tr is not None else False)
        with span("send"):
            fault_point("server.crash")
            resp = http_call("POST", f"{self.server_url}/query", body,
                             timeout=self.timeout_s,
                             content_type="application/octet-stream",
                             token=self.token)
        with span("deserialize"):
            result = decode_segment_result(resp)
        spans = getattr(result, "trace_spans", None)
        if tr is not None and spans:
            # already prefixed server-side with its instance id; rebase the server's
            # local clock onto this trace's axis at the dispatch point, and nest
            # its spans one level under the dispatching server:<id> span
            tr.splice(spans, offset_ms=dispatch_ms,
                      depth_offset=current_depth())
        return result

    def explain(self, table: str, ctx, segment_names: Sequence[str]):
        """EXPLAIN rows from the remote server (POST /explain, JSON)."""
        sql = ctx if isinstance(ctx, str) else ctx.sql
        body = encode_query_request(table, sql, segment_names)
        resp = http_call("POST", f"{self.server_url}/explain", body,
                         timeout=self.timeout_s,
                         content_type="application/octet-stream",
                         token=self.token)
        return json.loads(resp.decode())["rows"]

    def join_stage(self, spec, left, right, agg=None):
        """Run one multistage stage partition on the remote server (POST
        /stage with wire-encoded blocks — the worker-mailbox dispatch). The
        response is a chunked stream of length-prefixed frames: joined-row
        block frames are consumed incrementally (bounded buffering), a
        partial-aggregation frame decodes to a mergeable SegmentResult.
        Rides the keep-alive pool via `http_stream` (TCP_NODELAY + staleness
        retry + HttpError-vs-ConnectionError taxonomy, like every other
        exchange — this used to be the one raw-urllib bypass)."""
        import struct

        from ..multistage.runtime import agg_spec_to_json, spec_to_json
        from .http_service import http_stream
        from .wire import (decode_block, decode_segment_result, decode_value,
                           encode_value)
        body = encode_value({"spec": spec_to_json(spec),
                             "agg": agg_spec_to_json(agg),
                             "left": dict(left), "right": dict(right)})
        blocks = []
        with http_stream("POST", f"{self.server_url}/stage", body,
                         timeout=self.timeout_s,
                         token=self.token) as resp:
            while True:
                header = resp.read(4)
                if len(header) < 4:
                    raise ConnectionError("stage stream truncated")
                (n,) = struct.unpack(">I", header)
                payload = resp.read(n)
                if len(payload) < n:
                    raise ConnectionError("stage stream truncated")
                d = decode_value(payload)
                if d["kind"] == "end":
                    resp.read()  # consume the terminal chunk: pool the conn
                    break
                if d["kind"] == "partial":
                    return decode_segment_result(d["result"])
                blocks.append(decode_block(d["block"]))
        from ..multistage.runtime import _concat_blocks
        return _concat_blocks(blocks)


class ControllerDeepStore(DeepStoreFS):
    """Deep-store access proxied through the controller by URL (reference: the http
    segment-fetcher scheme in `SegmentFetcherFactory`; servers without direct
    deep-store credentials download through the controller)."""

    scheme = "http"

    def __init__(self, controller_url: str):
        self.controller_url = controller_url.rstrip("/")

    def upload(self, local_path: str, uri: str) -> None:
        fault_point("deepstore.upload.fail")
        with open(local_path, "rb") as f:
            http_call("POST", f"{self.controller_url}/deepstore/{uri}", f.read(),
                      content_type="application/octet-stream", timeout=120.0)

    def download(self, uri: str, local_path: str) -> None:
        data = http_call("GET", f"{self.controller_url}/deepstore/{uri}",
                         timeout=120.0, retries=2)
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        with open(local_path, "wb") as f:
            f.write(data)

    def delete(self, uri: str) -> None:
        http_call("DELETE", f"{self.controller_url}/deepstore/{uri}")

    def exists(self, uri: str) -> bool:
        try:
            get_json(f"{self.controller_url}/deepstore-exists/{uri}")
            return True
        except HttpError:
            return False

    def listdir(self, uri: str) -> list:
        return get_json(f"{self.controller_url}/deepstore-list/{uri}")
