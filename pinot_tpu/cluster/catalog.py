"""Cluster catalog: the metadata store replacing ZooKeeper/Helix.

Holds exactly what the reference keeps in ZK (SURVEY.md §1): table configs + schemas
(PropertyStore), `SegmentMeta` (= `SegmentZKMetadata`,
`pinot-common/.../metadata/segment/SegmentZKMetadata.java:34`), IdealState (desired
segment->server->state) and ExternalView (actual), plus live instances. Watches replace
Helix state-transition messages: writers mutate under a lock, subscribers get called
after the mutation (reference: Helix `SegmentOnlineOfflineStateModelFactory` transitions).

The in-proc implementation is authoritative for a single coordinator process; the HTTP
transport layer exposes it to remote roles. Persistence: `snapshot()`/`restore()` round-
trip the whole catalog as JSON (checkpoint/resume, SURVEY.md §5 "Checkpoint / resume").
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ..schema import Schema
from ..table import TableConfig

# segment lifecycle states (reference: SegmentOnlineOfflineStateModel)
ONLINE = "ONLINE"
OFFLINE = "OFFLINE"
CONSUMING = "CONSUMING"
DROPPED = "DROPPED"
ERROR = "ERROR"
# cold tier: the segment stays registered (catalog + routing) and deepstore
# holds the bytes, but no server keeps it loaded — first query lazily
# downloads and admits it like any other segment.
COLD = "COLD"

# segment metadata status (reference: SegmentZKMetadata.Status)
STATUS_IN_PROGRESS = "IN_PROGRESS"
STATUS_DONE = "DONE"
STATUS_UPLOADED = "UPLOADED"


@dataclass
class SegmentMeta:
    """Reference: SegmentZKMetadata — all durable per-segment facts."""

    name: str
    table: str                      # table name with type
    status: str = STATUS_UPLOADED
    num_docs: int = 0
    crc: int = 0
    size_bytes: int = 0
    download_path: str = ""         # deep-store location
    creation_time_ms: int = 0
    push_time_ms: int = 0
    start_time_ms: Optional[int] = None   # min of time column (time pruning)
    end_time_ms: Optional[int] = None
    partition_id: Optional[int] = None    # partition pruning
    # realtime (LLC) fields
    start_offset: Optional[str] = None
    end_offset: Optional[str] = None
    partition_group: Optional[int] = None
    sequence_number: Optional[int] = None
    # free-form marks (reference: SegmentZKMetadata custom map — e.g. which minion
    # task produced the segment, so generators don't re-process outputs)
    custom: Dict[str, Any] = field(default_factory=dict)

    def to_json(self):
        return {k: v for k, v in self.__dict__.items()}

    @staticmethod
    def from_json(d):
        return SegmentMeta(**d)


#: SegmentMeta.custom key holding per-column pruning metadata:
#: {column: {"min": v, "max": v, "bloom": "<hex>"}} lifted from the segment's
#: metadata.json at commit/upload so the broker can range/bloom-prune without
#: ever opening the segment (reference: ColumnValueSegmentPruner consuming
#: column metadata + bloom filters)
COLUMN_STATS_KEY = "columnStats"


def column_stats_from_meta(seg_meta_json: Dict[str, Any]) -> Dict[str, Any]:
    """Lift the broker-prunable per-column facts out of a segment's
    metadata.json `columns` block: min/max (range pruning) and the
    metadata-carried bloom payload (EQ/IN pruning)."""
    out: Dict[str, Any] = {}
    for col, cm in (seg_meta_json.get("columns") or {}).items():
        entry: Dict[str, Any] = {}
        if cm.get("minValue") is not None:
            entry["min"] = cm["minValue"]
            entry["max"] = cm.get("maxValue")
        if cm.get("bloomHex"):
            entry["bloom"] = cm["bloomHex"]
        if entry:
            out[col] = entry
    return out


@dataclass
class InstanceInfo:
    instance_id: str
    role: str                      # server | broker | controller | minion
    host: str = "localhost"
    port: int = 0
    tags: List[str] = field(default_factory=lambda: ["DefaultTenant"])
    alive: bool = True
    scheme: str = "http"           # https when the role serves TLS

    @property
    def url(self) -> str:
        return f"{self.scheme}://{self.host}:{self.port}"

    def to_json(self):
        return dict(self.__dict__)

    @staticmethod
    def from_json(d):
        return InstanceInfo(**d)


class Catalog:
    """Thread-safe in-memory metadata store with watch callbacks."""

    def __init__(self):
        self._lock = threading.RLock()
        self.schemas: Dict[str, Schema] = {}
        self.table_configs: Dict[str, TableConfig] = {}          # key: name_with_type
        self.segments: Dict[str, Dict[str, SegmentMeta]] = {}    # table -> seg -> meta
        self.ideal_state: Dict[str, Dict[str, Dict[str, str]]] = {}   # table->seg->srv->state
        self.external_view: Dict[str, Dict[str, Dict[str, str]]] = {}
        self.instances: Dict[str, InstanceInfo] = {}
        self.properties: Dict[str, Any] = {}                     # misc (lineage, jobs)
        self._watchers: List[Callable[[str, str], None]] = []    # (event, table)

    # -- watches -----------------------------------------------------------
    def subscribe(self, fn: Callable[[str, str], None]) -> None:
        """fn(event, table); events: ideal_state, external_view, table, schema, instance."""
        with self._lock:
            # graftcheck: ignore[unbounded-keyed-accumulation] -- one entry
            # per subscribing component at wiring time, not query-driven
            self._watchers.append(fn)

    def _notify(self, event: str, table: str) -> None:
        for fn in list(self._watchers):
            fn(event, table)

    # -- schemas / tables --------------------------------------------------
    def put_schema(self, schema: Schema) -> None:
        with self._lock:
            self.schemas[schema.name] = schema
        self._notify("schema", schema.name)

    def put_table_config(self, config: TableConfig) -> None:
        with self._lock:
            self.table_configs[config.table_name_with_type] = config
            self.segments.setdefault(config.table_name_with_type, {})
            self.ideal_state.setdefault(config.table_name_with_type, {})
            self.external_view.setdefault(config.table_name_with_type, {})
        self._notify("table", config.table_name_with_type)

    def drop_table(self, table: str) -> None:
        with self._lock:
            self.table_configs.pop(table, None)
            self.segments.pop(table, None)
            self.ideal_state.pop(table, None)
            self.external_view.pop(table, None)
        self._notify("table", table)

    def schema_for_table(self, table: str) -> Optional[Schema]:
        with self._lock:
            cfg = self.table_configs.get(table)
            if cfg is None:
                return None
            return self.schemas.get(cfg.name)

    # -- segment metadata --------------------------------------------------
    def put_segment_meta(self, meta: SegmentMeta) -> None:
        with self._lock:
            self.segments.setdefault(meta.table, {})[meta.name] = meta
        self._notify("segment", meta.table)

    def drop_segment_meta(self, table: str, segment: str) -> None:
        with self._lock:
            self.segments.get(table, {}).pop(segment, None)
        self._notify("segment", table)

    # -- ideal state (controller writes) -----------------------------------
    def update_ideal_state(self, table: str,
                           updates: Dict[str, Optional[Dict[str, str]]]) -> None:
        """updates: segment -> {server: state} (None value drops the segment entry)."""
        with self._lock:
            ist = self.ideal_state.setdefault(table, {})
            for seg, assignment in updates.items():
                if assignment is None:
                    ist.pop(seg, None)
                else:
                    ist[seg] = dict(assignment)
        self._notify("ideal_state", table)

    # -- external view (servers write) -------------------------------------
    def report_state(self, table: str, segment: str, server: str,
                     state: Optional[str]) -> None:
        with self._lock:
            ev = self.external_view.setdefault(table, {})
            entry = ev.setdefault(segment, {})
            if state is None or state == DROPPED:
                entry.pop(server, None)
                if not entry:
                    ev.pop(segment, None)
            else:
                entry[server] = state
        self._notify("external_view", table)

    # -- properties (reference: ZK property store misc nodes: lineage, tasks,
    # watermarks) ----------------------------------------------------------
    def put_property(self, key: str, value: Any) -> None:
        with self._lock:
            if value is None:
                self.properties.pop(key, None)
            else:
                self.properties[key] = value
        self._notify("property", key)

    def get_property(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self.properties.get(key, default)

    def mutate_property(self, key: str, fn: Callable[[Any], Any]) -> Any:
        """Atomic read-modify-write (the ZK compare-and-set analog)."""
        with self._lock:
            value = fn(self.properties.get(key))
            if value is None:
                self.properties.pop(key, None)
            else:
                self.properties[key] = value
        self._notify("property", key)
        return value

    # -- instances ---------------------------------------------------------
    def register_instance(self, info: InstanceInfo) -> None:
        with self._lock:
            self.instances[info.instance_id] = info
        self._notify("instance", info.instance_id)

    def set_instance_alive(self, instance_id: str, alive: bool) -> None:
        with self._lock:
            if instance_id in self.instances:
                self.instances[instance_id].alive = alive
        self._notify("instance", instance_id)

    def remove_instance(self, instance_id: str, only_if=None) -> bool:
        """Remove an instance; `only_if(info)` (evaluated under the lock)
        guards check-then-remove races — e.g. a dead-minion sweep must not
        delete an instance that was just marked alive again."""
        with self._lock:
            info = self.instances.get(instance_id)
            if info is None or (only_if is not None and not only_if(info)):
                return False
            del self.instances[instance_id]
        self._notify("instance", instance_id)
        return True

    def update_instance_tags(self, instance_id: str, tags: List[str]) -> None:
        with self._lock:
            info = self.instances.get(instance_id)
            if info is None:
                raise ValueError(f"unknown instance {instance_id!r}")
            info.tags = list(tags)
        self._notify("instance", instance_id)

    def live_servers(self, tenant: Optional[str] = None) -> List[str]:
        with self._lock:
            return [i.instance_id for i in self.instances.values()
                    if i.role == "server" and i.alive
                    and (tenant is None or tenant in i.tags)]

    # -- snapshots (checkpoint/resume) --------------------------------------
    def snapshot(self) -> str:
        with self._lock:
            return json.dumps({
                "schemas": {k: v.to_json() for k, v in self.schemas.items()},
                "tableConfigs": {k: v.to_json() for k, v in self.table_configs.items()},
                "segments": {t: {s: m.to_json() for s, m in segs.items()}
                             for t, segs in self.segments.items()},
                "idealState": self.ideal_state,
                "properties": self.properties,
            })

    def restore(self, blob: str) -> None:
        d = json.loads(blob)
        with self._lock:
            self.schemas = {k: Schema.from_json(v) for k, v in d["schemas"].items()}
            self.table_configs = {k: TableConfig.from_json(v)
                                  for k, v in d["tableConfigs"].items()}
            self.segments = {t: {s: SegmentMeta.from_json(m) for s, m in segs.items()}
                             for t, segs in d["segments"].items()}
            self.ideal_state = d["idealState"]
            self.external_view = {t: {} for t in self.ideal_state}
            self.properties = d.get("properties", {})
