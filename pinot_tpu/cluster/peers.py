"""Peer segment fetch: download a committed segment tar from a replica server.

Analog of the reference's `PeerServerSegmentFinder`
(`pinot-core/src/main/java/org/apache/pinot/core/util/PeerServerSegmentFinder.java`):
the external view IS the location map — every server reporting the segment
ONLINE can serve its local copy over `GET /segmentData/{table}/{segment}`.
Used when the deep store is slow/unavailable (download falls back
deep-store -> peer) and for `peer://` scheme segments whose commit-time upload
failed (`completion.py` PeerSchemeSplitSegmentCommitter analog).
"""

from __future__ import annotations

import os
from typing import List, Optional

from .catalog import ONLINE


def peer_urls(catalog, table: str, segment: str,
              exclude_instance: Optional[str] = None) -> List[str]:
    """Base URLs of live servers whose external-view state for the segment is
    ONLINE (they hold a loaded local copy), excluding the asking instance."""
    ev = catalog.external_view.get(table, {}).get(segment, {})
    urls = []
    for server_id, state in sorted(ev.items()):
        if state != ONLINE or server_id == exclude_instance:
            continue
        info = catalog.instances.get(server_id)
        if info is None or not info.alive or not info.port:
            continue
        urls.append(info.url)
    return urls


def download_segment_tar(deepstore, catalog, table: str, segment: str,
                         dest_tar: str, download_path: str,
                         exclude_instance: Optional[str] = None) -> None:
    """One download policy for every fetcher (server load, minion input,
    controller raw-download proxy): deep store first, falling back to a
    serving peer on a peer:// scheme OR any deep-store failure."""
    try:
        if download_path.startswith("peer://"):
            raise ConnectionError("peer-scheme segment")
        deepstore.download(download_path, dest_tar)
    except Exception:
        fetch_from_peer(catalog, table, segment, dest_tar,
                        exclude_instance=exclude_instance)


def fetch_from_peer(catalog, table: str, segment: str, dest_tar: str,
                    exclude_instance: Optional[str] = None,
                    timeout_s: float = 60.0) -> str:
    """Download the segment tar from the first answering peer; returns the
    peer URL used. Raises ConnectionError when no peer can serve it."""
    from .http_service import http_call
    last: Optional[Exception] = None
    for url in peer_urls(catalog, table, segment, exclude_instance):
        try:
            data = http_call("GET", f"{url}/segmentData/{table}/{segment}",
                             timeout=timeout_s)
        except Exception as e:
            last = e
            continue
        os.makedirs(os.path.dirname(dest_tar) or ".", exist_ok=True)
        with open(dest_tar, "wb") as f:
            f.write(data)
        return url
    raise ConnectionError(
        f"no peer can serve {table}/{segment}: {last!r}")
