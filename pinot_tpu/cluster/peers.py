"""Peer segment fetch: download a committed segment tar from a replica server.

Analog of the reference's `PeerServerSegmentFinder`
(`pinot-core/src/main/java/org/apache/pinot/core/util/PeerServerSegmentFinder.java`):
the external view IS the location map — every server reporting the segment
ONLINE can serve its local copy over `GET /segmentData/{table}/{segment}`.
Used when the deep store is slow/unavailable (download falls back
deep-store -> peer) and for `peer://` scheme segments whose commit-time upload
failed (`completion.py` PeerSchemeSplitSegmentCommitter analog).
"""

from __future__ import annotations

import os
import threading
import time
from typing import List, Optional, Set, Tuple

from ..utils.metrics import get_registry
from .catalog import ONLINE

# (table, segment) pairs whose deep-store download exhausted the retry
# budget: subsequent fetches skip straight to the peer path instead of
# re-burning the backoff schedule against a blob that keeps failing
# (mirror of the completion.py upload quarantine)
_download_quarantine: Set[Tuple[str, str]] = set()
_quarantine_lock = threading.Lock()


def clear_download_quarantine() -> None:
    """Operator/test hook: give quarantined blobs another shot at the deep
    store (e.g. after the store recovers)."""
    with _quarantine_lock:
        _download_quarantine.clear()


def peer_urls(catalog, table: str, segment: str,
              exclude_instance: Optional[str] = None) -> List[str]:
    """Base URLs of live servers whose external-view state for the segment is
    ONLINE (they hold a loaded local copy), excluding the asking instance."""
    ev = catalog.external_view.get(table, {}).get(segment, {})
    urls = []
    for server_id, state in sorted(ev.items()):
        if state != ONLINE or server_id == exclude_instance:
            continue
        info = catalog.instances.get(server_id)
        if info is None or not info.alive or not info.port:
            continue
        urls.append(info.url)
    return urls


def download_segment_tar(deepstore, catalog, table: str, segment: str,
                         dest_tar: str, download_path: str,
                         exclude_instance: Optional[str] = None) -> None:
    """One download policy for every fetcher (server load, minion input,
    controller raw-download proxy): deep store first — with the
    `deepstore.retry.*` exponential backoff the upload path already uses —
    falling back to a serving peer on a peer:// scheme, retry exhaustion
    (which also quarantines the blob so later fetches skip the backoff), or
    any other deep-store failure."""
    key = (table, segment)
    with _quarantine_lock:
        quarantined = key in _download_quarantine
    if download_path.startswith("peer://") or quarantined:
        fetch_from_peer(catalog, table, segment, dest_tar,
                        exclude_instance=exclude_instance)
        return
    max_attempts = 3
    backoff_ms = 50.0
    try:
        max_attempts = max(1, int(catalog.get_property(
            "clusterConfig/deepstore.retry.max", 3)))
        backoff_ms = float(catalog.get_property(
            "clusterConfig/deepstore.retry.backoff.ms", 50))
    # graftcheck: ignore[exception-hygiene] -- malformed retry knobs fall
    # back to the documented defaults; the retry loop below is the outcome
    except Exception:
        pass
    reg = get_registry()
    for attempt in range(1, max_attempts + 1):
        if attempt > 1:
            reg.counter("pinot_deepstore_download_retries").inc()
            time.sleep(backoff_ms * 2 ** (attempt - 2) / 1000.0)
        try:
            deepstore.download(download_path, dest_tar)
            return
        # graftcheck: ignore[exception-hygiene] -- each failed attempt is
        # observed: retries counted above, exhaustion counted + quarantined
        # below, and the peer fallback raises typed when it too fails
        except Exception:
            continue
    with _quarantine_lock:
        _download_quarantine.add(key)
    reg.counter("pinot_deepstore_download_quarantined").inc()
    fetch_from_peer(catalog, table, segment, dest_tar,
                    exclude_instance=exclude_instance)


def fetch_from_peer(catalog, table: str, segment: str, dest_tar: str,
                    exclude_instance: Optional[str] = None,
                    timeout_s: float = 60.0) -> str:
    """Download the segment tar from the first answering peer; returns the
    peer URL used. Raises ConnectionError when no peer can serve it."""
    from .http_service import http_call
    last: Optional[Exception] = None
    for url in peer_urls(catalog, table, segment, exclude_instance):
        try:
            data = http_call("GET", f"{url}/segmentData/{table}/{segment}",
                             timeout=timeout_s)
        except Exception as e:
            last = e
            continue
        os.makedirs(os.path.dirname(dest_tar) or ".", exist_ok=True)
        with open(dest_tar, "wb") as f:
            f.write(data)
        return url
    raise ConnectionError(
        f"no peer can serve {table}/{segment}: {last!r}")
