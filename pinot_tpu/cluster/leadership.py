"""Lead-controller election + standby failover over the shared deep store.

Analog of the reference's lead-controller machinery (`pinot-controller/.../
LeadControllerManager.java` + the Helix leader resource): exactly one
controller acts on the cluster at a time; standbys take over when the leader
stops renewing its claim.

Redesign for this architecture: the reference leans on ZK ephemeral nodes; the
shared durable medium here is the deep store, so leadership is a LEASE blob
(`_leadership/lease.json`: holder, epoch, deadline) that the leader renews and
standbys poll. Writes are atomic (temp+rename in LocalDeepStore) and
verify-after-write (no CAS on generic deep stores): a contender writes its
claim, waits a settle window, and reads back — if its claim survived, it leads
under a NEW epoch. Epochs bump on every acquisition of an expired/free lease —
including a restarted process reusing its instance id — so a stale incarnation
always sees a higher epoch and steps down (fencing).

The catalog (the ZK stand-in) rides the same medium: the leader checkpoints
`Catalog.snapshot()` to `_leadership/catalog.json` on every change — each
upload re-verifies the lease first, so a deposed leader cannot clobber its
successor's checkpoint — and a standby RESTORES that snapshot at takeover,
exactly like the reference's ZK state surviving controller churn.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

LEASE_URI = "_leadership/lease.json"
CATALOG_URI = "_leadership/catalog.json"


class LeaderElection:
    """One contender's view of the leadership lease."""

    def __init__(self, deepstore, instance_id: str, lease_ttl_s: float = 10.0,
                 settle_s: float = 0.05):
        self.deepstore = deepstore
        self.instance_id = instance_id
        self.lease_ttl_s = lease_ttl_s
        self.settle_s = settle_s
        self.epoch = 0
        self.is_leader = False

    # -- lease blob I/O ----------------------------------------------------
    def _read_lease(self) -> Optional[dict]:
        try:
            return json.loads(self.deepstore.get_bytes(LEASE_URI).decode())
        except Exception:
            return None

    def _write_lease(self, lease: dict) -> None:
        self.deepstore.put_bytes(json.dumps(lease).encode(), LEASE_URI)

    def _holds(self, cur: Optional[dict]) -> bool:
        """Does the CURRENT incarnation of this object hold `cur`?"""
        return bool(self.is_leader and cur is not None
                    and cur["holder"] == self.instance_id
                    and cur["epoch"] == self.epoch)

    # -- acquire/renew -----------------------------------------------------
    def try_acquire(self) -> bool:
        """Claim leadership if the lease is free/expired; verify-after-write."""
        now = time.time()
        cur = self._read_lease()
        if cur is not None and cur["deadline"] > now and not self._holds(cur):
            # someone (possibly an older incarnation of OUR id) holds a live
            # lease; a restarted process must wait for expiry like anyone else
            self.is_leader = False
            return False
        if self._holds(cur):
            return self.renew()
        # free/expired: every fresh acquisition bumps the epoch — even for the
        # same instance id — so stale incarnations are fenced out
        epoch = (cur["epoch"] if cur else 0) + 1
        claim = {"holder": self.instance_id, "epoch": epoch,
                 "deadline": now + self.lease_ttl_s}
        self._write_lease(claim)
        if self.settle_s:
            time.sleep(self.settle_s)   # let a racing contender's write land
        final = self._read_lease()
        won = bool(final and final["holder"] == self.instance_id
                   and final["epoch"] == epoch)
        self.epoch = epoch if won else self.epoch
        self.is_leader = won
        return won

    def renew(self) -> bool:
        """Extend the lease; returns False (and steps down) when deposed."""
        cur = self._read_lease()
        if not self._holds(cur):
            self.is_leader = False
            return False
        self._write_lease({"holder": self.instance_id, "epoch": self.epoch,
                           "deadline": time.time() + self.lease_ttl_s})
        return True

    def release(self) -> None:
        """Voluntary step-down: expire the lease — but only if THIS incarnation
        still holds it (a stale ex-leader must not clobber its successor)."""
        cur = self._read_lease()
        if self._holds(cur):
            self._write_lease({"holder": self.instance_id, "epoch": self.epoch,
                               "deadline": 0.0})
        self.is_leader = False


class ControllerFailover:
    """Wires a Controller to the election: leader checkpoints the catalog,
    standby polls and restores + takes over on lease expiry.

    Reference flow: LeadControllerManager callbacks start/stop the controller's
    periodic tasks and realtime manager on leadership changes."""

    CHECKPOINT_READ_RETRIES = 3

    def __init__(self, controller, election: LeaderElection,
                 on_gain: Optional[Callable[[], None]] = None,
                 on_loss: Optional[Callable[[], None]] = None):
        self.controller = controller
        self.election = election
        self.on_gain = on_gain
        self.on_loss = on_loss
        self._subscribed = False

    # -- leader side -------------------------------------------------------
    def lead(self) -> bool:
        """Become leader (if the lease allows) and start checkpointing."""
        if not self.election.try_acquire():
            return False
        self._on_become_leader()
        return True

    def _on_become_leader(self) -> None:
        from ..utils.events import emit as emit_event
        emit_event("leader.elected", node=self.election.instance_id,
                   epoch=self.election.epoch)
        self._checkpoint()
        if not self._subscribed:  # a re-elected standby must not double-write
            self.controller.catalog.subscribe(self._on_catalog_event)
            self._subscribed = True
        if self.on_gain:
            self.on_gain()

    def _on_catalog_event(self, event: str, key: str) -> None:
        if self.election.is_leader:
            self._checkpoint()

    def _checkpoint(self) -> None:
        # epoch fence: re-verify the lease IMMEDIATELY before uploading so a
        # deposed leader's late catalog events cannot overwrite the successor's
        # checkpoint (the lease is fenced; the checkpoint must be too)
        if not self.election._holds(self.election._read_lease()):
            self.election.is_leader = False
            return
        self.election.deepstore.put_bytes(
            self.controller.catalog.snapshot().encode(), CATALOG_URI)

    def heartbeat(self) -> bool:
        """Renew the lease; on deposition, stop acting (tests drive this
        deterministically; production wraps it in utils.periodic)."""
        ok = self.election.renew()
        if not ok:
            from ..utils.events import emit as emit_event
            emit_event("leader.lost", node=self.election.instance_id)
            if self.on_loss:
                self.on_loss()
        return ok

    # -- standby side ------------------------------------------------------
    def try_takeover(self) -> bool:
        """Standby poll: if the lease is free/expired, restore the last
        catalog checkpoint and assume leadership. A checkpoint that EXISTS but
        cannot be read aborts the takeover (stepping up with an empty catalog
        would overwrite the good checkpoint and lose all metadata)."""
        if self.election.is_leader:
            return True
        if not self.election.try_acquire():
            return False
        if self.election.deepstore.exists(CATALOG_URI):
            blob = None
            for _ in range(self.CHECKPOINT_READ_RETRIES):
                try:
                    blob = self.election.deepstore.get_bytes(CATALOG_URI)
                    self.controller.catalog.restore(blob.decode())
                    break
                except Exception:
                    blob = None
                    time.sleep(0.05)
            if blob is None:
                self.election.release()   # do NOT clobber what we can't read
                return False
        self._on_become_leader()
        return True
