"""Broker routing: external-view-driven routing tables, instance selection, pruning.

Analog of the reference's `BrokerRoutingManager`
(`pinot-broker/.../routing/BrokerRoutingManager.java:88,122`), instance selectors
(`routing/instanceselector/`), and segment pruners (`routing/segmentpruner/`): watch the
external view, keep segment -> online replica servers, select one replica per segment
per query (round-robin for balance), and prune segments by partition/time metadata
before scatter.
"""

from __future__ import annotations

import itertools
import threading
import zlib
from typing import Dict, List, Optional, Set, Tuple

from ..query.context import QueryContext
from ..sql.ast import Expr, Function, Identifier, Literal
from ..segment.indexes.bloom import bloom_hex_might_contain
from .catalog import (COLD, COLUMN_STATS_KEY, CONSUMING, ONLINE, Catalog,
                      SegmentMeta)

#: pruner kinds in evaluation order — the FIRST pruner that rejects a segment
#: gets the attribution (numSegmentsPrunedBy<Kind> in ExecutionStats)
PRUNER_KINDS = ("partition", "time", "range", "bloom")

#: key under which `_prune`/`route_query` accumulate pruned-doc counts in the
#: caller-supplied prune_stats dict (feeds scanRowsAvoided)
PRUNE_ROWS_AVOIDED = "rowsAvoided"


def partition_for_value(value, function: str, num_partitions: int) -> int:
    """Partition functions (reference: pinot-segment-spi partition functions)."""
    if function == "modulo":
        return int(value) % num_partitions
    # murmur stand-in: crc32 over the string form — stable across processes
    return zlib.crc32(str(value).encode("utf-8")) % num_partitions


class RoutingTable:
    """segment -> candidate servers, resolved per query to server -> [segments]."""

    def __init__(self, table: str):
        self.table = table
        self.segment_servers: Dict[str, List[str]] = {}
        # segments in the external view whose every replica server is DEAD
        # (left live_servers): undispatchable, but they must still surface in
        # the coverage audit — dropping them entirely would silently shorten
        # results with partialResult=False
        self.dead_segments: Set[str] = set()
        # CONSUMING segments: replicas consume the same partition at
        # INDEPENDENT offsets, so round-robin across them makes COUNT(*)
        # wobble between queries (reads jump to a less-caught-up replica).
        # These route to a STABLE choice — monotonic freshness per segment —
        # until that replica leaves rotation.
        self.consuming_segments: Set[str] = set()
        self._rr = itertools.count()

    def route(self, segments: Optional[Set[str]] = None,
              exclude: Optional[Set[str]] = None,
              selector: str = "balanced",
              uncovered: Optional[List[str]] = None) -> Dict[str, List[str]]:
        """Resolve one healthy replica per segment.

        Selectors (reference: instanceselector/ package):
        - "balanced": per-segment round-robin (BalancedInstanceSelector) —
          best load spread, segments of one query fan across replicas.
        - "replicaGroup"/"strictReplicaGroup": ONE replica ordinal per query
          (ReplicaGroupInstanceSelector / StrictReplicaGroupInstanceSelector):
          every segment is served by the same replica position, so with
          replica-group-aligned assignment a query touches one group — and,
          critically for upsert tables, all segments of a partition are read
          from the SAME server, whose valid-doc bitmaps are mutually
          consistent (mixing replicas can double-count a primary key mid
          upsert propagation)."""
        sel = selector.lower().replace("_", "")
        if sel not in ("balanced", "replicagroup", "strictreplicagroup"):
            raise ValueError(f"unknown routing selector {selector!r}")
        out: Dict[str, List[str]] = {}
        offset = next(self._rr)
        group_mode = sel in ("replicagroup", "strictreplicagroup")
        if group_mode:
            # one per-query PREFERENCE ORDER over all servers: every segment
            # picks its highest-preference candidate, so segments with equal
            # candidate sets always co-locate (partition-consistent realtime
            # assignment makes upsert partitions share candidate sets), and
            # overlapping sets co-locate whenever their preferred server is
            # shared — a per-segment modulo over differing candidate-list
            # lengths would scatter replicas instead
            all_servers = sorted({s for servers in self.segment_servers.values()
                                  for s in servers})
            if all_servers:
                rot = offset % len(all_servers)
                preference = {s: i for i, s in enumerate(
                    all_servers[rot:] + all_servers[:rot])}
        for i, (seg, servers) in enumerate(sorted(self.segment_servers.items())):
            if segments is not None and seg not in segments:
                continue
            candidates = [s for s in servers if not exclude or s not in exclude]
            if not candidates:
                # every replica is excluded (unhealthy): the segment cannot be
                # dispatched — REPORT it so the broker surfaces a partial
                # result instead of a silently-short answer
                if uncovered is not None:
                    uncovered.append(seg)
                continue
            if group_mode:
                chosen = min(candidates, key=preference.__getitem__)
            elif seg in self.consuming_segments:
                chosen = candidates[0]  # stable: monotonic consuming reads
            else:
                chosen = candidates[(offset + i) % len(candidates)]
            out.setdefault(chosen, []).append(seg)
        return out


class RoutingManager:
    """Watches the catalog and maintains routing tables per table."""

    def __init__(self, catalog: Catalog):
        self.catalog = catalog
        self._tables: Dict[str, RoutingTable] = {}
        self._unhealthy: Set[str] = set()
        self._lock = threading.RLock()
        catalog.subscribe(self._on_event)
        for table in list(catalog.external_view):
            self._rebuild(table)

    def _on_event(self, event: str, table: str) -> None:
        if event in ("external_view", "table", "instance"):
            if event == "instance":
                with self._lock:
                    tables = list(self._tables)
                for t in tables:
                    self._rebuild(t)
            else:
                self._rebuild(table)

    def _rebuild(self, table: str) -> None:
        ev = self.catalog.external_view.get(table)
        if ev is None:
            with self._lock:
                self._tables.pop(table, None)
            return
        rt = RoutingTable(table)
        alive = set(self.catalog.live_servers())
        for seg, states in ev.items():
            # COLD replicas stay routable: the assigned server holds no local
            # copy but lazily downloads from the deep store on first query
            servers = [srv for srv, st in states.items()
                       if st in (ONLINE, CONSUMING, COLD) and srv in alive]
            if servers:
                rt.segment_servers[seg] = sorted(servers)
                if any(st == CONSUMING for st in states.values()):
                    rt.consuming_segments.add(seg)
            elif any(st in (ONLINE, CONSUMING, COLD) for st in states.values()):
                # the segment WAS being served and every such replica died
                rt.dead_segments.add(seg)
        with self._lock:
            self._tables[table] = rt

    # -- health (reference: broker failure detector wiring) -----------------
    def mark_server_unhealthy(self, server: str) -> None:
        with self._lock:
            self._unhealthy.add(server)

    def unhealthy_servers(self) -> Set[str]:
        with self._lock:
            return set(self._unhealthy)

    def mark_server_healthy(self, server: str) -> None:
        with self._lock:
            self._unhealthy.discard(server)

    def segment_candidates(self, table: str, segment: str) -> List[str]:
        """Healthy-state candidate servers for one segment (broker retry)."""
        with self._lock:
            rt = self._tables.get(table)
            return list(rt.segment_servers.get(segment, ())) if rt else []

    # -- query routing -----------------------------------------------------
    def route_query(self, table: str, ctx: Optional[QueryContext] = None,
                    extra_filter: Optional[Expr] = None,
                    uncovered: Optional[List[str]] = None,
                    prune_stats: Optional[Dict[str, float]] = None
                    ) -> Dict[str, List[str]]:
        """`extra_filter` is an additional predicate the servers will apply (the
        broker's hybrid time-boundary split) — fed into the metadata pruner here so
        retained realtime segments entirely below the boundary are never dispatched
        (reference: TimeSegmentPruner sees the boundary-augmented filter).
        `uncovered`, when given, collects segments that survive pruning but have
        no healthy replica to serve them. `prune_stats`, when given, accumulates
        per-pruner-kind rejection counts (PRUNER_KINDS keys) plus the pruned
        segments' total doc count under PRUNE_ROWS_AVOIDED."""
        with self._lock:
            rt = self._tables.get(table)
            unhealthy = set(self._unhealthy)
        if rt is None:
            return {}
        cfg = self.catalog.table_configs.get(table)
        keep = set(rt.segment_servers) | rt.dead_segments
        hidden = self._lineage_hidden(table)
        if hidden:
            keep -= hidden
        if ctx is not None:
            keep = self._prune(table, keep, ctx, prune_stats)
        if extra_filter is not None and cfg is not None:
            metas = self.catalog.segments.get(table, {})
            kept: Set[str] = set()
            for seg in keep:
                meta = metas.get(seg)
                reason = (None if meta is None else
                          _prune_reason(extra_filter, cfg, meta))
                if reason is None:
                    kept.add(seg)
                else:
                    _count_prune(prune_stats, reason, meta)
            keep = kept
        if uncovered is not None:
            # dead-replica segments that survive pruning are part of the
            # query's answer set but have no server at all
            uncovered.extend(sorted(keep & rt.dead_segments))
        return rt.route(keep - rt.dead_segments, exclude=unhealthy,
                        selector=self.selector_for(table), uncovered=uncovered)

    def selector_for(self, table: str) -> str:
        """The table's effective instance selector, NORMALIZED (lowercase, no
        underscores — the same canonical form RoutingTable.route validates
        against) — single source of truth for the first scatter round AND the
        retry round (upsert correctness requires consistent-replica reads;
        reference: upsert tables mandate strictReplicaGroup routing)."""
        cfg = self.catalog.table_configs.get(table)
        if cfg is None:
            return "balanced"
        sel = cfg.routing_selector or (
            "strictReplicaGroup" if cfg.upsert else "balanced")
        return sel.lower().replace("_", "")

    def _lineage_hidden(self, table: str) -> Set[str]:
        """Segments hidden by replace-segment lineage (reference: SegmentLineage,
        `selectSegments` filtering): IN_PROGRESS hides the replacement outputs,
        COMPLETED hides the replaced inputs — so a query never sees both sides."""
        entries = self.catalog.get_property(f"lineage/{table}") or []
        hidden: Set[str] = set()
        for e in entries:
            hidden.update(e["to"] if e["state"] == "IN_PROGRESS" else e["from"])
        return hidden

    def _prune(self, table: str, segments: Set[str], ctx: QueryContext,
               prune_stats: Optional[Dict[str, float]] = None) -> Set[str]:
        """Metadata pruning from SegmentMeta (reference:
        MultiPartitionColumnsSegmentPruner + TimeSegmentPruner +
        ColumnValueSegmentPruner): partition/time from the typed meta fields,
        range/bloom from the commit-time columnStats custom block."""
        cfg = self.catalog.table_configs.get(table)
        metas = self.catalog.segments.get(table, {})
        if cfg is None or ctx.filter is None:
            return segments
        keep = set()
        for seg in segments:
            meta = metas.get(seg)
            if meta is None:
                keep.add(seg)
                continue
            reason = _prune_reason(ctx.filter, cfg, meta)
            if reason is not None:
                _count_prune(prune_stats, reason, meta)
                continue
            keep.add(seg)
        return keep


def _count_prune(prune_stats: Optional[Dict[str, float]], reason: str,
                 meta: Optional[SegmentMeta]) -> None:
    if prune_stats is None:
        return
    prune_stats[reason] = prune_stats.get(reason, 0) + 1
    if meta is not None:
        prune_stats[PRUNE_ROWS_AVOIDED] = (
            prune_stats.get(PRUNE_ROWS_AVOIDED, 0) + meta.num_docs)


def _segment_may_match(filt: Expr, cfg, meta: SegmentMeta) -> bool:
    """Conservative filter check against segment metadata (compat wrapper)."""
    return _prune_reason(filt, cfg, meta) is None


def _out_of_range(name: str, args: List, lo, hi) -> bool:
    """True when the comparison `name(col, *args)` PROVABLY misses [lo, hi].
    columnStats values round-trip through JSON, so a cross-type comparison
    (str vs int, bytes literal vs hex string) degrades to "may match"."""
    try:
        if name == "eq":
            return bool(args[0] < lo or hi < args[0])
        if name == "between":
            return bool(args[1] < lo or hi < args[0])
        if name == "gt":
            return not args[0] < hi          # col > v needs v < max
        if name == "gte":
            return bool(hi < args[0])        # col >= v needs v <= max
        if name == "lt":
            return not lo < args[0]          # col < v needs v > min
        if name == "lte":
            return bool(args[0] < lo)        # col <= v needs v >= min
        if name == "in":
            return all(v < lo or hi < v for v in args)
    except TypeError:
        return False
    return False


def _prune_reason(filt: Expr, cfg, meta: SegmentMeta) -> Optional[str]:
    """Why this segment PROVABLY cannot match `filt` (a PRUNER_KINDS name),
    or None when it may match. Strictly conservative: anything the metadata
    cannot decide is None."""
    if not isinstance(filt, Function):
        return None
    if filt.name == "and":
        for a in filt.args:
            r = _prune_reason(a, cfg, meta)
            if r is not None:
                return r
        return None
    if filt.name == "or":
        first: Optional[str] = None
        for a in filt.args:
            r = _prune_reason(a, cfg, meta)
            if r is None:
                return None      # one satisfiable branch keeps the segment
            if first is None:
                first = r
        return first
    # partition pruning: eq on the partition column
    if (filt.name == "eq" and cfg.partition and meta.partition_id is not None
            and isinstance(filt.args[0], Identifier)
            and filt.args[0].name == cfg.partition.column
            and isinstance(filt.args[1], Literal)):
        pid = partition_for_value(filt.args[1].value, cfg.partition.function,
                                  cfg.partition.num_partitions)
        if pid != meta.partition_id:
            return "partition"
        return None
    # time pruning: range on the time column vs [start_time, end_time]
    if (cfg.time_column and meta.start_time_ms is not None
            and meta.end_time_ms is not None
            and isinstance(filt.args[0], Identifier)
            and filt.args[0].name == cfg.time_column
            and all(isinstance(a, Literal) for a in filt.args[1:])):
        vals = [a.value for a in filt.args[1:]]
        lo, hi = meta.start_time_ms, meta.end_time_ms
        if filt.name == "between" and (vals[1] < lo or vals[0] > hi):
            return "time"
        if filt.name == "eq" and not lo <= vals[0] <= hi:
            return "time"
        if filt.name in ("gt", "gte") and not vals[0] <= hi:
            return "time"
        if filt.name in ("lt", "lte") and not vals[0] >= lo:
            return "time"
        return None
    # range + bloom pruning from the commit-time per-column stats
    col_stats = (meta.custom or {}).get(COLUMN_STATS_KEY)
    if (col_stats and filt.args and isinstance(filt.args[0], Identifier)
            and all(isinstance(a, Literal) for a in filt.args[1:])):
        cs = col_stats.get(filt.args[0].name)
        if not isinstance(cs, dict):
            return None
        vals = [a.value for a in filt.args[1:]]
        if ("min" in cs and "max" in cs and vals
                and _out_of_range(filt.name, vals, cs["min"], cs["max"])):
            return "range"
        if filt.name in ("eq", "in") and cs.get("bloom"):
            if not any(bloom_hex_might_contain(cs["bloom"], v) for v in vals):
                return "bloom"
    return None
