"""Server-side segment tier lifecycle: HBM as a managed hot tier.

`block_for()` made HBM an unmanaged cache — every queried segment's columns
stage in and stay until unload, so a table larger than device memory OOMs.
This module turns the PR 14 ledger into policy (Tailwind's
accelerator/framework split: the accelerator tier holds only what keeps it
saturated, the framework tier absorbs the rest):

* **hot (HBM)** — ledger-accounted `SegmentBlock` arrays; bounded by
  `capacity * (1 - server.hbm.target.headroom.pct / 100)`.
* **warm (host RAM)** — the `ImmutableSegment` readers that back the host
  plan. Eviction is just `release_block`: the device arrays drop, the host
  readers still serve; re-promotion is the existing `block_for` path.
* **cold (deepstore)** — segments assigned COLD in the ideal state keep
  their catalog/routing registration but no local copy; the first query
  lazily downloads + loads them (bounded by the query's propagated
  deadline) and they admit like any other segment.

Three ledger-driven mechanisms live here:

1. an **admission gate** (`admit`) that predicts a block's bytes from
   segment metadata BEFORE staging and synchronously evicts colder victims
   until the prediction fits under the target;
2. a **pressure loop** (`run_pressure_sweep`, a server periodic task) that
   evicts past the target using a bytes-times-coldness cost score;
3. **graceful degradation**: when eviction can't free enough, `admit`
   returns False and the caller runs the host plan for that segment
   (`segmentsServedHostTier` in stats) instead of OOMing.

Eviction is refcount-aware: a segment acquired by an in-flight query is
never a victim — its block drop defers until `TableDataManager.release`
drains the refcount (the satellite deferred-release fix), so a running
query never loses device arrays mid-kernel.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, Optional

from ..engine.datablock import has_block, predicted_block_bytes, release_block
from ..utils.events import emit as emit_event
from ..utils.memledger import get_ledger
from ..utils.metrics import get_registry

#: default percent of capacity the admission gate / pressure loop keep free
#: (the `server.hbm.target.headroom.pct` cluster knob overrides)
DEFAULT_TARGET_HEADROOM_PCT = 10.0

#: pressure-loop cadence (seconds) — frequent enough that a burst of
#: admissions is walked back within a few seconds, rare enough to be noise
PRESSURE_INTERVAL_S = 5.0


# -- join-intermediate pricing (PR 17) ---------------------------------------
# The device hash-join stages both sides' key codes plus the matched output
# in HBM alongside whatever segments are already resident. An exploding join
# (duplicate build keys fanning every probe row out) must degrade to the host
# `hash_join` path — flagged `joinServedHostTier` — instead of OOMing, the
# same graceful-degradation contract the segment admission gate gives scans.

def predicted_join_bytes(build_rows: int, probe_rows: int, ncols: int,
                         dup_factor: float = 1.0) -> int:
    """Metadata-only sizing of a device join's working set: the staged key
    codes for both sides (padded to the kernel's pow2 shapes) plus the
    expanded candidate index pairs. `dup_factor` is the build-side key
    duplication (rows / distinct keys) — the probe match-rate estimate's
    upper bound: every probe row matching `dup_factor` build rows."""
    def pow2(n: int) -> int:
        return 1 << (max(1, int(n)) - 1).bit_length()
    code_bytes = 4 * (pow2(build_rows) * 2 + pow2(probe_rows))
    out_rows = int(max(0.0, float(probe_rows)) * max(1.0, float(dup_factor)))
    # candidate (li, ri) int64 pairs + one gathered output column set
    pair_bytes = out_rows * 16
    out_bytes = out_rows * max(1, int(ncols)) * 8
    return int(code_bytes + pair_bytes + out_bytes)


def join_device_budget_bytes(headroom_pct: float = DEFAULT_TARGET_HEADROOM_PCT
                             ) -> int:
    """HBM bytes a device join may claim right now: target residency budget
    minus what the ledger already holds (0 when scans have HBM pinned)."""
    cap, _ = get_ledger().capacity_bytes()
    target = int(cap * (1.0 - max(0.0, min(99.0, headroom_pct)) / 100.0))
    return max(0, target - get_ledger().resident_bytes())


class _Admitted:
    """Book-keeping for one hot-tier resident: which TableDataManager owns
    it (for the refcount check + the segment handle), when a query last
    touched it (the coldness half of the eviction score), and the predicted
    bytes reserved at admission — counted against the target until the block
    actually stages, so a query admitting N segments back-to-back cannot
    over-commit the gate before any of them hit the ledger."""

    __slots__ = ("mgr", "last_access", "reserved")

    def __init__(self, mgr, reserved: int = 0):
        self.mgr = mgr
        self.last_access = time.monotonic()
        self.reserved = int(reserved)


class TieringManager:
    """Per-server hot-tier admission + eviction policy over the process
    MemoryLedger. One instance per ServerNode; in-process multi-server test
    clusters therefore run several managers against the shared ledger, which
    only makes each manager MORE conservative (it sees the process total)."""

    def __init__(self, catalog=None, node: str = ""):
        self._catalog = catalog
        self._node = node          # event journal label (the server's id)
        self._lock = threading.Lock()
        self._admitted: Dict[str, _Admitted] = {}
        self._counters = {"admissions": 0, "rejections": 0, "evictions": 0,
                          "promotions": 0, "coldLoads": 0}

    # -- policy inputs -------------------------------------------------------

    def _headroom_pct(self) -> float:
        if self._catalog is not None:
            try:
                raw = self._catalog.get_property(
                    "clusterConfig/server.hbm.target.headroom.pct", None)
                if raw is not None:
                    return max(0.0, min(99.0, float(raw)))
            except (TypeError, ValueError):
                pass
        return DEFAULT_TARGET_HEADROOM_PCT

    def target_bytes(self) -> int:
        """The resident-bytes budget: capacity minus the target headroom."""
        cap, _ = get_ledger().capacity_bytes()
        return max(1, int(cap * (1.0 - self._headroom_pct() / 100.0)))

    def _fused_pricing(self) -> bool:
        """Whether admission prices the COMPRESSED fused working set instead
        of the decoded one: on iff the cluster knob allows fusion and the
        calibrated caps regime enables it — exactly when queries skip the
        decoded HBM cache for single-value dict columns. Mispricing is
        safe in one direction only: a segment admitted on fused bytes whose
        query degrades to staged simply stages the decoded cache under the
        ledger (pressure eviction handles overshoot), while pricing decoded
        bytes for fused plans rejects segments that would have fit."""
        if self._catalog is not None:
            try:
                raw = self._catalog.get_property(
                    "clusterConfig/server.fused.enabled", "true")
                if str(raw).lower() == "false":
                    return False
            except (TypeError, ValueError):
                pass
        from ..engine.calibrate import get_caps
        return bool(get_caps().fused_enabled)

    def _reserved_bytes(self) -> int:
        """Predicted bytes of admitted-but-not-yet-staged blocks. A
        reservation expires the moment the block lands in the ledger (it
        would double-count) or the segment leaves its table manager."""
        total = 0
        with self._lock:
            for name, e in self._admitted.items():
                if not e.reserved:
                    continue
                seg = e.mgr.get(name) if e.mgr is not None else None
                if seg is None or has_block(seg):
                    e.reserved = 0
                else:
                    total += e.reserved
        return total

    # -- admission gate ------------------------------------------------------

    def admit(self, table: str, segment, mgr) -> bool:
        """Decide whether `segment` may stage its device block. Called in the
        query path BEFORE `block_for`; the caller routes rejected segments to
        the host plan. `mgr` is the owning TableDataManager (refcounts)."""
        name = getattr(segment, "name", str(segment))
        with self._lock:
            entry = self._admitted.get(name)
            if entry is not None and has_block(segment):
                entry.last_access = time.monotonic()   # hot-path touch
                return True
        try:
            need = predicted_block_bytes(segment, fused=self._fused_pricing())
        # graftcheck: ignore[exception-hygiene] -- a segment without sizing
        # metadata (synthetic test doubles) admits defensively; the ledger
        # still accounts whatever it actually stages
        except Exception:
            need = 0
        ledger = get_ledger()
        target = self.target_bytes()
        # in-flight reservations count: a query admits its whole segment set
        # before any block stages, so the ledger alone lags the commitment
        if need and ledger.resident_bytes() + self._reserved_bytes() \
                + need > target:
            self._evict_until(max(0, target - need - self._reserved_bytes()),
                              exclude={name})
        if need and ledger.resident_bytes() + self._reserved_bytes() \
                + need > target:
            with self._lock:
                self._counters["rejections"] += 1
            get_registry().counter(
                "pinot_server_hbm_admission_rejects",
                {"table": table}).inc()
            emit_event("tier.admission.rejected", node=self._node or None,
                       table=table, segment=name, neededBytes=need)
            return False
        with self._lock:
            self._counters["admissions"] += 1
            self._admitted[name] = _Admitted(mgr, reserved=need)
        return True

    def settle(self, names: Iterable[str]) -> None:
        """End-of-query hook: drop in-flight reservations for segments the
        query admitted but never staged (a COUNT(*) touches no columns, so
        no block lands in the ledger) — a reservation that outlives its
        query would starve every later admission against phantom bytes."""
        with self._lock:
            for name in names:
                e = self._admitted.get(name)
                if e is not None:
                    e.reserved = 0

    def note_promotion(self) -> None:
        """A freshly admitted segment actually staged (host→HBM)."""
        with self._lock:
            self._counters["promotions"] += 1

    def note_cold_load(self) -> None:
        """A COLD segment was lazily downloaded + loaded for a query."""
        with self._lock:
            self._counters["coldLoads"] += 1
        get_registry().counter("pinot_server_hbm_cold_loads").inc()

    def forget(self, name: str) -> None:
        """Unload hook: the segment left this server entirely (reconcile
        removal / table drop) — drop its admission entry without counting
        an eviction."""
        with self._lock:
            self._admitted.pop(name, None)

    # -- eviction ------------------------------------------------------------

    def _evict_until(self, budget_bytes: int,
                     exclude: Optional[Iterable[str]] = None) -> int:
        """Evict hot-tier residents, coldest-and-biggest first, until the
        ledger total is at or under `budget_bytes` or no victims remain.
        Residents with a drained refcount only — an in-flight query never
        loses its block. Returns the number of evictions."""
        excluded = set(exclude or ())
        ledger = get_ledger()
        now = time.monotonic()
        with self._lock:
            candidates = [
                (name, e) for name, e in self._admitted.items()
                if name not in excluded]
        # cost score: bytes * coldness — the biggest, least-recently-touched
        # block frees the most HBM per promotion we might regret
        scored = sorted(
            candidates,
            key=lambda ne: -(ledger.resident_bytes(segment=ne[0])
                             * max(now - ne[1].last_access, 1e-3)))
        evicted = 0
        for name, entry in scored:
            if ledger.resident_bytes() <= budget_bytes:
                break
            mgr = entry.mgr
            if mgr is not None and mgr.refcount(name) > 0:
                continue   # in-flight query holds it; the sweep retries later
            seg = mgr.get(name) if mgr is not None else None
            if seg is not None:
                release_block(seg)
            else:
                get_ledger().release(segment=name)
            with self._lock:
                self._admitted.pop(name, None)
                self._counters["evictions"] += 1
            get_registry().counter("pinot_server_hbm_evictions").inc()
            emit_event("tier.evicted", node=self._node or None, segment=name)
            evicted += 1
        return evicted

    def run_pressure_sweep(self) -> int:
        """Periodic-task body: walk residency back under the target. A no-op
        at or under target (the common case), so the loop is cheap."""
        target = self.target_bytes()
        if get_ledger().resident_bytes() <= target:
            return 0
        return self._evict_until(target)

    # -- observability -------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Rides the server's `/debug/memory` payload under `tiering` and is
        summed per table into the controller's memoryStatus verdicts."""
        with self._lock:
            out: Dict[str, Any] = dict(self._counters)
            out["admittedSegments"] = len(self._admitted)
        out["targetBytes"] = self.target_bytes()
        out["targetHeadroomPct"] = self._headroom_pct()
        return out
