"""Broker-side adaptive admission: the overload shed-state machine.

The static per-table QPS quota (`QueryQuotaManager`) caps each tenant's rate
but says nothing about the broker's own saturation — under a zipf-hot mix the
broker can be far below every per-table quota and still drown, taking every
tenant's p99 down together. This controller closes that gap with a three-state
machine driven by live signals:

  HEALTHY   — everything admits.
  SHEDDING  — in-flight depth crossed `broker.admission.queue.high` (or the
              recent dispatch-latency p99 crossed `broker.admission.latency.ms`
              when set): expensive scans shed, cheap served-path aggregations
              still admit. The expensive work is what holds worker slots for
              whole hedge delays; shedding it first keeps the served path fast.
  SATURATED — depth crossed `broker.admission.queue.max`: everything sheds,
              with a Retry-After hint so clients back off instead of hammering.

Independent of state, a query whose remaining `deadlineEpochMs` budget is
below the predicted service time (the recent dispatch-latency p99) is shed
up front: launching device work that cannot meet its deadline only steals
capacity from queries that still can (Tailwind framing: the host must keep
the chip fed with work that is still worth finishing).

Every shed is a typed `QueryRejectedError` plus a per-table
`pinot_broker_shed_queries` counter — overload is always visible, never
silent latency. Off by default (`broker.admission.enabled`), like hedging.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..constants import UNBOUNDED_LIMIT
from ..query.scheduler import QueryRejectedError
from ..utils.events import emit as emit_event

HEALTHY = "HEALTHY"
SHEDDING = "SHEDDING"
SATURATED = "SATURATED"
STATE_LEVEL = {HEALTHY: 0, SHEDDING: 1, SATURATED: 2}


class AdmissionController:
    #: dispatch-latency samples required before the p99 feeds shed decisions —
    #: an empty histogram must not reject the first queries of a quiet broker
    MIN_P99_SAMPLES = 8
    #: Retry-After fallback when the latency histogram has no samples yet
    DEFAULT_RETRY_MS = 50.0

    def __init__(self, catalog, node: str = ""):
        self.catalog = catalog
        self._node = node          # event journal label (the broker's id)
        self._lock = threading.Lock()
        self._inflight = 0
        self._state = HEALTHY
        self._admitted = 0
        self._sheds = 0
        self._shed_by_table: Dict[str, int] = {}
        self._shed_by_reason: Dict[str, int] = {}

    # -- clusterConfig knobs (all documented in README) ---------------------
    def _prop(self, key: str, default):
        v = self.catalog.get_property(f"clusterConfig/{key}", default)
        try:
            return float(v) if v not in (None, "") else float(default)
        except (TypeError, ValueError):
            return float(default)

    def enabled(self) -> bool:
        v = self.catalog.get_property("clusterConfig/broker.admission.enabled",
                                      False)
        return str(v).lower() in ("true", "1") if v is not None else False

    def _queue_high(self) -> float:
        return self._prop("broker.admission.queue.high", 16)

    def _queue_max(self) -> float:
        return self._prop("broker.admission.queue.max", 64)

    def _latency_threshold_ms(self) -> float:
        # 0 (default) = depth-driven only; latency joins the signal when set
        return self._prop("broker.admission.latency.ms", 0)

    def _expensive_limit(self) -> float:
        return self._prop("broker.admission.expensive.limit", 10000)

    # -- live signals -------------------------------------------------------
    def begin(self) -> None:
        """One query entered the broker (paired with `end` in a finally)."""
        from ..utils.metrics import get_registry
        with self._lock:
            self._inflight += 1
            n = self._inflight
        get_registry().gauge("pinot_broker_inflight_queries").set(n)

    def end(self) -> None:
        from ..utils.metrics import get_registry
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            n = self._inflight
        get_registry().gauge("pinot_broker_inflight_queries").set(n)

    def predicted_service_ms(self) -> tuple:
        """(recent dispatch-latency p99 in ms, sample count): the per-dispatch
        service-time estimate behind the deadline check and Retry-After."""
        from ..utils.metrics import get_registry
        return get_registry().histogram(
            "pinot_broker_dispatch_latency_ms").recent_percentile(0.99)

    def _compute_state(self, inflight: int, p99: float, n: int) -> str:
        if inflight >= self._queue_max():
            return SATURATED
        high = self._queue_high()
        lat = self._latency_threshold_ms()
        if inflight >= high \
                or (lat > 0 and n >= self.MIN_P99_SAMPLES and p99 >= lat):
            return SHEDDING
        # hysteresis: once shedding, stay there until depth falls to half the
        # trigger so the state doesn't flap at the boundary
        if self._state != HEALTHY and inflight > high * 0.5:
            return SHEDDING
        return HEALTHY

    def state(self) -> str:
        with self._lock:
            return self._state

    def overloaded(self) -> bool:
        """True while the shed-state machine is past HEALTHY — consumers like
        hedging use this to stop amplifying load."""
        return self.enabled() and self.state() != HEALTHY

    # -- the decision -------------------------------------------------------
    def is_expensive(self, ctx) -> bool:
        """Expensive = a selection scan with a large (or unbounded) LIMIT:
        no aggregation to collapse rows, so it holds a worker slot and
        materializes output proportional to its limit. Served-path
        aggregations/group-bys are the cheap class that keeps admitting in
        SHEDDING."""
        if getattr(ctx, "is_aggregation_query", False) or ctx.group_by:
            return False
        lim = ctx.limit if ctx.limit is not None else UNBOUNDED_LIMIT
        return lim >= self._expensive_limit()

    def admit(self, table: str, ctx) -> None:
        """Gate one query; raises QueryRejectedError on shed. Call AFTER the
        deadline is stamped on ctx.options so the budget check sees it."""
        if not self.enabled():
            return
        from ..utils.metrics import get_registry
        p99, n = self.predicted_service_ms()
        with self._lock:
            prev = self._state
            state = self._state = self._compute_state(self._inflight, p99, n)
            inflight = self._inflight
        get_registry().gauge("pinot_broker_shed_state").set(STATE_LEVEL[state])
        if state != prev:
            # edge-triggered: one event per flip, not one per admitted query
            emit_event("admission.state", node=self._node or None,
                       severity="INFO" if state == HEALTHY else "WARN",
                       fromState=prev, toState=state, inflight=inflight)

        # a query that cannot meet its own deadline shed up front, whatever
        # the state: the predicted per-dispatch service time already exceeds
        # the remaining budget, so launching it only wastes device capacity
        deadline_ms = None
        if ctx.options:
            try:
                deadline_ms = float(ctx.options.get("deadlineEpochMs"))
            except (TypeError, ValueError):
                deadline_ms = None
        if deadline_ms is not None and n >= self.MIN_P99_SAMPLES:
            remaining_ms = deadline_ms - time.time() * 1000.0
            if remaining_ms < p99:
                self._shed(table, "deadline",
                           f"query deadline budget {remaining_ms:.1f}ms is "
                           f"below the predicted service time {p99:.1f}ms")

        if state == SATURATED:
            self._shed(table, "saturated",
                       f"broker saturated ({self._inflight} queries in "
                       f"flight)",
                       retry_after_ms=p99 if p99 > 0 else self.DEFAULT_RETRY_MS)
        if state == SHEDDING and self.is_expensive(ctx):
            self._shed(table, "expensive",
                       f"broker shedding expensive scans under load "
                       f"({self._inflight} queries in flight)",
                       retry_after_ms=p99 if p99 > 0 else self.DEFAULT_RETRY_MS)
        with self._lock:
            self._admitted += 1

    def _shed(self, table: str, reason: str, message: str,
              retry_after_ms: Optional[float] = None) -> None:
        from ..utils.metrics import get_registry
        with self._lock:
            self._sheds += 1
            # graftcheck: ignore[unbounded-keyed-accumulation] -- key space
            # is the catalog's table set (topology-bounded, not query text)
            self._shed_by_table[table] = self._shed_by_table.get(table, 0) + 1
            # graftcheck: ignore[unbounded-keyed-accumulation] -- key space
            # is the fixed shed-reason enum
            self._shed_by_reason[reason] = \
                self._shed_by_reason.get(reason, 0) + 1
        get_registry().counter("pinot_broker_shed_queries",
                               {"table": table}).inc()
        raise QueryRejectedError(f"query shed ({reason}): {message}",
                                 retry_after_ms=retry_after_ms)

    def snapshot(self) -> Dict:
        """Operator view for /debug and cluster_top's admission panel."""
        p99, samples = self.predicted_service_ms()
        with self._lock:
            return {
                "enabled": self.enabled(),
                "state": self._state,
                "inflight": self._inflight,
                "admitted": self._admitted,
                "sheds": self._sheds,
                "shedByTable": dict(self._shed_by_table),
                "shedByReason": dict(self._shed_by_reason),
                "predictedServiceMs": round(p99, 3),
                "predictionSamples": samples,
                "queueHigh": self._queue_high(),
                "queueMax": self._queue_max(),
            }
