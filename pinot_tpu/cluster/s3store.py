"""S3-wire deep store: a PinotFS-analog speaking the S3 REST protocol.

Analog of the reference's cloud deep-store plugin
(`pinot-plugins/pinot-file-system/pinot-s3/src/main/java/org/apache/pinot/
plugin/filesystem/S3PinotFS.java`): segments and control blobs live in an
object store addressed by bucket/key over HTTP — PUT/GET/HEAD/DELETE objects
plus ListObjectsV2, with AWS Signature V4 request signing (optional; enabled
when credentials are configured, verified by the stub). The in-repo
`S3StubServer` proves the wire seam the same way `kafka_wire.py`'s vector
tests prove the stream seam: the client talks the real protocol, so pointing
it at actual S3/minio is a config change, not a code change.

Spec: `s3://bucket/prefix?endpoint=http://host:port[&accessKey=..&secretKey=..
&region=..]` (the endpoint is required — this build has zero egress, so there
is no default AWS endpoint to fall back to).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import threading
import urllib.error
import urllib.parse
# graftcheck: ignore[transport-bypass] -- external S3 endpoint, not the
# cluster data plane; SigV4-signed one-shot transfers gain nothing from the
# broker<->server keep-alive pool
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from .deepstore import DeepStoreFS

_EMPTY_SHA = hashlib.sha256(b"").hexdigest()


# ---------------------------------------------------------------------------
# AWS Signature Version 4 (public spec; the subset S3 object ops need)
# ---------------------------------------------------------------------------

def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_signature(secret_key: str, region: str, amz_date: str,
                    string_to_sign: str, service: str = "s3") -> str:
    date = amz_date[:8]
    k = _hmac(("AWS4" + secret_key).encode(), date)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    return hmac.new(k, string_to_sign.encode(), hashlib.sha256).hexdigest()


def sigv4_canonical(method: str, path: str, query: str, host: str,
                    amz_date: str, payload_sha: str) -> Tuple[str, str]:
    """(canonical request, signed headers). Signed header set is fixed:
    host;x-amz-content-sha256;x-amz-date — both sides agree by construction.

    `path` is the ON-WIRE (already percent-encoded) request path and is used
    VERBATIM: real S3 canonicalizes the once-encoded URI, so re-quoting here
    would turn '%20' into '%2520' and 403 against S3/minio for any key
    containing a space or special character."""
    cq = "&".join(sorted(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in urllib.parse.parse_qsl(query, keep_blank_values=True)))
    signed = "host;x-amz-content-sha256;x-amz-date"
    canonical = "\n".join([
        method,
        path,
        cq,
        f"host:{host}\nx-amz-content-sha256:{payload_sha}\n"
        f"x-amz-date:{amz_date}\n",
        signed,
        payload_sha,
    ])
    return canonical, signed


def sigv4_string_to_sign(canonical: str, amz_date: str, region: str,
                         service: str = "s3") -> str:
    scope = f"{amz_date[:8]}/{region}/{service}/aws4_request"
    return "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                      hashlib.sha256(canonical.encode()).hexdigest()])


def sign_request(method: str, url: str, payload: bytes, access_key: str,
                 secret_key: str, region: str,
                 amz_date: Optional[str] = None,
                 payload_sha: Optional[str] = None,
                 service: str = "s3") -> Dict[str, str]:
    """Headers for a sigv4-signed S3 request (spec: Authorization header
    form). `amz_date` is injectable for golden tests; `payload_sha` lets
    streaming uploads pre-hash the body without buffering it."""
    parsed = urllib.parse.urlparse(url)
    if amz_date is None:
        amz_date = datetime.datetime.now(datetime.timezone.utc
                                         ).strftime("%Y%m%dT%H%M%SZ")
    if payload_sha is None:
        payload_sha = hashlib.sha256(payload or b"").hexdigest()
    canonical, signed = sigv4_canonical(method, parsed.path, parsed.query,
                                        parsed.netloc, amz_date, payload_sha)
    sts = sigv4_string_to_sign(canonical, amz_date, region, service)
    sig = sigv4_signature(secret_key, region, amz_date, sts, service)
    scope = f"{amz_date[:8]}/{region}/{service}/aws4_request"
    return {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_sha,
        "Authorization": (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
                          f"SignedHeaders={signed}, Signature={sig}"),
    }


def sigv4_verify(headers, method: str, path: str, query: str, body: bytes,
                 access_key: str, secret_key: str, region: str,
                 service: str = "s3") -> bool:
    """Stub-side verification (shared by S3StubServer and KinesisStub):
    payload-hash binding, Credential access-key match, signature match."""
    import hmac as _hmac2
    auth = headers.get("Authorization", "")
    amz_date = headers.get("x-amz-date", "")
    sha = headers.get("x-amz-content-sha256", "")
    if not auth.startswith("AWS4-HMAC-SHA256") or not amz_date:
        return False
    if hashlib.sha256(body).hexdigest() != sha:
        return False
    canonical, _ = sigv4_canonical(method, path, query,
                                   headers.get("Host", ""), amz_date, sha)
    sts = sigv4_string_to_sign(canonical, amz_date, region, service)
    want = sigv4_signature(secret_key, region, amz_date, sts, service)
    got = auth.rsplit("Signature=", 1)[-1].strip()
    cred = auth.split("Credential=", 1)[-1].split("/", 1)[0]
    return cred == access_key and _hmac2.compare_digest(want, got)


# ---------------------------------------------------------------------------
# client: the deep-store FS
# ---------------------------------------------------------------------------

class S3Error(OSError):
    def __init__(self, status: int, code: str, message: str = ""):
        super().__init__(f"S3 {status} {code}: {message}")
        self.status = status
        self.code = code


def _raise_s3_error(e: "urllib.error.HTTPError") -> None:
    """ONE translation of an S3 HTTP error body to S3Error (every operation
    must raise the same shape for the same failure)."""
    payload = e.read()
    code = "Unknown"
    if b"<Code>" in payload:
        code = payload.split(b"<Code>")[1].split(b"</Code>")[0].decode()
    raise S3Error(e.code, code,
                  payload[:200].decode(errors="replace")) from None


from .deepstore import RemoteObjectFS


class S3DeepStoreFS(RemoteObjectFS):
    """Bytes-by-URI against an S3 endpoint (same shape as MemDeepStore: no
    rename — move() is the base class's copy+delete, exactly like
    S3PinotFS.move doing copyObject+delete). Spec parsing / recursive delete
    / existence semantics are the RemoteObjectFS contract; this class is the
    S3 wire (sigv4, ListObjectsV2 pagination, XML)."""

    scheme = "s3"

    def __init__(self, root: str):
        params = self._parse_spec(root, "s3")
        self.access_key = params.get("accessKey", "")
        self.secret_key = params.get("secretKey", "")
        self.region = params.get("region", "us-east-1")

    # -- wire ---------------------------------------------------------------
    def _url(self, key: str, query: str = "") -> str:
        path = f"/{self.bucket}/{urllib.parse.quote(key)}" if key \
            else f"/{self.bucket}"
        return f"{self.endpoint}{path}" + (f"?{query}" if query else "")

    def _call(self, method: str, url: str, body: Optional[bytes] = None
              ) -> Tuple[int, bytes]:
        headers = {}
        if self.access_key:
            headers = sign_request(method, url, body or b"", self.access_key,
                                   self.secret_key, self.region)
        req = urllib.request.Request(url, data=body, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            _raise_s3_error(e)

    # -- DeepStoreFS --------------------------------------------------------
    def upload(self, local_path: str, uri: str) -> None:
        """STREAMING put: the payload hash is computed in one pass and the
        body is sent from the open file — a multi-GB segment tar never
        buffers in memory (LocalDeepStore streams the same way)."""
        size = os.path.getsize(local_path)
        sha = hashlib.sha256()
        with open(local_path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                sha.update(chunk)
        url = self._url(self._key(uri))
        headers = {"Content-Length": str(size)}
        if self.access_key:
            headers.update(sign_request("PUT", url, b"", self.access_key,
                                        self.secret_key, self.region,
                                        payload_sha=sha.hexdigest()))
        with open(local_path, "rb") as f:
            req = urllib.request.Request(url, data=f, method="PUT",
                                         headers=headers)
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout_s) as resp:
                    resp.read()
            except urllib.error.HTTPError as e:
                _raise_s3_error(e)

    def put_bytes(self, data: bytes, uri: str) -> None:
        self._call("PUT", self._url(self._key(uri)), data)

    def get_bytes(self, uri: str) -> bytes:
        try:
            _, data = self._call("GET", self._url(self._key(uri)))
            return data
        except S3Error as e:
            if e.status == 404:
                raise FileNotFoundError(f"s3://{self.bucket}/{self._key(uri)}"
                                        ) from None
            raise

    def _delete_object(self, key: str) -> None:
        self._call("DELETE", self._url(key))

    def _head_ok(self, key: str) -> bool:
        try:
            self._call("HEAD", self._url(key))
            return True
        except S3Error as e:
            if e.status != 404:
                raise
            return False

    def _list_page(self, prefix: str, delimiter: str, token: str
                   ) -> Tuple[List[str], List[str], str]:
        """One ListObjectsV2 page -> (keys, common prefixes, next token)."""
        params = {"list-type": "2", "prefix": prefix,
                  "max-keys": str(self.page_size)}
        if delimiter:
            params["delimiter"] = delimiter
        if token:
            params["continuation-token"] = token
        _, payload = self._call("GET", self._url("",
                                                 urllib.parse.urlencode(params)))
        from xml.sax.saxutils import unescape
        # real S3 XML-escapes key text (&amp; etc.) — unescape or recursive
        # delete would target non-existent keys and silently orphan objects
        keys = [unescape(seg.split(b"</Key>")[0].decode())
                for seg in payload.split(b"<Key>")[1:]]
        prefixes = [unescape(seg.split(b"</Prefix>")[0].decode())
                    for seg in payload.split(b"<CommonPrefixes><Prefix>")[1:]]
        nxt = ""
        if b"<IsTruncated>true</IsTruncated>" in payload:
            nxt = unescape(payload.split(b"<NextContinuationToken>")[1].split(
                b"</NextContinuationToken>")[0].decode())
        return keys, prefixes, nxt

    def _list_keys(self, prefix: str, delimiter: str = "",
                   limit: int = 1 << 31) -> List[str]:
        """Full listing across pagination (real S3 caps a page at 1000 —
        IsTruncated/continuation-token MUST be followed or recursive delete
        and listdir silently see a partial view)."""
        keys: List[str] = []
        token = ""
        while True:
            page, _, token = self._list_page(prefix, delimiter, token)
            keys.extend(page)
            if not token or len(keys) >= limit:
                return keys[:limit] if limit < (1 << 31) else keys

    def listdir(self, uri: str) -> List[str]:
        key = self._key(uri)
        prefix = key.rstrip("/") + "/" if key else (
            f"{self.prefix}/" if self.prefix else "")
        names: set = set()
        token = ""
        while True:
            page, prefixes, token = self._list_page(prefix, "/", token)
            names |= {k[len(prefix):] for k in page}
            names |= {p[len(prefix):].rstrip("/") for p in prefixes}
            if not token:
                break
        return sorted(n for n in names if n)


# ---------------------------------------------------------------------------
# in-repo stub server (the wire-seam proof; reference analog: S3 itself)
# ---------------------------------------------------------------------------

class S3StubServer:
    """Minimal S3 REST endpoint: object PUT/GET/HEAD/DELETE + ListObjectsV2,
    sigv4 verification when credentials are set, and an `outage` switch for
    chaos tests (every request 503s, like an unreachable region)."""

    def __init__(self, bucket: str = "pinot", access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 host: str = "127.0.0.1", port: int = 0):
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.objects: Dict[str, bytes] = {}
        self.outage = False
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _xml_error(self, status: int, code: str) -> None:
                body = (f'<?xml version="1.0"?><Error><Code>{code}</Code>'
                        f"</Error>").encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/xml")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _ok(self, body: bytes = b"",
                    ctype: str = "application/octet-stream",
                    head_only: bool = False) -> None:
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.send_header("ETag", '"%s"' % hashlib.md5(body).hexdigest())
                self.end_headers()
                if not head_only and self.command != "HEAD":
                    self.wfile.write(body)

            def _authorized(self, payload: bytes) -> bool:
                if not stub.access_key:
                    return True
                parsed = urllib.parse.urlparse(self.path)
                return sigv4_verify(self.headers, self.command, parsed.path,
                                    parsed.query, payload, stub.access_key,
                                    stub.secret_key, stub.region)

            def _dispatch(self) -> None:
                if stub.outage:
                    return self._xml_error(503, "SlowDown")
                parsed = urllib.parse.urlparse(self.path)
                parts = parsed.path.lstrip("/").split("/", 1)
                if parts[0] != stub.bucket:
                    return self._xml_error(404, "NoSuchBucket")
                key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
                length = int(self.headers.get("Content-Length") or 0)
                payload = self.rfile.read(length) if length else b""
                if not self._authorized(payload):
                    return self._xml_error(403, "SignatureDoesNotMatch")
                params = dict(urllib.parse.parse_qsl(parsed.query))

                if self.command == "PUT":
                    with stub._lock:
                        stub.objects[key] = payload
                    return self._ok()
                if self.command in ("GET", "HEAD") and not key \
                        and params.get("list-type") == "2":
                    return self._ok(stub._list_xml(params),
                                    ctype="application/xml")
                if self.command in ("GET", "HEAD"):
                    with stub._lock:
                        data = stub.objects.get(key)
                    if data is None:
                        return self._xml_error(404, "NoSuchKey")
                    return self._ok(data, head_only=self.command == "HEAD")
                if self.command == "DELETE":
                    with stub._lock:
                        stub.objects.pop(key, None)
                    body = b""
                    self.send_response(204)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return None
                return self._xml_error(405, "MethodNotAllowed")

            do_GET = do_PUT = do_DELETE = do_HEAD = \
                lambda self: self._dispatch()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.url = f"http://{host}:{self._server.server_address[1]}"
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="s3-stub")
        self._thread.start()

    def _list_xml(self, params: Dict[str, str]) -> bytes:
        """ListObjectsV2 with real-S3 pagination semantics: max-keys caps the
        page (hard cap 1000 like S3), IsTruncated + NextContinuationToken
        mark more pages, continuation-token resumes strictly after the marked
        item — clients that ignore truncation see a partial view, exactly the
        bug the pagination loop in S3DeepStoreFS exists to prevent."""
        prefix = params.get("prefix", "")
        delimiter = params.get("delimiter", "")
        max_keys = min(int(params.get("max-keys", "1000")), 1000)
        token = params.get("continuation-token", "")
        with self._lock:
            keys = sorted(k for k in self.objects if k.startswith(prefix))
            sizes = {k: len(self.objects[k]) for k in keys}
        # one sorted item stream of content keys + collapsed common prefixes
        items: List[Tuple[str, bool]] = []    # (marker, is_common_prefix)
        seen = set()
        for k in keys:
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                    if cp not in seen:
                        seen.add(cp)
                        items.append((cp, True))
                    continue
            items.append((k, False))
        from xml.sax.saxutils import escape
        after = [it for it in items if it[0] > token]
        page, more = after[:max_keys], after[max_keys:]
        xml = ['<?xml version="1.0"?><ListBucketResult>',
               f"<IsTruncated>{'true' if more else 'false'}</IsTruncated>"]
        if more:
            xml.append(f"<NextContinuationToken>{escape(page[-1][0])}"
                       f"</NextContinuationToken>")
        for marker, is_cp in page:
            if is_cp:
                xml.append(f"<CommonPrefixes><Prefix>{escape(marker)}</Prefix>"
                           f"</CommonPrefixes>")
            else:
                xml.append(f"<Contents><Key>{escape(marker)}</Key>"
                           f"<Size>{sizes.get(marker, 0)}</Size></Contents>")
        xml.append("</ListBucketResult>")
        return "".join(xml).encode()

    def spec(self, prefix: str = "") -> str:
        """The s3:// deep-store spec pointing at this stub."""
        auth = (f"&accessKey={self.access_key}&secretKey={self.secret_key}"
                f"&region={self.region}" if self.access_key else "")
        p = f"/{prefix}" if prefix else ""
        return f"s3://{self.bucket}{p}?endpoint={self.url}{auth}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
