"""Multi-process cluster: each role as an OS process, joined over HTTP.

This is the real deployment shape (reference: one JVM per role started by
`PinotAdministrator` Start*Command; here one Python process per role started by
`python -m pinot_tpu.cluster.process` or the admin CLI). The controller owns the
catalog + deep store; servers and brokers join with `RemoteCatalog` (watch-based
mirror) and talk data-plane over the binary wire format.

`ProcessCluster` is the test/quickstart harness that spawns the processes and waits
for readiness (reference: ClusterTest boots embedded roles; here they are genuinely
separate processes so a kill is a real process death).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.parse
from typing import Dict, List, Optional, Sequence

from .http_service import HttpError, get_json, http_call, post_json


def _write_ready(run_dir: str, name: str, payload: Dict) -> None:
    path = os.path.join(run_dir, f"{name}.ready")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _load_config(config_path: str, cli_port: int, port_key: str):
    """Shared config stack for role starters. The CLI port (when explicitly
    given) is the topmost override layer, matching the documented precedence
    explicit args > env > files > defaults."""
    from .. import plugins
    from ..config import Configuration
    overrides = {port_key: cli_port} if cli_port else {}
    cfg = Configuration.load(config_path or None, overrides=overrides)
    plugins.load_from_config(cfg)
    return cfg


def _setup_auth(cfg):
    """Access control for this role's endpoints + this process's outgoing
    identity (reference: BasicAuthAccessControlFactory + per-service tokens)."""
    from ..auth import StaticTokenAccessControl
    from .http_service import set_default_token
    set_default_token(cfg.get_str("auth.service.token"))
    return StaticTokenAccessControl.from_config(cfg)


def _apply_client_tls(cfg) -> bool:
    """Apply the config's tls.* trust to THIS process's outgoing clients.
    Returns whether TLS is enabled (one parser for every consumer)."""
    from .http_service import set_default_tls
    if not cfg.get_bool("tls.enabled"):
        return False
    set_default_tls(cafile=cfg.get_str("tls.ca"),
                    insecure=cfg.get_bool("tls.insecure"))
    return True


def _setup_tls(cfg):
    """Server-side SSL context + this process's outgoing trust, from tls.*
    config (reference: pinot.*.tls.* keystore/truststore keys,
    TlsIntegrationTest): `tls.enabled`, `tls.cert`/`tls.key` (PEM), `tls.ca`
    (the cluster's CA bundle — self-signed in tests)."""
    if not _apply_client_tls(cfg):
        return None
    import ssl
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cfg.get_str("tls.cert"), cfg.get_str("tls.key"))
    return ctx


def run_controller(work_dir: str, run_dir: str, port: int = 0,
                   config_path: str = "") -> None:
    from .catalog import Catalog
    from .controller import Controller
    from .deepstore import create_fs
    from .services import ControllerService

    cfg = _load_config(config_path, port, "controller.port")
    access_control = _setup_auth(cfg)
    ssl_ctx = _setup_tls(cfg)
    catalog = Catalog()
    # deep store is configurable by scheme (reference:
    # controller.data.dir + pinot.controller.storage.factory.class.*),
    # optionally wrapped by the segment crypter (encryption at rest)
    from ..crypt import wrap_deepstore_from_config
    deepstore = wrap_deepstore_from_config(create_fs(cfg.get_str(
        "controller.deepstore",
        f"local://{os.path.join(work_dir, 'deepstore')}")), cfg)
    controller = Controller("controller_0", catalog, deepstore,
                            os.path.join(work_dir, "controller"))
    svc = ControllerService(controller, port=cfg.get_int("controller.port", 0),
                            access_control=access_control,
                            ssl_context=ssl_ctx)
    controller.start_periodic_tasks()  # retention/repair/relocation/status
    _write_ready(run_dir, "controller_0", {"url": svc.url})
    signal.sigwait({signal.SIGTERM, signal.SIGINT})
    controller.stop_periodic_tasks()


def run_server(controller_url: str, instance_id: str, work_dir: str,
               run_dir: str, port: int = 0, config_path: str = "") -> None:
    from ..query.scheduler import scheduler_from_config
    from .remote import ControllerDeepStore, RemoteCatalog, RemoteCompletion
    from .server import ServerNode
    from .services import ServerService

    # defaults < config file < PINOT_TPU_* env < CLI args (reference:
    # PinotConfiguration stack consumed by HelixServerStarter)
    cfg = _load_config(config_path, port, "server.port")
    access_control = _setup_auth(cfg)
    ssl_ctx = _setup_tls(cfg)
    catalog = RemoteCatalog(controller_url)
    deepstore = ControllerDeepStore(controller_url)
    from .device_server import pipeline_from_config
    server = ServerNode(instance_id, catalog, deepstore,
                        os.path.join(work_dir, instance_id),
                        tags=cfg.get_list("server.tenant.tags") or None,
                        completion=RemoteCompletion(controller_url),
                        scheduler=scheduler_from_config(cfg),
                        auto_consume=True,  # real processes pump themselves
                        device_pipeline=pipeline_from_config(cfg))
    svc = ServerService(server, port=cfg.get_int("server.port", 0),
                        access_control=access_control, ssl_context=ssl_ctx)
    _write_ready(run_dir, instance_id, {"url": svc.url})
    signal.sigwait({signal.SIGTERM, signal.SIGINT})
    server.shutdown()


def run_minion(controller_url: str, instance_id: str, work_dir: str,
               run_dir: str, port: int = 0, config_path: str = "") -> None:
    """Minion role process (reference: MinionStarter): joins via RemoteCatalog,
    claims tasks through the controller's atomic REST queue, fetches inputs
    through the deep-store proxy, pushes outputs through the standard segment
    upload/replace endpoints."""
    from ..minion.tasks import MinionWorker
    from .remote import (ControllerDeepStore, RemoteCatalog, RemoteController,
                         RemoteTaskQueue)
    from .services import MinionService

    cfg = _load_config(config_path, port, "minion.port")
    access_control = _setup_auth(cfg)
    ssl_ctx = _setup_tls(cfg)
    catalog = RemoteCatalog(controller_url)
    worker = MinionWorker(instance_id, catalog,
                          ControllerDeepStore(controller_url),
                          RemoteController(controller_url,
                                           cfg.get_str("auth.service.token")),
                          os.path.join(work_dir, instance_id),
                          queue=RemoteTaskQueue(controller_url))
    svc = MinionService(worker, port=cfg.get_int("minion.port", 0),
                        poll_s=cfg.get_float("minion.poll.seconds", 1.0),
                        access_control=access_control, ssl_context=ssl_ctx)
    _write_ready(run_dir, instance_id, {"url": svc.url})
    signal.sigwait({signal.SIGTERM, signal.SIGINT})
    svc.stop()
    catalog.close()


def run_broker(controller_url: str, instance_id: str, run_dir: str,
               port: int = 0, config_path: str = "") -> None:
    from .broker import Broker
    from .remote import RemoteCatalog
    from .services import BrokerService

    cfg = _load_config(config_path, port, "broker.port")
    access_control = _setup_auth(cfg)
    ssl_ctx = _setup_tls(cfg)
    catalog = RemoteCatalog(controller_url)
    broker = Broker(instance_id, catalog,
                    max_scatter_threads=cfg.get_int("broker.scatter.threads", 8))
    svc = BrokerService(broker, port=cfg.get_int("broker.port", 0),
                        access_control=access_control, ssl_context=ssl_ctx)
    _write_ready(run_dir, instance_id, {"url": svc.url})
    signal.sigwait({signal.SIGTERM, signal.SIGINT})


def run_service_manager(work_dir: str, run_dir: str, port: int = 0,
                        config_path: str = "", block: bool = True):
    """All roles in ONE process from one bootstrap config (reference:
    PinotServiceManager / StartServiceManagerCommand — the quickstarts' and
    small deployments' topology). Controller, one server, and a broker share
    the process; the server/broker still talk to the controller over its HTTP
    catalog so the wiring matches a distributed deployment."""
    from .broker import Broker
    from .catalog import Catalog
    from .controller import Controller
    from .deepstore import create_fs
    from .remote import ControllerDeepStore, RemoteCatalog, RemoteCompletion
    from .server import ServerNode
    from .services import BrokerService, ControllerService, ServerService

    os.makedirs(run_dir, exist_ok=True)
    cfg = _load_config(config_path, port, "controller.port")
    access_control = _setup_auth(cfg)
    ssl_ctx = _setup_tls(cfg)
    from ..crypt import wrap_deepstore_from_config
    catalog = Catalog()
    deepstore = wrap_deepstore_from_config(create_fs(cfg.get_str(
        "controller.deepstore",
        f"local://{os.path.join(work_dir, 'deepstore')}")), cfg)
    controller = Controller("controller_0", catalog, deepstore,
                            os.path.join(work_dir, "controller"))
    csvc = ControllerService(controller, port=cfg.get_int("controller.port", 0),
                             access_control=access_control,
                             ssl_context=ssl_ctx)
    controller.start_periodic_tasks()

    from ..query.scheduler import scheduler_from_config
    from .device_server import pipeline_from_config
    server_catalog = RemoteCatalog(csvc.url)
    server = ServerNode("server_0", server_catalog,
                        ControllerDeepStore(csvc.url),
                        os.path.join(work_dir, "server_0"),
                        tags=cfg.get_list("server.tenant.tags") or None,
                        completion=RemoteCompletion(csvc.url),
                        scheduler=scheduler_from_config(cfg),
                        auto_consume=True,
                        device_pipeline=pipeline_from_config(cfg))
    ssvc = ServerService(server, port=cfg.get_int("server.port", 0),
                         access_control=access_control, ssl_context=ssl_ctx)

    broker_catalog = RemoteCatalog(csvc.url)
    broker = Broker("broker_0", broker_catalog,
                    max_scatter_threads=cfg.get_int("broker.scatter.threads", 8))
    bsvc = BrokerService(broker, port=cfg.get_int("broker.port", 0),
                         access_control=access_control, ssl_context=ssl_ctx)

    from ..minion.tasks import MinionWorker
    from .remote import RemoteController, RemoteTaskQueue
    from .services import MinionService
    minion_catalog = RemoteCatalog(csvc.url)
    minion = MinionWorker("minion_0", minion_catalog,
                          ControllerDeepStore(csvc.url),
                          RemoteController(csvc.url,
                                           cfg.get_str("auth.service.token")),
                          os.path.join(work_dir, "minion_0"),
                          queue=RemoteTaskQueue(csvc.url))
    msvc = MinionService(minion, port=cfg.get_int("minion.port", 0),
                         poll_s=cfg.get_float("minion.poll.seconds", 1.0),
                         access_control=access_control, ssl_context=ssl_ctx)
    _write_ready(run_dir, "controller_0", {"url": csvc.url})
    _write_ready(run_dir, "server_0", {"url": ssvc.url})
    _write_ready(run_dir, "broker_0", {"url": bsvc.url})
    _write_ready(run_dir, "minion_0", {"url": msvc.url})
    handles = {"controller": csvc, "server": ssvc, "broker": bsvc,
               "minion": msvc,
               "catalogs": (server_catalog, broker_catalog, minion_catalog),
               "controller_obj": controller, "server_obj": server,
               "minion_obj": minion}
    if block:
        signal.sigwait({signal.SIGTERM, signal.SIGINT})
        # graceful teardown, same order as the per-role processes: server
        # first (consuming handlers flush/stop), then periodic tasks/watchers
        msvc.stop()
        server.shutdown()
        controller.stop_periodic_tasks()
        for c in (server_catalog, broker_catalog, minion_catalog):
            c.close()
        return None
    return handles


def main(argv: Optional[Sequence[str]] = None) -> None:
    p = argparse.ArgumentParser(prog="pinot_tpu.cluster.process")
    p.add_argument("--role", required=True,
                   choices=["controller", "server", "broker", "minion",
                            "service-manager"])
    p.add_argument("--controller-url", default="")
    p.add_argument("--instance-id", default="")
    p.add_argument("--work-dir", default="")
    p.add_argument("--run-dir", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--config", default="", help="properties/json config file")
    a = p.parse_args(argv)
    if a.role == "controller":
        run_controller(a.work_dir, a.run_dir, a.port, config_path=a.config)
    elif a.role == "server":
        run_server(a.controller_url, a.instance_id, a.work_dir, a.run_dir, a.port,
                   config_path=a.config)
    elif a.role == "minion":
        run_minion(a.controller_url, a.instance_id, a.work_dir, a.run_dir,
                   a.port, config_path=a.config)
    elif a.role == "service-manager":
        run_service_manager(a.work_dir, a.run_dir, a.port, config_path=a.config)
    else:
        run_broker(a.controller_url, a.instance_id, a.run_dir, a.port,
                   config_path=a.config)


class ControllerClient:
    """HTTP admin client for a controller (reference: the java-client /
    controller REST API consumers). `token` is per-client: each request carries
    it explicitly, never via process-global state."""

    def __init__(self, url: str, token: Optional[str] = None):
        self.url = url.rstrip("/")
        self.token = token

    def add_schema(self, schema) -> None:
        post_json(f"{self.url}/schemas", schema.to_json(), token=self.token)

    def add_table(self, config, num_partitions: int = 1) -> Dict:
        return post_json(f"{self.url}/tables",
                         {"config": config.to_json(),
                          "numPartitions": num_partitions}, token=self.token)

    def drop_table(self, table: str) -> None:
        http_call("DELETE", f"{self.url}/tables/{table}", token=self.token)

    def upload_segment(self, table: str, segment_dir: str) -> Dict:
        """Tar a built segment dir and push it (reference: segment tar push)."""
        from .deepstore import tar_segment
        name = os.path.basename(segment_dir.rstrip("/"))
        with tempfile.TemporaryDirectory() as tmp:
            tar_path = os.path.join(tmp, f"{name}.tar.gz")
            tar_segment(segment_dir, tar_path)
            with open(tar_path, "rb") as f:
                payload = f.read()
        q = urllib.parse.urlencode({"name": name})
        return json.loads(http_call(
            "POST", f"{self.url}/segments/{table}?{q}", payload,
            content_type="application/octet-stream", timeout=120.0,
            token=self.token).decode())

    def table_status(self, table: str) -> Dict:
        return get_json(f"{self.url}/tableStatus/{table}", token=self.token)

    def get_schema(self, name: str) -> Dict:
        return get_json(f"{self.url}/schemas/{name}", token=self.token)

    def list_tables(self) -> Dict:
        return get_json(f"{self.url}/tables", token=self.token)

    def table_config(self, table: str) -> Dict:
        return get_json(f"{self.url}/tables/{table}", token=self.token)

    def segments_meta(self, table: str) -> Dict:
        return get_json(f"{self.url}/segmentsMeta/{table}", token=self.token)

    def reload_table(self, table: str) -> Dict:
        return post_json(f"{self.url}/reload/{table}", {}, token=self.token)

    def rebalance(self, table: str) -> Dict:
        return post_json(f"{self.url}/rebalance/{table}", {}, token=self.token)


class BrokerClient:
    def __init__(self, url: str, token: Optional[str] = None):
        self.url = url.rstrip("/")
        self.token = token

    def query(self, sql: str, timeout: float = 120.0) -> Dict:
        return post_json(f"{self.url}/query", {"sql": sql}, timeout=timeout,
                         token=self.token)

    def query_stream(self, sql: str, timeout: float = 600.0):
        """Incremental results: yields the columns list first, then row
        batches as the broker streams them (chunked HTTP; reference: the gRPC
        streaming query endpoint). Use for large exports — rows are consumed
        without buffering the full result anywhere."""
        # graftcheck: ignore[transport-bypass] -- line-oriented response
        # streaming (iterates the raw response); the pooled client exposes
        # block reads only, and an export-sized stream amortizes its own
        # connection
        import urllib.request

        from .http_service import client_ssl_context
        req = urllib.request.Request(
            f"{self.url}/queryStream",
            data=json.dumps({"sql": sql}).encode(),
            headers={"Content-Type": "application/json",
                     **({"Authorization": f"Bearer {self.token}"}
                        if self.token else {})})
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=client_ssl_context()) as resp:
            for line in resp:
                if not line.strip():
                    continue
                d = json.loads(line)
                if "error" in d:
                    # a mid-stream failure arrives as the final event (headers
                    # were already 200/chunked by then)
                    raise RuntimeError(f"stream failed: {d['error']}")
                if "columns" in d:
                    yield ("schema", d["columns"])
                else:
                    yield ("rows", d["rows"])


class ProcessCluster:
    """Spawn controller + N servers + broker as OS processes and wait for ready.

    Server processes are pinned to CPU JAX by default (`JAX_PLATFORMS=cpu`) so a
    test cluster doesn't fight over the single TPU; production servers would own
    their chip(s).
    """

    def __init__(self, num_servers: int = 2, work_dir: Optional[str] = None,
                 server_env: Optional[Dict[str, str]] = None,
                 startup_timeout_s: float = 60.0, num_minions: int = 0,
                 config_path: str = ""):
        self.work_dir = work_dir or tempfile.mkdtemp(prefix="pinot_tpu_proc_")
        self.run_dir = os.path.join(self.work_dir, "run")
        os.makedirs(self.run_dir, exist_ok=True)
        self.procs: Dict[str, subprocess.Popen] = {}
        self._timeout = startup_timeout_s
        self._config_path = config_path
        if config_path:
            # the config is the single source of truth: apply its tls.* trust
            # to THIS process's clients too, so cluster.query() works against
            # the TLS cluster we are about to start without a separate
            # set_default_tls call
            from ..config import Configuration
            _apply_client_tls(Configuration.load(config_path))

        env = dict(os.environ)
        # scrub any TPU-tunnel plugin hooks: role subprocesses default to CPU jax
        # (same scrub as tests/conftest.py); production servers own their chips.
        env["JAX_PLATFORMS"] = env.get("PINOT_TPU_SUBPROCESS_PLATFORM", "cpu")
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and "axon_site" not in p) or os.getcwd()
        env.pop("XLA_FLAGS", None)
        if server_env:
            env.update(server_env)
        self._env = env

        self._spawn("controller_0", ["--role", "controller",
                                     "--work-dir", self.work_dir])
        self.controller_url = self._await_ready("controller_0")
        for i in range(num_servers):
            sid = f"server_{i}"
            self._spawn(sid, ["--role", "server", "--instance-id", sid,
                              "--controller-url", self.controller_url,
                              "--work-dir", self.work_dir])
        for i in range(num_servers):
            self._await_ready(f"server_{i}")
        self._spawn("broker_0", ["--role", "broker", "--instance-id", "broker_0",
                                 "--controller-url", self.controller_url])
        for i in range(num_minions):
            mid = f"minion_{i}"
            self._spawn(mid, ["--role", "minion", "--instance-id", mid,
                              "--controller-url", self.controller_url,
                              "--work-dir", self.work_dir])
        self.broker_url = self._await_ready("broker_0")
        for i in range(num_minions):
            self._await_ready(f"minion_{i}")
        self.controller = ControllerClient(self.controller_url)
        self.broker = BrokerClient(self.broker_url)

    def _spawn(self, name: str, args: List[str]) -> None:
        cmd = [sys.executable, "-m", "pinot_tpu.cluster.process",
               "--run-dir", self.run_dir] + args
        if self._config_path:
            cmd += ["--config", self._config_path]
        with open(os.path.join(self.run_dir, f"{name}.log"), "wb") as log:
            # the child holds its own dup of the fd; close the parent's copy
            # graftcheck: ignore[unbounded-keyed-accumulation] -- one handle
            # per launched OS process (cluster topology, reaped on stop)
            self.procs[name] = subprocess.Popen(
                cmd, env=self._env, stdout=log, stderr=subprocess.STDOUT)

    def _await_ready(self, name: str) -> str:
        path = os.path.join(self.run_dir, f"{name}.ready")
        deadline = time.time() + self._timeout
        while time.time() < deadline:
            if os.path.exists(path):
                with open(path) as f:
                    return json.load(f)["url"]
            proc = self.procs.get(name)
            if proc is not None and proc.poll() is not None:
                log = open(os.path.join(self.run_dir, f"{name}.log")).read()
                raise RuntimeError(f"{name} died at startup:\n{log[-4000:]}")
            time.sleep(0.05)
        raise TimeoutError(f"{name} not ready after {self._timeout}s")

    def query(self, sql: str) -> Dict:
        return self.broker.query(sql)

    def kill_server(self, instance_id: str) -> None:
        """SIGKILL a server process — a real process death, not a flag flip."""
        proc = self.procs.get(instance_id)
        if proc is not None:
            proc.kill()
            proc.wait()

    def _restart(self, instance_id: str, role: str) -> str:
        proc = self.procs.get(instance_id)
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        ready = os.path.join(self.run_dir, f"{instance_id}.ready")
        if os.path.exists(ready):
            os.remove(ready)  # _await_ready must see the NEW process's file
        self._spawn(instance_id, ["--role", role,
                                  "--instance-id", instance_id,
                                  "--controller-url", self.controller_url,
                                  "--work-dir", self.work_dir])
        return self._await_ready(instance_id)

    def restart_server(self, instance_id: str) -> str:
        """Start a fresh server process under the same instance id (reference:
        server restart recovery — it re-registers, reloads its assigned
        segments from the deep store, and resumes consuming from the
        checkpointed offsets). Returns the new process's URL."""
        return self._restart(instance_id, "server")

    def restart_minion(self, instance_id: str) -> str:
        """Fresh minion process under the same id (after a kill): it resumes
        claiming from the controller queue; lease gc requeues whatever the
        dead incarnation held."""
        return self._restart(instance_id, "minion")

    def shutdown(self) -> None:
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def __enter__(self) -> "ProcessCluster":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


if __name__ == "__main__":
    main()
