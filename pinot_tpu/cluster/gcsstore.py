"""GCS-wire deep store: the JSON/object API as a PinotFS-analog scheme.

Analog of the reference's GCS plugin
(`pinot-plugins/pinot-file-system/pinot-gcs/src/main/java/org/apache/pinot/
plugin/filesystem/GcsPinotFS.java`): objects addressed bucket/name over the
Cloud Storage JSON API — media upload (`POST /upload/storage/v1/b/{b}/o?
uploadType=media&name=...`), media download (`GET /storage/v1/b/{b}/o/{o}?
alt=media`), delete, and list with `prefix`/`delimiter`/`pageToken`
pagination — with Bearer-token auth. The in-repo `GcsStub` proves the wire
seam like `S3StubServer` does for S3: pointing the client at a real
endpoint (or fake-gcs-server) is a config change.

Spec: `gs://bucket/prefix?endpoint=http://host:port[&token=...]`.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from .deepstore import RemoteObjectFS


class GcsError(OSError):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"GCS {status}: {message}")
        self.status = status


class GcsDeepStoreFS(RemoteObjectFS):
    """Bytes-by-URI against a GCS JSON-API endpoint (no rename, like
    GcsPinotFS: move = copy + delete via the base class). Spec parsing /
    recursive delete / existence semantics are the RemoteObjectFS contract;
    this class is the JSON-API wire (Bearer auth, pageToken pagination)."""

    scheme = "gs"

    def __init__(self, root: str):
        params = self._parse_spec(root, "gs")
        self.token = params.get("token", "")

    # -- wire ---------------------------------------------------------------
    def _headers(self) -> Dict[str, str]:
        return {"Authorization": f"Bearer {self.token}"} if self.token else {}

    def _call(self, method: str, url: str, body: Optional[bytes] = None,
              headers: Optional[Dict[str, str]] = None) -> bytes:
        from .http_service import HttpError, _pooled_request
        h = self._headers()
        if headers:
            h.update(headers)
        try:
            return _pooled_request(method, url, body, h, self.timeout_s)
        except HttpError as e:
            raise GcsError(e.status, str(e)) from None

    # -- DeepStoreFS --------------------------------------------------------
    def put_bytes(self, data: bytes, uri: str) -> None:
        q = urllib.parse.urlencode({"uploadType": "media",
                                    "name": self._key(uri)})
        self._call("POST",
                   f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o?{q}",
                   data, {"Content-Type": "application/octet-stream"})

    def upload(self, local_path: str, uri: str) -> None:
        """STREAMING: the tar is sent from the open file with an explicit
        Content-Length — a multi-GB segment never buffers in memory (the
        deep-store contract S3DeepStoreFS documents and upholds)."""
        # graftcheck: ignore[transport-bypass] -- external GCS endpoint, not
        # the cluster data plane; streams a multi-GB tar from an open file,
        # which the pooled client's bytes-body API cannot
        import urllib.request
        q = urllib.parse.urlencode({"uploadType": "media",
                                    "name": self._key(uri)})
        url = f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o?{q}"
        headers = dict(self._headers())
        headers["Content-Type"] = "application/octet-stream"
        headers["Content-Length"] = str(os.path.getsize(local_path))
        with open(local_path, "rb") as f:
            req = urllib.request.Request(url, data=f, method="POST",
                                         headers=headers)
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout_s) as resp:
                    resp.read()
            except urllib.error.HTTPError as e:
                raise GcsError(e.code,
                               e.read()[:200].decode(errors="replace")
                               ) from None

    def get_bytes(self, uri: str) -> bytes:
        obj = urllib.parse.quote(self._key(uri), safe="")
        try:
            return self._call(
                "GET",
                f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{obj}?alt=media")
        except GcsError as e:
            if e.status == 404:
                raise FileNotFoundError(f"gs://{self.bucket}/{self._key(uri)}"
                                        ) from None
            raise

    def _delete_object(self, key: str) -> None:
        obj = urllib.parse.quote(key, safe="")
        self._call("DELETE",
                   f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{obj}")

    def _head_ok(self, key: str) -> bool:
        obj = urllib.parse.quote(key, safe="")
        try:
            self._call("GET",
                       f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{obj}")
            return True
        except GcsError as e:
            if e.status != 404:
                raise
            return False

    def _list_keys(self, prefix: str, limit: int = 1 << 31) -> List[str]:
        return self._list(prefix, "", limit)

    def _list(self, prefix: str, delimiter: str,
              limit: int = 1 << 31) -> List[str]:
        """Full item listing following pageToken pagination."""
        names: List[str] = []
        token = ""
        while True:
            params = {"prefix": prefix, "maxResults": str(self.page_size)}
            if delimiter:
                params["delimiter"] = delimiter
            if token:
                params["pageToken"] = token
            payload = self._call(
                "GET", f"{self.endpoint}/storage/v1/b/{self.bucket}/o?"
                       f"{urllib.parse.urlencode(params)}")
            d = json.loads(payload.decode())
            names.extend(item["name"] for item in d.get("items", []))
            names.extend(d.get("prefixes", []))
            token = d.get("nextPageToken", "")
            if not token or len(names) >= limit:
                return names[:limit] if limit < (1 << 31) else names

    def listdir(self, uri: str) -> List[str]:
        key = self._key(uri)
        prefix = key.rstrip("/") + "/" if key else (
            f"{self.prefix}/" if self.prefix else "")
        out = set()
        for name in self._list(prefix, "/"):
            out.add(name[len(prefix):].rstrip("/"))
        return sorted(n for n in out if n)


class GcsStub:
    """Minimal Cloud Storage JSON-API endpoint: media upload/download,
    delete, paginated list with prefixes; Bearer-token auth; an `outage`
    switch for chaos tests."""

    def __init__(self, bucket: str = "pinot", token: str = "",
                 host: str = "127.0.0.1", port: int = 0):
        self.bucket = bucket
        self.token = token
        self.objects: Dict[str, bytes] = {}
        self.outage = False
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, status: int, body: bytes,
                       ctype: str = "application/json") -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _err(self, status: int, msg: str) -> None:
                self._reply(status, json.dumps(
                    {"error": {"code": status, "message": msg}}).encode())

            def _auth_ok(self) -> bool:
                if not stub.token:
                    return True
                return self.headers.get("Authorization", "") \
                    == f"Bearer {stub.token}"

            def _dispatch(self, method: str) -> None:
                if stub.outage:
                    return self._err(503, "backendError")
                if not self._auth_ok():
                    return self._err(401, "unauthorized")
                parsed = urllib.parse.urlparse(self.path)
                params = dict(urllib.parse.parse_qsl(parsed.query))
                parts = [p for p in parsed.path.split("/") if p]
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                # /upload/storage/v1/b/{bucket}/o  (media upload)
                if method == "POST" and parts[:1] == ["upload"]:
                    if parts[4] != stub.bucket:
                        return self._err(404, "bucket")
                    name = params.get("name", "")
                    with stub._lock:
                        stub.objects[name] = body
                    return self._reply(200, json.dumps(
                        {"name": name, "size": str(len(body))}).encode())
                # /storage/v1/b/{bucket}/o[/...object...]
                if parts[:2] != ["storage", "v1"] or parts[3] != stub.bucket:
                    return self._err(404, "bucket")
                obj = urllib.parse.unquote(parts[5]) if len(parts) > 5 else ""
                if method == "GET" and not obj:
                    return self._reply(200, stub._list_json(params))
                if method == "GET":
                    with stub._lock:
                        data = stub.objects.get(obj)
                    if data is None:
                        return self._err(404, "notFound")
                    if params.get("alt") == "media":
                        return self._reply(200, data,
                                           "application/octet-stream")
                    return self._reply(200, json.dumps(
                        {"name": obj, "size": str(len(data))}).encode())
                if method == "DELETE":
                    with stub._lock:
                        existed = stub.objects.pop(obj, None)
                    if existed is None:
                        return self._err(404, "notFound")
                    return self._reply(204, b"")
                return self._err(405, "method")

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

        class _Server(ThreadingHTTPServer):
            request_queue_size = 64

        self._server = _Server((host, port), Handler)
        self._server.daemon_threads = True
        self.url = f"http://{host}:{self._server.server_address[1]}"
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="gcs-stub")
        self._thread.start()

    def _list_json(self, params: Dict[str, str]) -> bytes:
        prefix = params.get("prefix", "")
        delimiter = params.get("delimiter", "")
        max_results = min(int(params.get("maxResults", "1000")), 1000)
        token = params.get("pageToken", "")
        with self._lock:
            keys = sorted(k for k in self.objects if k.startswith(prefix))
            sizes = {k: len(self.objects[k]) for k in keys}
        items: List[Tuple[str, bool]] = []
        seen = set()
        for k in keys:
            if delimiter:
                rest = k[len(prefix):]
                if delimiter in rest:
                    cp = prefix + rest.split(delimiter, 1)[0] + delimiter
                    if cp not in seen:
                        seen.add(cp)
                        items.append((cp, True))
                    continue
            items.append((k, False))
        after = [it for it in items if it[0] > token]
        page, more = after[:max_results], after[max_results:]
        out: Dict[str, object] = {
            "items": [{"name": k, "size": str(sizes.get(k, 0))}
                      for k, cp in page if not cp],
            "prefixes": [k for k, cp in page if cp],
        }
        if more:
            out["nextPageToken"] = page[-1][0]
        return json.dumps(out).encode()

    def spec(self, prefix: str = "") -> str:
        auth = f"&token={self.token}" if self.token else ""
        p = f"/{prefix}" if prefix else ""
        return f"gs://{self.bucket}{p}?endpoint={self.url}{auth}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
