"""ADLS Gen2 deep store over the Data Lake Storage REST API.

Analog of the reference's ADLS plugin
(`pinot-plugins/pinot-file-system/pinot-adls/src/main/java/org/apache/pinot/
plugin/filesystem/ADLSGen2PinotFS.java`): where that plugin drives
azure-storage-file-datalake, this speaks the PUBLIC dfs REST protocol —
including Gen2's three-step write (create file, PATCH append at position,
PATCH flush to commit) and the NATIVE rename (`x-ms-rename-source` header, a
metadata move exactly like ADLSGen2PinotFS.move). Reads/deletes/listing use
GET / DELETE?recursive / `?resource=filesystem&directory=` paths-listing.

Spec: `adls://filesystem/prefix?endpoint=http://host:port[&token=...]` —
endpoint is the account's dfs endpoint; `token` rides as a Bearer (the AAD
auth mode of the reference plugin). The in-repo `AdlsStub` proves the wire
seam (create/append/flush state machine, rename source parsing); pointing
at a real account (or azurite) is a config change.
"""

from __future__ import annotations

import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .deepstore import RemoteObjectFS, register_fs


class AdlsError(OSError):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"ADLS {status}: {message}")
        self.status = status


# append chunk for streaming uploads: bounded memory per PATCH
_CHUNK = 8 << 20


class AdlsDeepStoreFS(RemoteObjectFS):
    """Spec parsing / _key / download come from RemoteObjectFS (the
    "bucket" is the Gen2 filesystem); delete/move/exists/listdir are
    OVERRIDDEN with the native filesystem operations Gen2 has that plain
    object stores lack (recursive delete, metadata rename, directory
    listing) — the same reason the reference's ADLSGen2PinotFS diverges
    from its object-store siblings."""

    scheme = "adls"

    def __init__(self, root: str):
        params = self._parse_spec(root, "adls")
        self.token = params.get("token", "")

    @property
    def filesystem(self) -> str:
        return self.bucket

    # -- wire ---------------------------------------------------------------
    def _url(self, key: str, **q) -> str:
        path = urllib.parse.quote(f"/{self.filesystem}/{key}")
        qs = urllib.parse.urlencode({k: v for k, v in q.items()
                                     if v is not None})
        return f"{self.endpoint}{path}" + (f"?{qs}" if qs else "")

    def _call(self, method: str, url: str, body: Optional[bytes] = None,
              headers: Optional[Dict[str, str]] = None) -> bytes:
        from .http_service import HttpError, _pooled_request
        h = {"Authorization": f"Bearer {self.token}"} if self.token else {}
        if headers:
            h.update(headers)
        try:
            return _pooled_request(method, url, body, h, self.timeout_s)
        except HttpError as e:
            raise AdlsError(e.status, str(e)) from None

    # -- DeepStoreFS --------------------------------------------------------
    def _create_append_flush(self, key: str, chunks) -> None:
        """Gen2 write protocol: create -> PATCH append at position -> flush."""
        self._call("PUT", self._url(key, resource="file"))
        pos = 0
        for chunk in chunks:
            if not chunk:
                continue
            self._call("PATCH",
                       self._url(key, action="append", position=str(pos)),
                       chunk,
                       {"Content-Type": "application/octet-stream"})
            pos += len(chunk)
        self._call("PATCH", self._url(key, action="flush",
                                      position=str(pos)))

    def put_bytes(self, data: bytes, uri: str) -> None:
        self._create_append_flush(self._key(uri), [data])

    def upload(self, local_path: str, uri: str) -> None:
        # STREAMING in bounded PATCH chunks: a multi-GB segment tar never
        # buffers whole in memory (the Gen2 protocol is built for this)
        def chunks():
            with open(local_path, "rb") as f:
                while True:
                    c = f.read(_CHUNK)
                    if not c:
                        return
                    yield c
        self._create_append_flush(self._key(uri), chunks())

    def get_bytes(self, uri: str) -> bytes:
        try:
            return self._call("GET", self._url(self._key(uri)))
        except AdlsError as e:
            if e.status == 404:
                raise FileNotFoundError(
                    f"adls://{self.filesystem}/{self._key(uri)}") from None
            raise

    def delete(self, uri: str) -> None:
        try:
            self._call("DELETE", self._url(self._key(uri),
                                           recursive="true"))
        except AdlsError as e:
            if e.status != 404:
                raise

    def move(self, src_uri: str, dst_uri: str) -> None:
        """Native Gen2 rename: PUT new path with x-ms-rename-source
        (reference: ADLSGen2PinotFS.move — a metadata operation)."""
        src = urllib.parse.quote(f"/{self.filesystem}/{self._key(src_uri)}")
        self._call("PUT", self._url(self._key(dst_uri)),
                   headers={"x-ms-rename-source": src})

    def exists(self, uri: str) -> bool:
        try:
            self._call("HEAD", self._url(self._key(uri)))
            return True
        except AdlsError as e:
            if e.status == 404:
                # a "directory" exists when ANY path (file OR subdirectory)
                # lives at/under it — directory entries count here, unlike
                # in listings of files
                return bool(self._list_paths(self._key(uri),
                                             recursive=False, limit=1,
                                             include_dirs=True))
            raise

    def listdir(self, uri: str) -> List[str]:
        key = self._key(uri)
        pre = key.rstrip("/") + "/" if key else ""
        names = set()
        # NON-recursive: the dfs list API returns exactly one level
        for p in self._list_paths(key, recursive=False, include_dirs=True):
            rest = p[len(pre):] if p.startswith(pre) else p
            if rest:
                names.add(rest.split("/", 1)[0])
        return sorted(names)

    def _list_paths(self, directory: str, recursive: bool = True,
                    limit: int = 1 << 31,
                    include_dirs: bool = False) -> List[str]:
        """Paths under `directory`, following x-ms-continuation pagination
        (a capped single page would silently truncate large tables — the
        s3/gcs stores page for the same reason)."""
        out: List[str] = []
        continuation = None
        while len(out) < limit:
            q = {"resource": "filesystem",
                 "recursive": "true" if recursive else "false",
                 "directory": directory,
                 "maxResults": str(min(self.page_size, limit - len(out)))}
            if continuation:
                q["continuation"] = continuation
            url = (f"{self.endpoint}/"
                   f"{urllib.parse.quote(self.filesystem)}"
                   f"?{urllib.parse.urlencode(q)}")
            try:
                body, headers = self._call_with_headers("GET", url)
            except AdlsError as e:
                if e.status == 404:
                    return out
                raise
            d = json.loads(body or b"{}")
            out.extend(p["name"] for p in d.get("paths", [])
                       if include_dirs or not p.get("isDirectory"))
            continuation = headers.get("x-ms-continuation")
            if not continuation:
                break
        return out[:limit]

    def _call_with_headers(self, method: str, url: str):
        """_call surfacing response headers (the continuation token rides a
        header, not the body) — same pooled, TLS-capable transport as every
        other ADLS operation."""
        from .http_service import HttpError, _pooled_request
        h = {"Authorization": f"Bearer {self.token}"} if self.token else {}
        try:
            return _pooled_request(method, url, None, h, self.timeout_s,
                                   return_headers=True)
        except HttpError as e:
            raise AdlsError(e.status, str(e)) from None


register_fs("adls", AdlsDeepStoreFS)


# ---------------------------------------------------------------------------
# in-repo ADLS Gen2 stub
# ---------------------------------------------------------------------------

class AdlsStub:
    """Minimal dfs-endpoint: the create/append/flush write state machine,
    ranged reads, recursive delete, x-ms-rename-source rename, filesystem
    listing; Bearer-token auth; an `outage` switch for chaos tests."""

    def __init__(self, filesystem: str = "pinot", token: str = "",
                 host: str = "127.0.0.1", port: int = 0):
        self.filesystem = filesystem
        self.token = token
        self.files: Dict[str, bytes] = {}
        self.staged: Dict[str, bytearray] = {}   # created, not yet flushed
        self.outage = False
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, status: int, body: bytes = b"") -> None:
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def _auth_ok(self) -> bool:
                if not stub.token:
                    return True
                if self.headers.get("Authorization") == \
                        f"Bearer {stub.token}":
                    return True
                self._reply(401, b'{"error":{"code":"AuthFailure"}}')
                return False

            def _parts(self):
                parsed = urllib.parse.urlsplit(self.path)
                segs = urllib.parse.unquote(parsed.path).lstrip("/")
                fs, _, key = segs.partition("/")
                q = dict(urllib.parse.parse_qsl(parsed.query))
                return fs, key, q

            def _guard(self) -> bool:
                if stub.outage:
                    self._reply(503, b'{"error":{"code":"ServerBusy"}}')
                    return True
                if not self._auth_ok():
                    return True
                return False

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def do_PUT(self):
                if self._guard():
                    return
                fs, key, q = self._parts()
                src = self.headers.get("x-ms-rename-source")
                self._body()
                with stub._lock:
                    if src:
                        src_key = urllib.parse.unquote(src).lstrip("/")
                        src_key = src_key.partition("/")[2]
                        if src_key in stub.files:
                            stub.files[key] = stub.files.pop(src_key)
                            self._reply(201)
                        else:
                            # directory rename: move every child
                            pre = src_key.rstrip("/") + "/"
                            moved = [k for k in stub.files
                                     if k.startswith(pre)]
                            for k in moved:
                                stub.files[key + k[len(src_key):]] = \
                                    stub.files.pop(k)
                            self._reply(201 if moved else 404)
                    elif q.get("resource") == "file":
                        stub.staged[key] = bytearray()
                        self._reply(201)
                    else:
                        self._reply(400)

            def do_PATCH(self):
                if self._guard():
                    return
                fs, key, q = self._parts()
                data = self._body()
                with stub._lock:
                    if q.get("action") == "append":
                        st = stub.staged.get(key)
                        if st is None:
                            self._reply(404)
                            return
                        if int(q.get("position", -1)) != len(st):
                            self._reply(409, b'{"error":{"code":'
                                        b'"InvalidFlushPosition"}}')
                            return
                        st.extend(data)
                        self._reply(202)
                    elif q.get("action") == "flush":
                        st = stub.staged.pop(key, None)
                        if st is None:
                            self._reply(404)
                            return
                        if int(q.get("position", -1)) != len(st):
                            self._reply(409)
                            return
                        stub.files[key] = bytes(st)
                        self._reply(200)
                    else:
                        self._reply(400)

            def do_GET(self):
                if self._guard():
                    return
                fs, key, q = self._parts()
                with stub._lock:
                    if q.get("resource") == "filesystem":
                        directory = q.get("directory", "").strip("/")
                        recursive = q.get("recursive", "true") == "true"
                        pre = directory + "/" if directory else ""
                        entries = {}   # name -> isDirectory
                        for k in sorted(stub.files):
                            if not (k.startswith(pre) or k == directory):
                                continue
                            if recursive:
                                entries[k] = False
                            else:
                                rest = k[len(pre):]
                                head = rest.split("/", 1)[0]
                                entries[pre + head] = "/" in rest
                        items = sorted(entries.items())
                        token = q.get("continuation", "")
                        items = [it for it in items if it[0] > token]
                        page_n = int(q.get("maxResults", "5000"))
                        page, more = items[:page_n], items[page_n:]
                        self.send_response(200)
                        body = json.dumps({"paths": [
                            {"name": n, "isDirectory": d}
                            for n, d in page]}).encode()
                        self.send_header("Content-Type", "application/json")
                        self.send_header("Content-Length", str(len(body)))
                        if more:
                            self.send_header("x-ms-continuation",
                                             page[-1][0])
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    data = stub.files.get(key)
                if data is None:
                    self._reply(404, b'{"error":{"code":"PathNotFound"}}')
                    return
                self._reply(200, data)

            def do_HEAD(self):
                if self._guard():
                    return
                fs, key, _q = self._parts()
                with stub._lock:
                    ok = key in stub.files
                self._reply(200 if ok else 404)

            def do_DELETE(self):
                if self._guard():
                    return
                fs, key, q = self._parts()
                with stub._lock:
                    existed = stub.files.pop(key, None) is not None
                    if q.get("recursive") == "true":
                        pre = key.rstrip("/") + "/"
                        for k in [k for k in stub.files
                                  if k.startswith(pre)]:
                            del stub.files[k]
                            existed = True
                self._reply(200 if existed else 404)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="adls-stub")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def spec(self, prefix: str = "") -> str:
        auth = f"&token={self.token}" if self.token else ""
        p = f"/{prefix}" if prefix else ""
        return f"adls://{self.filesystem}{p}?endpoint={self.url}{auth}"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
