"""HTTP services for each cluster role: controller, server, broker.

These wrap the in-proc role objects (controller.py / server.py / broker.py) with the
HTTP endpoints the reference exposes:

* ControllerService — table/schema CRUD + segment upload/download
  (`controller/api/resources/PinotSegmentUploadDownloadRestletResource.java`),
  segment completion protocol (`LLCSegmentCompletionHandlers.java`), and the
  catalog API standing in for ZooKeeper (snapshot + long-poll watch).
* ServerService — the query endpoint (`core/transport/InstanceRequestHandler.java:96`
  over Netty in the reference; HTTP/binary wire here).
* BrokerService — SQL entry (`pinot-broker/api/resources/PinotClientRequest.java`
  POST /query/sql).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional

from ..schema import Schema
from ..table import TableConfig
from .broker import Broker
from .catalog import Catalog, InstanceInfo
from .controller import Controller
from .http_service import (HttpService, binary_response, error_response,
                           json_response)
from .deepstore import untar_segment
from .remote import RemoteServerHandle
from .server import ServerNode
from .wire import decode_query_request, encode_segment_result


def _metrics_route(parts, params, body):
    """GET /metrics — Prometheus text exposition of the process registry
    (reference: the JMX->Prometheus exporter over the yammer metrics registry)."""
    from ..utils.metrics import get_registry
    return 200, "text/plain; version=0.0.4", get_registry().render_prometheus().encode()


def _events_route(params):
    """GET /debug/events?since=<gseq> — the process journal's incremental
    pull (shared by every role service): events past the cursor plus the
    cursor to pass next time. The controller's timeline collector polls
    this exactly like the memory checker polls /debug/memory."""
    from ..utils.events import get_journal
    try:
        since = int(params.get("since", 0))
    except (TypeError, ValueError):
        since = 0
    try:
        limit = int(params["limit"]) if "limit" in params else None
    except (TypeError, ValueError):
        limit = None
    return json_response(get_journal().events_since(since, limit))


def _configure_journal(catalog, instance_id: str) -> None:
    """Role-startup journal config: stamp the process journal's default node
    label and apply the `events.ring.size` knob. One journal per process —
    in OS-process deployments each role owns it; in-proc test clusters the
    last-constructed service wins the default label (emit sites pass their
    node explicitly, so only unlabeled emits are affected)."""
    from ..utils.events import get_journal
    cap = None
    try:
        raw = catalog.get_property("clusterConfig/events.ring.size", None)
        if raw is not None:
            cap = int(raw)
    except (TypeError, ValueError):
        cap = None   # malformed knob: keep the current capacity
    get_journal().configure(node=instance_id, capacity=cap)


def _untar_body(body: bytes, name: str, dest: str) -> str:
    """Write an uploaded segment tar to disk and unpack it; returns the segment dir."""
    tar_path = os.path.join(dest, f"{name}.tar.gz")
    with open(tar_path, "wb") as f:
        f.write(body)
    return untar_segment(tar_path, dest)


class ControllerService:
    """Controller role process: owns the authoritative catalog + deep store."""

    def __init__(self, controller: Controller, host: str = "127.0.0.1",
                 port: int = 0, access_control=None, ssl_context=None):
        self.controller = controller
        self.catalog = controller.catalog
        _configure_journal(self.catalog, controller.instance_id)
        self.http = HttpService(host, port, access_control=access_control,
                                ssl_context=ssl_context)
        self._version = 0
        self._version_cv = threading.Condition()
        self.catalog.subscribe(self._bump_version)
        s = self.http
        s.route("GET", "health", lambda p, q, b: json_response({"status": "OK"}))
        s.route("GET", "catalog", self._catalog_get)
        s.route("POST", "catalog", self._catalog_post, action="WRITE")
        s.route("POST", "schemas", self._post_schema, action="WRITE")
        s.route("POST", "tables", self._post_table, action="WRITE")
        s.route("DELETE", "tables", self._delete_table, action="ADMIN")
        s.route("POST", "segments", self._post_segment, action="WRITE")
        s.route("GET", "segments", self._get_segment)
        s.route("DELETE", "segments", self._delete_segment, action="ADMIN")
        s.route("POST", "segmentConsumed", self._segment_consumed, action="WRITE")
        s.route("POST", "segmentCommitStart", self._segment_commit_start,
                action="WRITE")
        s.route("POST", "segmentCommitEnd", self._segment_commit_end,
                action="WRITE")
        s.route("GET", "deepstore", self._deepstore_get)
        s.route("POST", "deepstore", self._deepstore_post, action="WRITE")
        s.route("GET", "tableStatus", self._table_status)
        s.route("GET", "tables", self._get_tables)
        s.route("GET", "schemas", self._get_schema)
        s.route("GET", "segmentsMeta", self._segments_meta)
        s.route("POST", "reload", self._reload_table, action="WRITE")
        s.route("GET", "tenants", self._list_tenants)
        s.route("GET", "clusterConfigs", self._get_cluster_configs)
        s.route("POST", "clusterConfigs", self._set_cluster_config,
                action="ADMIN")
        s.route("POST", "tableState", self._table_state, action="ADMIN")
        s.route("POST", "instanceTags", self._update_instance_tags, action="ADMIN")
        s.route("POST", "pauseConsumption", self._pause_consumption, action="ADMIN")
        s.route("POST", "resumeConsumption", self._resume_consumption, action="ADMIN")
        s.route("POST", "rebalance", self._rebalance, action="ADMIN")
        s.route("POST", "validate", self._validate, action="ADMIN")
        # minion task protocol (reference: Helix task framework; claims are
        # atomic against the authoritative catalog, so N remote minions can
        # never double-claim)
        s.route("POST", "tasks", self._tasks_post, action="WRITE")
        s.route("GET", "tasks", self._tasks_get)
        s.route("POST", "replaceSegments", self._replace_segments, action="WRITE")
        s.route("POST", "ingestJobs", self._ingest_jobs, action="WRITE")
        s.route("GET", "metrics", _metrics_route)
        s.route("GET", "debug", self._debug)
        s.route("POST", "sql", self._sql_proxy)  # query console backend
        s.route("GET", "", self._ui)       # admin UI at /
        s.route("GET", "ui", self._ui)
        self.http.start()

    @property
    def url(self) -> str:
        return self.http.url

    def stop(self) -> None:
        self.http.stop()

    def _debug(self, parts, params, body):
        """GET /debug — controller rollup (periodic tasks, verdict planes).
        GET /debug/events — this process's journal (incremental, ?since=).
        GET /debug/timeline — the merged cluster timeline in causal order
        (?kind= ?table= ?severity= ?since= ?limit= filters). GET
        /debug/incidents — the flight recorder's retained bundles
        (?id=<n> resolves one, 404 when evicted/unknown)."""
        if parts and parts[0] == "events":
            return _events_route(params)
        if parts and parts[0] == "timeline":
            try:
                since = float(params["since"]) if "since" in params else None
            except (TypeError, ValueError):
                since = None
            try:
                limit = int(params["limit"]) if "limit" in params else None
            except (TypeError, ValueError):
                limit = None
            rows = self.controller.timeline(
                kind=params.get("kind"), table=params.get("table"),
                severity=params.get("severity"), since=since, limit=limit)
            return json_response({"events": rows, "count": len(rows)})
        if parts and parts[0] == "incidents":
            inc_id = params.get("id")
            if inc_id:
                for b in self.controller.incidents():
                    if str(b.get("id")) == str(inc_id):
                        return json_response(b)
                return error_response(f"unknown incident {inc_id}", 404)
            try:
                limit = int(params["limit"]) if "limit" in params else None
            except (TypeError, ValueError):
                limit = None
            rows = self.controller.incidents(limit)
            return json_response({"incidents": rows, "count": len(rows)})
        return json_response(self.controller.debug_stats())

    _UI_STYLE = (
        "<style>body{font-family:sans-serif;margin:2em}table{border-collapse:"
        "collapse}td,th{border:1px solid #ccc;padding:4px 10px;text-align:left}"
        ".err{color:#b00}.warn{background:#fff3cd}nav a{margin-right:1em}"
        "textarea{width:100%;font-family:monospace}</style>"
        "<nav><a href=/ui>overview</a><a href=/ui/tasks>tasks</a>"
        "<a href=/ui/query>query console</a><a href=/metrics>metrics</a></nav>")

    def _ui(self, parts, params, body):
        """GET /ui[/...] — server-rendered admin console (stand-in for the
        reference's React controller app): cluster overview, per-table
        segment drill-down with replica placement (skew is visible at a
        glance), task states (stuck/failed tasks diagnosable from the
        browser), and a query console proxying to a live broker (reference:
        PinotQueryResource)."""
        page = parts[0] if parts else ""
        if page == "table" and len(parts) > 1:
            return self._ui_table(parts[1])
        if page == "tasks":
            return self._ui_tasks()
        if page == "query":
            return self._ui_query()
        return self._ui_overview()

    def _ui_overview(self):
        from html import escape
        with self.catalog._lock:
            tables = {
                t: {"segments": len(self.catalog.segments.get(t, {})),
                    "replication": cfg.replication,
                    "type": "REALTIME" if cfg.stream else "OFFLINE"}
                for t, cfg in self.catalog.table_configs.items()}
            instances = [(i.instance_id, i.role, "UP" if i.alive else "DOWN")
                         for i in self.catalog.instances.values()]
            # per-server segment counts across all tables: load skew at a glance
            load: Dict[str, int] = {}
            for t, ev in self.catalog.external_view.items():
                for seg, states in ev.items():
                    for srv, st in states.items():
                        if st in ("ONLINE", "CONSUMING"):
                            load[srv] = load.get(srv, 0) + 1
        # escape EVERY catalog-derived value: table/instance names are
        # client-supplied and would otherwise be stored XSS in the operator UI
        rows = "".join(
            f"<tr><td><a href='/ui/table/{escape(t)}'>{escape(t)}</a></td>"
            f"<td>{d['type']}</td><td>{d['segments']}</td>"
            f"<td>{d['replication']}</td></tr>" for t, d in sorted(tables.items()))
        inst = "".join(
            f"<tr><td>{escape(i)}</td><td>{escape(r)}</td><td>{s}</td>"
            f"<td>{load.get(i, 0)}</td></tr>" for i, r, s in sorted(instances))
        html = (
            "<!doctype html><title>pinot-tpu controller</title>"
            f"{self._UI_STYLE}<h1>pinot-tpu controller</h1>"
            "<h2>Tables</h2><table><tr><th>table</th><th>type</th>"
            f"<th>segments</th><th>replication</th></tr>{rows}</table>"
            "<h2>Instances</h2><table><tr><th>instance</th><th>role</th>"
            f"<th>status</th><th>segments served</th></tr>{inst}</table>")
        return 200, "text/html", html.encode()

    def _ui_table(self, table):
        """Per-segment drill-down: status, docs, size, time range, replica
        placement and per-server counts — a skewed table shows up as uneven
        'segments per server' and lopsided placements."""
        from html import escape
        with self.catalog._lock:
            segs = dict(self.catalog.segments.get(table, {}))
            ev = {s: dict(m) for s, m in
                  self.catalog.external_view.get(table, {}).items()}
        if not segs and not ev:
            return error_response(f"unknown table {table}", 404)
        per_server: Dict[str, int] = {}
        rows = []
        for name in sorted(set(segs) | set(ev)):
            m = segs.get(name)
            states = ev.get(name, {})
            for srv, st in states.items():
                if st in ("ONLINE", "CONSUMING"):
                    per_server[srv] = per_server.get(srv, 0) + 1
            placement = ", ".join(f"{escape(s)}:{escape(str(st))}"
                                  for s, st in sorted(states.items()))
            rows.append(
                f"<tr><td>{escape(name)}</td>"
                f"<td>{escape(str(m.status)) if m else '?'}</td>"
                f"<td>{m.num_docs if m else '?'}</td>"
                f"<td>{m.size_bytes if m else '?'}</td>"
                f"<td>{m.start_time_ms if m else ''}..{m.end_time_ms if m else ''}</td>"
                f"<td>{escape(str(m.download_path)) if m else ''}</td>"
                f"<td>{placement}</td></tr>")
        srv_rows = "".join(f"<tr><td>{escape(s)}</td><td>{n}</td></tr>"
                           for s, n in sorted(per_server.items()))
        html = (
            f"<!doctype html><title>{escape(table)}</title>{self._UI_STYLE}"
            f"<h1>table {escape(table)}</h1>"
            "<h2>Segments per server</h2>"
            f"<table><tr><th>server</th><th>segments</th></tr>{srv_rows}</table>"
            "<h2>Segments</h2><table><tr><th>segment</th><th>status</th>"
            "<th>docs</th><th>bytes</th><th>time range</th><th>download</th>"
            f"<th>placement</th></tr>{''.join(rows)}</table>")
        return 200, "text/html", html.encode()

    def _ui_tasks(self):
        """Task/job states: a STUCK task is RUNNING with an old lease, a
        failed one shows its error inline (reference: task states in the
        controller console)."""
        import time as _t
        from html import escape
        from ..minion.tasks import TaskQueue
        now_ms = int(_t.time() * 1000)
        rows = []
        for t in TaskQueue(self.catalog).tasks():
            age_s = (now_ms - t.claimed_ms) / 1000 if t.claimed_ms else None
            stuck = t.state == "RUNNING" and age_s is not None and age_s > 600
            cls = " class=warn" if stuck else ""
            rows.append(
                f"<tr{cls}><td>{escape(t.task_id)}</td>"
                f"<td>{escape(t.task_type)}</td><td>{escape(t.table)}</td>"
                f"<td>{escape(t.state)}{' (stale lease)' if stuck else ''}</td>"
                f"<td>{escape(t.worker)}</td>"
                f"<td>{f'{age_s:.0f}s' if age_s is not None else ''}</td>"
                f"<td class=err>{escape(t.error)}</td></tr>")
        html = (
            f"<!doctype html><title>tasks</title>{self._UI_STYLE}"
            "<h1>Minion tasks</h1><table><tr><th>task</th><th>type</th>"
            "<th>table</th><th>state</th><th>worker</th><th>claimed age</th>"
            f"<th>error</th></tr>{''.join(rows)}</table>"
            "<p>POST /tasks/gc requeues stale RUNNING tasks; POST "
            "/tasks/generate runs the generators now.</p>")
        return 200, "text/html", html.encode()

    def _ui_query(self):
        """Query console: textarea -> POST /sql (the controller-side broker
        proxy, reference: PinotQueryResource.handlePostSql)."""
        html = (
            f"<!doctype html><title>query console</title>{self._UI_STYLE}"
            "<h1>Query console</h1>"
            "<textarea id=q rows=4>SELECT * FROM mytable LIMIT 10</textarea>"
            "<p><button onclick='run()'>Run</button></p><div id=out></div>"
            "<script>async function run(){"
            "const r=await fetch('/sql',{method:'POST',headers:{'Content-Type'"
            ":'application/json'},body:JSON.stringify({sql:document."
            "getElementById('q').value})});const d=await r.json();"
            "const o=document.getElementById('out');if(d.error){o.innerHTML="
            "'<p class=err></p>';o.firstChild.textContent=d.error;return;}"
            "const t=d.resultTable||{};const cols=(t.dataSchema||{})."
            "columnNames||[];let h='<table><tr>'+cols.map(c=>'<th></th>')."
            "join('')+'</tr>'+(t.rows||[]).map(r=>'<tr>'+r.map(c=>'<td></td>')"
            ".join('')+'</tr>').join('')+'</table>';o.innerHTML=h;"
            "const cells=o.querySelectorAll('th');cols.forEach((c,i)=>cells[i]"
            ".textContent=c);let k=0;const tds=o.querySelectorAll('td');"
            "(t.rows||[]).forEach(r=>r.forEach(v=>tds[k++].textContent="
            "String(v)));}</script>")
        return 200, "text/html", html.encode()

    def _sql_proxy(self, parts, params, body):
        """POST /sql {\"sql\": ...} — forward to a live broker (reference:
        the controller's PinotQueryResource proxy, the query console's
        backend). Tries each live broker instance until one answers."""
        from .http_service import post_json
        d = json.loads(body.decode())
        with self.catalog._lock:
            brokers = [(i.instance_id, i.url)
                       for i in self.catalog.instances.values()
                       if i.role == "broker" and i.alive and i.port]
        last = "no live broker registered"
        for _bid, url in sorted(brokers):
            try:
                resp = post_json(f"{url}/query", {"sql": d["sql"]},
                                 timeout=60.0)
                return json_response(resp)
            except Exception as e:
                last = f"{type(e).__name__}: {e}"
        return json_response({"error": f"broker unavailable: {last}"},
                             status=503)

    # -- catalog API (the ZooKeeper stand-in) -------------------------------
    def _bump_version(self, event: str, table: str) -> None:
        with self._version_cv:
            self._version += 1
            self._version_cv.notify_all()

    def _catalog_get(self, parts, params, body):
        if parts and parts[0] == "snapshot":
            with self.catalog._lock:
                snap = {
                    "version": self._version,
                    "schemas": {k: v.to_json()
                                for k, v in self.catalog.schemas.items()},
                    "tableConfigs": {k: v.to_json()
                                     for k, v in self.catalog.table_configs.items()},
                    "segments": {t: {s: m.to_json() for s, m in segs.items()}
                                 for t, segs in self.catalog.segments.items()},
                    "idealState": self.catalog.ideal_state,
                    "externalView": self.catalog.external_view,
                    "instances": {k: v.to_json()
                                  for k, v in self.catalog.instances.items()},
                    "properties": self.catalog.properties,
                }
            return json_response(snap)
        if parts and parts[0] == "watch":
            since = int(params.get("since", -1))
            timeout = float(params.get("timeoutSec", 10.0))
            with self._version_cv:
                self._version_cv.wait_for(lambda: self._version != since,
                                          timeout=timeout)
                return json_response({"version": self._version})
        return error_response("not found", 404)

    def _catalog_post(self, parts, params, body):
        d = json.loads(body.decode())
        if parts and parts[0] == "instances":
            if "role" in d:
                self.catalog.register_instance(InstanceInfo.from_json(d))
            else:  # liveness update
                self.catalog.set_instance_alive(d["instance_id"], d["alive"])
            return json_response({"status": "OK"})
        if parts and parts[0] == "externalView":
            self.catalog.report_state(d["table"], d["segment"], d["server"],
                                      d["state"])
            return json_response({"status": "OK"})
        if parts and parts[0] == "property":
            self.catalog.put_property(d["key"], d.get("value"))
            return json_response({"status": "OK"})
        return error_response("not found", 404)

    # -- admin: schemas / tables / segments ---------------------------------
    def _post_schema(self, parts, params, body):
        self.controller.add_schema(Schema.from_json(json.loads(body.decode())))
        return json_response({"status": "OK"})

    def _post_table(self, parts, params, body):
        d = json.loads(body.decode())
        cfg = TableConfig.from_json(d["config"] if "config" in d else d)
        if cfg.stream is not None:
            segs = self.controller.add_realtime_table(
                cfg, int(d.get("numPartitions", 1)))
            return json_response({"status": "OK", "consumingSegments": segs})
        self.controller.add_table(cfg)
        return json_response({"status": "OK"})

    def _delete_table(self, parts, params, body):
        self.controller.drop_table(parts[0])
        return json_response({"status": "OK"})

    def _post_segment(self, parts, params, body):
        """POST /segments/{tableNameWithType}?name=...[&custom=json] with the
        tar as the body (reference: segment push via
        PinotSegmentUploadDownloadRestletResource)."""
        table = parts[0]
        from ..auth import require_table_access
        require_table_access(table, "WRITE")
        name = params["name"]
        custom = json.loads(params["custom"]) if params.get("custom") else None
        with tempfile.TemporaryDirectory() as tmp:
            seg_dir = _untar_body(body, name, tmp)
            meta = self.controller.upload_segment(table, seg_dir, custom=custom)
        return json_response({"status": "OK", "segment": meta.name})

    def _get_segment(self, parts, params, body):
        """GET /segments/{table}/{name} — download the committed tar by URL."""
        table, name = parts[0], parts[1]
        from ..auth import require_table_access
        require_table_access(table, "READ")  # raw data = same ACL as queries
        meta = self.catalog.segments.get(table, {}).get(name)
        if meta is None or not meta.download_path:
            return error_response(f"no such segment {table}/{name}", 404)
        with tempfile.TemporaryDirectory() as tmp:
            local = os.path.join(tmp, "seg.tar.gz")
            from .peers import download_segment_tar
            download_segment_tar(self.controller.deepstore, self.catalog,
                                 table, name, local, meta.download_path)
            with open(local, "rb") as f:
                return binary_response(f.read())

    def _delete_segment(self, parts, params, body):
        permanent = str(params.get("permanent", "")).lower() in ("true", "1")
        self.controller.delete_segment(parts[0], parts[1], permanent=permanent)
        return json_response({"status": "OK"})

    # -- minion task protocol -----------------------------------------------
    def _tasks_post(self, parts, params, body):
        """POST /tasks/claim {"worker", "taskTypes"} -> spec | null
        POST /tasks/finish {"taskId", "worker", "error"} -> {"applied": bool}
        POST /tasks/generate -> run every generator once (tests/admin)."""
        from ..minion.tasks import TaskQueue
        queue = TaskQueue(self.catalog)
        op = parts[0] if parts else ""
        d = json.loads(body.decode()) if body else {}
        if op == "claim":
            spec = queue.claim(d["worker"], list(d["taskTypes"]))
            return json_response({"task": spec.to_json() if spec else None})
        if op == "finish":
            applied = queue.finish(d["taskId"], error=d.get("error", ""),
                                   worker_id=d.get("worker"))
            return json_response({"applied": applied})
        if op == "generate":
            specs = self.controller.task_manager.generate_all()
            return json_response({"generated": [s.task_id for s in specs]})
        if op == "gc":
            # admin/ops: requeue stale RUNNING tasks (dead worker) + drop old
            # terminal entries; leaseMs override lets operators force-release
            n = queue.gc(lease_ms=int(d.get("leaseMs", 600_000)))
            return json_response({"removed": n})
        return error_response("claim|finish|generate|gc", 404)

    def _ingest_jobs(self, parts, params, body):
        """POST /ingestJobs {"table", "inputPaths": [...], ...} — split a
        batch ingestion job into one SegmentGenerationAndPushTask per input
        file and queue them for the minion fleet (the distributed analog of
        the reference's hadoop/spark batch runners: N workers ingest N files
        in parallel; reference: IngestionJobLauncher + per-file
        SegmentGenerationJobRunner units)."""
        import uuid as _uuid

        from ..auth import require_table_access
        from ..minion.tasks import (SEGMENT_GENERATION_AND_PUSH, TaskQueue,
                                    TaskSpec)
        d = json.loads(body.decode())
        table = d["table"]
        require_table_access(table, "WRITE")
        if table not in self.catalog.table_configs:
            return error_response(f"unknown table {table}", 404)
        paths = list(d.get("inputPaths") or [])
        if not paths:
            return error_response("inputPaths required", 400)
        logical = table.rsplit("_", 1)[0] if table.endswith(
            ("_OFFLINE", "_REALTIME")) else table
        prefix = (d.get("segmentNamePrefix")
                  or f"{logical}_batch_{_uuid.uuid4().hex[:6]}")
        queue = TaskQueue(self.catalog)
        ids = []
        for i, path in enumerate(paths):
            spec = TaskSpec(
                task_id=(f"{SEGMENT_GENERATION_AND_PUSH}_{table}_{i}_"
                         f"{_uuid.uuid4().hex[:8]}"),
                task_type=SEGMENT_GENERATION_AND_PUSH, table=table,
                config={"inputPath": path,
                        "inputFormat": d.get("inputFormat"),
                        "segmentNamePrefix": prefix,
                        "segmentRows": int(d.get("segmentRows", 1_000_000)),
                        "filterExpr": d.get("filterExpr"),
                        "columnTransforms": d.get("columnTransforms") or {},
                        "sequence": i})
            queue.submit(spec)
            ids.append(spec.task_id)
        return json_response({"tasks": ids, "segmentNamePrefix": prefix})

    def _tasks_get(self, parts, params, body):
        """GET /tasks[?table=...&type=...] — task states (admin surface)."""
        from ..minion.tasks import TaskQueue
        out = TaskQueue(self.catalog).tasks(params.get("table") or None,
                                            params.get("type") or None)
        return json_response({"tasks": [t.to_json() for t in out]})

    def _replace_segments(self, parts, params, body):
        """POST /replaceSegments/{table} {"from": [names], "stagedTars":
        [deep-store staging uris], "custom": {...}}: the minion stages the new
        segment tars through the deep-store proxy first, then this endpoint
        runs the controller's ATOMIC lineage swap (reference:
        startReplaceSegments/endReplaceSegments)."""
        table = parts[0]
        from ..auth import require_table_access
        require_table_access(table, "WRITE")
        d = json.loads(body.decode())
        new_dirs = []
        try:
            with tempfile.TemporaryDirectory() as tmp:
                for i, uri in enumerate(d.get("stagedTars", [])):
                    local = os.path.join(tmp, f"staged_{i}.tar.gz")
                    self.controller.deepstore.download(uri, local)
                    new_dirs.append(untar_segment(local,
                                                  os.path.join(tmp, f"d{i}")))
                new_names = self.controller.replace_segments(
                    table, list(d["from"]), new_dirs, custom=d.get("custom"))
        finally:
            # staged tars are consumed (or the swap failed) either way —
            # leaving them would accumulate unbounded deep-store garbage
            # across failed merge attempts
            for uri in d.get("stagedTars", []):
                try:
                    self.controller.deepstore.delete(uri)
                # graftcheck: ignore[exception-hygiene] -- staged-tar GC is
                # best-effort; a missed delete is re-collected by the next
                # merge round, never a correctness issue
                except Exception:
                    pass
        return json_response({"status": "OK", "segments": new_names})

    def _table_status(self, parts, params, body):
        return json_response(self.controller.table_status(parts[0]))

    # -- admin/read APIs (reference: PinotTableRestletResource et al.) -------
    # reads snapshot under catalog._lock: handlers run on concurrent HTTP
    # threads while writers mutate the same dicts in place (same discipline as
    # _catalog_get above)
    def _get_tables(self, parts, params, body):
        # GET /tables/{t}/ingestionStatus (reference:
        # /tables/{tableName}/ingestionStatus) polls servers over HTTP, so it
        # must not run under the catalog lock
        if len(parts) == 2 and parts[1] == "ingestionStatus":
            try:
                return json_response(self.controller.ingestion_status(parts[0]))
            except ValueError as e:
                return error_response(str(e), 404)
        # GET /tables/{t}/sloStatus — the burn-rate verdict computed by the
        # controller's periodic SLO check (companion of ingestionStatus)
        if len(parts) == 2 and parts[1] == "sloStatus":
            try:
                return json_response(self.controller.slo_status(parts[0]))
            except ValueError as e:
                return error_response(str(e), 404)
        # GET /tables/{t}/memoryStatus — the cluster HBM residency verdict
        # computed by the controller's periodic memory check
        if len(parts) == 2 and parts[1] == "memoryStatus":
            try:
                return json_response(self.controller.memory_status(parts[0]))
            except ValueError as e:
                return error_response(str(e), 404)
        with self.catalog._lock:
            if parts:  # GET /tables/{nameWithType} -> the table config
                cfg = self.catalog.table_configs.get(parts[0])
                resp = None if cfg is None else {"config": cfg.to_json()}
            else:
                resp = {"tables": sorted(self.catalog.table_configs)}
        if resp is None:
            return error_response(f"unknown table {parts[0]}", 404)
        return json_response(resp)

    def _get_schema(self, parts, params, body):
        with self.catalog._lock:
            schema = self.catalog.schemas.get(parts[0]) if parts else None
            resp = schema.to_json() if schema is not None else None
        if resp is None:
            return error_response(f"unknown schema {parts[0] if parts else ''}", 404)
        return json_response(resp)

    def _segments_meta(self, parts, params, body):
        """GET /segmentsMeta/{tableNameWithType} — per-segment metadata list."""
        table = parts[0]
        with self.catalog._lock:
            segs = self.catalog.segments.get(table)
            resp = None if segs is None else \
                {"segments": {s: m.to_json() for s, m in segs.items()}}
        if resp is None:
            return error_response(f"unknown table {table}", 404)
        return json_response(resp)

    def _reload_table(self, parts, params, body):
        if parts[0] not in self.catalog.table_configs:
            return error_response(f"unknown table {parts[0]}", 404)
        self.controller.reload_table(parts[0])
        return json_response({"status": "OK", "table": parts[0]})

    def _get_cluster_configs(self, parts, params, body):
        """GET /clusterConfigs (reference: /cluster/configs +
        OperateClusterConfigCommand) — cluster-level dynamic settings, stored
        in the catalog property store under clusterConfig/."""
        with self.catalog._lock:
            out = {k.split("/", 1)[1]: v for k, v in self.catalog.properties.items()
                   if k.startswith("clusterConfig/")}
        return json_response({"clusterConfigs": out})

    def _set_cluster_config(self, parts, params, body):
        """POST /clusterConfigs with {"key": ..., "value": ...} (value null
        deletes)."""
        d = json.loads(body.decode())
        self.catalog.put_property(f"clusterConfig/{d['key']}", d.get("value"))
        return json_response({"status": "OK", "key": d["key"],
                              "value": d.get("value")})

    def _table_state(self, parts, params, body):
        """POST /tableState/{table}?state=enable|disable (reference:
        ChangeTableState)."""
        state = str(params.get("state", "")).lower()
        if state not in ("enable", "disable"):
            return error_response("state must be enable|disable", 400)
        try:
            self.controller.set_table_state(parts[0], state == "enable")
        except ValueError as e:
            return error_response(str(e), 404)
        return json_response({"status": "OK", "table": parts[0], "state": state})

    def _list_tenants(self, parts, params, body):
        """GET /tenants (reference: PinotTenantRestletResource.getAllTenants)."""
        return json_response({"tenants": self.controller.list_tenants()})

    def _update_instance_tags(self, parts, params, body):
        """POST /instanceTags/{instanceId} with {"tags": [...]} (reference:
        PinotInstanceRestletResource.updateInstanceTags)."""
        d = json.loads(body.decode())
        try:
            self.controller.update_instance_tags(parts[0], list(d["tags"]))
        except ValueError as e:
            return error_response(str(e), 404)
        return json_response({"status": "OK", "instance": parts[0],
                              "tags": d["tags"]})

    def _pause_consumption(self, parts, params, body):
        """POST /pauseConsumption/{tableNameWithType} (reference:
        PinotRealtimeTableResource.pauseConsumption)."""
        try:
            return json_response(self.controller.llc.pause_consumption(parts[0]))
        except ValueError as e:
            return error_response(str(e), 400)

    def _resume_consumption(self, parts, params, body):
        try:
            return json_response(self.controller.llc.resume_consumption(parts[0]))
        except ValueError as e:
            return error_response(str(e), 400)

    def _rebalance(self, parts, params, body):
        moves = self.controller.rebalance(parts[0])
        return json_response({"status": "OK", "idealState": moves})

    def _validate(self, parts, params, body):
        """POST /validate — run one RealtimeSegmentValidationManager round now
        (successor repair, dead-replica reassignment, peer-segment healing);
        the same work the 60s periodic task does, on demand for operators."""
        return json_response(self.controller.llc.validate())

    # -- segment completion protocol ----------------------------------------
    def _segment_consumed(self, parts, params, body):
        d = json.loads(body.decode())
        return json_response(self.controller.llc.segment_consumed(
            d["segment"], d["server"], int(d["offset"])))

    def _segment_commit_start(self, parts, params, body):
        d = json.loads(body.decode())
        return json_response({"status": self.controller.llc.segment_commit_start(
            d["segment"], d["server"])})

    def _segment_commit_end(self, parts, params, body):
        """Commit with segment upload: body is the built segment tar."""
        segment = params["segment"]
        server = params["server"]
        offset = int(params["offset"])
        with tempfile.TemporaryDirectory() as tmp:
            seg_dir = _untar_body(body, segment, tmp)
            status = self.controller.llc.segment_commit_end(
                segment, server, seg_dir, offset)
        return json_response({"status": status})

    # -- deep-store proxy ----------------------------------------------------
    def _deepstore_get(self, parts, params, body):
        uri = "/".join(parts)
        # deep-store URIs lead with the table name ("{table}/{segment}.tar.gz"):
        # a table-scoped reader must not exfiltrate raw segments of denied tables
        from ..auth import require_table_access
        if parts:
            require_table_access(parts[0], "READ")
        with tempfile.TemporaryDirectory() as tmp:
            local = os.path.join(tmp, "blob")
            self.controller.deepstore.download(uri, local)
            with open(local, "rb") as f:
                return binary_response(f.read())

    def _deepstore_post(self, parts, params, body):
        uri = "/".join(parts)
        with tempfile.TemporaryDirectory() as tmp:
            local = os.path.join(tmp, "blob")
            with open(local, "wb") as f:
                f.write(body)
            self.controller.deepstore.upload(local, uri)
        return json_response({"status": "OK"})


class ServerService:
    """Server role process: query endpoint over the binary wire format."""

    def __init__(self, server: ServerNode, host: str = "127.0.0.1", port: int = 0,
                 access_control=None, ssl_context=None):
        self.server = server
        # graftfault: cluster-wide chaos drills install the plane at role
        # startup from the `fault.schedule` clusterConfig knob
        from ..utils.faults import activate_from_config
        activate_from_config(server.catalog)
        _configure_journal(server.catalog, server.instance_id)
        self.http = HttpService(host, port, access_control=access_control,
                                ssl_context=ssl_context)
        # mux executor: queries demuxed off mux streams run here, NOT on the
        # HTTP worker that owns the stream (it is busy reading frames); sized
        # by `server.mux.workers` — the scheduler underneath still enforces
        # its own admission control, this only bounds decode/dispatch threads
        workers = int(server.catalog.get_property(
            "clusterConfig/server.mux.workers", 16))
        self._mux_pool = ThreadPoolExecutor(max_workers=max(1, workers),
                                            thread_name_prefix="mux-exec")
        self._mux_open = 0           # open mux streams (gauge has no inc/dec)
        self._mux_lock = threading.Lock()
        self.http.route("POST", "query", self._query)
        self.http.route("POST", "mux", self._mux, duplex=True)
        self.http.route("POST", "explain", self._explain)
        self.http.route("POST", "stage", self._stage)
        # peer-to-peer mailbox shuffle (reference: GrpcMailboxService +
        # MailboxSend/ReceiveOperator; see multistage/shuffle.py)
        self.http.route("POST", "mailbox", self._mailbox, stream_body=True)
        self.http.route("DELETE", "mailbox", self._mailbox_cancel)
        self.http.route("POST", "leafStage", self._leaf_stage)
        self.http.route("POST", "leafAgg", self._leaf_agg)
        self.http.route("POST", "joinStage", self._join_stage)
        self.http.route("POST", "aggStage", self._agg_stage)
        self.http.route("GET", "health", self._health)
        self.http.route("GET", "debug", self._debug)
        self.http.route("GET", "segments", self._segments)
        self.http.route("GET", "segmentData", self._segment_data)
        self.http.route("GET", "metrics", _metrics_route)
        self.http.start()
        # advertise the query endpoint so brokers can find us (reference: Helix
        # instance config carries host/port)
        info = server.catalog.instances.get(server.instance_id)
        tags = info.tags if info else ["DefaultTenant"]
        server.catalog.register_instance(InstanceInfo(
            server.instance_id, "server", host=self.http.host,
            port=self.http.port, tags=tags, scheme=self.http.scheme))
        # device-routed shuffle: mark this process as the owner of our mailbox
        # endpoint so exchange legs targeting it skip the HTTP hop
        from ..multistage.shuffle import register_local_endpoint
        register_local_endpoint(self.http.url)
        # tiered storage: the HBM pressure sweep runs as a background
        # periodic task in real server processes (tests drive
        # tiering.run_pressure_sweep() directly for determinism)
        server.start_pressure_loop()

    @property
    def url(self) -> str:
        return self.http.url

    def stop(self) -> None:
        from ..multistage.shuffle import unregister_local_endpoint
        unregister_local_endpoint(self.http.url)
        self.server.stop_pressure_loop()
        self.http.stop()
        self._mux_pool.shutdown(wait=False)

    def _mux(self, parts, params, body):
        """POST /mux — one duplex multiplexed query stream (cluster/mux.py):
        tagged request frames demux into the mux executor under a per-stream
        flow-control window (`server.mux.max.inflight`); response frames
        stream back out of order as queries finish. The 200 + chunked headers
        go out before any frame is read — the client reads and writes
        concurrently on the one exchange."""
        from ..auth import current_principal
        from ..utils.metrics import get_registry
        from .mux import serve_mux_stream
        reg = get_registry()
        frames = reg.counter("pinot_server_mux_frames")
        streams_gauge = reg.gauge("pinot_server_mux_streams")
        with self._mux_lock:
            self._mux_open += 1
            streams_gauge.set(self._mux_open)
        max_inflight = int(self.server.catalog.get_property(
            "clusterConfig/server.mux.max.inflight", 64))
        inner = serve_mux_stream(body, self._mux_execute,
                                 executor=self._mux_pool,
                                 max_inflight=max(1, max_inflight),
                                 principal=current_principal(),
                                 on_frame=frames.inc)

        def gen():
            try:
                yield from inner
            finally:
                with self._mux_lock:
                    self._mux_open -= 1
                    streams_gauge.set(self._mux_open)
        return 200, "application/octet-stream", gen()

    def _reject_body(self, e) -> dict:
        """429 body: the error plus a Retry-After hint. The scheduler stamps
        its drain-rate estimate on the exception; when absent (e.g. a quota
        bucket rejection) fall back to asking the scheduler directly so every
        429 tells the client WHEN retrying could succeed."""
        body = {"error": str(e)}
        hint = getattr(e, "retry_after_ms", None)
        if hint is None and self.server.scheduler is not None:
            hint = self.server.scheduler.retry_after_ms()
        if hint is not None:
            body["retryAfterMs"] = round(float(hint), 3)
        return body

    @staticmethod
    def _timeout_body(e) -> dict:
        """408 body: the error plus the absolute deadline that expired, so the
        client can see exactly how stale its budget was."""
        body = {"error": str(e)}
        d = getattr(e, "deadline_epoch_ms", None)
        if d is not None:
            body["deadlineEpochMs"] = round(float(d), 3)
        return body

    def _mux_execute(self, payload, flow_wait_ms):
        """One mux request frame -> (status, response parts). Mirrors
        `_query` exactly — same ACL check, trace-splice surface, and
        backpressure statuses (429/408 ride the frame like HTTP statuses so
        the broker's failure taxonomy is transport-agnostic) — plus the
        flow-control wait recorded as a span and a stats key, keeping the
        milliseconds a frame spent gated by the window attributable. The
        response is gathered `encode_segment_result_parts` buffers: array
        payloads go to the socket without an intermediate join."""
        import time as _time
        from ..auth import require_table_access
        from ..query.scheduler import QueryRejectedError, QueryTimeoutError
        from ..query.stats import MUX_FLOW_CONTROL_MS
        from ..utils.trace import request_trace
        from .wire import encode_segment_result_parts
        t_decode = _time.perf_counter()
        req = decode_query_request(payload)
        decode_ms = (_time.perf_counter() - t_decode) * 1000
        require_table_access(req["table"], "READ")
        try:
            with request_trace(bool(req.get("trace")),
                               trace_id=req.get("traceId") or None) as tr:
                if tr is not None:
                    # pre-origin, like _query's deserialize: the window wait
                    # and the wire decode both preceded this trace's origin
                    if flow_wait_ms:
                        tr.record("mux:flow_control",
                                  -(decode_ms + flow_wait_ms), flow_wait_ms)
                    tr.record("deserialize", -decode_ms, decode_ms)
                result = self.server.execute_partial(
                    req["table"], req["sql"], req["segments"],
                    time_filter=req.get("timeFilter"))
        except QueryRejectedError as e:  # backpressure, not a server fault
            return 429, [json.dumps(self._reject_body(e)).encode()]
        except QueryTimeoutError as e:
            return 408, [json.dumps(self._timeout_body(e)).encode()]
        if flow_wait_ms:
            stats = result.stats if isinstance(result.stats, dict) else {}
            stats[MUX_FLOW_CONTROL_MS] = round(
                stats.get(MUX_FLOW_CONTROL_MS, 0.0) + flow_wait_ms, 3)
            result.stats = stats
        spans = None
        if tr is not None:
            spans = [dict(s,
                          name=f"server:{self.server.instance_id}/{s['name']}")
                     for s in tr.to_rows()]
        return 200, encode_segment_result_parts(result, trace_spans=spans)

    def _query(self, parts, params, body):
        import time as _time
        from ..auth import require_table_access
        from ..query.scheduler import QueryRejectedError, QueryTimeoutError
        from ..utils.trace import request_trace
        t_decode = _time.perf_counter()
        req = decode_query_request(body)
        decode_ms = (_time.perf_counter() - t_decode) * 1000
        require_table_access(req["table"], "READ")
        try:
            # traceId propagates the dispatching broker's trace context so this
            # server's spans splice into the SAME distributed trace
            with request_trace(bool(req.get("trace")),
                               trace_id=req.get("traceId") or None) as tr:
                if tr is not None:
                    # the wire decode ran just before this trace's origin;
                    # record it pre-origin (negative start) so the hop reads
                    # serialize -> send -> deserialize -> execute once rebased
                    tr.record("deserialize", -decode_ms, decode_ms)
                result = self.server.execute_partial(
                    req["table"], req["sql"], req["segments"],
                    time_filter=req.get("timeFilter"))
        except QueryRejectedError as e:   # backpressure, not a server fault
            return 429, "application/json", json.dumps(
                self._reject_body(e)).encode()
        except QueryTimeoutError as e:
            return 408, "application/json", json.dumps(
                self._timeout_body(e)).encode()
        spans = None
        if tr is not None:
            # prefix with this server's id so the broker's spliced view reads like
            # its own scatter spans (server:<id>/segment:...)
            spans = [dict(s, name=f"server:{self.server.instance_id}/{s['name']}")
                     for s in tr.to_rows()]
        return binary_response(encode_segment_result(result, trace_spans=spans))

    def _health(self, parts, params, body):
        """GET /health — pure liveness, always 200 while the process serves
        HTTP; GET /health/readiness — 503 until every ideal-state-assigned
        segment is served or consuming (reference: /health vs
        /health/readiness gated on ServiceStatus). Both are credential-less
        so orchestrators can probe without a token."""
        st = self.server.startup_status()
        st["instance"] = self.server.instance_id
        if self.server.device_pipeline is not None:
            # device-serving observability: batch sizes prove the pipeline
            # amortized fetches; tests/bench read this to verify the served
            # path actually executed on the device
            st["device"] = self.server.device_pipeline.stats()
        if parts and parts[0] == "readiness":
            return json_response(st, status=200 if st["ready"] else 503)
        return json_response(st, status=200)

    def _debug(self, parts, params, body):
        """GET /debug — server metric rollup + gauge rings; GET
        /debug/consuming — consumingSegmentsInfo analog: per-consuming-segment
        offsets, lag, and consumer state for every realtime table; GET
        /debug/memory — the HBM residency ledger panel (top segments by
        bytes, kind breakdown, watermark history, headroom)."""
        from ..utils.metrics import get_registry
        if parts and parts[0] == "consuming":
            return json_response({"instance": self.server.instance_id,
                                  "tables": self.server.ingestion_snapshot()})
        if parts and parts[0] == "memory":
            return json_response(self.server.memory_snapshot())
        if parts and parts[0] == "events":
            return _events_route(params)
        reg = get_registry()
        return json_response({
            "instance": self.server.instance_id,
            "serverMetrics": {k: v for k, v in reg.snapshot().items()
                              if k.startswith("pinot_server")},
            "gaugeHistories": reg.gauge_histories("pinot_server"),
        })

    def _explain(self, parts, params, body):
        from ..auth import require_table_access
        req = decode_query_request(body)
        require_table_access(req["table"], "READ")  # plans leak schema/indexes
        rows = self.server.explain_partial(req["table"], req["sql"],
                                           req["segments"])
        return json_response({"rows": rows})

    # rows per streamed stage-output frame: bounded buffering on both sides
    STAGE_FRAME_ROWS = 65536

    def _stage(self, parts, params, body):
        """POST /stage — run one multistage stage partition on this server:
        hash join, plus the partial GROUP BY when the broker marks this the
        final aggregation stage (reference: an intermediate-stage worker
        consuming its mailbox + AggregateOperator partial mode).

        The response STREAMS over chunked HTTP as length-prefixed wire
        frames: joined rows leave in bounded-row block frames as they are
        sliced (the mailbox-stream analog — neither side buffers the whole
        joined output), and a partial-aggregation result is one frame."""
        import struct

        from ..multistage.runtime import (agg_spec_from_json, run_join_stage,
                                          spec_from_json)
        from ..utils.metrics import get_registry
        from .wire import (decode_block, decode_value, encode_segment_result,
                           encode_value)
        d = decode_value(body)
        out = run_join_stage(spec_from_json(d["spec"]),
                             decode_block(d["left"]), decode_block(d["right"]),
                             agg_spec_from_json(d.get("agg")))
        get_registry().counter("pinot_server_join_stages").inc()

        def frame(obj) -> bytes:
            payload = encode_value(obj)
            return struct.pack(">I", len(payload)) + payload

        def gen():
            if isinstance(out, dict):  # joined block -> bounded row frames
                n = 0
                for v in out.values():
                    n = len(v)
                    break
                step = self.STAGE_FRAME_ROWS
                for lo in range(0, max(n, 1), step):
                    yield frame({"kind": "rows",
                                 "block": {c: v[lo:lo + step]
                                           for c, v in out.items()}})
            else:  # partial aggregation result
                yield frame({"kind": "partial",
                             "result": encode_segment_result(out)})
            yield frame({"kind": "end"})
        return 200, "application/octet-stream", gen()

    # -- peer-to-peer mailbox shuffle endpoints ------------------------------

    def _mailbox(self, parts, params, body):
        """POST /mailbox/{queryId}/{mailboxId} — a PEER streams partition
        frames into this server's mailbox as a chunked request body. Frames
        are enqueued into a BOUNDED per-mailbox queue; when the consuming
        worker falls behind, the enqueue blocks, this thread stops reading the
        socket, and TCP flow control backpressures the sender (reference: the
        gRPC mailbox stream's flow-control window, mailbox.proto:43)."""
        from ..multistage.shuffle import (REGISTRY, MailboxCancelled,
                                          read_frame)
        from .wire import decode_block, decode_segment_result
        qid, mid = parts[0], parts[1]
        from ..utils.metrics import get_registry
        try:
            box = REGISTRY.open(qid, mid)
            while True:
                d = read_frame(body)
                if d["kind"] == "eos":
                    box.put(("eos", d["sender"]))
                    break
                if d["kind"] == "block":
                    box.put(("block", decode_block(d["block"])))
                else:
                    box.put(("partial", decode_segment_result(d["result"])))
                get_registry().counter("pinot_server_mailbox_frames").inc()
        except MailboxCancelled:
            # the 409 must also drain (see below) or the RST race turns a
            # clean "query cancelled" into a misleading connection reset on
            # the sender; the remainder is bounded by the sender's in-memory
            # partition
            try:
                body.drain()
            # graftcheck: ignore[exception-hygiene] -- best-effort drain on
            # the cancel path; the 409 below reports the real outcome
            except Exception:
                pass
            return error_response("query cancelled", 409)
        # drain the chunked-body terminator BEFORE responding: closing the
        # socket with unread bytes in the receive buffer sends a TCP RST that
        # races the 200 on the sender's side (flaky "connection reset")
        body.drain()
        return json_response({"ok": True})

    def _mailbox_cancel(self, parts, params, body):
        """DELETE /mailbox/{queryId} — cancel every mailbox of a query: wakes
        blocked senders and consumers so a failed query unwinds instead of
        hanging on backpressure."""
        from ..multistage.shuffle import REGISTRY
        REGISTRY.cancel_query(parts[0])
        return json_response({"ok": True})

    def _leaf_stage(self, parts, params, body):
        """POST /leafStage — scan local segments, hash-partition on the join
        keys, stream partition frames DIRECTLY to the stage workers' mailboxes
        (the MailboxSendOperator on top of the v1 leaf executor). The broker
        never sees these rows."""
        from ..auth import require_table_access
        from ..multistage.shuffle import run_leaf_join_task
        from .wire import decode_value, encode_value
        task = decode_value(body)
        require_table_access(task["table"], "READ")
        return binary_response(encode_value(run_leaf_join_task(
            self.server, task)))

    def _leaf_agg(self, parts, params, body):
        """POST /leafAgg — distributed single-table GROUP BY leaf: partial
        aggregation locally, group partials hash-partitioned by key and
        streamed to the merge workers."""
        from ..auth import require_table_access
        from ..multistage.shuffle import run_leaf_agg_task
        from .wire import decode_value, encode_value
        task = decode_value(body)
        require_table_access(task["table"], "READ")
        return binary_response(encode_value(run_leaf_agg_task(
            self.server, task)))

    def _join_stage(self, parts, params, body):
        """POST /joinStage — one join-stage partition: consume both side
        mailboxes, join, and either forward to the next stage's mailboxes or
        stream final partial frames back. Errors surface as a terminal error
        frame so the broker reports the cause instead of a truncated stream."""
        from ..multistage.shuffle import frame_bytes, run_join_stage_task
        from ..utils.metrics import get_registry
        from .wire import decode_value
        task = decode_value(body)
        get_registry().counter("pinot_server_join_stages").inc()

        def gen():
            try:
                yield from run_join_stage_task(task)
            except Exception as e:
                yield frame_bytes({"kind": "error",
                                   "message": f"{type(e).__name__}: {e}"})
        return 200, "application/octet-stream", gen()

    def _agg_stage(self, parts, params, body):
        """POST /aggStage — one merge partition of a distributed GROUP BY:
        merge this disjoint key range, apply HAVING + top-k trim, stream the
        merged partial back."""
        from ..multistage.shuffle import frame_bytes, run_agg_stage_task
        from .wire import decode_value
        task = decode_value(body)

        def gen():
            try:
                yield from run_agg_stage_task(task)
            except Exception as e:
                yield frame_bytes({"kind": "error",
                                   "message": f"{type(e).__name__}: {e}"})
        return 200, "application/octet-stream", gen()

    def _segments(self, parts, params, body):
        return json_response({"segments": self.server.segments_served(parts[0])})

    def _segment_data(self, parts, params, body):
        """GET /segmentData/{table}/{segment} — tar of this server's LOADED
        copy (reference: peer download scheme; every ONLINE replica can serve
        the committed bytes when the deep store can't)."""
        import tempfile as _tf

        from ..auth import require_table_access
        from .deepstore import tar_segment
        table, name = parts[0], parts[1]
        require_table_access(table, "READ")  # raw data = same ACL as queries
        seg_dir = self.server.local_segment_dir(table, name)
        if seg_dir is None:
            return error_response(f"{table}/{name} not served here", 404)
        with _tf.TemporaryDirectory() as tmp:
            tar_path = os.path.join(tmp, "seg.tar.gz")
            tar_segment(seg_dir, tar_path)
            with open(tar_path, "rb") as f:
                return binary_response(f.read())


class MinionService:
    """Minion role process: claims tasks from the controller and executes them
    (reference: `pinot-minion/.../MinionStarter.java` — a worker that registers
    with Helix, polls the task framework, and runs registered executors).

    The claim loop runs on a daemon thread: claim one task, execute, repeat;
    sleep `poll_s` when the queue is empty. Task failures never kill the loop
    (MinionWorker.run_once already fences + records them)."""

    def __init__(self, worker, host: str = "127.0.0.1", port: int = 0,
                 poll_s: float = 1.0, access_control=None, ssl_context=None):
        self.worker = worker
        self.poll_s = poll_s
        self._stop = threading.Event()
        self.http = HttpService(host, port, access_control=access_control,
                                ssl_context=ssl_context)
        self.http.route("GET", "health", self._health)
        self.http.route("GET", "tasks", self._tasks)
        self.http.route("GET", "metrics", _metrics_route)
        self.http.start()
        worker.catalog.register_instance(InstanceInfo(
            worker.instance_id, "minion", host=self.http.host,
            port=self.http.port, scheme=self.http.scheme))
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"{worker.instance_id}-loop")
        self._thread.start()

    @property
    def url(self) -> str:
        return self.http.url

    def _loop(self) -> None:
        from ..utils.metrics import get_registry
        reg = get_registry()
        while not self._stop.is_set():
            try:
                spec = self.worker.run_once()
            except Exception:
                # claim-transport hiccup (controller restarting): back off
                reg.counter("pinot_minion_claim_errors").inc()
                spec = None
            if spec is None:
                self._stop.wait(self.poll_s)
            else:
                reg.counter("pinot_minion_tasks_executed").inc()

    def _health(self, parts, params, body):
        return json_response({"status": "OK",
                              "instance": self.worker.instance_id,
                              "completed": self.worker.completed,
                              "failed": self.worker.failed})

    def _tasks(self, parts, params, body):
        return json_response({"completed": self.worker.completed,
                              "failed": self.worker.failed})

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self.http.stop()


class BrokerService:
    """Broker role process: SQL entry over HTTP; discovers servers via catalog."""

    def __init__(self, broker: Broker, host: str = "127.0.0.1", port: int = 0,
                 access_control=None, ssl_context=None,
                 mux: Optional[bool] = None):
        self.broker = broker
        # graftfault: brokers join cluster-wide chaos drills too (frame drops
        # and conn resets inject on the dispatching side)
        from ..utils.faults import activate_from_config
        activate_from_config(broker.catalog)
        _configure_journal(broker.catalog, broker.instance_id)
        self._registered: Dict[str, str] = {}   # instance_id -> endpoint url
        self._handles: Dict[str, RemoteServerHandle] = {}  # for close()
        # `mux` pins the server-dispatch transport (tests dispatch both ways
        # and diff); None defers to the `broker.mux.enabled` knob per handle
        self._mux_override = mux
        self.http = HttpService(host, port, access_control=access_control,
                                ssl_context=ssl_context)
        self.http.route("POST", "query", self._query)
        self.http.route("POST", "queryStream", self._query_stream)
        self.http.route("GET", "health",
                        lambda p, q, b: json_response({"status": "OK"}))
        self.http.route("GET", "metrics", _metrics_route)
        # GET /debug — query rollups + recent slow queries (JSON); the
        # operator-facing companion to the Prometheus /metrics exposition.
        # GET /debug/traces — the sampled-trace ring (see _debug).
        self.http.route("GET", "debug", self._debug)
        # subscribe BEFORE the initial scan: a server registering in between then
        # fires an event we handle (re-scan), instead of being silently missed
        broker.catalog.subscribe(self._on_event)
        self._wire_server_handles()
        self.broker.failure_detector.start()  # background re-probe loop
        self.http.start()
        # advertise the SQL endpoint (the controller's query-console proxy
        # and external clients discover brokers through the catalog)
        broker.catalog.register_instance(InstanceInfo(
            broker.instance_id, "broker", host=self.http.host,
            port=self.http.port, scheme=self.http.scheme))

    @property
    def url(self) -> str:
        return self.http.url

    def stop(self) -> None:
        self.broker.failure_detector.stop()  # kill the background probe loop
        for handle in self._handles.values():
            handle.close()   # retire mux streams (goodbye frame, join threads)
        self._handles.clear()
        self.http.stop()

    def _mux_enabled(self) -> bool:
        if self._mux_override is not None:
            return self._mux_override
        v = self.broker.catalog.get_property(
            "clusterConfig/broker.mux.enabled", True)
        return str(v).lower() not in ("false", "0", "no")

    def _mux_streams(self) -> int:
        try:
            return max(1, int(self.broker.catalog.get_property(
                "clusterConfig/broker.mux.streams", 1)))
        except (TypeError, ValueError):
            return 1

    def _debug(self, parts, params, body):
        """GET /debug — broker query rollups. GET /debug/traces — the retained
        (sampled + slow) trace ring: `?id=<traceId>` resolves one trace (404
        when evicted/unknown), `?limit=N` bounds the listing, `?format=chrome`
        renders a Chrome trace-event document loadable in Perfetto.
        GET /debug/workload — the workload registry: per-shape profiles
        ranked by time share (`?k=N` trims the ranking, `?fp=<fingerprint>`
        drills into one shape, 404 when unknown/evicted)."""
        if parts and parts[0] == "workload":
            fp = params.get("fp")
            if fp:
                prof = self.broker.workload.shape(fp)
                if prof is None:
                    return error_response(f"unknown shape {fp}", 404)
                return json_response(prof)
            try:
                k = int(params["k"]) if "k" in params else None
            except (TypeError, ValueError):
                k = None
            return json_response(self.broker.workload.snapshot(k))
        if parts and parts[0] == "events":
            return _events_route(params)
        if parts and parts[0] == "traces":
            from ..utils.trace import to_chrome_trace
            ring = self.broker.trace_ring
            trace_id = params.get("id")
            if trace_id:
                entry = ring.get(trace_id)
                if entry is None:
                    return error_response(f"unknown trace {trace_id}", 404)
                if params.get("format") == "chrome":
                    return json_response(to_chrome_trace(entry))
                return json_response(entry)
            try:
                limit = int(params["limit"]) if "limit" in params else None
            except (TypeError, ValueError):
                limit = None
            traces = ring.entries(limit)
            if params.get("format") == "chrome":
                return json_response(to_chrome_trace(traces))
            return json_response({"traces": traces, "retained": len(ring),
                                  "capacity": ring.capacity})
        return (200, "application/json",
                json.dumps(self.broker.debug_stats(), default=str).encode())

    def _on_event(self, event: str, _key: str) -> None:
        if event == "instance":
            self._wire_server_handles()

    def _wire_server_handles(self) -> None:
        """Register an HTTP handle for every advertised live server instance.

        Only new/changed endpoints are (re)registered — re-registering marks the
        server healthy, which must not resurrect a server the failure detector
        already excluded (reference: routing exclusion survives until the
        detector's retry probe succeeds). Decommissioned/dead instances are
        FORGOTTEN by the detector so their probes stop and a reused port can
        never re-admit a dead server id."""
        for info in list(self.broker.catalog.instances.values()):
            if info.role != "server" or not info.port:
                continue
            if not info.alive:
                if self._registered.pop(info.instance_id, None):
                    self.broker.unregister_server(info.instance_id)
                    old = self._handles.pop(info.instance_id, None)
                    if old is not None:
                        old.close()
                continue
            url = info.url
            if self._registered.get(info.instance_id) == url:
                continue
            self._registered[info.instance_id] = url
            handle = RemoteServerHandle(url, use_mux=self._mux_enabled(),
                                        mux_streams=self._mux_streams())
            old = self._handles.pop(info.instance_id, None)
            if old is not None:
                old.close()  # endpoint changed: retire the old mux streams
            self._handles[info.instance_id] = handle

            def probe(u=url):
                # /health is auth-exempt; ready=false still proves liveness
                from .http_service import HttpError, http_call
                try:
                    http_call("GET", f"{u}/health", timeout=2.0)
                    return True
                except HttpError as e:
                    return e.status == 503  # alive but not ready: re-admit
                except Exception:
                    return False
            self.broker.register_server_handle(info.instance_id, handle,
                                               explain_handle=handle.explain,
                                               probe=probe,
                                               stage_handle=handle.join_stage,
                                               url=url)

    def _query(self, parts, params, body):
        d = json.loads(body.decode())
        sql = d["sql"]
        # table-level ACL before any work (reference: broker AccessControl
        # .hasAccess(requesterIdentity, tables) right after compile). The parsed
        # statement is handed to the broker so the SQL is parsed ONCE; a parse
        # failure defers to handle_query, which raises AND counts the broker
        # query-exception meter.
        from ..auth import current_principal, require_table_access
        stmt = None
        if current_principal() is not None:
            from ..sql.parser import parse_query
            try:
                stmt = parse_query(sql)
            except Exception:
                stmt = None
            if stmt is not None:
                for table in [stmt.table] + [j.table for j in stmt.joins]:
                    require_table_access(table, "READ")
        result = self.broker.handle_query(sql, stmt=stmt)
        return json_response(result.to_json())

    def _query_stream(self, parts, params, body):
        """POST /queryStream — JSON-lines over chunked HTTP: one
        {"columns": [...]} line, then {"rows": [...]} lines per batch
        (reference: the gRPC streaming endpoint server.proto:42)."""
        d = json.loads(body.decode())
        sql = d["sql"]
        from ..auth import current_principal, require_table_access
        stmt = None
        if current_principal() is not None:
            from ..sql.parser import parse_query
            try:
                stmt = parse_query(sql)
            except Exception:
                stmt = None
            if stmt is not None:
                for table in [stmt.table] + [j.table for j in stmt.joins]:
                    require_table_access(table, "READ")

        def gen(stmt=stmt):
            from ..query.result import _jsonify
            for kind, payload in self.broker.stream_query(sql, stmt=stmt):
                if kind == "schema":
                    yield (json.dumps({"columns": payload}) + "\n").encode()
                else:
                    yield (json.dumps(
                        {"rows": [[_jsonify(v) for v in r] for r in payload]})
                        + "\n").encode()
        return 200, "application/x-ndjson", gen()
