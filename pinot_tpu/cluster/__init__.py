"""Cluster control plane: catalog, controller, broker, server roles.

TPU-native replacement for the reference's Helix/ZooKeeper control plane (SURVEY.md §1
"Control plane backbone"): a single lightweight catalog holds what the reference keeps in
ZK — table configs, schemas, segment metadata, IdealState (desired) and ExternalView
(actual) — with watch callbacks in place of Helix state transitions. Roles are plain
Python objects that run in-process (the single-process cluster test enclosure, reference:
`ClusterTest.java:88`) or behind the stdlib-HTTP data plane in `transport.py`.
"""

from .catalog import Catalog, SegmentMeta
from .controller import Controller
from .broker import Broker
from .server import ServerNode
from .enclosure import QuickCluster

__all__ = ["Catalog", "SegmentMeta", "Controller", "Broker", "ServerNode", "QuickCluster"]
