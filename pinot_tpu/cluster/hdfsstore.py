"""HDFS deep store over the WebHDFS REST API as a PinotFS-analog scheme.

Analog of the reference's HDFS plugin
(`pinot-plugins/pinot-file-system/pinot-hdfs/src/main/java/org/apache/pinot/
plugin/filesystem/HadoopPinotFS.java`): where that plugin drives
org.apache.hadoop.fs.FileSystem, this one speaks the PUBLIC WebHDFS REST
protocol every namenode exposes — including the TWO-STEP redirect dance:
CREATE/OPEN answer `307 Location: <datanode-url>` and the data transfer goes
to the redirect target (`PUT ...?op=CREATE` -> 307 -> PUT data -> 201).
Unlike the object stores, HDFS is a real filesystem: DELETE is natively
recursive, RENAME is a metadata move (no copy+delete), and directories
exist — so this class implements DeepStoreFS directly instead of the
object-store base.

Ops: CREATE, OPEN, MKDIRS, DELETE(recursive), RENAME, GETFILESTATUS,
LISTSTATUS — the subset HadoopPinotFS uses (copyFromLocal/copyToLocal/
delete/move/exists/listFiles).

Spec: `hdfs://root-path?endpoint=http://host:port[&user=alice]` — the
endpoint is the namenode's HTTP address (`/webhdfs/v1` is appended), `user`
rides `user.name` like Hadoop simple auth. The in-repo `HdfsStub` proves the
wire seam (incl. the 307 redirects); pointing at a real namenode is a
config change.
"""

from __future__ import annotations

# graftcheck: ignore[transport-bypass] -- external WebHDFS namenode/datanode
# endpoints, not the cluster data plane; the 307-redirect dance needs raw
# connection control
import http.client
import json
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from .deepstore import DeepStoreFS, register_fs


class HdfsError(OSError):
    def __init__(self, status: int, message: str = ""):
        super().__init__(f"WebHDFS {status}: {message}")
        self.status = status


class HdfsDeepStoreFS(DeepStoreFS):
    scheme = "hdfs"

    def __init__(self, root: str):
        base, _, query = root.partition("?")
        params = dict(urllib.parse.parse_qsl(query))
        self.endpoint = params.get("endpoint", "").rstrip("/")
        if not self.endpoint:
            raise ValueError("hdfs deep store requires "
                             "?endpoint=http://namenode:port")
        self.root = "/" + base.strip("/")
        self.user = params.get("user", "")
        self.timeout_s = float(params.get("timeoutSec", 30.0))

    # -- wire ---------------------------------------------------------------
    def _path(self, uri: str) -> str:
        p = uri.strip("/")
        return f"{self.root}/{p}" if p else self.root

    def _url(self, path: str, op: str, **extra) -> str:
        q = {"op": op}
        if self.user:
            q["user.name"] = self.user
        q.update({k: v for k, v in extra.items() if v is not None})
        quoted = urllib.parse.quote(path)
        return (f"{self.endpoint}/webhdfs/v1{quoted}"
                f"?{urllib.parse.urlencode(q)}")

    def _request(self, method: str, url: str, body=None,
                 follow_redirect: bool = True) -> Tuple[int, bytes, str]:
        """One HTTP exchange WITHOUT automatic redirect mangling (urllib
        would turn a redirected PUT into a GET); returns (status, body,
        location). The WebHDFS two-step is explicit in the callers."""
        parts = urllib.parse.urlsplit(url)
        conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                          timeout=self.timeout_s)
        try:
            path = parts.path + ("?" + parts.query if parts.query else "")
            headers = {"Content-Type": "application/octet-stream"}
            if body is not None and not hasattr(body, "read"):
                headers["Content-Length"] = str(len(body))
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            loc = resp.getheader("Location", "")
            if resp.status in (301, 302, 307) and follow_redirect and loc:
                return self._request(method, loc, body,
                                     follow_redirect=False)
            return resp.status, data, loc
        finally:
            conn.close()

    def _two_step_put(self, url: str, body) -> None:
        """CREATE dance: PUT no-body -> 307 Location -> PUT data there."""
        status, data, loc = self._request("PUT", url, None,
                                          follow_redirect=False)
        if status in (301, 302, 307) and loc:
            status, data, _ = self._request("PUT", loc, body,
                                            follow_redirect=False)
        if status not in (200, 201):
            raise HdfsError(status, data[:200].decode(errors="replace"))

    def _check(self, status: int, data: bytes) -> bytes:
        if status == 404:
            raise FileNotFoundError(data[:200].decode(errors="replace"))
        if status >= 400:
            raise HdfsError(status, data[:200].decode(errors="replace"))
        return data

    # -- DeepStoreFS --------------------------------------------------------
    def upload(self, local_path: str, uri: str) -> None:
        # STREAMING from the open file (Content-Length from stat): a multi-GB
        # segment tar never buffers in memory
        with open(local_path, "rb") as f:
            size = os.path.getsize(local_path)
            url = self._url(self._path(uri), "CREATE", overwrite="true")
            status, data, loc = self._request("PUT", url, None,
                                              follow_redirect=False)
            if status in (301, 302, 307) and loc:
                parts = urllib.parse.urlsplit(loc)
                conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                                  timeout=self.timeout_s)
                try:
                    conn.request("PUT", parts.path + "?" + parts.query,
                                 body=f,
                                 headers={"Content-Length": str(size)})
                    resp = conn.getresponse()
                    self._check(resp.status, resp.read())
                finally:
                    conn.close()
            else:
                self._check(status, data)

    def put_bytes(self, data: bytes, uri: str) -> None:
        self._two_step_put(self._url(self._path(uri), "CREATE",
                                     overwrite="true"), data)

    def get_bytes(self, uri: str) -> bytes:
        status, data, _ = self._request(
            "GET", self._url(self._path(uri), "OPEN"))
        return self._check(status, data)

    def download(self, uri: str, local_path: str) -> None:
        """STREAMING to disk in chunks — the upload side deliberately never
        buffers a multi-GB segment tar in memory, and neither does this."""
        os.makedirs(os.path.dirname(local_path) or ".", exist_ok=True)
        url = self._url(self._path(uri), "OPEN")
        for _hop in range(3):   # namenode -> datanode redirect chain
            parts = urllib.parse.urlsplit(url)
            conn = http.client.HTTPConnection(parts.hostname, parts.port,
                                              timeout=self.timeout_s)
            try:
                conn.request("GET", parts.path +
                             ("?" + parts.query if parts.query else ""))
                resp = conn.getresponse()
                if resp.status in (301, 302, 307):
                    resp.read()
                    url = resp.getheader("Location", "")
                    if not url:
                        raise HdfsError(resp.status, "redirect without location")
                    continue
                self._check(resp.status, b"" if resp.status < 400
                            else resp.read())
                with open(local_path, "wb") as f:
                    while True:
                        chunk = resp.read(1 << 20)
                        if not chunk:
                            break
                        f.write(chunk)
                return
            finally:
                conn.close()
        raise HdfsError(310, f"too many redirects for {uri}")

    def delete(self, uri: str) -> None:
        status, data, _ = self._request(
            "DELETE", self._url(self._path(uri), "DELETE", recursive="true"))
        self._check(status, data)

    def move(self, src_uri: str, dst_uri: str) -> None:
        """Native metadata rename — no copy+delete round trip."""
        dst = self._path(dst_uri)
        parent = dst.rsplit("/", 1)[0]
        if parent:
            self._request("PUT", self._url(parent, "MKDIRS"))
        status, data, _ = self._request(
            "PUT", self._url(self._path(src_uri), "RENAME", destination=dst))
        d = json.loads(self._check(status, data) or b"{}")
        if not d.get("boolean", False):
            raise HdfsError(500, f"rename {src_uri} -> {dst_uri} refused")

    def exists(self, uri: str) -> bool:
        status, data, _ = self._request(
            "GET", self._url(self._path(uri), "GETFILESTATUS"))
        if status == 404:
            return False
        self._check(status, data)
        return True

    def listdir(self, uri: str) -> List[str]:
        status, data, _ = self._request(
            "GET", self._url(self._path(uri), "LISTSTATUS"))
        if status == 404:
            return []
        d = json.loads(self._check(status, data))
        return sorted(s["pathSuffix"]
                      for s in d.get("FileStatuses", {}).get("FileStatus", []))


def _hdfs_fs(root: str) -> DeepStoreFS:
    return HdfsDeepStoreFS(root)


register_fs("hdfs", _hdfs_fs)


# ---------------------------------------------------------------------------
# in-repo WebHDFS stub (namenode + "datanode" on one server, real redirects)
# ---------------------------------------------------------------------------

class HdfsStub:
    """Minimal WebHDFS endpoint: CREATE/OPEN answer 307 redirects to the
    same server with `&step2=true` (the namenode->datanode dance), MKDIRS /
    DELETE(recursive) / RENAME / GETFILESTATUS / LISTSTATUS over an
    in-memory path tree; an `outage` switch for chaos tests."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.files: Dict[str, bytes] = {}
        self.dirs = {"/"}
        self.outage = False
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, status: int, body: bytes = b"",
                       location: str = "") -> None:
                self.send_response(status)
                if location:
                    self.send_header("Location", location)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _parts(self):
                parsed = urllib.parse.urlsplit(self.path)
                assert parsed.path.startswith("/webhdfs/v1"), parsed.path
                path = urllib.parse.unquote(parsed.path[len("/webhdfs/v1"):]) \
                    or "/"
                q = dict(urllib.parse.parse_qsl(parsed.query))
                return path, q

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def _guard(self) -> bool:
                if stub.outage:
                    self._reply(503, json.dumps({"RemoteException": {
                        "message": "stub outage"}}).encode())
                    return True
                return False

            def do_PUT(self):
                if self._guard():
                    return
                path, q = self._parts()
                op = q.get("op", "").upper()
                if op == "CREATE":
                    if q.get("step2") != "true":
                        loc = (f"http://{stub.host}:{stub.port}/webhdfs/v1"
                               f"{urllib.parse.quote(path)}?"
                               + urllib.parse.urlencode(
                                   dict(q, step2="true")))
                        self._body()  # drain
                        self._reply(307, b"", location=loc)
                        return
                    data = self._body()
                    with stub._lock:
                        if path in stub.dirs:
                            self._reply(403, b'{"RemoteException":{}}')
                            return
                        stub.files[path] = data
                        stub._mkparents(path)
                    self._reply(201)
                elif op == "MKDIRS":
                    with stub._lock:
                        stub.dirs.add(path)
                        stub._mkparents(path + "/x")
                    self._reply(200, b'{"boolean": true}')
                elif op == "RENAME":
                    dst = q.get("destination", "")
                    with stub._lock:
                        moved = False
                        if path in stub.files:
                            stub.files[dst] = stub.files.pop(path)
                            stub._mkparents(dst)
                            moved = True
                        else:
                            pre = path.rstrip("/") + "/"
                            keys = [k for k in stub.files if
                                    k.startswith(pre)]
                            for k in keys:
                                stub.files[dst + k[len(path):]] = \
                                    stub.files.pop(k)
                                moved = True
                            if path in stub.dirs:
                                stub.dirs.discard(path)
                                stub.dirs.add(dst)
                                moved = True
                    self._reply(200, json.dumps({"boolean": moved}).encode())
                else:
                    self._reply(400, b'{"RemoteException":{}}')

            def do_GET(self):
                if self._guard():
                    return
                path, q = self._parts()
                op = q.get("op", "").upper()
                with stub._lock:
                    if op == "OPEN":
                        if q.get("step2") != "true":
                            loc = (f"http://{stub.host}:{stub.port}"
                                   f"/webhdfs/v1{urllib.parse.quote(path)}?"
                                   + urllib.parse.urlencode(
                                       dict(q, step2="true")))
                            self._reply(307, b"", location=loc)
                            return
                        data = stub.files.get(path)
                        if data is None:
                            self._404(path)
                            return
                        self._reply(200, data)
                    elif op == "GETFILESTATUS":
                        if path in stub.files:
                            self._reply(200, json.dumps({"FileStatus": {
                                "type": "FILE", "length":
                                    len(stub.files[path])}}).encode())
                        elif stub._is_dir(path):
                            self._reply(200, json.dumps({"FileStatus": {
                                "type": "DIRECTORY",
                                "length": 0}}).encode())
                        else:
                            self._404(path)
                    elif op == "LISTSTATUS":
                        if path in stub.files:
                            self._reply(200, json.dumps({"FileStatuses": {
                                "FileStatus": [{"pathSuffix": "",
                                                "type": "FILE"}]}}).encode())
                            return
                        if not stub._is_dir(path):
                            self._404(path)
                            return
                        pre = path.rstrip("/") + "/"
                        names = set()
                        for k in list(stub.files) + list(stub.dirs):
                            if k.startswith(pre):
                                names.add(k[len(pre):].split("/", 1)[0])
                        self._reply(200, json.dumps({"FileStatuses": {
                            "FileStatus": [{"pathSuffix": n}
                                           for n in sorted(names)
                                           if n]}}).encode())
                    else:
                        self._reply(400, b'{"RemoteException":{}}')

            def do_DELETE(self):
                if self._guard():
                    return
                path, q = self._parts()
                recursive = q.get("recursive", "false") == "true"
                with stub._lock:
                    existed = False
                    if path in stub.files:
                        del stub.files[path]
                        existed = True
                    pre = path.rstrip("/") + "/"
                    children = [k for k in stub.files if k.startswith(pre)]
                    if children and not recursive:
                        self._reply(403, b'{"RemoteException":{}}')
                        return
                    for k in children:
                        del stub.files[k]
                        existed = True
                    for d in [d for d in stub.dirs
                              if d == path or d.startswith(pre)]:
                        stub.dirs.discard(d)
                        existed = True
                self._reply(200, json.dumps({"boolean": existed}).encode())

            def _404(self, path: str) -> None:
                self._reply(404, json.dumps({"RemoteException": {
                    "exception": "FileNotFoundException",
                    "message": f"File does not exist: {path}"}}).encode())

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="hdfs-stub")
        self._thread.start()

    def _mkparents(self, path: str) -> None:
        parts = path.strip("/").split("/")[:-1]
        cur = ""
        for p in parts:
            cur += "/" + p
            # graftcheck: ignore[unbounded-keyed-accumulation] -- in-memory
            # filesystem stub: the directory tree IS the stored dataset
            self.dirs.add(cur)

    def _is_dir(self, path: str) -> bool:
        if path in self.dirs or path == "/":
            return True
        pre = path.rstrip("/") + "/"
        return any(k.startswith(pre) for k in self.files)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def spec(self, root: str = "deepstore") -> str:
        return f"hdfs://{root}?endpoint={self.url}&user=pinot"

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
