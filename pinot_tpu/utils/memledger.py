"""Device-memory residency ledger: who owns every byte of HBM.

Device memory is the scarcest resource in the system (ROADMAP "tiered
storage" item; PIMDAL's memory-bottleneck framing in PAPERS.md) and until
now nothing could say *what* is resident, *who* owns it, or *how close to
the edge* a server is. This ledger is the accounting substrate every
promotion/eviction policy will sit on:

* every named device allocation — segment column arrays, bitmap/valid
  words, consuming-segment staging, decoded/dedupe cache outputs —
  registers `(table, segment, kind, nbytes)` at staging time via the
  `staged()` wrapper and deregisters on release (segment unload, table
  drop, consuming retire);
* `reconcile()` checks the ledger total against jax's live-buffer view so
  drift (an allocation path that forgot to register, or a release hook
  that leaked) is *detectable*, not silent;
* residency is exported as `pinot_server_hbm_resident_bytes{table,kind}`
  gauges plus total/watermark/headroom/capacity gauges, and `snapshot()`
  backs the server's `GET /debug/memory` panel.

The ledger is process-global (same idiom as the metrics registry):
registration happens deep in engine code that has no server handle. In
multi-server in-process test clusters the servers therefore share one
ledger — per-server residency from `/debug/memory` is the *process* view
there, which is also what jax reports, so reconciliation stays honest.

Kinds are a bounded enum (`KINDS`): ledger gauges are labeled
`{table, kind}`, and metric label values must stay lifecycle-bounded —
never label by segment or query.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .metrics import get_registry

#: the closed set of allocation kinds the ledger accounts (gauge label values)
KINDS = ("ids", "raw", "dict", "valid", "valid_words", "bitmap", "null",
         "decoded", "consuming", "transient")

#: fallback per-device HBM capacity when jax can't report one (CPU backend);
#: override with PINOT_TPU_HBM_CAPACITY_BYTES
_DEFAULT_CAPACITY = 16 << 30

#: watermark history ring length (matches the metrics Gauge history ring)
_HISTORY_LEN = 240

#: min seconds between gauge publishes on the register hot path. Staging a
#: segment registers one entry per column in a tight loop; publishing every
#: gauge per entry would dominate the (near-free on CPU) device transfer.
#: Deferred updates flush on the next release/snapshot/flush or after this
#: interval — internal accounting is always exact, only gauge freshness is
#: throttled.
_PUBLISH_INTERVAL_S = 0.05


def device_capacity_bytes() -> Tuple[int, bool]:
    """(capacity_bytes, estimated): the device memory budget headroom is
    computed against. Order: env override, jax `memory_stats()["bytes_limit"]`,
    then a flagged 16 GiB estimate (CPU backends report no limit)."""
    env = os.environ.get("PINOT_TPU_HBM_CAPACITY_BYTES")
    if env:
        try:
            return max(1, int(env)), False
        except ValueError:
            pass
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        limit = int((stats or {}).get("bytes_limit", 0))
        if limit > 0:
            return limit, False
    # graftcheck: ignore[exception-hygiene] -- memory_stats() is optional
    # backend introspection (absent/raising on CPU); the flagged-estimate
    # return below IS the observable outcome of this probe failing
    except Exception:
        pass
    return _DEFAULT_CAPACITY, True


def live_device_bytes() -> Optional[int]:
    """Sum of nbytes over jax's live device arrays, or None when the runtime
    can't enumerate them — the reconciliation ground truth."""
    try:
        import jax
        total = 0
        for arr in jax.live_arrays():
            try:
                total += int(arr.nbytes)
            # graftcheck: ignore[exception-hygiene] -- a deleted/donated
            # buffer raising on .nbytes mid-enumeration just drops out of
            # the sum; reconcile() reports the resulting drift
            except Exception:
                pass
        return total
    except Exception:
        return None


class MemoryLedger:
    """Byte-accurate device-residency accounting, keyed
    (table, segment, kind, name); re-registration of the same key replaces
    (idempotent re-staging, e.g. a cache rebuild)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str, str, str], int] = {}
        self._by_table_kind: Dict[Tuple[str, str], int] = {}
        self._segment_tables: Dict[str, str] = {}
        self._total = 0
        self._transient_peak = 0
        self._watermark = 0
        self._watermark_history: deque = deque(maxlen=_HISTORY_LEN)
        self._capacity, self._capacity_estimated = device_capacity_bytes()
        # gauge-handle cache + publish throttle (rebuilt when the registry
        # is swapped out, e.g. a test reset)
        self._reg = None
        self._tk_gauges: Dict[Tuple[str, str], Any] = {}
        self._g_total: Any = None
        self._g_headroom: Any = None
        self._dirty: set = set()
        self._last_publish = float("-inf")

    # -- table attribution ---------------------------------------------------

    def bind_segment(self, table: str, segment: str) -> None:
        """Record that `segment` belongs to `table` so staging sites that
        only know the segment (datablock) still attribute bytes correctly."""
        with self._lock:
            self._segment_tables[segment] = table

    def _table_for_locked(self, segment: str) -> str:
        t = self._segment_tables.get(segment)
        if t is not None:
            return t
        # LLC names embed the table: {table}__{partition}__{seq}__{creation}
        if "__" in segment:
            return segment.split("__", 1)[0]
        return "-"

    # -- write side ----------------------------------------------------------

    def register(self, table: Optional[str], segment: str, kind: str,
                 name: str, nbytes: int) -> None:
        """Account a named device allocation. `table=None` resolves through
        the segment binding (or the LLC name prefix)."""
        nbytes = int(nbytes)
        with self._lock:
            t = table if table is not None else self._table_for_locked(segment)
            key = (t, segment, kind, name)
            prev = self._entries.get(key, 0)
            self._entries[key] = nbytes
            delta = nbytes - prev
            self._total += delta
            tk = (t, kind)
            self._by_table_kind[tk] = self._by_table_kind.get(tk, 0) + delta
            self._publish_locked(dirty=(tk,))

    def release(self, table: Optional[str] = None,
                segment: Optional[str] = None,
                kind: Optional[str] = None) -> int:
        """Drop every entry matching the non-None filters (and the segment's
        table binding when releasing by segment); returns bytes released."""
        with self._lock:
            doomed = [k for k in self._entries
                      if (table is None or k[0] == table)
                      and (segment is None or k[1] == segment)
                      and (kind is None or k[2] == kind)]
            freed = 0
            dirty = set()
            for key in doomed:
                nbytes = self._entries.pop(key)
                freed += nbytes
                tk = (key[0], key[2])
                self._by_table_kind[tk] = self._by_table_kind.get(tk, 0) - nbytes
                dirty.add(tk)
            self._total -= freed
            if segment is not None:
                self._segment_tables.pop(segment, None)
            if table is not None and segment is None:
                stale = [s for s, t in self._segment_tables.items()
                         if t == table]
                for s in stale:
                    self._segment_tables.pop(s, None)
            if doomed:
                self._publish_locked(dirty=tuple(dirty), force=True)
            return freed

    def note_transient(self, nbytes: int) -> None:
        """Track the peak transient launch/fetch buffer footprint — a single
        gauge update, cheap enough for the per-fetch hot path."""
        nbytes = int(nbytes)
        with self._lock:
            if nbytes <= self._transient_peak:
                return
            self._transient_peak = nbytes
            reg = get_registry()
            reg.gauge("pinot_server_hbm_transient_peak_bytes").set(nbytes)
            self._update_watermark_locked()

    def set_capacity(self, nbytes: int, estimated: bool = False) -> None:
        """Override the device-memory budget at runtime (the
        `server.hbm.capacity.bytes` cluster knob; tests/bench pin tiny
        capacities per server with it). Republishes the capacity gauge —
        `_gauges_locked` only publishes it once per registry swap — and
        force-flushes headroom so verdicts see the new budget immediately."""
        nbytes = max(1, int(nbytes))
        with self._lock:
            self._capacity = nbytes
            self._capacity_estimated = bool(estimated)
            reg = self._gauges_locked()
            reg.gauge("pinot_server_hbm_capacity_bytes").set(nbytes)
            self._publish_locked(force=True)

    def capacity_bytes(self) -> Tuple[int, bool]:
        """(capacity_bytes, estimated) — the budget admission/eviction and
        headroom math run against."""
        with self._lock:
            return self._capacity, self._capacity_estimated

    def flush(self) -> None:
        """Publish any throttle-deferred gauge updates now. The register hot
        path defers gauge writes up to `_PUBLISH_INTERVAL_S`; release and
        snapshot flush implicitly — call this before reading gauges straight
        off the registry after a registration burst."""
        with self._lock:
            self._publish_locked(force=True)

    # -- read side -----------------------------------------------------------

    def resident_bytes(self, table: Optional[str] = None,
                       segment: Optional[str] = None,
                       kind: Optional[str] = None) -> int:
        with self._lock:
            if table is None and segment is None and kind is None:
                return self._total
            return sum(n for (t, s, k, _), n in self._entries.items()
                       if (table is None or t == table)
                       and (segment is None or s == segment)
                       and (kind is None or k == kind))

    def snapshot(self) -> Dict[str, Any]:
        """The `GET /debug/memory` payload: totals, kind/table breakdowns,
        top segments by bytes, watermark history, capacity + headroom."""
        with self._lock:
            self._publish_locked(force=True)   # flush throttled gauge updates
            kinds: Dict[str, int] = {}
            tables: Dict[str, int] = {}
            segments: Dict[Tuple[str, str], int] = {}
            for (t, s, k, _), n in self._entries.items():
                kinds[k] = kinds.get(k, 0) + n
                tables[t] = tables.get(t, 0) + n
                segments[(t, s)] = segments.get((t, s), 0) + n
            top = sorted(segments.items(), key=lambda kv: -kv[1])[:10]
            cap = self._capacity
            headroom = max(0.0, 100.0 * (cap - self._total) / cap)
            return {
                "totalBytes": self._total,
                "transientPeakBytes": self._transient_peak,
                "capacityBytes": cap,
                "capacityEstimated": self._capacity_estimated,
                "headroomPct": round(headroom, 3),
                "watermarkBytes": self._watermark,
                "watermarkHistory": list(self._watermark_history),
                "entries": len(self._entries),
                "kinds": kinds,
                "tables": tables,
                "topSegments": [{"table": t, "segment": s, "bytes": n}
                                for (t, s), n in top],
            }

    def reconcile(self, baseline_bytes: int = 0) -> Dict[str, Any]:
        """Ledger total vs jax live-buffer bytes. `baseline_bytes` subtracts
        allocations that predate the measurement window (compile-time
        constants, calibration arrays) so drift isolates *tracked* staging.
        driftPct is None when the runtime can't enumerate live arrays."""
        device = live_device_bytes()
        with self._lock:
            ledger = self._total
        out: Dict[str, Any] = {"ledgerBytes": ledger, "deviceBytes": device,
                               "baselineBytes": int(baseline_bytes)}
        if device is None:
            out["driftBytes"] = None
            out["driftPct"] = None
            return out
        tracked = device - int(baseline_bytes)
        drift = tracked - ledger
        denom = max(ledger, tracked, 1)
        out["driftBytes"] = drift
        out["driftPct"] = round(100.0 * abs(drift) / denom, 3)
        return out

    # -- internals -----------------------------------------------------------

    def _update_watermark_locked(self) -> None:
        footprint = self._total + self._transient_peak
        if footprint > self._watermark:
            self._watermark = footprint
            self._watermark_history.append(
                (int(time.time() * 1000), footprint))
            get_registry().gauge(
                "pinot_server_hbm_watermark_bytes").set(footprint)

    def _gauges_locked(self):
        """Registry + cached gauge handles, rebuilt when the process registry
        is swapped (test resets) — handle reuse keeps the flush path off the
        registry's lookup lock."""
        reg = get_registry()
        if self._reg is not reg:
            self._reg = reg
            self._tk_gauges = {}
            self._g_total = reg.gauge("pinot_server_hbm_resident_total_bytes")
            self._g_headroom = reg.gauge("pinot_server_hbm_headroom_pct")
            # capacity is fixed for the process: published once per registry
            reg.gauge("pinot_server_hbm_capacity_bytes").set(self._capacity)
        return reg

    def _publish_locked(self, dirty: Iterable[Tuple[str, str]] = (),
                        force: bool = False) -> None:
        self._dirty.update(dirty)
        now = time.perf_counter()
        if not force and (now - self._last_publish) < _PUBLISH_INTERVAL_S:
            return   # hot staging loop: defer; flushed by release/snapshot
        self._last_publish = now
        reg = self._gauges_locked()
        for tk in self._dirty:
            t, k = tk
            n = self._by_table_kind.get(tk, 0)
            if n <= 0:
                # stale teardown: a dropped table/kind must not keep
                # exporting a zero series forever
                # graftcheck: ignore[lock-unguarded-write] -- _locked suffix:
                # every caller holds self._lock (register/release/note_transient)
                self._by_table_kind.pop(tk, None)
                self._tk_gauges.pop(tk, None)
                reg.remove_gauge("pinot_server_hbm_resident_bytes",
                                 {"table": t, "kind": k})
            else:
                g = self._tk_gauges.get(tk)
                if g is None:
                    g = reg.gauge("pinot_server_hbm_resident_bytes",
                                  {"table": t, "kind": k})
                    self._tk_gauges[tk] = g
                g.set(n)
        self._dirty.clear()
        self._g_total.set(self._total)
        cap = self._capacity
        self._g_headroom.set(
            max(0.0, round(100.0 * (cap - self._total) / cap, 3)))
        self._update_watermark_locked()


# -- process-global singleton (same idiom as utils.metrics.REGISTRY) ---------

_LEDGER = MemoryLedger()


def get_ledger() -> MemoryLedger:
    return _LEDGER


def reset_ledger() -> None:
    """Test hook: fresh ledger (the old one's gauges are left to the test's
    registry reset)."""
    global _LEDGER
    _LEDGER = MemoryLedger()


def staged(arr, segment: str, kind: str, name: Optional[str] = None,
           table: Optional[str] = None):
    """Register a freshly staged device array in the ledger and return it
    unchanged — THE sanctioned wrapper for device staging in engine/segment/
    cluster code (the `memory-untracked-staging` graftcheck rule flags bare
    `jnp.asarray`/`jax.device_put` staging that bypasses it)."""
    try:
        nbytes = int(arr.nbytes)
    except (AttributeError, TypeError):
        nbytes = 0
    _LEDGER.register(table, segment, kind, name or kind, nbytes)
    return arr
