"""Shared utilities: periodic task scheduling, tracing, metrics."""
