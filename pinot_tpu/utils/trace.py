"""Per-request tracing: named spans with timings, across scatter threads.

Analog of the reference's trace SPI (`pinot-spi/src/main/java/org/apache/pinot/spi/
trace/Tracing.java:32` + `DefaultRequestContext`): a request-scoped recorder that
operators register phase timings into, surfaced in the broker response when the query
sets OPTION(trace=true) (reference: `CommonConstants.Request.TRACE`).

Design departure: the reference builds a tree of per-operator trace nodes per server
and merges them in the broker reduce. Here a single flat span list with depth markers
is shared by every thread working the request (the broker's scatter pool threads
`activate` the same Trace), which keeps the recorder lock-free on the read side and
needs no cross-process merge for the in-proc transport. Remote (HTTP) servers attach
their span lists to the serialized partial and the broker splices them in.

Always-on sampling layer (the broker owns one of each):

* every query gets a Trace (span recording is a dict append — cheap enough to
  leave on unconditionally), identified by a `trace_id` that rides the wire to
  servers and back in the response stats;
* `TraceSampler` — head-based probabilistic admission (`broker.trace.sample.rate`)
  deciding which traces are RETAINED; seedable for deterministic tests;
* `TraceRing` — the bounded retention ring behind `GET /debug/traces`. Queries
  crossing `broker.slow.query.ms` are force-admitted at the tail regardless of
  the head decision, so every slow-query log line resolves to a full trace;
* `to_chrome_trace` — renders ring entries as a Chrome trace-event JSON document
  (loadable in Perfetto / chrome://tracing) with one track per server hop.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Union

_local = threading.local()


def new_trace_id() -> str:
    """16-hex-char unique id (the W3C trace-context span-id width)."""
    return uuid.uuid4().hex[:16]


class Trace:
    """Request-scoped span recorder. Thread-safe appends; one instance per query."""

    def __init__(self, request_id: str = "", trace_id: Optional[str] = None):
        self.request_id = request_id
        self.trace_id = trace_id or new_trace_id()
        #: head-sampling decision (set by the broker); tail retention may admit
        #: the trace into the ring even when False
        self.sampled = False
        self.spans: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    def now_ms(self) -> float:
        """Milliseconds since this trace's origin — THE public clock. Span
        starts, remote rebasing, and pipeline attribution read this instead of
        reaching into `_t0`."""
        return (time.perf_counter() - self._t0) * 1000

    def elapsed_ms(self) -> float:
        """Alias of `now_ms` (kept for the dispatch-rebasing call sites)."""
        return self.now_ms()

    def record(self, name: str, start_ms: float, duration_ms: float,
               depth: int = 0, error: bool = False) -> None:
        span = {
            "name": name,
            "startMs": round(start_ms, 3),
            "durationMs": round(duration_ms, 3),
            "depth": depth,
        }
        if error:
            span["error"] = True
        with self._lock:
            self.spans.append(span)

    def splice(self, spans: List[Dict[str, Any]], prefix: str = "",
               offset_ms: float = 0.0, depth_offset: int = 0) -> None:
        """Merge a remote server's span list. Its startMs values are relative to the
        SERVER's request start; `offset_ms` (when the dispatch left this trace's
        timeline) rebases them so the merged view sorts on one axis. `depth_offset`
        rebases the remote depths the same way — the server recorded depth 0 at its
        own request root, but spliced spans nest under the dispatching span (pass
        `current_depth()` from inside it) so the merged tree renders correctly."""
        with self._lock:
            for s in spans:
                s = dict(s)
                if prefix:
                    s["name"] = f"{prefix}/{s['name']}"
                s["startMs"] = round(s.get("startMs", 0.0) + offset_ms, 3)
                s["depth"] = int(s.get("depth", 0)) + depth_offset
                self.spans.append(s)

    def to_rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return sorted(self.spans, key=lambda s: s["startMs"])

    @contextmanager
    def activate(self, depth: int = 0):
        """Make this trace current for the calling thread (scatter-pool workers).
        `depth` seeds the thread's nesting level — a server scheduler thread
        passes the dispatch-site depth so its spans nest under the dispatching
        span exactly like HTTP-spliced spans do."""
        prev = getattr(_local, "trace", None)
        prev_depth = getattr(_local, "depth", 0)
        _local.trace = self
        _local.depth = depth
        try:
            yield self
        finally:
            _local.trace = prev
            _local.depth = prev_depth


def current_trace() -> Optional[Trace]:
    return getattr(_local, "trace", None)


def current_depth() -> int:
    """The calling thread's span nesting depth — what a span opened NOW would
    record. Used to nest spliced remote spans under their dispatch span."""
    return getattr(_local, "depth", 0)


@contextmanager
def request_trace(enabled: bool, request_id: str = "",
                  trace_id: Optional[str] = None):
    """Start a trace for this request on the current thread; None when disabled —
    `span()` then degrades to a no-op so instrumented code never branches.
    `trace_id` carries a propagated wire context (server side of a dispatch)."""
    if not enabled:
        yield None
        return
    tr = Trace(request_id, trace_id=trace_id)
    with tr.activate():
        yield tr


@contextmanager
def span(name: str):
    """Record a named span on the current thread's active trace (no-op if none).
    A body that exits via exception marks the span `error: true` so failed
    phases are visible in exported timelines."""
    tr = getattr(_local, "trace", None)
    if tr is None:
        yield
        return
    depth = getattr(_local, "depth", 0)
    _local.depth = depth + 1
    start_ms = tr.now_ms()
    t0 = time.perf_counter()
    error = False
    try:
        yield
    except BaseException:
        error = True
        raise
    finally:
        _local.depth = depth
        tr.record(name, start_ms, (time.perf_counter() - t0) * 1000, depth,
                  error=error)


# -- sampling + retention -----------------------------------------------------

class TraceSampler:
    """Head-based probabilistic sampler. The rate is passed per call (the
    broker re-reads `broker.trace.sample.rate` from clusterConfig each query);
    inject a seeded `random.Random` for deterministic tests."""

    def __init__(self, rng: Optional[random.Random] = None):
        self._rng = rng if rng is not None else random.Random()
        self._lock = threading.Lock()

    def sample(self, rate: float) -> bool:
        if rate <= 0.0:
            return False
        if rate >= 1.0:
            return True
        with self._lock:
            return self._rng.random() < rate


class TraceRing:
    """Bounded ring of retained traces, keyed by trace id. Head-sampled traces
    and tail-retained (slow / errored) traces both land here; eviction is
    strictly oldest-first so the ring can never grow past `capacity`."""

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._entries: "deque" = deque()        # oldest -> newest
        self._by_id: Dict[str, Dict[str, Any]] = {}

    def admit(self, trace: Trace, **meta: Any) -> Dict[str, Any]:
        """Retain one finished trace; `meta` carries query-level context
        (sql, timeUsedMs, slow/error flags)."""
        entry: Dict[str, Any] = {
            "traceId": trace.trace_id,
            "requestId": trace.request_id,
            "sampled": bool(trace.sampled),
            "spans": trace.to_rows(),
        }
        entry.update(meta)
        with self._lock:
            self._entries.append(entry)
            self._by_id[entry["traceId"]] = entry
            while len(self._entries) > self.capacity:
                dead = self._entries.popleft()
                if self._by_id.get(dead["traceId"]) is dead:
                    del self._by_id[dead["traceId"]]
        return entry

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._by_id.get(trace_id)

    def entries(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Newest-first retained entries (bounded by `limit` when given)."""
        with self._lock:
            rows = list(self._entries)
        rows.reverse()
        return rows[:limit] if limit is not None else rows

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# -- Chrome trace-event export ------------------------------------------------

def to_chrome_trace(entries: Union[Dict[str, Any], Iterable[Dict[str, Any]]]
                    ) -> Dict[str, Any]:
    """Render ring entries as a Chrome trace-event JSON document (the
    `{"traceEvents": [...]}` format Perfetto and chrome://tracing load).

    Each retained query becomes one pid; span tracks split by hop — the
    broker's own spans on one tid, each `server:<id>/...` spliced hop on its
    own — so the broker↔server decomposition reads as parallel timelines.
    All events are complete events (`ph: "X"`, microsecond ts/dur) plus
    metadata events naming the process/threads."""
    if isinstance(entries, dict):
        entries = [entries]
    events: List[Dict[str, Any]] = []
    for pid, entry in enumerate(entries, start=1):
        label = entry.get("sql") or entry.get("requestId") or ""
        events.append({"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                       "args": {"name": f"query {entry.get('traceId', '')} "
                                        f"{label}".strip()}})
        tids: Dict[str, int] = {}
        for s in entry.get("spans", ()):
            name = str(s.get("name", ""))
            track = (name.split("/", 1)[0]
                     if name.startswith("server:") and "/" in name
                     else "broker")
            if track not in tids:
                tids[track] = len(tids) + 1
                events.append({"name": "thread_name", "ph": "M", "pid": pid,
                               "tid": tids[track], "args": {"name": track}})
            args: Dict[str, Any] = {"depth": int(s.get("depth", 0))}
            if s.get("error"):
                args["error"] = True
            events.append({
                "name": name,
                "cat": "query",
                "ph": "X",
                "ts": round(max(float(s.get("startMs", 0.0)), 0.0) * 1000.0, 3),
                "dur": round(max(float(s.get("durationMs", 0.0)), 0.0) * 1000.0, 3),
                "pid": pid,
                "tid": tids[track],
                "args": args,
            })
        # device-memory residency rides the same timeline as counter events
        # (`ph: "C"` renders as a filled area track under the spans), so a
        # trace shows HBM residency next to the work that created it
        for sample in entry.get("memory") or ():
            ts = round(max(float(sample.get("tsMs", 0.0)), 0.0) * 1000.0, 3)
            for series, value in (sample.get("series") or {}).items():
                events.append({"name": str(series), "cat": "memory",
                               "ph": "C", "ts": ts, "pid": pid, "tid": 0,
                               "args": {"bytes": value}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}
