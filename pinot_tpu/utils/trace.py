"""Per-request tracing: named spans with timings, across scatter threads.

Analog of the reference's trace SPI (`pinot-spi/src/main/java/org/apache/pinot/spi/
trace/Tracing.java:32` + `DefaultRequestContext`): a request-scoped recorder that
operators register phase timings into, surfaced in the broker response when the query
sets OPTION(trace=true) (reference: `CommonConstants.Request.TRACE`).

Design departure: the reference builds a tree of per-operator trace nodes per server
and merges them in the broker reduce. Here a single flat span list with depth markers
is shared by every thread working the request (the broker's scatter pool threads
`activate` the same Trace), which keeps the recorder lock-free on the read side and
needs no cross-process merge for the in-proc transport. Remote (HTTP) servers attach
their span lists to the serialized partial and the broker splices them in.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_local = threading.local()


class Trace:
    """Request-scoped span recorder. Thread-safe appends; one instance per query."""

    def __init__(self, request_id: str = ""):
        self.request_id = request_id
        self.spans: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    def record(self, name: str, start_ms: float, duration_ms: float,
               depth: int = 0) -> None:
        with self._lock:
            self.spans.append({
                "name": name,
                "startMs": round(start_ms, 3),
                "durationMs": round(duration_ms, 3),
                "depth": depth,
            })

    def splice(self, spans: List[Dict[str, Any]], prefix: str = "",
               offset_ms: float = 0.0, depth_offset: int = 0) -> None:
        """Merge a remote server's span list. Its startMs values are relative to the
        SERVER's request start; `offset_ms` (when the dispatch left this trace's
        timeline) rebases them so the merged view sorts on one axis. `depth_offset`
        rebases the remote depths the same way — the server recorded depth 0 at its
        own request root, but spliced spans nest under the dispatching span (pass
        `current_depth()` from inside it) so the merged tree renders correctly."""
        with self._lock:
            for s in spans:
                s = dict(s)
                if prefix:
                    s["name"] = f"{prefix}/{s['name']}"
                s["startMs"] = round(s.get("startMs", 0.0) + offset_ms, 3)
                s["depth"] = int(s.get("depth", 0)) + depth_offset
                self.spans.append(s)

    def elapsed_ms(self) -> float:
        """Milliseconds since this trace's origin (for rebasing remote spans)."""
        return (time.perf_counter() - self._t0) * 1000

    def to_rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return sorted(self.spans, key=lambda s: s["startMs"])

    @contextmanager
    def activate(self):
        """Make this trace current for the calling thread (scatter-pool workers)."""
        prev = getattr(_local, "trace", None)
        prev_depth = getattr(_local, "depth", 0)
        _local.trace = self
        _local.depth = 0
        try:
            yield self
        finally:
            _local.trace = prev
            _local.depth = prev_depth


def current_trace() -> Optional[Trace]:
    return getattr(_local, "trace", None)


def current_depth() -> int:
    """The calling thread's span nesting depth — what a span opened NOW would
    record. Used to nest spliced remote spans under their dispatch span."""
    return getattr(_local, "depth", 0)


@contextmanager
def request_trace(enabled: bool, request_id: str = ""):
    """Start a trace for this request on the current thread; None when disabled —
    `span()` then degrades to a no-op so instrumented code never branches."""
    if not enabled:
        yield None
        return
    tr = Trace(request_id)
    with tr.activate():
        yield tr


@contextmanager
def span(name: str):
    """Record a named span on the current thread's active trace (no-op if none)."""
    tr = getattr(_local, "trace", None)
    if tr is None:
        yield
        return
    depth = getattr(_local, "depth", 0)
    _local.depth = depth + 1
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _local.depth = depth
        tr.record(name, (t0 - tr._t0) * 1000,
                  (time.perf_counter() - t0) * 1000, depth)
