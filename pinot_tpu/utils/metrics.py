"""Process-wide metrics registry: counters, gauges, timers, Prometheus export.

Analog of the reference's metrics stack (`pinot-common/src/main/java/org/apache/pinot/
common/metrics/`: AbstractMetrics + ServerMeter/BrokerMeter/ControllerMeter catalogs,
exported via the yammer/dropwizard registry). One flat registry per process; metric
identity is (name, sorted label pairs), mirroring the reference's per-table metric
names (`pinot.server.query.exceptions.{table}` etc. become labels here).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

LabelPairs = Tuple[Tuple[str, str], ...]


def _key(name: str, labels: Optional[Dict[str, str]]) -> Tuple[str, LabelPairs]:
    return name, tuple(sorted((labels or {}).items()))


class Counter:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    #: bounded time-series ring: every set() appends (epoch_ms, value), so
    #: /debug can show a gauge's recent trajectory (lag growing vs flat)
    #: without an external scraper. 240 points ≈ 20 min at a 5s poll.
    HISTORY_LEN = 240

    __slots__ = ("value", "_history", "_lock")

    def __init__(self):
        self.value = 0.0
        self._history: deque = deque(maxlen=self.HISTORY_LEN)
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)
            self._history.append((int(time.time() * 1000), self.value))

    def history(self) -> List[Tuple[int, float]]:
        """Recent (epoch_ms, value) samples, oldest first (bounded ring)."""
        with self._lock:
            return list(self._history)


class Timer:
    """Duration accumulator: count / total / min / max (ms)."""

    __slots__ = ("count", "total_ms", "min_ms", "max_ms", "_lock")

    def __init__(self):
        self.count = 0
        self.total_ms = 0.0
        self.min_ms = float("inf")
        self.max_ms = 0.0
        self._lock = threading.Lock()

    def update(self, duration_ms: float) -> None:
        with self._lock:
            self.count += 1
            self.total_ms += duration_ms
            self.min_ms = min(self.min_ms, duration_ms)
            self.max_ms = max(self.max_ms, duration_ms)

    def time(self):
        """Context manager measuring a block."""
        timer = self

        class _Ctx:
            def __enter__(self):
                self._t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                timer.update((time.perf_counter() - self._t0) * 1000)
                return False

        return _Ctx()

    @property
    def mean_ms(self) -> float:
        return self.total_ms / self.count if self.count else 0.0


class Histogram:
    """Fixed-bucket duration histogram (ms) with Prometheus histogram exposition.

    Buckets are cumulative upper bounds; percentiles are read back from the
    bucket counts (upper-bound estimate), which is exactly the resolution a
    scrape-side `histogram_quantile` would have."""

    DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                       1000.0, 2500.0, 5000.0, 10000.0)

    #: rotation period for the recent-window view: `recent_percentile` reads
    #: the last 1-2 windows, so an overload spike ages out of admission
    #: decisions within ~2 windows instead of polluting the lifetime quantile
    WINDOW_S = 60.0

    __slots__ = ("buckets", "bucket_counts", "count", "total", "max", "_lock",
                 "_win_counts", "_prev_counts", "_win_started")

    def __init__(self, buckets: Optional[Tuple[float, ...]] = None):
        self.buckets = tuple(buckets) if buckets else self.DEFAULT_BUCKETS
        self.bucket_counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.count = 0
        self.total = 0.0
        self.max = 0.0
        self._win_counts = [0] * (len(self.buckets) + 1)
        self._prev_counts = [0] * (len(self.buckets) + 1)
        self._win_started = time.monotonic()
        self._lock = threading.Lock()

    def _rotate_locked(self, now: float) -> None:
        age = now - self._win_started
        if age < self.WINDOW_S:
            return
        zeros = [0] * len(self.bucket_counts)
        # one stale window becomes "previous"; two or more means both views
        # predate the window and are dropped entirely
        self._prev_counts = self._win_counts if age < 2 * self.WINDOW_S else zeros
        # graftcheck: ignore[lock-unguarded-write] -- _locked suffix is the
        # contract: every caller (observe, percentile paths) already holds
        # self._lock around this rotation
        self._win_counts = list(zeros)
        self._win_started = now

    def observe(self, v: float) -> None:
        # the whole observe runs under the lock: scanning outside it let a
        # concurrent snapshot/render see count incremented before the bucket
        # row, breaking the cumulative-bucket invariant readers rely on
        with self._lock:
            self._rotate_locked(time.monotonic())
            i = 0
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    break
            else:
                i = len(self.buckets)
            self.bucket_counts[i] += 1
            self._win_counts[i] += 1
            self.count += 1
            self.total += v
            self.max = max(self.max, v)

    def _percentile_locked(self, q: float, counts, total: int) -> float:
        if not total:
            return 0.0
        target = q * total
        cum = 0
        for i, n in enumerate(counts):
            cum += n
            if cum >= target:
                return self.buckets[i] if i < len(self.buckets) else self.max
        return self.max

    def percentile(self, q: float) -> float:
        """Upper-bound estimate of the q-quantile (0 < q <= 1) from buckets."""
        with self._lock:
            return self._percentile_locked(q, self.bucket_counts, self.count)

    def recent_percentile(self, q: float) -> Tuple[float, int]:
        """Quantile over the last 1-2 rotation windows (see WINDOW_S), plus the
        sample count it was computed from so callers can gate on confidence.
        Falls back to the lifetime quantile (count included) while the window
        is empty."""
        with self._lock:
            self._rotate_locked(time.monotonic())
            counts = [a + b for a, b in zip(self._prev_counts, self._win_counts)]
            total = sum(counts)
            if not total:
                return (self._percentile_locked(q, self.bucket_counts,
                                                self.count), self.count)
            return self._percentile_locked(q, counts, total), total

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def summary(self) -> Dict[str, float]:
        return {"count": self.count, "meanMs": round(self.mean, 3),
                "p50Ms": round(self.percentile(0.5), 3),
                "p95Ms": round(self.percentile(0.95), 3),
                "maxMs": round(self.max, 3)}

    def recent_summary(self) -> Dict[str, float]:
        """summary() restricted to the rotating recent window: p50/p99 plus
        the sample count they were computed from (recent_percentile's
        lifetime fallback applies while the window is empty)."""
        p50, n = self.recent_percentile(0.5)
        p99, _ = self.recent_percentile(0.99)
        return {"recentSamples": n, "recentP50Ms": round(p50, 3),
                "recentP99Ms": round(p99, 3)}


class MetricsRegistry:
    def __init__(self):
        self._counters: Dict[Tuple[str, LabelPairs], Counter] = {}
        self._gauges: Dict[Tuple[str, LabelPairs], Gauge] = {}
        self._timers: Dict[Tuple[str, LabelPairs], Timer] = {}
        self._histograms: Dict[Tuple[str, LabelPairs], Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None) -> Counter:
        k = _key(name, labels)
        with self._lock:
            if k not in self._counters:
                self._counters[k] = Counter()
            return self._counters[k]

    def gauge(self, name: str, labels: Optional[Dict[str, str]] = None) -> Gauge:
        k = _key(name, labels)
        with self._lock:
            if k not in self._gauges:
                self._gauges[k] = Gauge()
            return self._gauges[k]

    def timer(self, name: str, labels: Optional[Dict[str, str]] = None) -> Timer:
        k = _key(name, labels)
        with self._lock:
            if k not in self._timers:
                self._timers[k] = Timer()
            return self._timers[k]

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Tuple[float, ...]] = None) -> Histogram:
        k = _key(name, labels)
        with self._lock:
            if k not in self._histograms:
                self._histograms[k] = Histogram(buckets)
            return self._histograms[k]

    def remove(self, name: str, labels: Optional[Dict[str, str]] = None) -> None:
        """Drop a metric series of ANY kind (counter/gauge/timer/histogram).
        Used when the labeled entity disappears — e.g. per-table series after
        the table is dropped; exporting metrics for nonexistent tables
        misleads dashboards."""
        k = _key(name, labels)
        with self._lock:
            self._counters.pop(k, None)
            self._gauges.pop(k, None)
            self._timers.pop(k, None)
            self._histograms.pop(k, None)

    def remove_gauge(self, name: str, labels: Optional[Dict[str, str]] = None
                     ) -> None:
        """Back-compat alias of `remove` (originally gauge-only)."""
        self.remove(name, labels)

    # -- read side ----------------------------------------------------------
    def counter_value(self, name: str, labels: Optional[Dict[str, str]] = None) -> float:
        return self.counter(name, labels).value

    def snapshot(self) -> Dict[str, float]:
        """Flat {rendered-name: value} map (counters + gauges + timer aggregates)."""
        out: Dict[str, float] = {}
        with self._lock:
            for (name, labels), c in self._counters.items():
                out[_render_name(name, labels)] = c.value
            for (name, labels), g in self._gauges.items():
                out[_render_name(name, labels)] = g.value
            for (name, labels), t in self._timers.items():
                base = _render_name(name, labels)
                out[f"{base}_count"] = t.count
                out[f"{base}_total_ms"] = t.total_ms
            for (name, labels), h in self._histograms.items():
                base = _render_name(name, labels)
                out[f"{base}_count"] = h.count
                out[f"{base}_sum"] = h.total
                out[f"{base}_p50"] = h.percentile(0.5)
        return out

    def gauge_histories(self, prefix: Optional[str] = None
                        ) -> Dict[str, List[Tuple[int, float]]]:
        """Per-gauge bounded time series for /debug: {rendered-name:
        [(epoch_ms, value), ...]}, optionally filtered by name prefix so a
        role's debug endpoint only ships its own series."""
        with self._lock:
            gauges = [(name, labels, g) for (name, labels), g
                      in self._gauges.items()
                      if prefix is None or name.startswith(prefix)]
        # g.history() takes the per-gauge lock; never nest it under _lock
        return {_render_name(name, labels): g.history()
                for name, labels, g in gauges}

    def render_prometheus(self) -> str:
        """Text exposition format (the /metrics endpoint body): exactly one
        `# TYPE` line per metric family, series grouped under it."""
        lines: List[str] = []
        with self._lock:
            for kind, series in (("counter", self._counters),
                                 ("gauge", self._gauges)):
                last_family = None
                for (name, labels), m in sorted(series.items()):
                    if name != last_family:
                        lines.append(f"# TYPE {name} {kind}")
                        last_family = name
                    lines.append(f"{_prom_name(name, labels)} {m.value}")
            last_family = None
            for (name, labels), t in sorted(self._timers.items()):
                if name != last_family:
                    lines.append(f"# TYPE {name} summary")
                    last_family = name
                lines.append(f"{_prom_name(name + '_count', labels)} {t.count}")
                lines.append(f"{_prom_name(name + '_sum', labels)} {t.total_ms}")
            last_family = None
            for (name, labels), h in sorted(self._histograms.items()):
                if name != last_family:
                    lines.append(f"# TYPE {name} histogram")
                    last_family = name
                cum = 0
                for i, ub in enumerate(h.buckets):
                    cum += h.bucket_counts[i]
                    lines.append(_prom_name(name + "_bucket",
                                            labels + (("le", "%g" % ub),))
                                 + f" {cum}")
                lines.append(_prom_name(name + "_bucket",
                                        labels + (("le", "+Inf"),))
                             + f" {h.count}")
                lines.append(f"{_prom_name(name + '_sum', labels)} {h.total}")
                lines.append(f"{_prom_name(name + '_count', labels)} {h.count}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()
            self._histograms.clear()


def _render_name(name: str, labels: LabelPairs) -> str:
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


def _prom_name(name: str, labels: LabelPairs) -> str:
    if not labels:
        return name
    inner = ",".join(f'{k}="{_prom_escape(v)}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def _prom_escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


# the process-wide default registry (reference: PinotMetricUtils singleton registry)
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
