"""graftfault: the deterministic, seed-driven fault-injection plane.

The failure machinery that makes this a *distributed* store — broker retry
rounds, hedged requests, `FailureDetector` backoff probing, the committer
takeover FSM, `reassign_dead_consuming_segments` — only earns trust when it
runs under actual faults. This module provides the injection side: named
fault sites threaded through the transports, the server execute path, the
stream consumers, the deep store, and the device pipeline, each crossed via
one `fault_point(site)` call.

Design constraints, in order:

1. **Zero overhead when disabled.** `fault_point` is on the mux write loop,
   the consume pump, and the server execute path; disabled it is one module
   global load + a None check (the bench's chaos lane publishes the measured
   cost as `fault_plane_overhead_pct`). No registry lookups, no dict walks.
2. **Deterministic under a seed.** Every site draws from its own
   `random.Random(f"{seed}:{site}")` stream, so concurrency *between* sites
   never perturbs a site's decision sequence, and two runs of the same
   schedule against the same workload fire the same faults. For strict
   cross-run determinism under multi-threaded traffic use probability 1.0
   with a `count` budget — firing then depends only on the budget, not on
   thread interleaving of draws.
3. **Typed failures.** An injected fault raises `FaultInjected`, a
   `ConnectionError` subclass — the broker's existing failure taxonomy
   (`_is_transport_failure`) classifies it as a transport death, which is
   exactly what the sites simulate (crashed server, reset stream, lost
   partition). Latency-only sites (`*.slow`, `stream.stall`) sleep and
   return.

Activation: `activate(schedule)` / `deactivate()` (or the `active(...)`
context manager) from a test fixture, or cluster-wide via the clusterConfig
knob `fault.schedule` holding the JSON spec — role services call
`activate_from_config(catalog)` at startup. The plane is process-wide (one
module-level slot), mirroring the metrics registry's one-flat-surface idiom.

Spec format (JSON or the equivalent dict)::

    {"seed": 42,
     "sites": {
       "server.slow":  {"p": 0.3, "latencyMs": 50, "count": 10},
       "server.crash": {"p": 1.0, "count": 1},
       "mux.frame.drop": {"p": 0.05}}}

Per-site fields: `p` (fire probability, default 1.0), `count` (total fire
budget, default unlimited), `latencyMs` (sleep before the verdict, default
0), `fail` (raise `FaultInjected`; defaults to true when `latencyMs` is 0,
false otherwise — a latency-only spec is a slowdown, not a failure).
"""

from __future__ import annotations

import json
import random
import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, Optional, Union

#: every named injection site threaded through the stack; FaultSchedule
#: validates spec keys against this so a typo'd site fails loudly at parse
#: time instead of silently never firing.
SITES = frozenset((
    "mux.frame.drop",       # mux client write loop: frame vanishes on the wire
    "mux.conn.reset",       # outbound connection mint fails (mux + pooled HTTP)
    "server.crash",         # server partial-execute dies as a transport failure
    "server.slow",          # server partial-execute stalls (straggler)
    "stream.stall",         # stream fetch stalls (slow upstream)
    "stream.partition.lost",  # stream fetch dies (lost partition / rebalance)
    "deepstore.upload.fail",  # segment upload to the deep store fails
    "deepstore.download.fail",  # segment download from the deep store fails
    "device.launch.slow",   # device pipeline dispatch stalls before launch
))


class FaultInjected(ConnectionError):
    """An injected fault. Subclasses ConnectionError deliberately: the
    broker/server failure taxonomy treats it as a transport death, which is
    the behavior the fault sites simulate."""

    def __init__(self, site: str):
        super().__init__(f"graftfault: injected fault at {site!r}")
        self.site = site


class _SiteSpec:
    __slots__ = ("site", "probability", "count", "latency_ms", "fail", "rng")

    def __init__(self, site: str, probability: float = 1.0,
                 count: Optional[int] = None, latency_ms: float = 0.0,
                 fail: Optional[bool] = None, seed: int = 0):
        self.site = site
        self.probability = float(probability)
        self.count = count if count is None else int(count)
        self.latency_ms = float(latency_ms)
        # latency-only specs model slowdowns; anything else is a failure
        self.fail = bool(fail) if fail is not None else self.latency_ms == 0.0
        # per-site stream: cross-site concurrency never perturbs a site's
        # draw sequence, so same seed + same workload => same decisions
        self.rng = random.Random(f"{seed}:{site}")


class FaultSchedule:
    """Seeded, budgeted fault decisions for a set of sites.

    Thread-safe; `fired()` exposes per-site fire counts so tests and the
    bench can assert exactly what the schedule did."""

    def __init__(self, sites: Dict[str, dict], seed: int = 0):
        unknown = set(sites) - SITES
        if unknown:
            raise ValueError(
                f"unknown fault site(s) {sorted(unknown)}; known sites: "
                f"{sorted(SITES)}")
        self.seed = int(seed)
        self._specs: Dict[str, _SiteSpec] = {}
        for site, spec in sites.items():
            spec = dict(spec or {})
            self._specs[site] = _SiteSpec(
                site,
                probability=spec.pop("p", spec.pop("probability", 1.0)),
                count=spec.pop("count", None),
                latency_ms=spec.pop("latencyMs", spec.pop("latency_ms", 0.0)),
                fail=spec.pop("fail", None),
                seed=self.seed)
            if spec:
                raise ValueError(
                    f"unknown field(s) {sorted(spec)} in fault spec for "
                    f"{site!r} (known: p, count, latencyMs, fail)")
        self._fired: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_json(cls, text: str) -> "FaultSchedule":
        data = json.loads(text)
        return cls(data.get("sites", {}), seed=data.get("seed", 0))

    def fired(self, site: Optional[str] = None) -> Union[int, Dict[str, int]]:
        """Fire count for one site, or the whole per-site map."""
        with self._lock:
            if site is not None:
                return self._fired.get(site, 0)
            return dict(self._fired)

    def check(self, site: str) -> None:
        """One site crossing: decide (seeded, budgeted), then sleep and/or
        raise. Called via `fault_point`, never directly from hook sites."""
        spec = self._specs.get(site)
        if spec is None:
            return
        with self._lock:
            if spec.count is not None and \
                    self._fired.get(site, 0) >= spec.count:
                return
            if spec.probability < 1.0 and \
                    spec.rng.random() >= spec.probability:
                return
            self._fired[site] = self._fired.get(site, 0) + 1
        from .events import emit as emit_event
        from .metrics import get_registry
        get_registry().counter("pinot_fault_injections").inc()
        emit_event("fault.fired", site=site,
                   latencyMs=spec.latency_ms, fail=bool(spec.fail))
        if spec.latency_ms > 0:
            time.sleep(spec.latency_ms / 1000.0)
        if spec.fail:
            raise FaultInjected(site)


#: the process-wide active schedule; None = plane disabled (the common case —
#: `fault_point` must stay one load + None check on every hot path).
_active: Optional[FaultSchedule] = None


def fault_point(site: str) -> None:
    """The hook every injection site crosses. Near-free when no schedule is
    active; otherwise delegates the (seeded, budgeted) decision — which may
    sleep and/or raise `FaultInjected` — to the schedule."""
    sched = _active
    if sched is None:
        return
    sched.check(site)


def activate(schedule: Optional[FaultSchedule]) -> None:
    global _active
    _active = schedule


def deactivate() -> None:
    activate(None)


def active_schedule() -> Optional[FaultSchedule]:
    return _active


@contextmanager
def active(schedule: FaultSchedule) -> Iterator[FaultSchedule]:
    """Test-fixture activation: installs the schedule for the scope and
    always restores the previous plane state (including nesting)."""
    global _active
    prev = _active
    _active = schedule
    try:
        yield schedule
    finally:
        _active = prev


def activate_from_config(catalog) -> Optional[FaultSchedule]:
    """Cluster-wide activation: read the `fault.schedule` clusterConfig knob
    (a JSON spec, see module docstring) and install it process-wide. Called
    by role services at startup; a missing/empty knob leaves the plane
    untouched, a malformed one raises (a chaos drill with a typo'd schedule
    silently not running is worse than failing the start)."""
    raw = catalog.get_property("clusterConfig/fault.schedule")
    if not raw:
        return None
    sched = FaultSchedule.from_json(raw)
    activate(sched)
    return sched
