"""Periodic task framework.

Analog of the reference's `BasePeriodicTask` + `PeriodicTaskScheduler`
(`pinot-core/.../periodictask/`): named tasks on fixed intervals, start/stop lifecycle,
manual `run_once` for deterministic tests (the reference's tests do the same).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class PeriodicTask:
    def __init__(self, name: str, interval_s: float, fn: Callable[[], None],
                 initial_delay_s: float = 0.0):
        self.name = name
        self.interval_s = interval_s
        self.fn = fn
        self.initial_delay_s = initial_delay_s
        self.run_count = 0
        self.error_count = 0
        self.last_error: Optional[BaseException] = None
        self.last_run_ms: Optional[int] = None

    def run_once(self) -> None:
        # exported per run (reference: ControllerMetrics' periodic task meters)
        # so a task that silently fails every tick shows up as a climbing
        # pinot_periodic_task_errors series and a stale last-run gauge when it
        # stops being scheduled at all
        from .metrics import get_registry
        labels = {"task": self.name}
        try:
            self.fn()
            self.last_error = None  # a clean run clears a stale error
            self.run_count += 1
        except BaseException as e:  # periodic tasks never kill the scheduler
            self.last_error = e
            self.run_count += 1
            self.error_count += 1
            get_registry().counter("pinot_periodic_task_errors", labels).inc()
        self.last_run_ms = int(time.time() * 1000)
        get_registry().gauge("pinot_periodic_task_last_run_ts_ms",
                             labels).set(self.last_run_ms)

    def stats(self) -> Dict[str, object]:
        """One task's health for the controller /debug rollup."""
        return {"runCount": self.run_count, "errorCount": self.error_count,
                "lastRunMs": self.last_run_ms, "intervalS": self.interval_s,
                "lastError": (f"{type(self.last_error).__name__}: "
                              f"{self.last_error}"
                              if self.last_error is not None else None)}


class PeriodicTaskScheduler:
    def __init__(self):
        self._tasks: Dict[str, PeriodicTask] = {}
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    def register(self, task: PeriodicTask) -> None:
        self._tasks[task.name] = task

    def task(self, name: str) -> PeriodicTask:
        return self._tasks[name]

    def run_all_once(self) -> None:
        """Deterministic tick for tests."""
        for t in self._tasks.values():
            t.run_once()

    def stats(self) -> Dict[str, Dict[str, object]]:
        """{task name: run/error/last-run rollup} for debug endpoints."""
        return {name: t.stats() for name, t in self._tasks.items()}

    def start(self) -> None:
        self._stop.clear()
        for t in self._tasks.values():
            th = threading.Thread(target=self._loop, args=(t,), daemon=True,
                                  name=f"periodic-{t.name}")
            th.start()
            self._threads.append(th)

    def _loop(self, task: PeriodicTask) -> None:
        if task.initial_delay_s and self._stop.wait(task.initial_delay_s):
            return
        while not self._stop.is_set():
            task.run_once()
            if self._stop.wait(task.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=5)
        self._threads.clear()
