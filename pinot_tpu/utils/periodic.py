"""Periodic task framework.

Analog of the reference's `BasePeriodicTask` + `PeriodicTaskScheduler`
(`pinot-core/.../periodictask/`): named tasks on fixed intervals, start/stop lifecycle,
manual `run_once` for deterministic tests (the reference's tests do the same).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional


class PeriodicTask:
    def __init__(self, name: str, interval_s: float, fn: Callable[[], None],
                 initial_delay_s: float = 0.0):
        self.name = name
        self.interval_s = interval_s
        self.fn = fn
        self.initial_delay_s = initial_delay_s
        self.run_count = 0
        self.last_error: Optional[BaseException] = None

    def run_once(self) -> None:
        try:
            self.fn()
            self.run_count += 1
        except BaseException as e:  # periodic tasks never kill the scheduler
            self.last_error = e
            self.run_count += 1


class PeriodicTaskScheduler:
    def __init__(self):
        self._tasks: Dict[str, PeriodicTask] = {}
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()

    def register(self, task: PeriodicTask) -> None:
        self._tasks[task.name] = task

    def task(self, name: str) -> PeriodicTask:
        return self._tasks[name]

    def run_all_once(self) -> None:
        """Deterministic tick for tests."""
        for t in self._tasks.values():
            t.run_once()

    def start(self) -> None:
        self._stop.clear()
        for t in self._tasks.values():
            th = threading.Thread(target=self._loop, args=(t,), daemon=True,
                                  name=f"periodic-{t.name}")
            th.start()
            self._threads.append(th)

    def _loop(self, task: PeriodicTask) -> None:
        if task.initial_delay_s and self._stop.wait(task.initial_delay_s):
            return
        while not self._stop.is_set():
            task.run_once()
            if self._stop.wait(task.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        for th in self._threads:
            th.join(timeout=5)
        self._threads.clear()
