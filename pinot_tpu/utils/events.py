"""Process-global cluster event journal: a causal timeline of state transitions.

The observability planes built before this one (metrics, tracing, the HBM
ledger, workload shapes) are all *level*-based — they say the cluster IS
degraded, not the ordered sequence of transitions that got it there. The
journal is the flight-recorder substrate underneath them: every interesting
state transition (segment lifecycle, tiering admit/evict, admission flips,
detector edges, deepstore quarantine, verdict-plane edges, fault firings)
calls `emit()` with a registered kind, and the bounded ring retains the most
recent window for `/debug/events` and the controller's merged
`/debug/timeline`.

Design points:

* one journal per process (`get_journal()`), mirroring the metrics registry
  singleton — all in-proc roles share it, each stamping its own `node`;
* per-node monotonic `seq` (exact under concurrency — assigned inside the
  ring lock), plus a journal-local arrival counter `gseq` used as the
  incremental-pull cursor for `GET /debug/events?since=<gseq>`;
* `KINDS` is the closed schema table: `emit()` of an unregistered kind
  raises, and the `event-kind-drift` graftcheck rule holds call sites and
  the README glossary to this table;
* the ring evicts strictly oldest-first (like `TraceRing`) and keeps
  emitted/evicted conservation counters so the bench lane can assert
  `emitted == retained + evicted`;
* events emitted while a traced query is active on the calling thread
  inherit the trace id, so query reports can interleave cluster events
  into the waterfall.

The `emit()` fast path is a dataclass construction plus one lock-guarded
deque append and a cached counter increment — benched under 1% of the
in-proc query p50 (`bench.py --events`).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Tuple

from .metrics import Counter, get_registry
from .trace import current_trace

#: severity levels, mildest first (used by timeline filters: a severity
#: filter admits its level and everything worse)
SEVERITIES: Tuple[str, ...] = ("INFO", "WARN", "ERROR")

#: the registered-kind schema table: kind -> (default severity, description).
#: This literal IS the contract — `emit()` rejects kinds not listed here,
#: the `event-kind-drift` graftcheck rule requires every call site to use a
#: registered kind and every registered kind to appear in the README
#: glossary. Keep it a plain dict literal (the rule reads it via `ast`).
KINDS: Dict[str, Tuple[str, str]] = {
    "segment.consuming.created": ("INFO", "new CONSUMING segment opened on a stream partition"),
    "segment.committed": ("INFO", "consuming segment sealed and committed to the deepstore"),
    "segment.online": ("INFO", "committed segment flipped CONSUMING->ONLINE in the ideal state"),
    "segment.cold.demoted": ("INFO", "segment demoted to the cold tier (forms dropped, deepstore-backed)"),
    "segment.cold.loaded": ("INFO", "cold segment lazily reloaded from the deepstore on query touch"),
    "segment.reassigned": ("WARN", "consuming segment moved off a dead server"),
    "tier.admission.rejected": ("WARN", "HBM admission rejected a segment load (headroom below floor)"),
    "tier.evicted": ("INFO", "tiering manager evicted a resident segment to reclaim HBM"),
    "tier.promoted": ("INFO", "queried cold segment promoted back to the hot tier"),
    "admission.state": ("WARN", "broker admission controller changed state (HEALTHY/SHEDDING/SATURATED)"),
    "backpressure.hold": ("WARN", "server 429 put it on backpressure hold (out of hedge/retry sets)"),
    "hedge.suppressed": ("WARN", "hedging suppressed because the broker itself is overloaded"),
    "server.down": ("ERROR", "failure detector marked a server unhealthy (probing started)"),
    "server.up": ("INFO", "failure detector restored a probed server to healthy routing"),
    "server.registered": ("INFO", "server handle registered with the broker"),
    "server.unregistered": ("INFO", "server handle unregistered from the broker"),
    "leader.elected": ("INFO", "controller won or took over the leadership lease"),
    "leader.lost": ("WARN", "controller lost the leadership lease"),
    "deepstore.quarantined": ("ERROR", "deepstore upload retries exhausted; segment quarantined"),
    "deepstore.healed": ("INFO", "quarantined/missing deepstore copy healed from a server peer"),
    "fault.fired": ("WARN", "graftfault injection fired at an instrumented site"),
    "verdict.ingestion": ("WARN", "ingestion health verdict changed for a table"),
    "verdict.slo": ("WARN", "freshness/latency SLO verdict changed for a table"),
    "verdict.memory": ("WARN", "device-memory health verdict changed for a table"),
    "verdict.workload": ("WARN", "workload shape regression verdict changed for a fingerprint"),
    "incident.captured": ("ERROR", "flight recorder captured an incident bundle"),
    "bench.probe": ("INFO", "synthetic event emitted by the bench --events lane"),
}


@dataclass
class Event:
    """One journal entry. `seq` is per-node monotonic (exact); `gseq` is the
    journal-local arrival counter used as the incremental-pull cursor."""
    __slots__ = ("seq", "gseq", "ts_ms", "node", "kind", "severity", "table",
                 "segment", "attrs", "trace_id")
    seq: int
    gseq: int
    ts_ms: int
    node: str
    kind: str
    severity: str
    table: str
    segment: str
    attrs: Dict[str, Any]
    trace_id: str

    def as_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "seq": self.seq, "gseq": self.gseq, "tsMs": self.ts_ms,
            "node": self.node, "kind": self.kind, "severity": self.severity,
        }
        if self.table:
            d["table"] = self.table
        if self.segment:
            d["segment"] = self.segment
        if self.attrs:
            d["attrs"] = self.attrs
        if self.trace_id:
            d["traceId"] = self.trace_id
        return d


class EventJournal:
    """Bounded, lock-guarded ring of typed events with strict oldest-first
    eviction (the `TraceRing` discipline: admit then popleft, so retention
    can never exceed `capacity`)."""

    def __init__(self, capacity: int = 512, node: str = "proc"):
        self.capacity = max(1, int(capacity))
        self.node = node
        self._lock = threading.Lock()
        self._entries: Deque[Event] = deque()       # oldest -> newest
        self._seqs: Dict[str, int] = {}
        self._gseq = 0
        self.emitted = 0
        self.evicted = 0
        #: per-kind Counter cache — emit() must not pay the registry's
        #: name+labels dict lookup on every transition
        self._counters: Dict[str, Counter] = {}

    def configure(self, node: Optional[str] = None,
                  capacity: Optional[int] = None) -> None:
        """Late (re)configuration by role services: the default node label
        and ring capacity (`events.ring.size`). Shrinking trims oldest-first
        immediately."""
        with self._lock:
            if node is not None:
                self.node = node
            if capacity is not None:
                self.capacity = max(1, int(capacity))
                while len(self._entries) > self.capacity:
                    self._entries.popleft()
                    self.evicted += 1

    def _counter(self, kind: str) -> Counter:
        c = self._counters.get(kind)
        if c is None:
            c = get_registry().counter("pinot_events_total", {"kind": kind})
            self._counters[kind] = c
        return c

    def emit(self, kind: str, node: Optional[str] = None, table: str = "",
             segment: str = "", severity: Optional[str] = None,
             trace_id: Optional[str] = None, **attrs: Any) -> Event:
        """Record one transition. `kind` must be registered in `KINDS`
        (closed schema — unregistered kinds raise so drift is loud, and the
        `event-kind-drift` rule catches it statically first). Severity
        defaults from the schema table; sites whose severity depends on
        direction (verdict edges, admission flips) override it. The trace id
        defaults to the calling thread's active query trace, if any."""
        spec = KINDS.get(kind)
        if spec is None:
            raise ValueError(f"unregistered event kind: {kind!r}")
        if trace_id is None:
            tr = current_trace()
            trace_id = tr.trace_id if tr is not None else ""
        ev_node = node if node is not None else self.node
        ts_ms = int(time.time() * 1000)
        with self._lock:
            seq = self._seqs.get(ev_node, 0) + 1
            self._seqs[ev_node] = seq
            self._gseq += 1
            ev = Event(seq, self._gseq, ts_ms, ev_node, kind,
                       severity if severity is not None else spec[0],
                       table, segment, attrs, trace_id)
            self._entries.append(ev)
            self.emitted += 1
            if len(self._entries) > self.capacity:
                self._entries.popleft()
                self.evicted += 1
        self._counter(kind).inc()
        return ev

    def events_since(self, since: int = 0,
                     limit: Optional[int] = None) -> Dict[str, Any]:
        """Incremental pull: events with `gseq > since`, oldest first, plus
        the cursor to pass next time. This is the `/debug/events` payload —
        the controller's timeline merge polls it exactly like the PR 14
        memory checker polls `/debug/memory`."""
        with self._lock:
            rows = [e for e in self._entries if e.gseq > since]
            cursor = self._gseq
        if limit is not None:
            rows = rows[-limit:]
        return {"events": [e.as_dict() for e in rows], "cursor": cursor}

    def entries(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Newest-first retained events (the human-facing read side)."""
        with self._lock:
            rows = list(self._entries)
        rows.reverse()
        rows = rows[:limit] if limit is not None else rows
        return [e.as_dict() for e in rows]

    def snapshot(self) -> Dict[str, Any]:
        """Conservation view: emitted == retained + evicted always holds
        (asserted by the bench lane's ring-eviction check)."""
        with self._lock:
            return {"node": self.node, "capacity": self.capacity,
                    "retained": len(self._entries), "emitted": self.emitted,
                    "evicted": self.evicted, "cursor": self._gseq}

    def clear(self) -> None:
        """Reset ring, sequences and conservation counters (tests/bench)."""
        with self._lock:
            self._entries.clear()
            self._seqs.clear()
            self._gseq = 0
            self.emitted = 0
            self.evicted = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# the process-wide journal (mirrors the metrics REGISTRY singleton)
JOURNAL = EventJournal()


def get_journal() -> EventJournal:
    return JOURNAL


def emit(kind: str, node: Optional[str] = None, table: str = "",
         segment: str = "", severity: Optional[str] = None,
         trace_id: Optional[str] = None, **attrs: Any) -> Event:
    """Record one transition on the process journal (see
    `EventJournal.emit`). Instrumented sites call this module function so
    they never hold a journal reference."""
    return JOURNAL.emit(kind, node=node, table=table, segment=segment,
                        severity=severity, trace_id=trace_id, **attrs)
