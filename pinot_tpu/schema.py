"""Table schema model: typed field specs for dimensions, metrics and time columns.

TPU-native redesign of the reference's schema model
(`pinot-spi/src/main/java/org/apache/pinot/spi/data/Schema.java` and `FieldSpec.java`).
The key departure: every field declares a *storage dtype* that is guaranteed to be a
fixed-width machine type so the column can live in HBM as a dense array. STRING/BYTES/JSON
columns are therefore always dictionary-encoded; their device representation is an int32
dict-id array and all predicate work happens on dict ids (the reference does the same on its
scan path — see SURVEY.md §7 "Hard parts").
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

import numpy as np


class DataType(Enum):
    """Logical column types (reference: FieldSpec.DataType)."""

    INT = "INT"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    BOOLEAN = "BOOLEAN"
    TIMESTAMP = "TIMESTAMP"  # epoch millis, stored as int64
    STRING = "STRING"
    JSON = "JSON"
    BYTES = "BYTES"

    @property
    def is_numeric(self) -> bool:
        return self in (DataType.INT, DataType.LONG, DataType.FLOAT, DataType.DOUBLE,
                        DataType.BOOLEAN, DataType.TIMESTAMP)

    @property
    def numpy_dtype(self) -> np.dtype:
        """Host/disk representation of *raw* (non-dict-encoded) values."""
        return {
            DataType.INT: np.dtype(np.int32),
            DataType.LONG: np.dtype(np.int64),
            DataType.FLOAT: np.dtype(np.float32),
            DataType.DOUBLE: np.dtype(np.float64),
            DataType.BOOLEAN: np.dtype(np.int32),
            DataType.TIMESTAMP: np.dtype(np.int64),
            DataType.STRING: np.dtype(object),
            DataType.JSON: np.dtype(object),
            DataType.BYTES: np.dtype(object),
        }[self]

    @property
    def default_null(self) -> Any:
        """Default placeholder for nulls (reference: FieldSpec default null values)."""
        return {
            DataType.INT: -(2 ** 31),
            DataType.LONG: -(2 ** 63),
            DataType.FLOAT: float("-inf"),
            DataType.DOUBLE: float("-inf"),
            DataType.BOOLEAN: 0,
            DataType.TIMESTAMP: 0,
            DataType.STRING: "null",
            DataType.JSON: "null",
            DataType.BYTES: b"",
        }[self]

    def coerce(self, value: Any) -> Any:
        """Coerce an ingested python value to this type (DataTypeTransformer analog)."""
        if value is None:
            return self.default_null
        if self in (DataType.INT, DataType.LONG):
            return int(value)
        if self in (DataType.FLOAT, DataType.DOUBLE):
            return float(value)
        if self is DataType.BOOLEAN:
            if isinstance(value, str):
                return 1 if value.lower() in ("true", "1", "t", "yes") else 0
            return int(bool(value))
        if self is DataType.TIMESTAMP:
            return int(value)
        if self is DataType.BYTES:
            if isinstance(value, str):
                return bytes.fromhex(value)
            return bytes(value)
        if self is DataType.JSON:
            if not isinstance(value, str):
                return json.dumps(value)
            return value
        return str(value)


class FieldRole(Enum):
    """Reference: FieldSpec.FieldType (DIMENSION / METRIC / DATE_TIME)."""

    DIMENSION = "DIMENSION"
    METRIC = "METRIC"
    DATE_TIME = "DATE_TIME"


@dataclass
class FieldSpec:
    name: str
    data_type: DataType
    role: FieldRole = FieldRole.DIMENSION
    single_value: bool = True
    # DATE_TIME metadata (reference: DateTimeFieldSpec format/granularity)
    format: Optional[str] = None
    granularity: Optional[str] = None
    default_null_value: Optional[Any] = None

    @property
    def null_value(self) -> Any:
        if self.default_null_value is not None:
            return self.default_null_value
        # Metrics default to 0 (reference: FieldSpec.DEFAULT_METRIC_NULL_VALUE_OF_*) so a
        # null-filled metric can't poison SUM/MIN; dimensions use type sentinels.
        if self.role is FieldRole.METRIC and self.data_type.is_numeric:
            return 0 if self.data_type.numpy_dtype.kind == "i" else 0.0
        return self.data_type.default_null

    def to_json(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "name": self.name,
            "dataType": self.data_type.value,
            "role": self.role.value,
            "singleValue": self.single_value,
        }
        if self.format:
            d["format"] = self.format
        if self.granularity:
            d["granularity"] = self.granularity
        if self.default_null_value is not None:
            d["defaultNullValue"] = self.default_null_value
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "FieldSpec":
        return FieldSpec(
            name=d["name"],
            data_type=DataType(d["dataType"]),
            role=FieldRole(d.get("role", "DIMENSION")),
            single_value=d.get("singleValue", True),
            format=d.get("format"),
            granularity=d.get("granularity"),
            default_null_value=d.get("defaultNullValue"),
        )


@dataclass
class Schema:
    """Reference: pinot-spi Schema (JSON-serialized, stored in the catalog)."""

    name: str
    fields: List[FieldSpec] = field(default_factory=list)
    primary_key_columns: List[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        self._by_name = {f.name: f for f in self.fields}
        if len(self._by_name) != len(self.fields):
            raise ValueError(f"duplicate column names in schema {self.name}")

    # -- accessors ---------------------------------------------------------
    def field_spec(self, name: str) -> FieldSpec:
        try:
            return self._by_name[name]
        except KeyError:
            raise KeyError(f"unknown column {name!r} in schema {self.name}") from None

    def has_column(self, name: str) -> bool:
        return name in self._by_name

    @property
    def column_names(self) -> List[str]:
        return [f.name for f in self.fields]

    @property
    def dimension_columns(self) -> List[str]:
        return [f.name for f in self.fields if f.role is FieldRole.DIMENSION]

    @property
    def metric_columns(self) -> List[str]:
        return [f.name for f in self.fields if f.role is FieldRole.METRIC]

    @property
    def time_columns(self) -> List[str]:
        return [f.name for f in self.fields if f.role is FieldRole.DATE_TIME]

    # -- serde -------------------------------------------------------------
    def to_json(self) -> Dict[str, Any]:
        return {
            "schemaName": self.name,
            "fields": [f.to_json() for f in self.fields],
            "primaryKeyColumns": self.primary_key_columns,
        }

    def to_json_str(self) -> str:
        return json.dumps(self.to_json(), indent=2)

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "Schema":
        fields = [FieldSpec.from_json(f) for f in d.get("fields", [])]
        if not fields:
            # accept the reference's schema JSON layout too
            # (dimensionFieldSpecs / metricFieldSpecs / dateTimeFieldSpecs),
            # so schemas written for Apache Pinot load unchanged
            for key, role in (("dimensionFieldSpecs", FieldRole.DIMENSION),
                              ("metricFieldSpecs", FieldRole.METRIC),
                              ("dateTimeFieldSpecs", FieldRole.DATE_TIME)):
                for f in d.get(key, []):
                    fields.append(FieldSpec(
                        name=f["name"],
                        data_type=DataType(f["dataType"]),
                        role=role,
                        single_value=f.get("singleValueField", True),
                        format=f.get("format"),
                        granularity=f.get("granularity"),
                        default_null_value=f.get("defaultNullValue"),
                    ))
        return Schema(
            name=d["schemaName"],
            fields=fields,
            primary_key_columns=d.get("primaryKeyColumns", []),
        )

    @staticmethod
    def from_json_str(s: str) -> "Schema":
        return Schema.from_json(json.loads(s))


def normalize_mv_cell(spec: FieldSpec, v: Any):
    """(values list, is_null) for one multi-value cell — the single normalization
    used by BOTH the batch writer and the mutable (realtime) segment so the two
    ingestion paths store identical values. None/empty -> one default null value
    (reference: MV default null is a one-element array); scalars wrap; every
    element goes through the type's coerce."""
    if v is None or (hasattr(v, "__len__") and len(v) == 0
                     and not isinstance(v, (str, bytes))):
        return [spec.null_value], True
    if isinstance(v, (list, tuple, np.ndarray)):
        return [spec.data_type.coerce(x) for x in v], False
    return [spec.data_type.coerce(v)], False


def dimension(name: str, data_type: DataType = DataType.STRING, **kw) -> FieldSpec:
    return FieldSpec(name, data_type, FieldRole.DIMENSION, **kw)


def metric(name: str, data_type: DataType = DataType.DOUBLE, **kw) -> FieldSpec:
    return FieldSpec(name, data_type, FieldRole.METRIC, **kw)


def date_time(name: str, data_type: DataType = DataType.TIMESTAMP, **kw) -> FieldSpec:
    return FieldSpec(name, data_type, FieldRole.DATE_TIME, **kw)
