"""Table configuration model.

Analog of the reference's `TableConfig`
(`pinot-spi/src/main/java/org/apache/pinot/spi/config/table/TableConfig.java:37`) plus the
nested configs we support so far (IndexingConfig, SegmentsValidationAndRetentionConfig,
StreamConfig subset, UpsertConfig/DedupConfig stubs wired in later milestones). JSON
round-trips; stored in the catalog property store like the reference stores it in ZK.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional


class TableType(Enum):
    OFFLINE = "OFFLINE"
    REALTIME = "REALTIME"


@dataclass
class IndexingConfig:
    inverted_index_columns: List[str] = field(default_factory=list)
    range_index_columns: List[str] = field(default_factory=list)
    bloom_filter_columns: List[str] = field(default_factory=list)
    no_dictionary_columns: List[str] = field(default_factory=list)
    json_index_columns: List[str] = field(default_factory=list)
    text_index_columns: List[str] = field(default_factory=list)
    # trigram regex prefilter over the dictionary (reference: FST index,
    # fieldConfigList FST indexType)
    fst_index_columns: List[str] = field(default_factory=list)
    sorted_column: Optional[str] = None
    star_tree_configs: List[Dict[str, Any]] = field(default_factory=list)
    geo_index_pairs: List[str] = field(default_factory=list)  # "lngCol,latCol"
    raw_compression: str = ""  # chunk codec for raw fwd indexes (zlib/lzma)

    def to_json(self):
        return {
            "invertedIndexColumns": self.inverted_index_columns,
            "rangeIndexColumns": self.range_index_columns,
            "bloomFilterColumns": self.bloom_filter_columns,
            "noDictionaryColumns": self.no_dictionary_columns,
            "jsonIndexColumns": self.json_index_columns,
            "textIndexColumns": self.text_index_columns,
            "fstIndexColumns": self.fst_index_columns,
            "sortedColumn": self.sorted_column,
            "starTreeIndexConfigs": self.star_tree_configs,
            "geoIndexPairs": self.geo_index_pairs,
            "rawCompression": self.raw_compression,
        }

    @staticmethod
    def from_json(d):
        return IndexingConfig(
            inverted_index_columns=d.get("invertedIndexColumns", []),
            range_index_columns=d.get("rangeIndexColumns", []),
            bloom_filter_columns=d.get("bloomFilterColumns", []),
            no_dictionary_columns=d.get("noDictionaryColumns", []),
            json_index_columns=d.get("jsonIndexColumns", []),
            text_index_columns=d.get("textIndexColumns", []),
            fst_index_columns=d.get("fstIndexColumns", []),
            sorted_column=d.get("sortedColumn"),
            star_tree_configs=d.get("starTreeIndexConfigs", []),
            geo_index_pairs=d.get("geoIndexPairs", []),
            raw_compression=d.get("rawCompression", ""),
        )


@dataclass
class SegmentPartitionConfig:
    """Reference: SegmentPartitionConfig — enables partition-aware routing pruning."""
    column: str = ""
    function: str = "murmur"  # murmur | modulo
    num_partitions: int = 0

    def to_json(self):
        return {"column": self.column, "function": self.function,
                "numPartitions": self.num_partitions}

    @staticmethod
    def from_json(d):
        return SegmentPartitionConfig(d.get("column", ""), d.get("function", "murmur"),
                                      d.get("numPartitions", 0))


@dataclass
class StreamConfig:
    """Reference: stream configs map inside IndexingConfig (spi/stream/StreamConfig)."""
    stream_type: str = "memory"           # plugin name (memory/file/kafka-protocol)
    topic: str = ""
    decoder: str = "json"
    properties: Dict[str, Any] = field(default_factory=dict)
    # segment completion thresholds (reference: realtime.segment.flush.*)
    flush_threshold_rows: int = 100_000
    flush_threshold_seconds: int = 6 * 3600

    def to_json(self):
        return {"streamType": self.stream_type, "topic": self.topic,
                "decoder": self.decoder, "properties": self.properties,
                "flushThresholdRows": self.flush_threshold_rows,
                "flushThresholdSeconds": self.flush_threshold_seconds}

    @staticmethod
    def from_json(d):
        return StreamConfig(d.get("streamType", "memory"), d.get("topic", ""),
                            d.get("decoder", "json"), d.get("properties", {}),
                            d.get("flushThresholdRows", 100_000),
                            d.get("flushThresholdSeconds", 6 * 3600))


@dataclass
class UpsertConfig:
    """Reference: spi/config/table/UpsertConfig (FULL or PARTIAL mode)."""
    mode: str = "FULL"  # FULL | PARTIAL
    comparison_column: Optional[str] = None
    partial_strategies: Dict[str, str] = field(default_factory=dict)  # col -> strategy

    def to_json(self):
        return {"mode": self.mode, "comparisonColumn": self.comparison_column,
                "partialUpsertStrategies": self.partial_strategies}

    @staticmethod
    def from_json(d):
        return UpsertConfig(d.get("mode", "FULL"), d.get("comparisonColumn"),
                            d.get("partialUpsertStrategies", {}))


@dataclass
class TierConfig:
    """One storage tier: segments older than `segment_age_days` relocate to the
    server pool tagged `server_tag` (reference: spi/config/table/TierConfig with
    segmentSelectorType=time, storageType=pinot_server; applied by the
    SegmentRelocator periodic task)."""
    name: str
    segment_age_days: float
    server_tag: str

    def to_json(self):
        return {"name": self.name, "segmentAge": f"{self.segment_age_days}d",
                "serverTag": self.server_tag}

    @staticmethod
    def from_json(d):
        age = d.get("segmentAge", "0d")
        days = float(age[:-1]) if isinstance(age, str) and age.endswith("d") else float(age)
        return TierConfig(d.get("name", ""), days, d.get("serverTag", ""))


@dataclass
class QuotaConfig:
    """Reference: spi/config/table/QuotaConfig (maxQueriesPerSecond + storage)."""
    max_qps: Optional[float] = None
    storage_bytes: Optional[int] = None

    def to_json(self):
        return {"maxQueriesPerSecond": self.max_qps, "storageBytes": self.storage_bytes}

    @staticmethod
    def from_json(d):
        return QuotaConfig(d.get("maxQueriesPerSecond"), d.get("storageBytes"))


@dataclass
class TableConfig:
    name: str                       # raw table name (no type suffix)
    table_type: TableType = TableType.OFFLINE
    replication: int = 1
    retention_days: Optional[float] = None
    time_column: Optional[str] = None
    indexing: IndexingConfig = field(default_factory=IndexingConfig)
    partition: Optional[SegmentPartitionConfig] = None
    stream: Optional[StreamConfig] = None
    upsert: Optional[UpsertConfig] = None
    dedup_enabled: bool = False
    tenant: str = "DefaultTenant"
    # dimension table: small, fully replicated to every server, loaded into a PK map
    # for LOOKUP joins (reference: DimensionTableConfig / isDimTable)
    is_dim_table: bool = False
    # minion task configs by task type (reference: TableTaskConfig, e.g.
    # {"MergeRollupTask": {"bucketMs": 86400000}, "RealtimeToOfflineSegmentsTask": {}})
    task_configs: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    # per-table query quota (reference: QuotaConfig)
    quota: Optional[QuotaConfig] = None
    # instance selector: "" = auto (strictReplicaGroup for upsert tables,
    # balanced otherwise); explicit "balanced" | "replicaGroup" |
    # "strictReplicaGroup" (reference: RoutingConfig.instanceSelectorType)
    routing_selector: str = ""
    # storage tiers, checked oldest-threshold-first by the SegmentRelocator
    # (reference: tierConfigs in TableConfig)
    tiers: List[TierConfig] = field(default_factory=list)

    @property
    def table_name_with_type(self) -> str:
        return f"{self.name}_{self.table_type.value}"

    def to_json(self) -> Dict[str, Any]:
        d = {
            "tableName": self.name,
            "tableType": self.table_type.value,
            "replication": self.replication,
            "retentionDays": self.retention_days,
            "timeColumn": self.time_column,
            "indexing": self.indexing.to_json(),
            "tenant": self.tenant,
            "dedupEnabled": self.dedup_enabled,
            "isDimTable": self.is_dim_table,
            "taskConfigs": self.task_configs,
        }
        if self.routing_selector:
            d["routingSelector"] = self.routing_selector
        if self.partition:
            d["segmentPartitionConfig"] = self.partition.to_json()
        if self.stream:
            d["streamConfig"] = self.stream.to_json()
        if self.upsert:
            d["upsertConfig"] = self.upsert.to_json()
        if self.quota:
            d["quota"] = self.quota.to_json()
        if self.tiers:
            d["tierConfigs"] = [t.to_json() for t in self.tiers]
        return d

    @staticmethod
    def from_json(d: Dict[str, Any]) -> "TableConfig":
        return TableConfig(
            name=d["tableName"],
            table_type=TableType(d.get("tableType", "OFFLINE")),
            replication=d.get("replication", 1),
            retention_days=d.get("retentionDays"),
            time_column=d.get("timeColumn"),
            indexing=IndexingConfig.from_json(d.get("indexing", {})),
            partition=SegmentPartitionConfig.from_json(d["segmentPartitionConfig"])
            if d.get("segmentPartitionConfig") else None,
            stream=StreamConfig.from_json(d["streamConfig"]) if d.get("streamConfig") else None,
            upsert=UpsertConfig.from_json(d["upsertConfig"]) if d.get("upsertConfig") else None,
            dedup_enabled=d.get("dedupEnabled", False),
            is_dim_table=d.get("isDimTable", False),
            tenant=d.get("tenant", "DefaultTenant"),
            task_configs=d.get("taskConfigs", {}),
            quota=QuotaConfig.from_json(d["quota"]) if d.get("quota") else None,
            tiers=[TierConfig.from_json(t) for t in d.get("tierConfigs", [])],
            routing_selector=d.get("routingSelector", ""),
        )

    def to_json_str(self) -> str:
        return json.dumps(self.to_json(), indent=2)
