#!/usr/bin/env python
"""Headline benchmark: SSB Q1.1-style filter+aggregate scan rate, rows/sec/chip.

Runs the fused TPU scan (MeshQueryExecutor over however many devices are visible — one
real chip under axon) on synthetic SSB lineorder data, and compares against a
single-thread vectorized numpy evaluation of the same query — the stand-in for the
reference's Java vectorized engine (the JVM engine itself cannot run in this image; see
BASELINE.md). Prints ONE JSON line:

    {"metric": ..., "value": rows_per_sec, "unit": "rows/s", "vs_baseline": ratio}

The headline rate is PIPELINED throughput (`MeshQueryExecutor.execute_many`): the axon
relay charges one ~65ms host round trip per synchronization regardless of covered work,
so a serving loop drains its queue with one fetch per batch — the steady-state shape of
an OLAP server. Single-query p50 latency (one dispatch + one fetch round trip) and the
group-by / HLL configs from BASELINE.json are reported in `detail`.

Env knobs: PINOT_BENCH_ROWS (default 16M), PINOT_BENCH_SEGMENTS (8),
PINOT_BENCH_ITERS (20), PINOT_BENCH_DIR (cache dir).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

# 16M rows = 2M/segment x 8: the largest padded block that keeps the group-by
# one-hot matmul inside the f32-exact 2^24-increment budget on ONE device
# (multi-chip divides rows per device, so real meshes scale past this)
ROWS = int(os.environ.get("PINOT_BENCH_ROWS", 16 * 1024 * 1024))
SEGMENTS = int(os.environ.get("PINOT_BENCH_SEGMENTS", 8))
ITERS = int(os.environ.get("PINOT_BENCH_ITERS", 20))
CACHE = os.environ.get("PINOT_BENCH_DIR", "/tmp/pinot_tpu_bench")

QUERY = ("SELECT SUM(lo_extendedprice * lo_discount) FROM lineorder "
         "WHERE lo_orderdate BETWEEN 19930101 AND 19931231 "
         "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 LIMIT 10")

GROUP_QUERY = ("SELECT lo_region, SUM(lo_revenue), COUNT(*) FROM lineorder "
               "WHERE lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 "
               "GROUP BY lo_region ORDER BY lo_region LIMIT 10")

HLL_QUERY = "SELECT DISTINCTCOUNTHLL(lo_orderdate) FROM lineorder WHERE lo_quantity < 25"

# BASELINE.json config 3: the filter hits only star-tree split dimensions, so every
# segment answers from its pre-aggregated record table (dict-id LUT lookup fused
# into the predicate mask over ~100s of records instead of a 2M-row scan)
STAR_QUERY = ("SELECT lo_region, SUM(lo_revenue) FROM lineorder "
              "WHERE lo_discount BETWEEN 1 AND 3 "
              "GROUP BY lo_region ORDER BY lo_region LIMIT 10")


# TPC-H Q1-shape: group-by with per-group COUNT DISTINCT (HLL) on device —
# BASELINE config 5 as written (the grouped presence-matrix kernel path)
HLL_GROUP_QUERY = ("SELECT lo_region, COUNT(*), SUM(lo_revenue), "
                   "DISTINCTCOUNTHLL(lo_orderdate) FROM lineorder "
                   "WHERE lo_quantity < 25 GROUP BY lo_region "
                   "ORDER BY lo_region LIMIT 10")

# 20k keys: exercises the CHUNKED 64x64 one-hot matmul group-by
# (engine/kernels.py _grouped_chunk64, MATMUL_KEY_CAP < keys <= CHUNK_KEY_CAP)
# plus the vectorized dense decode (query/dense_reduce.py)
HIGH_CARD_QUERY = ("SELECT lo_suppkey, SUM(lo_revenue), COUNT(*) "
                   "FROM lineorder GROUP BY lo_suppkey LIMIT 100000")

THETA_QUERY = ("SELECT DISTINCTCOUNTTHETASKETCH(lo_orderdate) FROM lineorder "
               "WHERE lo_quantity < 25")

# BASELINE config 3 as designed: a LARGE record table (high-cardinality split
# dims) runs the STACKED DEVICE star path — record tables stack like base
# segments, split-dim LUT fused into the kernel mask
STAR_HC_QUERY = ("SELECT lo_orderdate, SUM(lo_revenue) FROM lineorder "
                 "WHERE lo_discount BETWEEN 1 AND 3 GROUP BY lo_orderdate "
                 "LIMIT 100000")

HIGH_CARD_SUPPKEYS = 20_000


def ssb_schema():
    from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
    return Schema("lineorder", [
        dimension("lo_region", DataType.STRING),
        dimension("lo_suppkey", DataType.INT),
        date_time("lo_orderdate", DataType.INT),
        metric("lo_quantity", DataType.INT),
        metric("lo_extendedprice", DataType.DOUBLE),
        metric("lo_discount", DataType.INT),
        metric("lo_revenue", DataType.DOUBLE),
    ])


def make_columns(n: int):
    rng = np.random.default_rng(20260729)
    regions = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
    region_ids = rng.integers(0, 5, n)
    return {
        "lo_region": np.array(regions, dtype=object)[region_ids],
        "lo_suppkey": rng.integers(0, HIGH_CARD_SUPPKEYS, n).astype(np.int32),
        "lo_orderdate": (19920101 + rng.integers(0, 7, n) * 10000
                         + rng.integers(1, 13, n) * 100
                         + rng.integers(1, 29, n)).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
        "lo_extendedprice": np.round(rng.uniform(1.0, 10_000.0, n), 2).astype(np.float64),
        "lo_discount": rng.integers(0, 11, n).astype(np.int32),
        "lo_revenue": np.round(rng.uniform(1.0, 60_000.0, n), 2).astype(np.float64),
    }


def build_or_load_segments(schema, cols, star_tree=False, rows=None, tag=None,
                           star_hc=False):
    from pinot_tpu.segment import (SegmentGeneratorConfig, StarTreeIndexConfig,
                                   load_segment)
    from pinot_tpu.segment.writer import build_aligned_segments
    rows = rows if rows is not None else ROWS
    tag = tag or (f"r{rows}_s{SEGMENTS}_v2"
                  f"{'_st' if star_tree else ''}{'_sthc' if star_hc else ''}")
    seg_root = os.path.join(CACHE, tag)
    marker = os.path.join(seg_root, "DONE")
    if not os.path.exists(marker):
        os.makedirs(seg_root, exist_ok=True)
        config = None
        if star_tree:
            config = SegmentGeneratorConfig(star_tree_configs=[
                StarTreeIndexConfig(
                    dimensions_split_order=["lo_region", "lo_discount"],
                    function_column_pairs=["SUM__lo_revenue"])])
        elif star_hc:
            # high-cardinality split dims -> 1e5+ combined records: the
            # stacked DEVICE star path (small trees keep the host path)
            config = SegmentGeneratorConfig(star_tree_configs=[
                StarTreeIndexConfig(
                    dimensions_split_order=["lo_orderdate", "lo_discount"],
                    function_column_pairs=["SUM__lo_revenue"])])
        build_aligned_segments(schema, cols, seg_root, "lineorder", SEGMENTS,
                               config=config)
        with open(marker, "w") as f:
            f.write("ok")
    names = sorted(d for d in os.listdir(seg_root) if d.startswith("lineorder_"))
    return [load_segment(os.path.join(seg_root, d)) for d in names]


def numpy_baseline(cols, iters=3) -> float:
    """Single-thread vectorized scan of the same query (Java-engine stand-in)."""
    od, disc, qty = cols["lo_orderdate"], cols["lo_discount"], cols["lo_quantity"]
    price = cols["lo_extendedprice"]

    def run():
        mask = ((od >= 19930101) & (od <= 19931231)
                & (disc >= 1) & (disc <= 3) & (qty < 25))
        return float(np.sum(price[mask] * disc[mask]))

    run()  # warm caches
    t0 = time.perf_counter()
    for _ in range(iters):
        result = run()
    dt = (time.perf_counter() - t0) / iters
    return len(od) / dt, result


def ingest_bench(rows: int = 50_000):
    """Realtime consumption speed: kafkalite BINARY frames through
    fetch->decode->MutableSegment.index — the full per-event realtime path —
    vs a vectorized numpy column-append of the same rows (reference:
    pinot-perf BenchmarkRealtimeConsumptionSpeed.java)."""
    import json as _json

    from pinot_tpu.ingest.kafkalite import (KafkaLiteConsumer, LogBrokerClient,
                                            LogBrokerServer)
    from pinot_tpu.schema import (DataType, Schema, date_time, dimension,
                                  metric)
    from pinot_tpu.segment.mutable import MutableSegment

    schema = Schema("events", [
        dimension("site", DataType.STRING), metric("clicks", DataType.LONG),
        metric("cost", DataType.DOUBLE), date_time("ts", DataType.LONG)])
    rng = np.random.default_rng(7)
    raws = [{"site": f"s{int(i) % 50}.com", "clicks": int(c), "cost": float(x),
             "ts": 1700000000000 + j}
            for j, (i, c, x) in enumerate(zip(
                rng.integers(0, 50, rows), rng.integers(1, 9, rows),
                np.round(rng.uniform(0.1, 9.9, rows), 3)))]
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("bench_ingest", 1)
        payloads = [_json.dumps(r) for r in raws]
        for lo in range(0, rows, 500):   # realistic producer batching
            client.produce_many("bench_ingest", payloads[lo:lo + 500])
        from pinot_tpu.ingest.transform import TransformPipeline
        consumer = KafkaLiteConsumer(srv.bootstrap, "bench_ingest", 0)
        seg = MutableSegment("events__0__0__b", schema)
        pipeline = TransformPipeline(schema)   # same path as the consume FSM
        t0 = time.perf_counter()
        off = 0
        from pinot_tpu.ingest.transform import rows_to_all_columns
        while off < rows:
            batch = consumer.fetch(off, 8192)
            decoded = [_json.loads(m.value) for m in batch.messages]
            seg.index_batch(pipeline.apply(rows_to_all_columns(decoded)),
                            coerced=True)
            off = batch.next_offset
        dt = time.perf_counter() - t0
        consumer.close()
        total_clicks = sum(seg.columns["clicks"][:seg.num_docs])
        if seg.num_docs != rows or total_clicks != sum(
                r["clicks"] for r in raws):
            print(f"WARNING: ingest count mismatch {seg.num_docs} != {rows}",
                  file=sys.stderr)
    finally:
        srv.stop()
    # numpy append baseline: same rows into plain column arrays, no indexes
    t0 = time.perf_counter()
    cols = {k: [] for k in ("site", "clicks", "cost", "ts")}
    for r in raws:
        for k in cols:
            cols[k].append(r[k])
    _ = {k: np.asarray(v) for k, v in cols.items()}
    np_dt = time.perf_counter() - t0
    return rows / dt, rows / np_dt


def e2e_bench(n_clients: int = 8, queries_per_client: int = 25):
    """End-to-end QPS/p50 through a REAL ProcessCluster broker over HTTP —
    wire encode/decode, scheduler, scatter/gather included (reference:
    README.md:56 'tens of thousands of queries per second'). Server processes
    run the CPU engine (the TPU library rate is the headline metric; this
    measures the serving stack around it)."""
    import tempfile
    import threading

    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.segment.writer import SegmentBuilder
    from pinot_tpu.table import TableConfig

    schema = ssb_schema()
    n = 100_000
    cols = make_columns(n)
    work = tempfile.mkdtemp(prefix="pinot_bench_e2e_")
    sqls = [QUERY, GROUP_QUERY,
            "SELECT COUNT(*) FROM lineorder WHERE lo_quantity < 10 LIMIT 5"]
    with ProcessCluster(num_servers=2, work_dir=work) as cluster:
        cluster.controller.add_schema(schema)
        cfg = TableConfig("lineorder")
        cluster.controller.add_table(cfg)
        b = SegmentBuilder(schema)
        for i in range(4):
            part = {k: v[i * n // 4:(i + 1) * n // 4] for k, v in cols.items()}
            cluster.controller.upload_segment(
                cfg.table_name_with_type,
                b.build(part, os.path.join(work, "b"), f"lineorder_{i}"))
        deadline = time.time() + 60
        loaded = 0
        while time.time() < deadline:
            r = cluster.query("SELECT COUNT(*) FROM lineorder")[
                "resultTable"]["rows"]
            loaded = r[0][0] if r else 0
            if loaded == n:
                break
            time.sleep(0.2)
        if loaded != n:
            print(f"WARNING: e2e bench started with {loaded}/{n} rows loaded "
                  f"— qps/p50 measured over PARTIAL data", file=sys.stderr)
        for q in sqls:     # warm every shape through every server
            cluster.query(q)
        lat: list = []
        lock = threading.Lock()

        def client(ci: int) -> None:
            mine = []
            for qi in range(queries_per_client):
                q = sqls[(ci + qi) % len(sqls)]
                t0 = time.perf_counter()
                cluster.query(q)
                mine.append(time.perf_counter() - t0)
            with lock:
                lat.extend(mine)

        threads = [threading.Thread(target=client, args=(ci,))
                   for ci in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
    return (n_clients * queries_per_client) / dt, \
        float(np.median(lat)) * 1000


def e2e_device_bench(rows: int, n_clients: int = 32,
                     queries_per_client: int = 12):
    """End-to-end QPS/p50 with the TPU INSIDE the server role (VERDICT r4
    #1): controller + broker run as REAL OS processes; the server runs in
    THIS process because it owns the device (the one-device-owning-process
    topology), serving broker-routed HTTP queries through the
    DeviceQueryPipeline — concurrent queries batch into shared device
    fetches (cluster/device_server.py). Returns (qps, p50_ms, pipeline
    stats, loaded_rows)."""
    import tempfile
    import threading

    from pinot_tpu.cluster.device_server import DeviceQueryPipeline
    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.cluster.remote import (ControllerDeepStore, RemoteCatalog,
                                          RemoteCompletion)
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import ServerService
    from pinot_tpu.segment.writer import SegmentBuilder
    from pinot_tpu.table import TableConfig

    schema = ssb_schema()
    cols = make_columns(rows)
    work = tempfile.mkdtemp(prefix="pinot_bench_e2edev_")
    sqls = [QUERY, GROUP_QUERY,
            "SELECT COUNT(*) FROM lineorder WHERE lo_quantity < 10 LIMIT 5"]
    with ProcessCluster(num_servers=0, work_dir=work) as cluster:
        catalog = RemoteCatalog(cluster.controller_url)
        pipeline = DeviceQueryPipeline()
        server = ServerNode("server_device_0", catalog,
                            ControllerDeepStore(cluster.controller_url),
                            os.path.join(work, "server_device_0"),
                            completion=RemoteCompletion(cluster.controller_url),
                            device_pipeline=pipeline)
        svc = ServerService(server)
        try:
            cluster.controller.add_schema(schema)
            cfg = TableConfig("lineorder")
            cluster.controller.add_table(cfg)
            b = SegmentBuilder(schema)
            n_segs = 4
            for i in range(n_segs):
                part = {k: v[i * rows // n_segs:(i + 1) * rows // n_segs]
                        for k, v in cols.items()}
                cluster.controller.upload_segment(
                    cfg.table_name_with_type,
                    b.build(part, os.path.join(work, "b"), f"lineorder_{i}"))
            deadline = time.time() + 120
            loaded = 0
            while time.time() < deadline:
                r = cluster.query("SELECT COUNT(*) FROM lineorder")[
                    "resultTable"]["rows"]
                loaded = r[0][0] if r else 0
                if loaded == rows:
                    break
                time.sleep(0.2)
            for q in sqls:   # warm every kernel shape
                cluster.query(q)
                cluster.query(q)
            lat: list = []
            lock = threading.Lock()

            def client(ci: int) -> None:
                mine = []
                for qi in range(queries_per_client):
                    q = sqls[(ci + qi) % len(sqls)]
                    t0 = time.perf_counter()
                    cluster.query(q)
                    mine.append(time.perf_counter() - t0)
                with lock:
                    lat.extend(mine)

            threads = [threading.Thread(target=client, args=(ci,))
                       for ci in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            stats = pipeline.stats()
        finally:
            svc.stop()
            server.shutdown()
            catalog.close()
    return (n_clients * queries_per_client) / dt, \
        float(np.median(lat)) * 1000, stats, loaded


def relay_floor_ms(iters=7) -> float:
    """Median dispatch+fetch of a TRIVIAL kernel: the transport's per-query
    latency floor. Published next to p50 so engine overhead (p50 - floor) is
    readable regardless of how the relay's round-trip cost drifts."""
    import jax
    f = jax.jit(lambda x: x + 1)
    x = jax.device_put(np.float32(1.0))
    jax.device_get(f(x))
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.device_get(f(x))
        lat.append(time.perf_counter() - t0)
    return float(np.median(lat)) * 1000


def main():
    schema = ssb_schema()
    cols = make_columns(ROWS)
    segments = build_or_load_segments(schema, cols)
    star_segments = build_or_load_segments(schema, cols, star_tree=True)

    import jax
    from pinot_tpu.parallel import MeshQueryExecutor, default_mesh
    n_dev = len(jax.devices())
    mesh_exec = MeshQueryExecutor(default_mesh(n_dev))

    # warmup: device transfer + jit compile (all device query shapes)
    for q in (QUERY, GROUP_QUERY, HLL_QUERY):
        mesh_exec.execute(segments, q)
        mesh_exec.execute(segments, q)
    mesh_exec.execute(star_segments, STAR_QUERY)

    def p50_latency(q, iters=9, segs=segments):
        lat = []
        for _ in range(iters):
            t0 = time.perf_counter()
            r = mesh_exec.execute(segs, q)
            lat.append(time.perf_counter() - t0)
        return float(np.median(lat)) * 1000, r

    def pipelined_rate(q, iters=ITERS, segs=segments):
        t0 = time.perf_counter()
        results = mesh_exec.execute_many(segs, [q] * iters)
        dt = time.perf_counter() - t0
        return ROWS * iters / dt, results[-1]

    q11_p50, _ = p50_latency(QUERY)
    q11_rate, res = pipelined_rate(QUERY)
    grp_p50, _ = p50_latency(GROUP_QUERY)
    grp_rate, grp_res = pipelined_rate(GROUP_QUERY)
    hll_rate, hll_res = pipelined_rate(HLL_QUERY)
    star_p50, star_res = p50_latency(STAR_QUERY, segs=star_segments)
    star_rate, _ = pipelined_rate(STAR_QUERY, segs=star_segments)

    # r4 configs: grouped HLL, >cap scatter group-by, device theta
    for q in (HLL_GROUP_QUERY, HIGH_CARD_QUERY, THETA_QUERY):
        mesh_exec.execute(segments, q)
        mesh_exec.execute(segments, q)
    hllg_rate, hllg_res = pipelined_rate(HLL_GROUP_QUERY)
    hc_rate, hc_res = pipelined_rate(HIGH_CARD_QUERY, iters=max(4, ITERS // 4))
    theta_rate, theta_res = pipelined_rate(THETA_QUERY)

    # r4: stacked-device star path over a LARGE record table
    star_hc_segments = build_or_load_segments(schema, cols, star_hc=True)
    from pinot_tpu.parallel.combine import StarSetPlan
    from pinot_tpu.query.context import compile_query as _cq
    star_hc_on_device = isinstance(
        mesh_exec._plan_star_device(_cq(STAR_HC_QUERY, schema),
                                    star_hc_segments), StarSetPlan)
    mesh_exec.execute(star_hc_segments, STAR_HC_QUERY)
    mesh_exec.execute(star_hc_segments, STAR_HC_QUERY)
    star_hc_rate, star_hc_res = pipelined_rate(STAR_HC_QUERY,
                                               segs=star_hc_segments)
    # host star path on the same trees, for the device-vs-host comparison
    from pinot_tpu.query.executor import ServerQueryExecutor as _SQE
    host_exec = _SQE(use_device=False)
    host_exec.execute(star_hc_segments, STAR_HC_QUERY)
    t0 = time.perf_counter()
    host_exec.execute(star_hc_segments, STAR_HC_QUERY)
    star_hc_host_rate = ROWS / (time.perf_counter() - t0)

    # single-query latency at serving-sized row counts (1M rows after pruning)
    small_rows = 1024 * 1024
    small_segs = build_or_load_segments(schema, make_columns(small_rows),
                                        rows=small_rows,
                                        tag=f"r{small_rows}_s{SEGMENTS}_v1")
    mesh_exec.execute(small_segs, QUERY)
    mesh_exec.execute(small_segs, QUERY)
    p50_1m, _ = p50_latency(QUERY, segs=small_segs)
    floor_ms = relay_floor_ms()

    np_rows_per_sec, np_result = numpy_baseline(cols)
    ours = res.rows[0][0]
    if abs(ours - np_result) > 2e-3 * max(1.0, abs(np_result)):
        print(f"WARNING: result mismatch tpu={ours} numpy={np_result}", file=sys.stderr)

    # differential checks for the secondary configs (numpy ground truth)
    gmask = ((cols["lo_discount"] >= 1) & (cols["lo_discount"] <= 3)
             & (cols["lo_quantity"] < 25))
    for region, got_sum, got_cnt in grp_res.rows:
        m = gmask & (cols["lo_region"] == region)
        want = float(np.sum(cols["lo_revenue"][m]))
        if int(m.sum()) != got_cnt or abs(got_sum - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: group mismatch {region}: tpu=({got_sum},{got_cnt}) "
                  f"numpy=({want},{int(m.sum())})", file=sys.stderr)
    exact = len(np.unique(cols["lo_orderdate"][cols["lo_quantity"] < 25]))
    if abs(hll_res.rows[0][0] - exact) > 0.05 * exact:
        print(f"WARNING: HLL estimate {hll_res.rows[0][0]} vs exact {exact}",
              file=sys.stderr)
    if abs(theta_res.rows[0][0] - exact) > 0.05 * exact:
        print(f"WARNING: theta estimate {theta_res.rows[0][0]} vs {exact}",
              file=sys.stderr)
    # grouped-HLL differential: per-region exact distinct within theta/HLL error
    qmask = cols["lo_quantity"] < 25
    for region, got_cnt, got_sum, got_hll in hllg_res.rows:
        m = qmask & (cols["lo_region"] == region)
        want_d = len(np.unique(cols["lo_orderdate"][m]))
        if int(m.sum()) != got_cnt or abs(got_hll - want_d) > 0.05 * want_d:
            print(f"WARNING: hll-groupby mismatch {region}: "
                  f"cnt {got_cnt}/{int(m.sum())} hll {got_hll}/{want_d}",
                  file=sys.stderr)
    # high-card group-by differential: group count + sampled sums + count total
    hc_groups = {r[0]: (r[1], r[2]) for r in hc_res.rows}
    if len(hc_groups) != len(np.unique(cols["lo_suppkey"])):
        print(f"WARNING: high-card group count {len(hc_groups)}", file=sys.stderr)
    if sum(c for _, c in hc_groups.values()) != ROWS:
        print("WARNING: high-card counts do not sum to ROWS", file=sys.stderr)
    for sk in (0, 777, HIGH_CARD_SUPPKEYS - 1):
        m = cols["lo_suppkey"] == sk
        want = float(np.sum(cols["lo_revenue"][m]))
        got = hc_groups.get(sk, (0.0, 0))
        if got[1] != int(m.sum()) or abs(got[0] - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: high-card mismatch suppkey={sk}: {got} vs "
                  f"({want},{int(m.sum())})", file=sys.stderr)
    # stacked-device star differential: sampled dates vs raw columns
    dmask = (cols["lo_discount"] >= 1) & (cols["lo_discount"] <= 3)
    star_hc_groups = {r[0]: r[1] for r in star_hc_res.rows}
    dates = np.unique(cols["lo_orderdate"])
    for d in (dates[0], dates[len(dates) // 2], dates[-1]):
        want = float(np.sum(cols["lo_revenue"][dmask
                                               & (cols["lo_orderdate"] == d)]))
        got = star_hc_groups.get(int(d), 0.0)
        if abs(got - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: star-hc mismatch {d}: {got} vs {want}",
                  file=sys.stderr)

    # realtime ingest + end-to-end serving stack
    ingest_rate, ingest_np_rate = ingest_bench()
    e2e_qps, e2e_p50 = e2e_bench()
    # theta numpy baseline: filter + bulk sketch build, both timed — the
    # device query it is compared against pays for the filter too
    from pinot_tpu.query.sketches import ThetaSketch
    t0 = time.perf_counter()
    ThetaSketch.from_values(
        cols["lo_orderdate"][cols["lo_quantity"] < 25])
    theta_np_rate = ROWS / (time.perf_counter() - t0)
    # star-tree differential: same group-by truth, filter lo_discount in [1,3]
    smask = (cols["lo_discount"] >= 1) & (cols["lo_discount"] <= 3)
    for region, got_sum in star_res.rows:
        want = float(np.sum(cols["lo_revenue"][smask & (cols["lo_region"] == region)]))
        if abs(got_sum - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: star-tree mismatch {region}: {got_sum} vs {want}",
                  file=sys.stderr)

    print(json.dumps({
        "metric": "ssb_q1.1_filter_agg_scan_rate",
        "value": round(q11_rate / n_dev, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(q11_rate / n_dev / np_rows_per_sec, 3),
        "detail": {
            "rows": ROWS, "segments": SEGMENTS, "devices": n_dev,
            "pipeline_depth": ITERS,
            "p50_query_latency_ms": round(q11_p50, 3),
            "p50_query_latency_1m_rows_ms": round(p50_1m, 3),
            "relay_roundtrip_floor_ms": round(floor_ms, 3),
            "groupby_rows_per_sec": round(grp_rate / n_dev, 1),
            "groupby_p50_latency_ms": round(grp_p50, 3),
            "hll_rows_per_sec": round(hll_rate / n_dev, 1),
            "hll_vs_numpy": round(hll_rate / n_dev / np_rows_per_sec, 3),
            "hll_groupby_rows_per_sec": round(hllg_rate / n_dev, 1),
            "high_card_groupby_rows_per_sec": round(hc_rate / n_dev, 1),
            "high_card_groups": len(hc_groups),
            "theta_rows_per_sec": round(theta_rate / n_dev, 1),
            "theta_vs_numpy": round(theta_rate / n_dev / theta_np_rate, 3),
            "startree_rows_per_sec": round(star_rate / n_dev, 1),
            "startree_p50_latency_ms": round(star_p50, 3),
            "startree_device_rows_per_sec": round(star_hc_rate / n_dev, 1),
            "startree_device_on_device": star_hc_on_device,
            "startree_device_vs_host": round(star_hc_rate / n_dev
                                             / max(star_hc_host_rate, 1.0), 3),
            "ingest_rows_per_sec": round(ingest_rate, 1),
            "ingest_vs_numpy_append": round(ingest_rate / ingest_np_rate, 3),
            "e2e_qps": round(e2e_qps, 1),
            "e2e_p50_ms": round(e2e_p50, 3),
            "numpy_single_thread_rows_per_sec": round(np_rows_per_sec, 1),
            "backend": jax.default_backend(),
        },
    }))


if __name__ == "__main__":
    main()
