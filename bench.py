#!/usr/bin/env python
"""Headline benchmark: SSB Q1.1-style filter+aggregate scan rate, rows/sec/chip.

Runs the fused TPU scan (MeshQueryExecutor over however many devices are visible — one
real chip under axon) on synthetic SSB lineorder data, and compares against a
single-thread vectorized numpy evaluation of the same query — the stand-in for the
reference's Java vectorized engine (the JVM engine itself cannot run in this image; see
BASELINE.md). Prints ONE JSON line:

    {"metric": ..., "value": rows_per_sec, "unit": "rows/s", "vs_baseline": ratio}

The headline rate is PIPELINED throughput (`MeshQueryExecutor.execute_many`): the axon
relay charges one ~65ms host round trip per synchronization regardless of covered work,
so a serving loop drains its queue with one fetch per batch — the steady-state shape of
an OLAP server. Single-query p50 latency (one dispatch + one fetch round trip) and the
group-by / HLL configs from BASELINE.json are reported in `detail`.

Env knobs: PINOT_BENCH_ROWS (default 16M), PINOT_BENCH_SEGMENTS (8),
PINOT_BENCH_ITERS (20), PINOT_BENCH_DIR (cache dir).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

from pinot_tpu.engine import calibrate as _caps_mod  # noqa: E402

# 16M rows = 2M/segment x 8: the largest padded block that keeps the group-by
# one-hot matmul inside the f32-exact 2^24-increment budget on ONE device
# (multi-chip divides rows per device, so real meshes scale past this)
ROWS = int(os.environ.get("PINOT_BENCH_ROWS", 16 * 1024 * 1024))
SEGMENTS = int(os.environ.get("PINOT_BENCH_SEGMENTS", 8))
ITERS = int(os.environ.get("PINOT_BENCH_ITERS", 20))
CACHE = os.environ.get("PINOT_BENCH_DIR", "/tmp/pinot_tpu_bench")

QUERY = ("SELECT SUM(lo_extendedprice * lo_discount) FROM lineorder "
         "WHERE lo_orderdate BETWEEN 19930101 AND 19931231 "
         "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 LIMIT 10")

GROUP_QUERY = ("SELECT lo_region, SUM(lo_revenue), COUNT(*) FROM lineorder "
               "WHERE lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25 "
               "GROUP BY lo_region ORDER BY lo_region LIMIT 10")

HLL_QUERY = "SELECT DISTINCTCOUNTHLL(lo_orderdate) FROM lineorder WHERE lo_quantity < 25"

# BASELINE.json config 3: the filter hits only star-tree split dimensions, so every
# segment answers from its pre-aggregated record table (dict-id LUT lookup fused
# into the predicate mask over ~100s of records instead of a 2M-row scan)
STAR_QUERY = ("SELECT lo_region, SUM(lo_revenue) FROM lineorder "
              "WHERE lo_discount BETWEEN 1 AND 3 "
              "GROUP BY lo_region ORDER BY lo_region LIMIT 10")


# TPC-H Q1-shape: group-by with per-group COUNT DISTINCT (HLL) on device —
# BASELINE config 5 as written (the grouped presence-matrix kernel path)
HLL_GROUP_QUERY = ("SELECT lo_region, COUNT(*), SUM(lo_revenue), "
                   "DISTINCTCOUNTHLL(lo_orderdate) FROM lineorder "
                   "WHERE lo_quantity < 25 GROUP BY lo_region "
                   "ORDER BY lo_region LIMIT 10")

# 20k keys: exercises the CHUNKED 64x64 one-hot matmul group-by
# (engine/kernels.py _grouped_chunk64, MATMUL_KEY_CAP < keys <= CHUNK_KEY_CAP)
# plus the vectorized dense decode (query/dense_reduce.py)
HIGH_CARD_QUERY = ("SELECT lo_suppkey, SUM(lo_revenue), COUNT(*) "
                   "FROM lineorder GROUP BY lo_suppkey LIMIT 100000")

THETA_QUERY = ("SELECT DISTINCTCOUNTTHETASKETCH(lo_orderdate) FROM lineorder "
               "WHERE lo_quantity < 25")

# 500k keys: past chunk_cap, the calibrated high-card regime (default: the
# radix/rank-partitioned sort kernel replacing the old segment_sum scatter —
# the honest very-high-cardinality line VERDICT r4 asked for)
VERY_HIGH_CARD_QUERY = ("SELECT lo_custkey, SUM(lo_revenue), COUNT(*) "
                        "FROM lineorder GROUP BY lo_custkey LIMIT 600000")

VERY_HIGH_CARD_KEYS = 500_000

# regime-ladder sweep: per-regime rows/s at each cardinality, every high-card
# regime forced in turn via set_caps (output schema: detail.very_high_card_sweep
# = {card: {partitioned|sorted|scatter_rows_per_sec, auto_rows_per_sec,
# auto_regime, groups}})
VHC_SWEEP_CARDS = (128 * 1024, 500_000, 2_000_000)
VHC_SWEEP_ITERS = int(os.environ.get("PINOT_BENCH_VHC_ITERS", 3))

# BASELINE config 3 as designed: a LARGE record table (high-cardinality split
# dims) runs the STACKED DEVICE star path — record tables stack like base
# segments, split-dim LUT fused into the kernel mask
STAR_HC_QUERY = ("SELECT lo_orderdate, SUM(lo_revenue) FROM lineorder "
                 "WHERE lo_discount BETWEEN 1 AND 3 GROUP BY lo_orderdate "
                 "LIMIT 100000")

HIGH_CARD_SUPPKEYS = 20_000


def ssb_schema():
    from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
    return Schema("lineorder", [
        dimension("lo_region", DataType.STRING),
        dimension("lo_suppkey", DataType.INT),
        dimension("lo_custkey", DataType.INT),
        date_time("lo_orderdate", DataType.INT),
        metric("lo_quantity", DataType.INT),
        metric("lo_extendedprice", DataType.DOUBLE),
        metric("lo_discount", DataType.INT),
        metric("lo_revenue", DataType.DOUBLE),
    ])


def make_columns(n: int):
    rng = np.random.default_rng(20260729)
    regions = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
    region_ids = rng.integers(0, 5, n)
    return {
        "lo_region": np.array(regions, dtype=object)[region_ids],
        "lo_suppkey": rng.integers(0, HIGH_CARD_SUPPKEYS, n).astype(np.int32),
        "lo_custkey": rng.integers(0, VERY_HIGH_CARD_KEYS, n).astype(np.int32),
        "lo_orderdate": (19920101 + rng.integers(0, 7, n) * 10000
                         + rng.integers(1, 13, n) * 100
                         + rng.integers(1, 29, n)).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
        "lo_extendedprice": np.round(rng.uniform(1.0, 10_000.0, n), 2).astype(np.float64),
        "lo_discount": rng.integers(0, 11, n).astype(np.int32),
        "lo_revenue": np.round(rng.uniform(1.0, 60_000.0, n), 2).astype(np.float64),
    }


def build_or_load_segments(schema, cols, star_tree=False, rows=None, tag=None,
                           star_hc=False):
    from pinot_tpu.segment import (SegmentGeneratorConfig, StarTreeIndexConfig,
                                   load_segment)
    from pinot_tpu.segment.writer import build_aligned_segments
    rows = rows if rows is not None else ROWS
    tag = tag or (f"r{rows}_s{SEGMENTS}_v3"
                  f"{'_st' if star_tree else ''}{'_sthc' if star_hc else ''}")
    seg_root = os.path.join(CACHE, tag)
    marker = os.path.join(seg_root, "DONE")
    if not os.path.exists(marker):
        os.makedirs(seg_root, exist_ok=True)
        config = None
        if star_tree:
            config = SegmentGeneratorConfig(star_tree_configs=[
                StarTreeIndexConfig(
                    dimensions_split_order=["lo_region", "lo_discount"],
                    function_column_pairs=["SUM__lo_revenue"])])
        elif star_hc:
            # high-cardinality split dims -> 1e5+ combined records: the
            # stacked DEVICE star path (small trees keep the host path)
            config = SegmentGeneratorConfig(star_tree_configs=[
                StarTreeIndexConfig(
                    dimensions_split_order=["lo_orderdate", "lo_discount"],
                    function_column_pairs=["SUM__lo_revenue"])])
        build_aligned_segments(schema, cols, seg_root, "lineorder", SEGMENTS,
                               config=config)
        with open(marker, "w") as f:
            f.write("ok")
    names = sorted(d for d in os.listdir(seg_root) if d.startswith("lineorder_"))
    return [load_segment(os.path.join(seg_root, d)) for d in names]


def _vhc_sweep_segments(card: int, rows: int):
    """Dedicated two-column [k, v] sets per sweep cardinality (cached)."""
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import load_segment
    from pinot_tpu.segment.writer import (SegmentGeneratorConfig,
                                          build_aligned_segments)
    schema = Schema("vhsweep", [dimension("k", DataType.INT),
                                metric("v", DataType.DOUBLE)])
    seg_root = os.path.join(CACHE, f"vhc{card}_r{rows}_s{SEGMENTS}_v2")
    marker = os.path.join(seg_root, "DONE")
    if not os.path.exists(marker):
        os.makedirs(seg_root, exist_ok=True)
        rng = np.random.default_rng(card)
        # one full pass of every key, the rest random repeats: the sweep's
        # group count IS its nominal cardinality, not a random-draw fraction
        base = min(card, rows)
        k = np.concatenate([np.arange(base, dtype=np.int64),
                            rng.integers(0, base, rows - base)])
        rng.shuffle(k)
        cols = {"k": k.astype(np.int32),
                "v": np.round(rng.uniform(1.0, 60_000.0, rows), 2)}
        # dict-encode k even at cardinality ~ rows: raw columns would demote
        # the whole sweep to the host path
        cfg = SegmentGeneratorConfig(raw_cardinality_fraction=4.0)
        build_aligned_segments(schema, cols, seg_root, "vhsweep", SEGMENTS,
                               config=cfg)
        with open(marker, "w") as f:
            f.write("ok")
    names = sorted(d for d in os.listdir(seg_root) if d.startswith("vhsweep_"))
    return schema, [load_segment(os.path.join(seg_root, d)) for d in names]


def very_high_card_sweep(mesh_exec, n_dev: int) -> dict:
    """Per-regime rows/s at 128k/500k/2M groups: every high-card regime forced
    in turn (set_caps recompiles), plus the rate the CALIBRATED default caps
    actually dispatch ("auto"). The regime ladder's measured crossover story."""
    from pinot_tpu.engine.calibrate import KernelCaps, get_caps, set_caps
    rows = min(ROWS, 4 * 1024 * 1024)
    prev = get_caps()
    sweep = {}
    try:
        for card in VHC_SWEEP_CARDS:
            schema, segs = _vhc_sweep_segments(card, rows)
            sql = (f"SELECT k, SUM(v), COUNT(*) FROM vhsweep GROUP BY k "
                   f"LIMIT {3 * card}")
            entry = {}
            for regime in ("partitioned", "sorted", "scatter"):
                # chunk_cap floored so EVERY sweep size dispatches through the
                # regime under test rather than the chunked matmul
                set_caps(KernelCaps(matmul_cap=prev.matmul_cap, chunk_cap=4096,
                                    minmax_bcast_cap=prev.minmax_bcast_cap,
                                    high_card_regime=regime,
                                    partition_block=prev.partition_block))
                mesh_exec.execute(segs, sql)  # compile + transfer warmup
                t0 = time.perf_counter()
                mesh_exec.execute_many(segs, [sql] * VHC_SWEEP_ITERS)
                dt = time.perf_counter() - t0
                entry[f"{regime}_rows_per_sec"] = round(
                    rows * VHC_SWEEP_ITERS / dt / n_dev, 1)
            set_caps(prev)
            mesh_exec.execute(segs, sql)
            t0 = time.perf_counter()
            results = mesh_exec.execute_many(segs, [sql] * VHC_SWEEP_ITERS)
            dt = time.perf_counter() - t0
            entry["auto_rows_per_sec"] = round(
                rows * VHC_SWEEP_ITERS / dt / n_dev, 1)
            entry["auto_regime"] = ("chunk" if card <= prev.chunk_cap
                                    else prev.high_card_regime)
            entry["groups"] = len(results[-1].rows)
            sweep[str(card)] = entry
    finally:
        set_caps(prev)
    return sweep


def numpy_baseline(cols, iters=3) -> float:
    """Single-thread vectorized scan of the same query (Java-engine stand-in)."""
    od, disc, qty = cols["lo_orderdate"], cols["lo_discount"], cols["lo_quantity"]
    price = cols["lo_extendedprice"]

    def run():
        mask = ((od >= 19930101) & (od <= 19931231)
                & (disc >= 1) & (disc <= 3) & (qty < 25))
        return float(np.sum(price[mask] * disc[mask]))

    run()  # warm caches
    t0 = time.perf_counter()
    for _ in range(iters):
        result = run()
    dt = (time.perf_counter() - t0) / iters
    return len(od) / dt, result


def _ingest_topic(rows: int, partitions: int = 1):
    """Produce `rows` JSON events per partition into a fresh log broker."""
    import json as _json

    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer

    rng = np.random.default_rng(7)
    raws = [{"site": f"s{int(i) % 50}.com", "clicks": int(c), "cost": float(x),
             "ts": 1700000000000 + j}
            for j, (i, c, x) in enumerate(zip(
                rng.integers(0, 50, rows), rng.integers(1, 9, rows),
                np.round(rng.uniform(0.1, 9.9, rows), 3)))]
    srv = LogBrokerServer()
    client = LogBrokerClient(srv.bootstrap)
    client.create_topic("bench_ingest", partitions)
    payloads = [_json.dumps(r) for r in raws]
    for part in range(partitions):
        for lo in range(0, rows, 500):
            client.produce_many("bench_ingest", payloads[lo:lo + 500],
                                partition=part)
    return srv, raws


def _ingest_schema():
    from pinot_tpu.schema import (DataType, Schema, date_time, dimension,
                                  metric)
    return Schema("events", [
        dimension("site", DataType.STRING), metric("clicks", DataType.LONG),
        metric("cost", DataType.DOUBLE), date_time("ts", DataType.LONG)])


def _consume_partition(bootstrap: str, partition: int, rows: int):
    """Consume one partition through the SAME decode strategy the realtime
    pump takes (kafkalite fetch_spliced -> native columnar decode ->
    index_batch; ingest/realtime.py path 0). Returns (rows, clicks_sum)."""
    from pinot_tpu.ingest.kafkalite import KafkaLiteConsumer
    from pinot_tpu.ingest.transform import columns_from_spliced_json
    from pinot_tpu.segment.mutable import MutableSegment

    schema = _ingest_schema()
    consumer = KafkaLiteConsumer(bootstrap, "bench_ingest", partition)
    seg = MutableSegment(f"events__{partition}__0__b", schema)
    off = 0
    while off < rows:
        out = consumer.fetch_spliced(off, 16384)
        if out is None:   # no C compiler on this host: pure-Python path
            import json as _json
            batch = consumer.fetch(off, 16384)
            decoded = [_json.loads(m.value) for m in batch.messages]
            from pinot_tpu.ingest.transform import (TransformPipeline,
                                                    rows_to_all_columns)
            seg.index_batch(TransformPipeline(schema).apply(
                rows_to_all_columns(decoded)), coerced=True)
            off = batch.next_offset
            continue
        data, n, off = out
        if n:
            cols = columns_from_spliced_json(data, n, schema)
            if cols is None:
                import json as _json
                from pinot_tpu.ingest.transform import (TransformPipeline,
                                                        rows_to_all_columns)
                decoded = _json.loads(b"[" + data + b"]")
                cols = TransformPipeline(schema).apply(
                    rows_to_all_columns(decoded))
            seg.index_batch(cols, coerced=True)
    consumer.close()
    return seg.num_docs, int(sum(seg.columns["clicks"][:seg.num_docs]))


def _ingest_topic_blocks(rows: int, partitions: int = 1, block: int = 16384):
    """Produce `rows` rows per partition as PCB1 columnar blocks (the
    vectorized ingest plane's wire format, ingest/vectorized.py) into a
    fresh log broker. Same value distribution as `_ingest_topic` so the
    lanes are comparable. Returns (server, expected clicks sum)."""
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
    from pinot_tpu.ingest.vectorized import encode_columnar_block

    schema = _ingest_schema()
    rng = np.random.default_rng(7)
    site_ids = rng.integers(0, 50, rows)
    clicks = rng.integers(1, 9, rows).astype(np.int64)
    cost = np.round(rng.uniform(0.1, 9.9, rows), 3)
    ts = 1700000000000 + np.arange(rows, dtype=np.int64)
    site_pool = [f"s{i}.com" for i in range(50)]
    sites = [site_pool[i] for i in site_ids]
    payloads = []
    for lo in range(0, rows, block):
        hi = min(lo + block, rows)
        payloads.append(encode_columnar_block(schema, {
            "site": sites[lo:hi], "clicks": clicks[lo:hi],
            "cost": cost[lo:hi], "ts": ts[lo:hi]}))
    srv = LogBrokerServer()
    client = LogBrokerClient(srv.bootstrap)
    client.create_topic("bench_blocks", partitions)
    for part in range(partitions):
        for lo in range(0, len(payloads), 64):
            client.produce_many("bench_blocks", payloads[lo:lo + 64],
                                partition=part)
    return srv, int(clicks.sum())


def _consume_partition_vectorized(bootstrap: str, partition: int, rows: int):
    """Consume one partition of PCB1 blocks through the SAME decode path the
    realtime pump takes for block streams (kafkalite fetch_spliced with the
    block separator -> decode_columnar_blocks -> DeviceMutableSegment
    .index_arrays; ingest/realtime.py path -1). Returns (rows, clicks_sum)."""
    from pinot_tpu.ingest.kafkalite import KafkaLiteConsumer
    from pinot_tpu.ingest.vectorized import (BLOCK_SEP, decode_columnar_block,
                                             decode_columnar_blocks)
    from pinot_tpu.segment.mutable_device import DeviceMutableSegment

    schema = _ingest_schema()
    consumer = KafkaLiteConsumer(bootstrap, "bench_blocks", partition)
    seg = DeviceMutableSegment(f"events__{partition}__0__b", schema)
    off = 0
    while seg.num_docs < rows:
        out = consumer.fetch_spliced(off, 64, sep=BLOCK_SEP)
        if out is None:   # no C splicer on this host: per-message decode
            batch = consumer.fetch_raw(off, 64)
            values, off = batch
            if not values:
                break
            for v in values:
                seg.index_arrays(decode_columnar_block(
                    v if isinstance(v, bytes) else bytes(v)))
            continue
        data, n, off = out
        if not n:
            break
        for cb in decode_columnar_blocks(data, n):
            seg.index_arrays(cb)
    consumer.close()
    clicks = int(np.asarray(seg.column("clicks").fwd).sum())
    return seg.num_docs, clicks


def ingest_vectorized_bench(rows: int = 400_000):
    """Vectorized consumption speed, single partition: PCB1 columnar blocks
    through the native splice -> decode_columnar_blocks ->
    DeviceMutableSegment.index_arrays — the device ingest plane's hot lane.
    Correctness is pinned against the topic's known clicks aggregate."""
    srv, want_clicks = _ingest_topic_blocks(rows)
    try:
        dts = []
        for _ in range(2):
            t0 = time.perf_counter()
            n, clicks = _consume_partition_vectorized(srv.bootstrap, 0, rows)
            elapsed = time.perf_counter() - t0
            if n != rows or clicks != want_clicks:
                print(f"WARNING: vectorized ingest mismatch {n}/{rows} "
                      f"clicks {clicks} vs {want_clicks}", file=sys.stderr)
            else:
                dts.append(elapsed)
        dt = min(dts) if dts else float("inf")
    finally:
        srv.stop()
    return rows / dt


def ingest_multi_bench(partitions: int = 8, rows: int = 100_000):
    """AGGREGATE vectorized consume rate over `partitions` partitions driven
    by independent threaded pump lanes against one broker — the topology
    `RealtimeTableManager.pump_all` runs (one lane per consumer, no shared
    lock). Returns total rows/s across lanes; each lane's row count and
    clicks aggregate is verified against the produced topic."""
    from concurrent.futures import ThreadPoolExecutor

    srv, want_clicks = _ingest_topic_blocks(rows, partitions)
    best = 0.0
    try:
        # best-of-2, like the single-partition lanes: thread scheduling on
        # the shared 1-core host adds strictly positive noise
        for _ in range(2):
            with ThreadPoolExecutor(max_workers=partitions) as pool:
                t0 = time.perf_counter()
                futs = [pool.submit(_consume_partition_vectorized,
                                    srv.bootstrap, p, rows)
                        for p in range(partitions)]
                results = [f.result(timeout=600) for f in futs]
                dt = time.perf_counter() - t0
            ok = True
            for p, (n, clicks) in enumerate(results):
                if n != rows or clicks != want_clicks:
                    ok = False
                    print(f"WARNING: multi-ingest mismatch partition {p}: "
                          f"{n}/{rows} clicks {clicks}", file=sys.stderr)
            if ok:   # an invalid run must not win the best-of
                best = max(best, sum(n for n, _ in results) / dt)
    finally:
        srv.stop()
    return best


def ingest_bench(rows: int = 400_000):
    """Realtime consumption speed, single partition: kafkalite BINARY frames
    through the native splice + columnar-JSON decode into
    MutableSegment.index_batch — the realtime pump's fastest decode path
    (ingest/realtime.py path 0) — vs a vectorized numpy column-append of the
    same rows (reference: pinot-perf BenchmarkRealtimeConsumptionSpeed.java)."""
    srv, raws = _ingest_topic(rows)
    try:
        # best-of-2 (noise on the shared 1-core host is strictly additive;
        # the numpy denominator below gets the same best-of treatment)
        dts = []
        want_clicks = sum(r["clicks"] for r in raws)
        for _ in range(2):
            t0 = time.perf_counter()
            n, clicks = _consume_partition(srv.bootstrap, 0, rows)
            elapsed = time.perf_counter() - t0
            if n != rows or clicks != want_clicks:
                # an invalid run must not win the best-of
                print(f"WARNING: ingest mismatch {n}/{rows} clicks {clicks}",
                      file=sys.stderr)
            else:
                dts.append(elapsed)
        dt = min(dts) if dts else float("inf")
    finally:
        srv.stop()
    # numpy append baseline: same rows into plain column arrays, no indexes
    # (best of 3 — the pure-Python loop's rate swings ~50% run to run; both
    # sides of the ratio get the best-of treatment)
    np_dts = []
    for _ in range(3):
        t0 = time.perf_counter()
        cols = {k: [] for k in ("site", "clicks", "cost", "ts")}
        for r in raws:
            for k in cols:
                cols[k].append(r[k])
        _ = {k: np.asarray(v) for k, v in cols.items()}
        np_dts.append(time.perf_counter() - t0)
    np_dt = float(np.min(np_dts))
    return rows / dt, rows / np_dt


def e2e_bench(n_clients: int = 8, queries_per_client: int = 25,
              rows: int = 100_000, num_servers: int = 2,
              measure_sampled: bool = False):
    """End-to-end QPS/p50 through a REAL ProcessCluster broker over HTTP —
    wire encode/decode, scheduler, scatter/gather included (reference:
    README.md:56 'tens of thousands of queries per second'). Server processes
    run the CPU engine — the head-to-head partner for `e2e_device_bench`
    on the same data.

    With `measure_sampled` the same client loop runs a second time with
    `broker.trace.sample.rate=0.01` so BENCH json carries the tracing
    overhead head-to-head (acceptance: < 2% qps regression); returns
    (qps, p50_ms, qps_sampled) then, (qps, p50_ms) otherwise."""
    import tempfile
    import threading

    from pinot_tpu.cluster.http_service import get_json, post_json
    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.segment.writer import SegmentBuilder
    from pinot_tpu.table import TableConfig

    schema = ssb_schema()
    n = rows
    cols = make_columns(n)
    work = tempfile.mkdtemp(prefix="pinot_bench_e2e_")
    sqls = [QUERY, GROUP_QUERY,
            "SELECT COUNT(*) FROM lineorder WHERE lo_quantity < 10 LIMIT 5"]
    with ProcessCluster(num_servers=num_servers, work_dir=work) as cluster:
        cluster.controller.add_schema(schema)
        cfg = TableConfig("lineorder")
        cluster.controller.add_table(cfg)
        b = SegmentBuilder(schema)
        for i in range(4):
            part = {k: v[i * n // 4:(i + 1) * n // 4] for k, v in cols.items()}
            cluster.controller.upload_segment(
                cfg.table_name_with_type,
                b.build(part, os.path.join(work, "b"), f"lineorder_{i}"))
        deadline = time.time() + 60
        loaded = 0
        while time.time() < deadline:
            r = cluster.query("SELECT COUNT(*) FROM lineorder")[
                "resultTable"]["rows"]
            loaded = r[0][0] if r else 0
            if loaded == n:
                break
            time.sleep(0.2)
        if loaded != n:
            print(f"WARNING: e2e bench started with {loaded}/{n} rows loaded "
                  f"— qps/p50 measured over PARTIAL data", file=sys.stderr)
        for q in sqls:     # warm every shape through every server
            cluster.query(q)
        lock = threading.Lock()

        def run_clients():
            lat: list = []

            def client(ci: int) -> None:
                mine = []
                for qi in range(queries_per_client):
                    q = sqls[(ci + qi) % len(sqls)]
                    t0 = time.perf_counter()
                    cluster.query(q)
                    mine.append(time.perf_counter() - t0)
                with lock:
                    lat.extend(mine)

            threads = [threading.Thread(target=client, args=(ci,))
                       for ci in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            return (n_clients * queries_per_client) / dt, \
                float(np.median(lat)) * 1000

        qps, p50 = run_clients()
        if not measure_sampled:
            return qps, p50
        # second pass with head sampling on: the broker's RemoteCatalog
        # mirror picks the property up via its watch loop — wait until the
        # broker's /debug reflects the new rate before re-measuring
        post_json(f"{cluster.controller_url}/catalog/property",
                  {"key": "clusterConfig/broker.trace.sample.rate",
                   "value": "0.01"})
        deadline = time.time() + 30
        while time.time() < deadline:
            ring = get_json(f"{cluster.broker_url}/debug").get(
                "traceRing") or {}
            if ring.get("sampleRate") == 0.01:
                break
            time.sleep(0.2)
        else:
            print("WARNING: broker never saw broker.trace.sample.rate=0.01 — "
                  "sampled e2e pass measures the UNSAMPLED path",
                  file=sys.stderr)
        qps_sampled, _ = run_clients()
    return qps, p50, qps_sampled


def e2e_device_bench(rows: int, n_clients: int = 32,
                     queries_per_client: int = 12):
    """End-to-end QPS/p50 with the TPU INSIDE the server role (VERDICT r4
    #1): controller + broker run as REAL OS processes; the server runs in
    THIS process because it owns the device (the one-device-owning-process
    topology), serving broker-routed HTTP queries through the
    DeviceQueryPipeline — concurrent queries batch into shared device
    fetches (cluster/device_server.py). Returns (qps, p50_ms, pipeline
    stats, loaded_rows)."""
    import tempfile
    import threading

    from pinot_tpu.cluster.device_server import DeviceQueryPipeline
    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.cluster.remote import (ControllerDeepStore, RemoteCatalog,
                                          RemoteCompletion)
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import ServerService
    from pinot_tpu.segment.writer import SegmentBuilder
    from pinot_tpu.table import TableConfig

    schema = ssb_schema()
    cols = make_columns(rows)
    work = tempfile.mkdtemp(prefix="pinot_bench_e2edev_")
    sqls = [QUERY, GROUP_QUERY,
            "SELECT COUNT(*) FROM lineorder WHERE lo_quantity < 10 LIMIT 5"]
    with ProcessCluster(num_servers=0, work_dir=work) as cluster:
        catalog = RemoteCatalog(cluster.controller_url)
        pipeline = DeviceQueryPipeline()
        server = ServerNode("server_device_0", catalog,
                            ControllerDeepStore(cluster.controller_url),
                            os.path.join(work, "server_device_0"),
                            completion=RemoteCompletion(cluster.controller_url),
                            device_pipeline=pipeline)
        svc = ServerService(server)
        try:
            cluster.controller.add_schema(schema)
            cfg = TableConfig("lineorder")
            cluster.controller.add_table(cfg)
            b = SegmentBuilder(schema)
            n_segs = 4
            for i in range(n_segs):
                part = {k: v[i * rows // n_segs:(i + 1) * rows // n_segs]
                        for k, v in cols.items()}
                cluster.controller.upload_segment(
                    cfg.table_name_with_type,
                    b.build(part, os.path.join(work, "b"), f"lineorder_{i}"))
            deadline = time.time() + 420
            loaded = 0
            while time.time() < deadline:
                r = cluster.query("SELECT COUNT(*) FROM lineorder")[
                    "resultTable"]["rows"]
                loaded = r[0][0] if r else 0
                if loaded == rows:
                    break
                time.sleep(0.2)
            if loaded != rows:
                print(f"WARNING: device e2e started with {loaded}/{rows} "
                      f"rows loaded — results below are INVALID",
                      file=sys.stderr)
            for q in sqls:   # warm every kernel shape
                cluster.query(q)
                cluster.query(q)
            # single-client p50: one query in flight -> no batch-wait, the
            # relay round trip + kernel + HTTP hops (the latency floor of
            # the served device path, vs QPS under concurrency below)
            solo = []
            for qi in range(9):
                t0 = time.perf_counter()
                cluster.query(sqls[qi % len(sqls)])
                solo.append(time.perf_counter() - t0)
            solo_p50 = float(np.median(solo)) * 1000
            lat: list = []
            lock = threading.Lock()

            def client(ci: int) -> None:
                mine = []
                for qi in range(queries_per_client):
                    q = sqls[(ci + qi) % len(sqls)]
                    t0 = time.perf_counter()
                    cluster.query(q)
                    mine.append(time.perf_counter() - t0)
                with lock:
                    lat.extend(mine)

            threads = [threading.Thread(target=client, args=(ci,))
                       for ci in range(n_clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            stats = pipeline.stats()
            stats["soloP50Ms"] = round(solo_p50, 3)
        finally:
            svc.stop()
            server.shutdown()
            catalog.close()
    return (n_clients * queries_per_client) / dt, \
        float(np.median(lat)) * 1000, stats, loaded


def wire_codec_bench(n: int = 4_000_000, iters: int = 5) -> dict:
    """Wire-codec throughput (satellite of the zero-copy mux transport):
    encode/decode GB/s over (a) a flat typed-array payload and (b) a
    DensePartial-shaped SegmentResult — the shapes the data plane actually
    ships. The gathered-parts encode and the `np.frombuffer` decode must
    show up as *bandwidth* in the perf trajectory, not just as an absence
    of copies in a unit test."""
    from pinot_tpu.cluster.wire import (decode_segment_result, decode_value,
                                        encode_segment_result_parts,
                                        encode_value)
    from pinot_tpu.query.reduce import DensePartial, SegmentResult

    def _timed(fn):
        best = float("inf")
        for _ in range(iters):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    arr = {"v": np.arange(n, dtype=np.float64),
           "c": np.arange(n, dtype=np.int64)}
    nbytes = sum(a.nbytes for a in arr.values())
    enc = encode_value(arr)
    t_enc = _timed(lambda: encode_value(arr))
    t_dec = _timed(lambda: decode_value(enc))

    keys = max(n // 8, 1)
    dp = DensePartial(
        token=("k", (keys,), ("h",), keys), cards=(keys,), strides=(1,),
        num_keys_real=keys, counts=np.ones(keys, dtype=np.int64),
        outs={"0.sum": np.arange(keys, dtype=np.float64)},
        group_values=[np.arange(keys, dtype=np.int64)])
    sr = SegmentResult(kind="groups", dense=dp, num_docs_scanned=n)
    dp_bytes = dp.counts.nbytes + dp.outs["0.sum"].nbytes \
        + dp.group_values[0].nbytes
    sr_enc = b"".join(bytes(p) for p in encode_segment_result_parts(sr))
    t_sr_enc = _timed(lambda: encode_segment_result_parts(sr))
    t_sr_dec = _timed(lambda: decode_segment_result(sr_enc))
    return {
        "wire_encode_gbps": round(nbytes / max(t_enc, 1e-9) * 1e-9, 2),
        "wire_decode_gbps": round(nbytes / max(t_dec, 1e-9) * 1e-9, 2),
        "wire_dense_partial_encode_gbps": round(
            dp_bytes / max(t_sr_enc, 1e-9) * 1e-9, 2),
        "wire_dense_partial_decode_gbps": round(
            dp_bytes / max(t_sr_dec, 1e-9) * 1e-9, 2),
    }


def chaos_bench() -> dict:
    """Chaos lane (host-only, in-proc dual-server cluster):

    1. `fault_plane_overhead_pct` — what the DISABLED graftfault plane costs
       a query: the measured per-crossing price of `fault_point` (one module
       global load + None check) times a generous 8-crossings-per-query
       bound, as a percentage of the measured in-proc query p50. Gate: <1%.
    2. `chaos_recovery_ticks` — kill a server, revive it, count the
       deterministic failure-detector ticks until routing re-admits it.
    3. `chaos_hedge_*_p99_ms` — p99 under a seeded `server.slow` straggler
       schedule with hedging off vs on: the hedge must measurably cut p99,
       and every hedged answer must stay full (numSegmentsQueried counted
       once, partialResult false).
    """
    import shutil
    import tempfile

    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.schema import DataType, Schema, dimension
    from pinot_tpu.schema import metric as smetric
    from pinot_tpu.table import TableConfig
    from pinot_tpu.utils import faults

    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        faults.fault_point("server.crash")
    per_call_s = (time.perf_counter() - t0) / n

    work = tempfile.mkdtemp(prefix="pinot_tpu_chaos_")
    try:
        cluster = QuickCluster(num_servers=2, work_dir=work)
        schema = Schema("chaosm", [dimension("user", DataType.STRING),
                                   smetric("value", DataType.DOUBLE)])
        cfg = cluster.create_table(schema,
                                   TableConfig("chaosm", replication=2))
        cluster.ingest_columns(cfg,
                               {"user": [f"u{i}" for i in range(20_000)],
                                "value": [1.0] * 20_000})
        sql = "SELECT COUNT(*), SUM(value) FROM chaosm"
        for _ in range(3):
            cluster.query(sql)
        lats = []
        for _ in range(15):
            q0 = time.perf_counter()
            cluster.query(sql)
            lats.append(time.perf_counter() - q0)
        p50_s = float(np.median(lats))
        overhead_pct = 100.0 * (8 * per_call_s) / p50_s

        detector = cluster.broker.failure_detector
        for s in cluster.servers:
            detector.register_probe(
                s.instance_id,
                lambda sid=s.instance_id:
                    cluster.catalog.instances[sid].alive)
        cluster.kill_server("server_0")
        detector.notify_unhealthy("server_0")
        now = time.time()
        for _ in range(3):      # stays dead: backoff grows, probes fail
            now += 40.0         # > max_interval_s, so every tick is due
            detector.tick(now=now)
        cluster.catalog.set_instance_alive("server_0", True)
        recovery_ticks = 0
        for _ in range(8):
            now += 40.0
            recovery_ticks += 1
            detector.tick(now=now)
            if "server_0" not in cluster.broker.routing.unhealthy_servers():
                break

        def slow_p99(hedge: bool, iters=15) -> float:
            if hedge:
                cluster.catalog.put_property(
                    "clusterConfig/broker.hedge.enabled", "true")
                cluster.catalog.put_property(
                    "clusterConfig/broker.hedge.delay.ms", "5")
            else:
                cluster.catalog.put_property(
                    "clusterConfig/broker.hedge.enabled", None)
            lat = []
            for i in range(iters):
                # budget of ONE stall per query: the primary dispatch eats
                # it deterministically, so a hedge (when enabled) always
                # races a fast replica — same straggler load both modes
                sched = faults.FaultSchedule(
                    {"server.slow": {"latencyMs": 40, "count": 1}},
                    seed=100 + i)
                with faults.active(sched):
                    q0 = time.perf_counter()
                    r = cluster.query(sql)
                    lat.append((time.perf_counter() - q0) * 1000)
                # hedged or not, the answer must stay full and count each
                # segment exactly once
                assert not r.stats["partialResult"]
                assert r.rows[0][0] == 20_000
                assert r.stats["numSegmentsQueried"] == 1
            lat.sort()
            return lat[int(0.99 * (len(lat) - 1))]

        p99_off = slow_p99(hedge=False)
        p99_on = slow_p99(hedge=True)
        return {
            "fault_point_ns_disabled": round(per_call_s * 1e9, 1),
            "fault_plane_overhead_pct": round(overhead_pct, 4),
            "chaos_recovery_ticks": recovery_ticks,
            "chaos_hedge_off_p99_ms": round(p99_off, 3),
            "chaos_hedge_on_p99_ms": round(p99_on, 3),
            "chaos_hedge_p99_cut_pct": round(
                (1.0 - p99_on / p99_off) * 100.0, 1) if p99_off else None,
        }
    finally:
        faults.deactivate()
        shutil.rmtree(work, ignore_errors=True)


def pruning_bench() -> dict:
    """Pruning + bitmap-index lane (PR 12):

    1. Routing scale — synthesized SegmentMeta (columnStats only, no real
       segments) at 100 / 1k / 10k segments; a fixed selective range filter
       must touch a near-constant handful of segments while the table
       grows, so the prune RATE climbs monotonically with scale. Floors:
       `prune_rate_10k` ≥ 50x (acceptance), rate monotone in segment count.
    2. Real mini-cluster — per-pruner-kind breakdown + `scan_rows_avoided_pct`
       through the in-proc broker (the same counters EXPLAIN ANALYZE renders).
    3. Bitmap vs gather — effective filter rows/s of the same COUNT-shaped
       predicate pinned to the packed-word path (`compute_filter_count`:
       k-row OR-fold + popcount, O(k * docs/32)) vs the LUT-gather mask scan
       (`compute_mask` + sum, O(docs)), swept over predicate selectivity;
       both arms are answer-checked against each other. Publishes the
       measured `bitmap_vs_gather_crossover_sel` (highest swept selectivity
       where the bitmap path still wins). Floor: bitmap wins on the most
       selective predicate.
    """
    import shutil
    import tempfile

    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.cluster.catalog import (COLUMN_STATS_KEY, ONLINE, Catalog,
                                           InstanceInfo, SegmentMeta)
    from pinot_tpu.cluster.routing import PRUNE_ROWS_AVOIDED, RoutingManager
    from pinot_tpu.engine import kernels
    from pinot_tpu.engine.datablock import block_for
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.query.predicate import LutLeaf
    from pinot_tpu.schema import DataType, Schema, dimension
    from pinot_tpu.schema import metric as smetric
    from pinot_tpu.table import TableConfig

    out: dict = {}

    # -- 1. routing scale on synthesized metadata ---------------------------
    # segment i holds v in [i*100, i*100+99]; the window below overlaps
    # exactly 3 segments at EVERY scale, so segments-touched stays flat
    # while the table grows 100x
    rows_per_seg = 1000
    ctx = compile_query("SELECT COUNT(*) FROM pscale "
                        "WHERE v BETWEEN 1000 AND 1299")
    scales = (100, 1000, 10_000)
    touched: dict = {}
    rates: dict = {}
    for count in scales:
        catalog = Catalog()
        cfg = TableConfig("pscale")
        catalog.put_table_config(cfg)
        table = cfg.table_name_with_type
        catalog.register_instance(InstanceInfo("server_0", "server"))
        for i in range(count):
            seg = f"pscale_{i}"
            meta = SegmentMeta(seg, table, num_docs=rows_per_seg)
            meta.custom[COLUMN_STATS_KEY] = {
                "v": {"min": i * 100, "max": i * 100 + 99}}
            catalog.put_segment_meta(meta)
            catalog.external_view.setdefault(table, {})[seg] = {
                "server_0": ONLINE}
        rm = RoutingManager(catalog)
        lats = []
        prune_stats: dict = {}
        routing: dict = {}
        for _ in range(7):
            prune_stats = {}
            q0 = time.perf_counter()
            routing = rm.route_query(table, ctx, prune_stats=prune_stats)
            lats.append((time.perf_counter() - q0) * 1000)
        segs = sum(len(v) for v in routing.values())
        assert segs > 0, "selective window routed zero segments"
        pruned = sum(prune_stats.get(k, 0)
                     for k in prune_stats if k != PRUNE_ROWS_AVOIDED)
        assert segs + pruned == count, (segs, pruned, count)
        lats.sort()
        touched[count] = segs
        rates[count] = count / segs
        tag = f"{count // 1000}k" if count >= 1000 else str(count)
        out[f"prune_segments_touched_{tag}"] = segs
        out[f"prune_route_p50_ms_{tag}"] = round(lats[len(lats) // 2], 3)
    # monotone scaling: the prune rate must IMPROVE with segment count —
    # touched stays flat while the table grows, or pruning isn't metadata-
    # bounded and the 10k floor is luck
    assert rates[100] <= rates[1000] <= rates[10_000], rates
    out["prune_rate_10k"] = round(rates[10_000], 1)
    assert out["prune_rate_10k"] >= 50, out["prune_rate_10k"]

    # -- 2. per-kind breakdown through the real in-proc broker --------------
    work = tempfile.mkdtemp(prefix="pinot_tpu_prune_")
    try:
        cluster = QuickCluster(num_servers=2, work_dir=work)
        schema = Schema("pev", [dimension("site", DataType.STRING),
                                smetric("v", DataType.LONG)])
        cfg = cluster.create_table(schema, TableConfig("pev", replication=1))
        n_segs, n_rows = 8, 5000
        sites = ["a", "b", "c", "d"]
        for i in range(n_segs):
            cluster.ingest_columns(cfg, {
                "site": np.array(sites).repeat(n_rows // len(sites)),
                "v": np.arange(i * n_rows, (i + 1) * n_rows, dtype=np.int64),
            })
        total = n_segs * n_rows
        res = cluster.query(
            f"SELECT COUNT(*) FROM pev WHERE v >= {(n_segs - 1) * n_rows}")
        assert res.rows[0][0] == n_rows
        assert res.stats["numSegmentsPrunedByRange"] == n_segs - 1
        miss = cluster.query("SELECT COUNT(*) FROM pev WHERE site = 'bb'")
        assert miss.rows[0][0] == 0
        assert miss.stats["numSegmentsPrunedByBloom"] == n_segs
        out["prune_by_kind_range"] = res.stats["numSegmentsPrunedByRange"]
        out["prune_by_kind_bloom"] = miss.stats["numSegmentsPrunedByBloom"]
        out["scan_rows_avoided_pct"] = round(
            res.stats["scanRowsAvoided"] / total * 100.0, 1)
        assert out["scan_rows_avoided_pct"] >= 50.0, out
    finally:
        shutil.rmtree(work, ignore_errors=True)

    # -- 3. bitmap vs LUT-gather rows/s by selectivity ----------------------
    from pinot_tpu.segment.reader import load_segment
    from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

    card, n = 64, 1 << 19
    bschema = Schema("bmsweep", [dimension("g"),
                                 smetric("v", DataType.LONG)])
    rng = np.random.default_rng(12)
    gvals = [f"g{i:02d}" for i in range(card)]
    work = tempfile.mkdtemp(prefix="pinot_tpu_bmsweep_")
    try:
        seg = load_segment(SegmentBuilder(bschema, SegmentGeneratorConfig())
                           .build({"g": [gvals[i] for i in
                                         rng.integers(0, card, n)],
                                   "v": np.arange(n, dtype=np.int64)},
                                  work, "bmsweep_0"))
        block = block_for(seg)
        ex = ServerQueryExecutor()
        iters = 10
        sweep = []
        crossover = None
        for k in (1, 2, 4, 8, 16, 32, 48):
            sel = k / card
            inlist = ", ".join(f"'{v}'" for v in gvals[:k])
            sctx = compile_query(
                f"SELECT COUNT(*) FROM bmsweep WHERE g IN ({inlist})", bschema)
            from pinot_tpu.query.planner import plan_segment
            plan = plan_segment(sctx, seg)
            bm = tuple(i for i, leaf in enumerate(plan.filter_prog.leaves)
                       if isinstance(leaf, LutLeaf)
                       and block.bitmap_words(leaf.col) is not None)
            assert bm, "sweep predicate must be bitmap-eligible"
            rates_rs = {}
            answers = {}
            for path, leaves in (("bitmap", bm), ("gather", ())):
                plan.bitmap_leaves = leaves
                spec = kernels.KernelSpec(plan.filter_prog, (), 1, (), {},
                                          block.padded, bitmap_leaves=leaves)
                inputs = ex._kernel_inputs(plan, spec, block)
                if path == "bitmap":
                    def consume(s=spec, i=inputs):
                        return int(kernels.compute_filter_count(s, i))
                else:
                    def consume(s=spec, i=inputs):
                        return int(np.asarray(
                            kernels.compute_mask(s, i)).sum())
                answers[path] = consume()                   # warm compile
                q0 = time.perf_counter()
                for _ in range(iters):
                    consume()
                rates_rs[path] = n * iters / (time.perf_counter() - q0)
            assert answers["bitmap"] == answers["gather"], answers
            sweep.append({"selectivity": round(sel, 4),
                          "bitmap_rows_per_sec": round(rates_rs["bitmap"], 1),
                          "gather_rows_per_sec": round(rates_rs["gather"], 1)})
            if rates_rs["bitmap"] > rates_rs["gather"]:
                crossover = sel
        assert sweep[0]["bitmap_rows_per_sec"] > \
            sweep[0]["gather_rows_per_sec"], sweep[0]
        out["bitmap_vs_gather_sweep"] = sweep
        out["bitmap_vs_gather_crossover_sel"] = round(crossover, 4)
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return out


def soak_bench(tenants: int = 96, hog_threads: int = 12, good_threads: int = 4,
               phase_s: float = 5.0, rows_per_tenant: int = 512) -> dict:
    """Overload soak lane (host-only, in-proc dual-server cluster): sustained
    mixed workload under ~4x overload proving graceful degradation.

    Many small tenant tables serve a zipf-mixed stream of cheap aggregations
    from `good_threads` workers while one hog tenant floods expensive
    unaggregated scans from `hog_threads` workers and a background thread
    keeps ingesting segments — with broker adaptive admission on and
    per-tenant fair scheduling on every server. Published gates:

    - `overload_protected_p99_ms` — the well-behaved tenants' p99 UNDER
      overload; the budget is <= 2x `soak_unloaded_p99_ms`.
    - `shed_rate` — fraction of broker arrivals shed (the hog's scans).
    - `tenant_fairness_index` — Jain's index over per-tenant success ratios
      of the good tenants (1.0 = perfectly even service).
    """
    import shutil
    import tempfile
    import threading

    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.query.scheduler import QueryScheduler
    from pinot_tpu.schema import DataType, Schema, dimension
    from pinot_tpu.schema import metric as smetric
    from pinot_tpu.table import TableConfig

    work = tempfile.mkdtemp(prefix="pinot_tpu_soak_")
    try:
        cluster = QuickCluster(num_servers=2, work_dir=work)
        # per-tenant fair scheduling on every server: weighted-fair queue,
        # capped per-table share, so the hog degrades alone server-side too
        for s in cluster.servers:
            s.scheduler = QueryScheduler(max_concurrent=4, max_pending=64,
                                         per_table_share=0.5)
        rng = np.random.default_rng(97)
        names = [f"soak{i:03d}" for i in range(tenants)]
        for nm in names:
            schema = Schema(nm, [dimension("user", DataType.STRING),
                                 smetric("value", DataType.DOUBLE)])
            cfg = cluster.create_table(schema, TableConfig(nm, replication=2))
            cluster.ingest_columns(cfg, {
                "user": [f"u{j % 64}" for j in range(rows_per_tenant)],
                "value": np.round(rng.uniform(0, 10, rows_per_tenant),
                                  3).tolist()})
        hog_rows = 50_000
        hog_schema = Schema("soakhog", [dimension("user", DataType.STRING),
                                        smetric("value", DataType.DOUBLE)])
        hog_cfg = cluster.create_table(hog_schema,
                                       TableConfig("soakhog", replication=2))
        cluster.ingest_columns(hog_cfg, {
            "user": [f"h{j % 997}" for j in range(hog_rows)],
            "value": [1.0] * hog_rows})
        hog_sql = f"SELECT user, value FROM soakhog LIMIT {hog_rows}"

        # zipf tenant mix, precomputed so every run draws the same stream
        zipf = np.random.default_rng(1234).zipf(1.4, size=200_000)
        tenant_seq = ((zipf - 1) % tenants).tolist()

        def good_sql(idx: int) -> str:
            return f"SELECT COUNT(*), SUM(value) FROM {names[idx]}"

        def run_good_phase(duration_s: float, offset: int):
            """good_threads workers draw tenants from the zipf stream for
            duration_s; returns (latencies_ms, per-tenant attempts,
            per-tenant successes)."""
            lats: list = []
            attempts: dict = {}
            successes: dict = {}
            lock = threading.Lock()
            stop_at = time.perf_counter() + duration_s

            def worker(wi: int) -> None:
                pos = offset + wi * 50_000 // good_threads
                while time.perf_counter() < stop_at:
                    idx = tenant_seq[pos % len(tenant_seq)]
                    pos += 1
                    q0 = time.perf_counter()
                    try:
                        cluster.query(good_sql(idx))
                        ok = True
                    except Exception:
                        ok = False
                    dt = (time.perf_counter() - q0) * 1000
                    with lock:
                        attempts[idx] = attempts.get(idx, 0) + 1
                        if ok:
                            successes[idx] = successes.get(idx, 0) + 1
                            lats.append(dt)

            threads = [threading.Thread(target=worker, args=(wi,))
                       for wi in range(good_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return lats, attempts, successes

        def p99(lats) -> float:
            if not lats:
                return 0.0
            lats = sorted(lats)
            return lats[int(0.99 * (len(lats) - 1))]

        # warm the compile caches off the clock
        for idx in (0, 1, 2):
            cluster.query(good_sql(idx))
        cluster.query(hog_sql)

        # phase A: unloaded baseline p99 of the good-tenant mix
        unloaded_lats, _, _ = run_good_phase(phase_s, offset=0)
        unloaded_p99 = p99(unloaded_lats)

        # phase B: admission on, hog flood + concurrent ingest + good mix.
        # The latency threshold keys off the measured unloaded p99: once a
        # few admitted hog scans inflate the recent dispatch p99 past it the
        # machine parks in SHEDDING and the expensive class stays shed.
        cluster.catalog.put_property(
            "clusterConfig/broker.admission.enabled", "true")
        cluster.catalog.put_property(
            "clusterConfig/broker.admission.queue.high", "6")
        cluster.catalog.put_property(
            "clusterConfig/broker.admission.queue.max", "48")
        cluster.catalog.put_property(
            "clusterConfig/broker.admission.latency.ms",
            str(max(2.0 * unloaded_p99, 15.0)))
        stop = threading.Event()
        hog_counts = {"attempts": 0, "shed": 0}
        hog_lock = threading.Lock()

        def hog_worker() -> None:
            while not stop.is_set():
                try:
                    cluster.query(hog_sql)
                    shed = False
                except Exception as e:
                    # a well-formed client honors the 429's Retry-After hint
                    # instead of hammering; cap it so the flood stays a flood
                    shed = True
                    hint = getattr(e, "retry_after_ms", None)
                    wait_s = (min(float(hint), 50.0) / 1000.0
                              if hint else 0.02)
                    stop.wait(wait_s)
                with hog_lock:
                    hog_counts["attempts"] += 1
                    hog_counts["shed"] += int(shed)

        def ingest_worker() -> None:
            j = 0
            while not stop.is_set():
                cluster.ingest_columns(hog_cfg, {
                    "user": [f"g{j}_{k}" for k in range(256)],
                    "value": [0.5] * 256})
                j += 1
                stop.wait(0.2)

        background = ([threading.Thread(target=hog_worker)
                       for _ in range(hog_threads)]
                      + [threading.Thread(target=ingest_worker)])
        for t in background:
            t.start()
        b0 = time.perf_counter()
        loaded_lats, attempts, successes = run_good_phase(
            phase_s, offset=50_000)
        stop.set()
        for t in background:
            t.join()
        b_elapsed = time.perf_counter() - b0

        snap = cluster.broker.admission.snapshot()
        arrivals = snap["admitted"] + snap["sheds"]
        shed_rate = snap["sheds"] / arrivals if arrivals else 0.0
        # Jain's fairness index over the good tenants' per-tenant success
        # ratios: (sum x)^2 / (n * sum x^2); 1.0 = every tenant served evenly
        ratios = [successes.get(i, 0) / attempts[i]
                  for i in attempts if attempts[i] > 0]
        fairness = ((sum(ratios) ** 2 / (len(ratios) * sum(r * r
                     for r in ratios))) if ratios and sum(ratios) else 0.0)
        good_qps = len(loaded_lats) / b_elapsed if b_elapsed else 0.0
        return {
            "soak_tenants": tenants,
            "soak_unloaded_p99_ms": round(unloaded_p99, 3),
            "overload_protected_p99_ms": round(p99(loaded_lats), 3),
            "soak_p99_ratio": round(p99(loaded_lats) / unloaded_p99, 3)
            if unloaded_p99 else None,
            "shed_rate": round(shed_rate, 4),
            "tenant_fairness_index": round(fairness, 4),
            # every worker is a closed-loop saturated client, so offered
            # demand is the thread count: the unloaded baseline ran
            # good_threads of them, overload adds hog_threads more
            "soak_overload_factor": round(
                (good_threads + hog_threads) / good_threads, 2),
            "soak_good_qps_under_overload": round(good_qps, 1),
            "soak_hog_attempts": hog_counts["attempts"],
            "soak_hog_shed": hog_counts["shed"],
            "soak_admission_state": snap["state"],
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def memory_bench(cycles: int = 100, rows: int = 65536) -> dict:
    """Device-memory observability lane: proves the HBM ledger is truthful
    and cheap. Three published gates:

    - `memory_reconcile_drift_pct` — ledger delta vs `jax.live_arrays()`
      delta across a full segment-staging pass (expected ~0: every resident
      byte the runtime sees is a byte the ledger accounted);
    - `memory_ledger_overhead_pct` — added cost of `staged()` registration
      on the host->device staging hot path (budget < 1%);
    - `memory_leak_bytes_after_cycles` / `memory_unload_leak_bytes` — ledger
      residency left behind by `cycles` block stage/release rounds and by
      the final unload of every staged segment (expected 0: release paths
      must free exactly what staging registered).
    """
    import jax.numpy as jnp

    from pinot_tpu.engine import datablock
    from pinot_tpu.utils.memledger import get_ledger, live_device_bytes, staged

    segs = build_or_load_segments(ssb_schema(), make_columns(rows), rows=rows,
                                  tag=f"memlane_r{rows}_v1")
    ledger = get_ledger()
    base_ledger = ledger.resident_bytes()
    base_device = live_device_bytes()

    def stage_all(seg) -> None:
        blk = datablock.block_for(seg)
        blk.valid
        blk.ids("lo_region")
        for col in ("lo_quantity", "lo_extendedprice"):
            blk.values(col)

    # 1) reconciliation drift across a full staging pass
    for seg in segs:
        stage_all(seg)
    d_ledger = ledger.resident_bytes() - base_ledger
    now_device = live_device_bytes()
    drift_pct = None
    if base_device is not None and now_device is not None:
        d_device = now_device - base_device
        drift_pct = round(100.0 * abs(d_ledger - d_device)
                          / max(d_ledger, d_device, 1), 3)

    # 2) stage/release leak cycles on one segment
    for seg in segs:
        datablock.release_block(seg)
    staged_per_cycle = None
    for _ in range(cycles):
        stage_all(segs[0])
        if staged_per_cycle is None:
            staged_per_cycle = ledger.resident_bytes() - base_ledger
        datablock.release_block(segs[0])
    cycle_leak = ledger.resident_bytes() - base_ledger
    unload_leak = ledger.resident_bytes() - base_ledger  # all blocks released

    # 3) registration overhead on the staging hot path: registration cost
    #    measured alone (it's deterministic at ~µs scale) over the device
    #    staging cost it rides on — a paired A/B timing of the transfer
    #    itself swings far more run-to-run than the delta being measured
    host = np.zeros(256 * 1024, dtype=np.float32)   # 1 MiB transfer
    reps, iters = 5, 40
    bare_s = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            staged(jnp.asarray(host), "memlane_overhead", "raw",
                   name="probe").block_until_ready()
        bare_s = min(bare_s, (time.perf_counter() - t0) / iters)
    reg_iters = 10_000
    t0 = time.perf_counter()
    for _ in range(reg_iters):
        ledger.register(None, "memlane_overhead", "raw", "probe",
                        host.nbytes)
    reg_s = (time.perf_counter() - t0) / reg_iters
    ledger.release(segment="memlane_overhead")
    overhead_pct = 100.0 * reg_s / max(bare_s - reg_s, 1e-9)

    return {
        "memory_reconcile_drift_pct": drift_pct,
        "memory_staged_bytes": d_ledger,
        "memory_ledger_overhead_pct": round(overhead_pct, 3),
        "memory_leak_cycles": cycles,
        "memory_leak_bytes_after_cycles": cycle_leak,
        "memory_unload_leak_bytes": unload_leak,
        "memory_cycle_resident_bytes": staged_per_cycle,
        "memory_prior_resident_bytes": base_ledger,
    }


def tiering_bench(cycles: int = 100, rows: int = 8192,
                  segments: int = 4) -> dict:
    """Tiered-storage lane (host-only in-proc cluster): a table ~4x the
    pinned HBM capacity served through the admission gate / eviction /
    cold-reload lifecycle (README "Tiered storage"). Published gates:

    - `tiering_cold_ttfq_ms` — time to the first full answer after EVERY
      segment was demoted COLD (lazy deep-store reload inside the query);
    - `tiering_overhead_pct` — steady-state cost the tiering machinery adds
      vs an unconstrained run: the per-query admission fast-path touches
      (once per segment) plus the pressure sweep's no-op duty cycle
      (sweep time / PRESSURE_INTERVAL_S), relative to the unconstrained
      query latency; budget < 2%;
    - `tiering_leak_bytes_after_cycles` — ledger residency left after
      `cycles` evict-everything/re-promote rounds (expected 0: eviction
      must free exactly what promotion staged).
    """
    import shutil
    import tempfile

    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.engine.datablock import predicted_block_bytes
    from pinot_tpu.table import TableConfig
    from pinot_tpu.utils.memledger import get_ledger

    ledger = get_ledger()
    cap_before = ledger.capacity_bytes()
    base_resident = ledger.resident_bytes()   # earlier lanes' blocks stay
    work = tempfile.mkdtemp(prefix="pinot_tpu_tiering_")
    try:
        cluster = QuickCluster(num_servers=1, work_dir=work)
        schema = ssb_schema()
        cfg = TableConfig(schema.name, replication=1,
                          time_column="lo_orderdate")
        cluster.create_table(schema, cfg)
        rng = np.random.default_rng(31)
        names = [cluster.ingest_columns(cfg, make_columns(rows))
                 for _ in range(segments)]
        table = cfg.table_name_with_type
        server = cluster.servers[0]
        mgr = server.tables[table]
        predicted = predicted_block_bytes(mgr.get(names[0]))
        sql = "SELECT lo_region, SUM(lo_revenue) FROM lineorder " \
              "GROUP BY lo_region LIMIT 10"

        # steady-state overhead: under target, queries ride the admission
        # fast path (dict hit + has_block touch, once per segment) and the
        # pressure loop no-ops once per PRESSURE_INTERVAL_S. Both are timed
        # directly and published relative to the unconstrained query latency
        # — a subtractive A/B of two near-equal query medians only measures
        # timer noise, not the machinery.
        from pinot_tpu.cluster.tiering import PRESSURE_INTERVAL_S
        ledger.set_capacity(base_resident + 100 * predicted * segments)
        cluster.query(sql)                    # stage + warm compile caches
        lats = []
        for _ in range(15):
            t0 = time.perf_counter()
            cluster.query(sql)
            lats.append(time.perf_counter() - t0)
        base_s = float(np.median(lats))
        seg0 = mgr.get(names[0])
        t0 = time.perf_counter()
        for _ in range(200):
            server.tiering.admit(table, seg0, mgr)
        admit_s = (time.perf_counter() - t0) / 200
        t0 = time.perf_counter()
        for _ in range(200):
            server.tiering.run_pressure_sweep()
        sweep_s = (time.perf_counter() - t0) / 200
        overhead_pct = 100.0 * (segments * admit_s / base_s
                                + sweep_s / PRESSURE_INTERVAL_S)

        # cold-start TTFQ: demote EVERY segment, first query lazily reloads
        # the whole table from the deep store
        for nm in names:
            assert cluster.controller.demote_segment_to_cold(table, nm)
        assert not mgr.segment_names
        t0 = time.perf_counter()
        res = cluster.query("SELECT COUNT(*) FROM lineorder")
        ttfq_ms = (time.perf_counter() - t0) * 1000
        full = (res.rows[0][0] == segments * rows
                and not res.stats["partialResult"])

        # leak check: `cycles` evict-everything/re-promote rounds. Refcount-
        # aware eviction means a query's own segments are never victims
        # while it runs, so steady state under a fixed tight capacity stops
        # churning (one stable hot resident + host-tier rejects). Force a
        # full cycle deterministically instead: query promotes under a
        # 1.3-block budget, then the pressure sweep drains the hot tier
        # between queries. Residency left after the last sweep is the leak
        # (expected 0: eviction must free exactly what promotion staged).
        churn_cap = base_resident + int(predicted * 1.3)
        tiering_before = server.tiering.snapshot()
        for _ in range(cycles):
            ledger.set_capacity(churn_cap)
            cluster.query(sql)
            ledger.set_capacity(max(1, base_resident))
            server.tiering.run_pressure_sweep()
        tiering_after = server.tiering.snapshot()
        leak = ledger.resident_bytes() - base_resident
        return {
            "tiering_cold_ttfq_ms": round(ttfq_ms, 2),
            "tiering_cold_full_answer": bool(full),
            "tiering_cold_segments": segments,
            "tiering_overhead_pct": round(overhead_pct, 3),
            "tiering_leak_cycles": cycles,
            "tiering_leak_bytes_after_cycles": int(leak),
            "tiering_cycle_evictions":
                tiering_after["evictions"] - tiering_before["evictions"],
            "tiering_cycle_promotions":
                tiering_after["promotions"] - tiering_before["promotions"],
        }
    finally:
        if cap_before[0]:
            ledger.set_capacity(cap_before[0], estimated=cap_before[1])
        shutil.rmtree(work, ignore_errors=True)


def workload_bench(rows: int = 32768, shapes: int = 20,
                   queries: int = 200) -> dict:
    """Workload-intelligence lane (host-only in-proc cluster): proves the
    plan-fingerprint registry is correct under a realistic mix and cheap on
    the served path. Published gates:

    - `workload_overhead_pct` — added cost of fingerprint normalization +
      registry fold per query over the served-path query p50 (budget < 1%;
      same methodology as the PR 14 ledger-overhead lane: the registry cost
      is deterministic at µs scale and measured alone, because a paired A/B
      of two near-equal query medians only measures timer noise);
    - `workload_conservation_ok` — after a zipf mix over `shapes` distinct
      shapes, per-shape counts + the evicted overflow == total queries, and
      each literal-varied query mapped to exactly one fingerprint.
    """
    import shutil
    import tempfile

    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.sql.fingerprint import fingerprint_statement
    from pinot_tpu.sql.parser import parse_query
    from pinot_tpu.table import TableConfig

    work = tempfile.mkdtemp(prefix="pinot_tpu_workload_")
    try:
        cluster = QuickCluster(num_servers=1, work_dir=work)
        schema = ssb_schema()
        cfg = TableConfig(schema.name, replication=1,
                          time_column="lo_orderdate")
        cluster.create_table(schema, cfg)
        cluster.ingest_columns(cfg, make_columns(rows))

        # zipf-ranked shape templates: distinct column/aggregate mixes so
        # every template is a genuinely different plan shape
        cols = ["lo_quantity", "lo_discount", "lo_suppkey", "lo_custkey",
                "lo_revenue"]
        aggs = ["COUNT(*)", "SUM(lo_revenue)", "MIN(lo_quantity)",
                "MAX(lo_extendedprice)"]
        templates = []
        for i in range(shapes):
            templates.append(
                f"SELECT {aggs[i % len(aggs)]} FROM lineorder "
                f"WHERE {cols[i % len(cols)]} > {{v}} "
                f"AND lo_orderdate > {{v2}} LIMIT {1 + i // len(aggs)}")
        rng = np.random.default_rng(47)
        # one seeding pass over every template, then the zipf tail — the mix
        # always covers all `shapes` distinct shapes
        ranks = np.concatenate([
            np.arange(shapes),
            np.minimum(rng.zipf(1.3, size=queries - shapes) - 1,
                       shapes - 1)]).astype(int)
        cluster.query(templates[0].format(v=1, v2=0))   # warm compile caches
        reg = cluster.broker.workload
        base_total = reg.snapshot()["totalQueries"]
        fps: dict = {}
        lats = []
        for i, r in enumerate(ranks):
            sql = templates[r].format(v=int(rng.integers(0, 50)),
                                      v2=19920101 + int(rng.integers(0, 9)))
            t0 = time.perf_counter()
            res = cluster.query(sql)
            lats.append(time.perf_counter() - t0)
            fps.setdefault(r, set()).add(
                res.stats.get("workloadFingerprint"))
        p50_s = float(np.median(lats))
        snap = reg.snapshot()
        one_fp_per_shape = all(len(s) == 1 and None not in s
                               for s in fps.values())
        counted = sum(s["count"] for s in snap["shapes"]) \
            + snap["evictedQueries"]
        conservation_ok = (counted == snap["totalQueries"]
                           and snap["totalQueries"] - base_total == queries
                           and one_fp_per_shape)

        # registry cost measured alone: normalize + fold of one parsed
        # statement, per-iteration deterministic at µs scale
        stmt = parse_query(templates[0].format(v=7, v2=19940101))
        stats = dict(cluster.query(templates[0].format(v=7, v2=19940101)
                                   ).stats)
        reps, reg_iters = 3, 10_000
        reg_s = float("inf")
        for _ in range(reps):   # min-of-reps: the cost is deterministic,
            t0 = time.perf_counter()    # timer noise only ever inflates it
            for _ in range(reg_iters):
                shape = fingerprint_statement(stmt)
                reg.observe(shape, 1.0, stats)
            reg_s = min(reg_s, (time.perf_counter() - t0) / reg_iters)
        overhead_pct = 100.0 * reg_s / max(p50_s - reg_s, 1e-9)

        return {
            "workload_overhead_pct": round(overhead_pct, 3),
            "workload_registry_cost_us": round(reg_s * 1e6, 2),
            "workload_query_p50_ms": round(p50_s * 1000, 3),
            "workload_queries": queries,
            "workload_distinct_shapes": len(snap["shapes"]),
            "workload_shapes_seen": snap["shapesSeen"],
            "workload_conservation_ok": bool(conservation_ok),
            "workload_top_share_pct":
                snap["shapes"][0]["timeSharePct"] if snap["shapes"] else 0.0,
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def events_bench(rows: int = 32768, queries: int = 60) -> dict:
    """Event-journal lane (host-only in-proc cluster): proves emitting a
    state-transition event is invisible on the query path and the ring's
    conservation law holds under forced overflow. Published gates:

    - `events_emit_overhead_pct` — cost of one `emit()` over the served-path
      query p50 (budget < 1%; same methodology as the workload lane: the
      emit cost is deterministic at µs scale and measured alone via a
      min-of-reps tight loop, because a paired A/B of two near-equal query
      medians only measures timer noise);
    - `events_conservation_ok` — after emitting 2x a private ring's capacity,
      `emitted == retained + evicted` and retention is pinned at capacity
      with strictly oldest-first eviction (the survivor window is exactly
      the newest half).
    """
    import shutil
    import tempfile

    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.table import TableConfig
    from pinot_tpu.utils.events import EventJournal, get_journal

    work = tempfile.mkdtemp(prefix="pinot_tpu_events_")
    try:
        cluster = QuickCluster(num_servers=1, work_dir=work)
        schema = ssb_schema()
        cfg = TableConfig(schema.name, replication=1,
                          time_column="lo_orderdate")
        cluster.create_table(schema, cfg)
        cluster.ingest_columns(cfg, make_columns(rows))
        sql = "SELECT SUM(lo_revenue) FROM lineorder WHERE lo_quantity > 10"
        cluster.query(sql)   # warm compile caches
        lats = []
        for _ in range(queries):
            t0 = time.perf_counter()
            cluster.query(sql)
            lats.append(time.perf_counter() - t0)
        p50_s = float(np.median(lats))

        # emit cost measured alone: one ring append + cached counter inc,
        # per-iteration deterministic at µs scale
        journal = get_journal()
        reps, iters = 3, 10_000
        emit_s = float("inf")
        for _ in range(reps):   # min-of-reps: timer noise only inflates it
            t0 = time.perf_counter()
            for _ in range(iters):
                journal.emit("bench.probe", node="bench")
            emit_s = min(emit_s, (time.perf_counter() - t0) / iters)
        overhead_pct = 100.0 * emit_s / max(p50_s - emit_s, 1e-9)

        # ring conservation under forced 2x overflow, on a private journal
        ring = EventJournal(capacity=256, node="bench")
        for i in range(512):
            ring.emit("bench.probe", i=i)
        snap = ring.snapshot()
        survivors = ring.entries()          # newest first
        oldest_first_ok = (
            len(survivors) == 256 and
            survivors[0]["attrs"]["i"] == 511 and
            survivors[-1]["attrs"]["i"] == 256)
        conservation_ok = (
            snap["emitted"] == snap["retained"] + snap["evicted"]
            and snap["emitted"] == 512 and snap["retained"] == 256
            and oldest_first_ok)

        return {
            "events_emit_overhead_pct": round(overhead_pct, 3),
            "events_emit_cost_us": round(emit_s * 1e6, 2),
            "events_query_p50_ms": round(p50_s * 1000, 3),
            "events_conservation_ok": bool(conservation_ok),
        }
    finally:
        shutil.rmtree(work, ignore_errors=True)


def relay_floor_ms(iters=7) -> float:
    """Median dispatch+fetch of a TRIVIAL kernel: the transport's per-query
    latency floor. Published next to p50 so engine overhead (p50 - floor) is
    readable regardless of how the relay's round-trip cost drifts."""
    import jax
    f = jax.jit(lambda x: x + 1)
    x = jax.device_put(np.float32(1.0))
    jax.device_get(f(x))
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.device_get(f(x))
        lat.append(time.perf_counter() - t0)
    return float(np.median(lat)) * 1000


def platform_calibration():
    """Measured ceilings of THIS device environment, so per-config
    efficiency is judged against what the platform actually delivers —
    not the v5e datasheet (VERDICT r4 weak #5: publish the roofline).

    Every probe is fold-proof: a traced scalar knob derived from the
    running accumulator perturbs each iteration, so XLA can neither CSE
    iterations nor algebraically collapse the chain (a plain `sum(x)`
    chain or repeated elementwise scale IS collapsible and measured ~10x
    optimistic before this harness).

    Measured on this axon-relay v5e (varies run to run — the chip is
    shared): dense 8k^3 bf16 matmul ~15-70 TFLOPS (8-35% of the 197
    nominal), fused 4-column Q1.1 scan streaming ~50 GB/s, r+w copy ~20-35
    GB/s — single-digit percent of the 819 GB/s nominal HBM. Memory-bound
    kernels are capped ~20x below directly-attached HBM; the honest
    roofline denominator is the measured `fused_scan_gbps`."""
    import jax
    import jax.numpy as jnp
    rng = np.random.default_rng(0)
    chain_n = 8

    def timed(fn, *args):
        g = jax.jit(fn)
        jax.device_get(g(*args))
        t0 = time.perf_counter()
        jax.device_get(g(*args))
        return (time.perf_counter() - t0) / chain_n

    # 1) dense matmul TFLOPS (chained A@x: cannot fold without computing)
    m = 8192
    a = jax.device_put(rng.normal(0, 1, (m, m)).astype(np.float32)).astype(jnp.bfloat16)
    b = jax.device_put(rng.normal(0, 1, (m, m)).astype(np.float32)).astype(jnp.bfloat16)

    def mm_chain(a, b):
        x = b
        for _ in range(chain_n):
            x = jax.lax.dot(a, x, preferred_element_type=jnp.bfloat16) \
                * jnp.bfloat16(1e-2)
        return x.astype(jnp.float32).sum()

    tflops = 2 * m ** 3 / timed(mm_chain, a, b) / 1e12

    # 2) r+w streaming copy: per-iteration roll forces a real materialized
    #    pass (the knob multiply blocks roll-composition folding)
    n = 32 * 1024 * 1024
    x = jax.device_put(rng.uniform(0, 1, n).astype(np.float32).reshape(8, -1))

    def copy_chain(x):
        y = x
        acc = jnp.float32(0)
        for _ in range(chain_n):
            y = jnp.roll(y, 1, axis=1) * (1.0 + acc * 1e-30)
            acc = acc + y[0, 0]
        return acc + y.sum()

    copy_gbps = 2 * 4 * n / timed(copy_chain, x) / 1e9

    # 3) fused scan — EXACTLY the Q1.1 traffic: 3 compare columns
    #    (orderdate, discount, quantity) + 1 masked-sum column
    #    (extendedprice; discount is re-used from the filter read), i.e.
    #    16B/row — THE roofline denominator for the engine's scan kernels.
    #    The numerator in the main report counts the SAME 16B/row, so
    #    scan_pct_of_measured_roofline compares like with like.
    cols4 = [jax.device_put(arr.reshape(8, -1)) for arr in (
        rng.integers(19920101, 19990101, n).astype(np.int32),
        rng.integers(0, 11, n).astype(np.int32),
        rng.integers(1, 51, n).astype(np.int32),
        rng.uniform(1, 10000, n).astype(np.float32))]

    def scan_chain(od, dc, qt, pr):
        acc = jnp.float32(0)
        for _ in range(chain_n):
            ki = (acc * 1e-30).astype(jnp.int32)
            mask = ((od >= 19930101 + ki) & (od <= 19931231) & (dc >= 1 + ki)
                    & (dc <= 3) & (qt < 25))
            fm = mask.astype(jnp.float32)
            acc = acc + (pr * fm * dc).sum() * 1e-30
        return acc

    scan_dt = timed(scan_chain, *cols4)
    scan_gbps = round(16 * n / scan_dt / 1e9, 1)
    # persist THE roofline denominator: serving-side rooflinePct
    # (kernels.roofline_hbm_gbps) and every bench pct divide by this same
    # measured figure — the one-number fix for the 464.8% self-inconsistency
    try:
        _caps_mod.save_measured_hbm_gbps(scan_gbps)
    except (ValueError, OSError) as e:
        print(f"WARNING: measured-roofline persist failed: {e}",
              file=sys.stderr)
    return {"dense_matmul_tflops_bf16": round(tflops, 1),
            "copy_rw_gbps": round(copy_gbps, 1),
            "fused_scan_gbps": scan_gbps,
            "fused_scan_rows_per_sec": round(n / scan_dt, 1),
            "nominal_bf16_tflops": 197,
            "nominal_hbm_gbps": 819}


def fused_bench(rows: int = None, iters: int = None) -> dict:
    """Fused-vs-staged lane: the SAME per-segment filter+aggregate shapes
    executed through the single-launch fused plan (compressed resident
    forms, `run_kernel`) and the two-launch staged fallback
    (`run_kernel_staged`), head to head. Publishes per shape: rows/s both
    ways, fused/staged speedup, device-launch counts and the launch-count
    reduction (>= 2x on filtered shapes), plus
    `fused_scan_pct_of_measured_roofline` — achieved compressed-form
    bandwidth of the pure scan shape over `kernels.roofline_hbm_gbps()`,
    the ONE calibrated figure `platform_calibration` persists. The pct is
    asserted <= 110: a scan cannot beat the measured streaming ceiling on
    the same device by more than timing jitter."""
    from pinot_tpu.engine import kernels
    from pinot_tpu.query import stats as qstats
    from pinot_tpu.query.executor import ServerQueryExecutor

    rows = rows or int(os.environ.get("PINOT_BENCH_FUSED_ROWS",
                                      4 * 1024 * 1024))
    iters = iters or int(os.environ.get("PINOT_BENCH_FUSED_ITERS", 5))
    schema = ssb_schema()
    segments = build_or_load_segments(schema, make_columns(rows), rows=rows,
                                      tag=f"fused_r{rows}_s{SEGMENTS}_v1")
    fused_ex = ServerQueryExecutor(fused_enabled=True)
    staged_ex = ServerQueryExecutor(fused_enabled=False)
    floor_s = relay_floor_ms() / 1000.0
    shapes = {
        "scan_q11": QUERY,
        "groupby": GROUP_QUERY,
        "filter_agg": ("SELECT COUNT(*), SUM(lo_revenue), MAX(lo_quantity) "
                       "FROM lineorder WHERE lo_quantity < 25 "
                       "AND lo_discount BETWEEN 1 AND 3 LIMIT 5"),
    }
    out: dict = {"fused_rows": rows, "fused_segments": len(segments),
                 "fused_shapes": {}}
    scan_wall = None
    for name, sql in shapes.items():
        rf = fused_ex.execute(segments, sql)     # warm compile + transfer
        rs = staged_ex.execute(segments, sql)
        # same f32 kernels, same reduction order: byte-identical or broken
        assert [tuple(r) for r in rf.rows] == [tuple(r) for r in rs.rows], \
            f"fused != staged on {name}"
        entry = {}
        for tag, ex in (("fused", fused_ex), ("staged", staged_ex)):
            with qstats.collect_stats() as st:
                t0 = time.perf_counter()
                for _ in range(iters):
                    ex.execute(segments, sql)
                wall = time.perf_counter() - t0
            entry[f"{tag}_rows_per_sec"] = round(rows * iters / wall, 1)
            entry[f"{tag}_launches"] = int(
                st.counters.get(qstats.DEVICE_LAUNCHES, 0)) // iters
            if tag == "fused" and name == "scan_q11":
                scan_wall = wall
        entry["fused_vs_staged"] = round(
            entry["fused_rows_per_sec"]
            / max(entry["staged_rows_per_sec"], 1.0), 3)
        entry["launch_reduction"] = round(
            entry["staged_launches"] / max(entry["fused_launches"], 1), 2)
        # a filtered shape pays mask + aggregate when staged: fusing it must
        # at least halve the per-segment launch count
        assert entry["launch_reduction"] >= 2.0, (name, entry)
        out["fused_shapes"][name] = entry

    # roofline share of the pure scan shape, on COMPRESSED-form traffic:
    # Q1.1 streams 3 dict-id columns (orderdate, discount, quantity) + the
    # raw extendedprice floats = 16B/row — the same per-row bytes the
    # calibration denominator counts, now without a decode pass in between
    roofline = kernels.roofline_hbm_gbps()
    dev_s = max(scan_wall / iters - floor_s, 1e-6)
    gbps = 16 * rows / dev_s / 1e9
    pct = 100.0 * gbps / roofline
    assert pct <= 110.0, \
        f"fused roofline accounting inconsistent: {pct:.1f}% of {roofline}"
    out["fused_scan_effective_gbps"] = round(gbps, 1)
    out["fused_roofline_gbps"] = round(roofline, 1)
    out["fused_scan_pct_of_measured_roofline"] = round(pct, 1)
    return out


# --------------------------------------------------------------------------
# device hash-join lane: build/probe rows/s device vs the host oracle across
# build cardinalities, zipf probe-key skew, broadcast-vs-partitioned crossover
# --------------------------------------------------------------------------

JOIN_PROBE_ROWS = int(os.environ.get("PINOT_BENCH_JOIN_PROBE_ROWS", 1 << 20))
JOIN_BUILD_CARDS = tuple(
    int(x) for x in os.environ.get("PINOT_BENCH_JOIN_CARDS",
                                   "1000,100000,2000000").split(","))
JOIN_ITERS = int(os.environ.get("PINOT_BENCH_JOIN_ITERS", 3))


def _zipf_probe(rng, n: int, card: int, s) -> np.ndarray:
    """Probe-side keys in [0, card): uniform when `s` is None, else drawn
    from a zipf(s) rank distribution — s=1.5 puts ~65% of probes on a
    handful of hot build keys, the JSPIM skew shape."""
    if s is None:
        return rng.integers(0, card, n).astype(np.int64)
    p = np.arange(1, card + 1, dtype=np.float64) ** (-float(s))
    p /= p.sum()
    return rng.choice(card, size=n, p=p).astype(np.int64)


def join_bench(probe_rows: int = None, iters: int = None) -> dict:
    """Device hash-join lane (PR 17), three sub-sweeps:

    1. device-vs-host across build cardinalities (1k / 100k / 2M by default,
       uniform probe keys): the device scatter/sort-merge fast path against
       `hash_join_host`, both verified against a direct numpy oracle
       (row count + payload sums). Publishes rows/s both ways, the speedup,
       and `gate_3x` per 100k+ cardinality. The >= 3x gate hard-asserts only
       on a real accelerator backend: when jax "device" IS this host's CPU,
       the scatter/sort launches and numpy's vectorized factorize run on the
       same silicon and converge, so the gate is published + warned instead
       of failing a box that has no accelerator attached.
    2. zipf skew sweep at the middle cardinality (uniform / 1.1 / 1.5):
       the kernels' fold-histogram must actually fire (`joinSkewPct` > 0 on
       the skewed probes) and zipf-1.5 must hold within 2x of the uniform
       rate — with a unique-key build side every probe matches exactly once,
       so a slowdown here could only come from the skew plumbing itself.
    3. broadcast-vs-partitioned crossover on the same shapes: per
       cardinality, the stats-driven chooser's pick, the exchange bytes both
       ways through the real partitioner (`_partition_join_input`, 4
       workers — broadcast ships p build replicas, partitioned hashes both
       sides), and the measured wall of executing all 4 per-worker joins
       under each strategy. Broadcast wins while the build side is small
       (p tiny replicas beat hash-routing a 1M-row probe side); by the 2M
       build side the p-fold replicated build work has to lose.
    """
    import jax

    from pinot_tpu.multistage import runtime as mrt
    from pinot_tpu.multistage.planner import (BROADCAST_MAX_BYTES_DEFAULT,
                                              JoinSpec, choose_join_strategy)
    from pinot_tpu.multistage.shuffle import _partition_join_input
    from pinot_tpu.query import stats as qstats

    probe_rows = probe_rows or JOIN_PROBE_ROWS
    iters = iters or JOIN_ITERS
    rng = np.random.default_rng(17)
    accel = jax.default_backend() != "cpu"
    spec = JoinSpec(right_alias="r", join_type="inner",
                    left_keys=["lk"], right_keys=["rk"])
    saved = dict(mrt._DEVICE_JOIN)
    mrt.configure_device_join(enabled=True, min_rows=0)
    out: dict = {"join_probe_rows": probe_rows,
                 "join_build_cards": list(JOIN_BUILD_CARDS),
                 "join_cards": {}, "join_skew": {}}

    def exchange_wall(left, right, strategy):
        """One full p-worker exchange + join under `strategy`: partition
        both sides, run every per-worker join (codes ride the JoinInput
        hand-off exactly as `_deliver_local` passes them), return (wall_s,
        bytes_shuffled, rows_out)."""
        p = 4
        rparts, rbytes = _partition_join_input(right, ["rk"], p, strategy,
                                               "R")
        lparts, lbytes = _partition_join_input(left, ["lk"], p, strategy,
                                               "L")
        t0 = time.perf_counter()
        rows = 0
        for lp, rp in zip(lparts, rparts):
            j = mrt.hash_join(lp.block, rp.block, spec,
                              lcodes=lp.codes, rcodes=rp.codes)
            rows += mrt._block_rows(j)
        return time.perf_counter() - t0, int(rbytes + lbytes), rows

    try:
        # -- 1) device vs host oracle across build cardinalities -----------
        for card in JOIN_BUILD_CARDS:
            right = {"rk": np.arange(card, dtype=np.int64),
                     "w": rng.uniform(0.0, 10.0, card)}
            lk = _zipf_probe(rng, probe_rows, card, None)
            left = {"lk": lk, "v": rng.uniform(0.0, 10.0, probe_rows)}
            dev = mrt.hash_join(left, right, spec)        # warm jit shapes
            # numpy oracle: every probe key exists exactly once on the build
            # side, so the inner join is a pure gather — count and payload
            # sums must agree to fp tolerance
            want_v = float(np.sum(left["v"]))
            want_w = float(np.sum(right["w"][lk]))
            assert mrt._block_rows(dev) == probe_rows, \
                (card, mrt._block_rows(dev))
            for col, want in (("v", want_v), ("w", want_w)):
                got = float(np.sum(dev[col]))
                assert abs(got - want) <= 1e-6 * max(1.0, abs(want)), \
                    (card, col, got, want)
            with qstats.collect_stats() as st:
                t0 = time.perf_counter()
                for _ in range(iters):
                    mrt.hash_join(left, right, spec)
                dev_wall = time.perf_counter() - t0
            assert not st.counters.get(qstats.JOIN_SERVED_HOST_TIER), \
                f"device join degraded to host at card={card}"
            host = mrt.hash_join_host(left, right, spec)
            assert mrt._block_rows(host) == probe_rows
            host_iters = max(1, iters - 1)
            t0 = time.perf_counter()
            for _ in range(host_iters):
                mrt.hash_join_host(left, right, spec)
            host_wall = time.perf_counter() - t0
            total = probe_rows + card
            dev_rate = total * iters / dev_wall
            host_rate = total * host_iters / host_wall
            entry = {
                "device_rows_per_sec": round(dev_rate, 1),
                "host_rows_per_sec": round(host_rate, 1),
                "device_vs_host": round(dev_rate / max(host_rate, 1.0), 3),
                "build_ms": round(
                    st.counters.get(qstats.JOIN_BUILD_MS, 0.0) / iters, 3),
                "probe_ms": round(
                    st.counters.get(qstats.JOIN_PROBE_MS, 0.0) / iters, 3),
            }
            # acceptance gate: >= 3x host from 100k build keys up — binding
            # on accelerator backends (see docstring); published + warned on
            # a CPU-hosted "device"
            if card >= 100_000:
                entry["gate_3x"] = entry["device_vs_host"] >= 3.0
                if accel:
                    assert entry["gate_3x"], (card, entry)
                elif not entry["gate_3x"]:
                    print(f"WARNING: join device_vs_host "
                          f"{entry['device_vs_host']} < 3.0 at card={card} "
                          "(cpu-hosted device backend)", file=sys.stderr)
            # -- 3) broadcast-vs-partitioned crossover on the same shapes --
            est = mrt._block_nbytes(right)
            strategy = choose_join_strategy("inner", est)
            entry["est_build_bytes"] = int(est)
            entry["strategy"] = strategy
            for tag in ("broadcast", "partitioned"):
                exchange_wall(left, right, tag)           # warm jit shapes
                wall, nbytes, rows = exchange_wall(left, right, tag)
                assert rows == probe_rows, (card, tag, rows)
                entry[f"{tag}_exchange_bytes"] = nbytes
                entry[f"{tag}_exchange_join_ms"] = round(wall * 1000, 3)
            faster = ("broadcast" if entry["broadcast_exchange_join_ms"]
                      <= entry["partitioned_exchange_join_ms"]
                      else "partitioned")
            # the chooser must not replicate a build side that measures
            # slower by more than timing jitter (20%)
            if strategy != faster and (
                    entry[f"{strategy}_exchange_join_ms"]
                    > 1.2 * entry[f"{faster}_exchange_join_ms"]):
                print(f"WARNING: join strategy {strategy} measured "
                      f"{entry[f'{strategy}_exchange_join_ms']}ms vs "
                      f"{faster} {entry[f'{faster}_exchange_join_ms']}ms "
                      f"at card={card}", file=sys.stderr)
            out["join_cards"][str(card)] = entry

        out["join_broadcast_crossover_build_rows"] = (
            BROADCAST_MAX_BYTES_DEFAULT // 16)  # 2 int64/f64 cols = 16B/row

        # -- 2) zipf probe-key skew sweep at the middle cardinality --------
        card = JOIN_BUILD_CARDS[min(1, len(JOIN_BUILD_CARDS) - 1)]
        right = {"rk": np.arange(card, dtype=np.int64),
                 "w": rng.uniform(0.0, 10.0, card)}
        uniform_rate = None
        for s in (None, 1.1, 1.5):
            lk = _zipf_probe(rng, probe_rows, card, s)
            left = {"lk": lk, "v": rng.uniform(0.0, 10.0, probe_rows)}
            mrt.hash_join(left, right, spec)              # warm
            with qstats.collect_stats() as st:
                t0 = time.perf_counter()
                for _ in range(iters):
                    dev = mrt.hash_join(left, right, spec)
                wall = time.perf_counter() - t0
            assert mrt._block_rows(dev) == probe_rows
            rate = (probe_rows + card) * iters / wall
            skew = float(st.counters.get(qstats.JOIN_SKEW_PCT, 0.0))
            tag = "uniform" if s is None else f"zipf_{s}"
            out["join_skew"][tag] = {
                "device_rows_per_sec": round(rate, 1),
                "join_skew_pct": round(skew, 1),
            }
            if s is None:
                uniform_rate = rate
            else:
                out["join_skew"][tag]["vs_uniform"] = round(
                    rate / max(uniform_rate, 1.0), 3)
            if s == 1.5:
                # acceptance gates: the histogram must actually detect the
                # hot keys, and salting must hold the skewed probe within
                # 2x of the uniform rate
                assert skew > 0.0, out["join_skew"]
                assert rate >= 0.5 * uniform_rate, out["join_skew"]
    finally:
        mrt.configure_device_join(**saved)
    return out


# --------------------------------------------------------------------------
# multichip scaling lane: scan + high-card group-by + shuffle exchange at
# 1/2/4/8 devices (virtual CPU devices when no real mesh is attached)
# --------------------------------------------------------------------------

MULTICHIP_DEVICES = tuple(
    int(x) for x in os.environ.get("PINOT_BENCH_MULTICHIP_DEVICES",
                                   "1,2,4,8").split(","))
MULTICHIP_ROWS = int(os.environ.get("PINOT_BENCH_MULTICHIP_ROWS",
                                    1024 * 1024))
MULTICHIP_ITERS = int(os.environ.get("PINOT_BENCH_MULTICHIP_ITERS", 3))

_COUNTER_INVARIANT_KEYS = ("deviceLaunches", "stackedLaunches",
                           "numDocsScanned")


def _clone_partial(leaf):
    """Fresh copy of a leaf group-by partial: partition_groups_stable
    materializes (destroys) the dense form in place, so each timed exchange
    iteration must start from an intact partial."""
    from pinot_tpu.query.reduce import DensePartial, SegmentResult
    out = SegmentResult("groups", num_docs_scanned=leaf.num_docs_scanned)
    if leaf.dense is not None:
        dp = leaf.dense
        out.dense = DensePartial(dp.token, dp.cards, dp.strides,
                                 dp.num_keys_real,
                                 dp.counts.astype(np.int64, copy=True),
                                 {k: v.copy() for k, v in dp.outs.items()},
                                 dp.group_values, aggs=dp.aggs)
    else:
        out.groups = {k: list(v) for k, v in leaf.groups.items()}
    return out


def _multichip_shuffle_rate(mesh_exec, segments, n: int, iters: int):
    """Leaf->reduce exchange rate at P=n partitions, through the REAL
    in-process mailbox fabric (shuffle.py): partition the leaf partial,
    deliver each partition to its reduce mailbox, consume, merge. The leaf
    partial is the mesh's own server-level dispatch (a DensePartial for this
    high-card shape). At P=1 — the partition count the device-routed
    coordinator collapses to when every stage worker is local — the
    array-form partial must survive the exchange intact (zero host-side
    value merges)."""
    from pinot_tpu.multistage.shuffle import (_deliver_local, consume_mailbox,
                                              partition_groups_stable)
    from pinot_tpu.query.aggregates import make_agg
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.reduce import merge_segment_results

    ctx = compile_query(HIGH_CARD_QUERY, segments[0].schema)
    aggs = [make_agg(f) for f in ctx.aggregations]
    disp = mesh_exec.dispatch_partial(ctx, segments)
    assert disp is not None, "high-card leaf did not plan on the mesh"
    outs_dev, decode = disp
    leaf = decode(mesh_exec.fetch([outs_dev])[0])
    rows = leaf.num_docs_scanned
    dense_in = leaf.dense is not None

    def exchange(tag: str):
        src = _clone_partial(leaf)
        parts = partition_groups_stable(src, n)
        qid = f"mcbench_{tag}"
        for i, part in enumerate(parts):
            _deliver_local(qid, f"A.{i}", part, "partial", "s0")
        got = []
        for i in range(n):
            _, partials = consume_mailbox(qid, f"A.{i}", 1)
            got.extend(partials)
        return merge_segment_results(got, aggs)

    merged = exchange("warm")
    t0 = time.perf_counter()
    for it in range(iters):
        exchange(str(it))
    dt = time.perf_counter() - t0
    return (rows * iters / dt,
            dense_in and n == 1 and merged.dense is not None)


def _multichip_child(n: int) -> None:
    """One device-count point of the scaling lane (re-exec'd with
    xla_force_host_platform_device_count=n when no real mesh is attached).
    Prints ONE JSON line consumed by run_multichip_lane."""
    import jax
    assert len(jax.devices()) == n, \
        f"child sees {len(jax.devices())} devices, wanted {n}"

    from pinot_tpu.parallel import MeshQueryExecutor, default_mesh
    from pinot_tpu.query import stats as qstats

    schema = ssb_schema()
    rows = MULTICHIP_ROWS
    segments = build_or_load_segments(
        schema, make_columns(rows), rows=rows,
        tag=f"mc_r{rows}_s{SEGMENTS}_v1")
    mesh_exec = MeshQueryExecutor(default_mesh(n))

    shapes = {"scan": QUERY, "high_card_groupby": HIGH_CARD_QUERY}
    rates, counters = {}, {}
    for name, q in shapes.items():
        mesh_exec.execute(segments, q)   # transfer + compile warmup
        mesh_exec.execute(segments, q)
        with qstats.collect_stats() as st:
            res = mesh_exec.execute(segments, q)
        merged = dict(res.stats or {})
        merged.update(st.counters)
        counters[name] = {
            k: int(merged.get(k, 0)) for k in _COUNTER_INVARIANT_KEYS}
        counters[name]["bytesFetched"] = int(
            st.counters.get(qstats.BYTES_FETCHED, 0))
        counters[name]["collectiveMs"] = round(
            float(st.counters.get(qstats.COLLECTIVE_MS, 0.0)), 3)
        counters[name]["deviceSkewPct"] = round(
            float(st.counters.get(qstats.DEVICE_SKEW_PCT, 0.0)), 3)
        t0 = time.perf_counter()
        mesh_exec.execute_many(segments, [q] * MULTICHIP_ITERS)
        rates[name] = rows * MULTICHIP_ITERS / (time.perf_counter() - t0)

    shuffle_rate, dense_preserved = _multichip_shuffle_rate(
        mesh_exec, segments, n, MULTICHIP_ITERS)
    rates["shuffle_exchange"] = shuffle_rate
    print(json.dumps({
        "devices": n,
        "rows": rows,
        "rates_rows_per_sec": {k: round(v, 1) for k, v in rates.items()},
        "counters": counters,
        "shuffle_dense_preserved": dense_preserved,
    }))


def run_multichip_lane(devices=MULTICHIP_DEVICES) -> dict:
    """Benched 1->8 device lane: re-exec one child per device count (the
    scrubbed-env trick from __graft_entry__.dryrun_multichip / conftest.py),
    collect per-shape rows/s, and compute scaling_efficiency = rate_n /
    (n * rate_1) per shape. Asserts the mesh path stays launch-invariant:
    deviceLaunches / docs-scanned counters must not grow with device count
    (the zero-host-side-value-merge criterion — more chips must NOT mean more
    launches or host merges), and the P-collapsed exchange must preserve the
    dense partial. On a host without n physical cores the EFFICIENCY is
    core-bound (virtual devices time-share the host); the launch counters and
    differential answers are exact regardless, so `host_cpu_cores` is
    published next to the rates."""
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    per_dev = {}
    for n in devices:
        env = dict(os.environ)
        xla = [f for f in env.get("XLA_FLAGS", "").split()
               if "xla_force_host_platform_device_count" not in f]
        env.update({
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",   # sitecustomize no-ops without this
            "PYTHONPATH": os.pathsep.join(
                [here] + [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
                          if p and "axon_site" not in p]),
            "XLA_FLAGS": " ".join(
                xla + [f"--xla_force_host_platform_device_count={n}"]),
        })
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "bench.py"),
             "--multichip-child", str(n)],
            env=env, cwd=here, capture_output=True, text=True, timeout=1200)
        assert proc.returncode == 0, \
            (f"multichip child n={n} failed (rc={proc.returncode}):\n"
             f"{proc.stderr[-2000:]}")
        line = [ln for ln in proc.stdout.splitlines() if ln.strip()][-1]
        per_dev[n] = json.loads(line)

    base = per_dev[devices[0]]
    shapes = list(base["rates_rows_per_sec"])
    rates = {s: {str(n): per_dev[n]["rates_rows_per_sec"][s]
                 for n in devices} for s in shapes}
    eff = {s: {str(n): round(
        per_dev[n]["rates_rows_per_sec"][s]
        / (n * base["rates_rows_per_sec"][s]), 3) for n in devices}
        for s in shapes}
    speedup = {s: round(per_dev[devices[-1]]["rates_rows_per_sec"][s]
                        / base["rates_rows_per_sec"][s], 3) for s in shapes}

    # launch-count invariance: the mesh path must answer every device count
    # with the SAME launches and scanned docs — scaling chips must never
    # reintroduce per-segment fetches or host-side partial merges
    for shape in base["counters"]:
        for key in _COUNTER_INVARIANT_KEYS:
            vals = {n: per_dev[n]["counters"][shape][key] for n in devices}
            assert len(set(vals.values())) == 1, \
                f"{shape}.{key} varies with device count: {vals}"
        b0 = base["counters"][shape]["bytesFetched"]
        for n in devices:
            bn = per_dev[n]["counters"][shape]["bytesFetched"]
            # scattered outputs drop the replicated overflow row, so fetched
            # bytes may shrink slightly — they must never grow with devices
            assert bn <= b0 * 1.05, \
                f"{shape}.bytesFetched grew with devices: {bn} vs {b0}"
    assert per_dev[devices[0]]["shuffle_dense_preserved"], \
        "P-collapsed exchange densified the partial (host value merges)"

    detail = {
        "rows": base["rows"],
        "device_counts": list(devices),
        "rates_rows_per_sec": rates,
        "scaling_efficiency": eff,
        "speedup_at_max_devices": speedup,
        "counters": {n: per_dev[n]["counters"] for n in devices},
        "counter_invariance": True,
        "shuffle_dense_preserved_p1": True,
        # virtual CPU devices time-share this many physical cores: wall-clock
        # speedup is core-bound here; launch invariance + answers are exact
        "host_cpu_cores": os.cpu_count(),
        "backend": "cpu_virtual_devices",
    }
    out = {
        "metric": "multichip_scaling",
        "value": speedup["high_card_groupby"],
        "unit": f"x_at_{devices[-1]}dev",
        "detail": detail,
    }
    print(json.dumps(out))
    return out


def main():
    schema = ssb_schema()
    cols = make_columns(ROWS)
    segments = build_or_load_segments(schema, cols)
    star_segments = build_or_load_segments(schema, cols, star_tree=True)

    import jax
    from pinot_tpu.parallel import MeshQueryExecutor, default_mesh
    n_dev = len(jax.devices())
    mesh_exec = MeshQueryExecutor(default_mesh(n_dev))

    # warmup: device transfer + jit compile (all device query shapes)
    for q in (QUERY, GROUP_QUERY, HLL_QUERY):
        mesh_exec.execute(segments, q)
        mesh_exec.execute(segments, q)
    mesh_exec.execute(star_segments, STAR_QUERY)

    def p50_latency(q, iters=9, segs=segments):
        lat = []
        for _ in range(iters):
            t0 = time.perf_counter()
            r = mesh_exec.execute(segs, q)
            lat.append(time.perf_counter() - t0)
        return float(np.median(lat)) * 1000, r

    walls = {}  # query -> (wall_s, iters): device-time accounting input

    def pipelined_rate(q, iters=ITERS, segs=segments):
        t0 = time.perf_counter()
        results = mesh_exec.execute_many(segs, [q] * iters)
        dt = time.perf_counter() - t0
        walls[q] = (dt, iters)
        return ROWS * iters / dt, results[-1]

    q11_p50, _ = p50_latency(QUERY)
    q11_rate, res = pipelined_rate(QUERY)
    # second pipelined point at half depth: the slope between the two walls
    # cancels the relay round trip AND its overlap with device execution,
    # which the single-point (wall - floor)/iters estimate cannot — that
    # overlap is what drove scan_pct_of_measured_roofline past 100%
    t0 = time.perf_counter()
    mesh_exec.execute_many(segments, [QUERY] * max(1, ITERS // 2))
    walls_half = {QUERY: (time.perf_counter() - t0, max(1, ITERS // 2))}
    grp_p50, _ = p50_latency(GROUP_QUERY)
    grp_rate, grp_res = pipelined_rate(GROUP_QUERY)
    hll_rate, hll_res = pipelined_rate(HLL_QUERY)
    star_p50, star_res = p50_latency(STAR_QUERY, segs=star_segments)
    star_rate, _ = pipelined_rate(STAR_QUERY, segs=star_segments)

    # r4 configs: grouped HLL, >cap scatter group-by, device theta
    for q in (HLL_GROUP_QUERY, HIGH_CARD_QUERY, THETA_QUERY):
        mesh_exec.execute(segments, q)
        mesh_exec.execute(segments, q)
    hllg_rate, hllg_res = pipelined_rate(HLL_GROUP_QUERY)
    hc_rate, hc_res = pipelined_rate(HIGH_CARD_QUERY, iters=max(4, ITERS // 4))
    theta_rate, theta_res = pipelined_rate(THETA_QUERY)
    mesh_exec.execute(segments, VERY_HIGH_CARD_QUERY)
    vhc_rate, vhc_res = pipelined_rate(VERY_HIGH_CARD_QUERY, iters=3)
    # regime-ladder sweep: 128k/500k/2M groups, every high-card regime forced
    vhc_sweep = very_high_card_sweep(mesh_exec, n_dev)

    # r4: stacked-device star path over a LARGE record table
    star_hc_segments = build_or_load_segments(schema, cols, star_hc=True)
    from pinot_tpu.parallel.combine import StarSetPlan
    from pinot_tpu.query.context import compile_query as _cq
    star_hc_on_device = isinstance(
        mesh_exec._plan_star_device(_cq(STAR_HC_QUERY, schema),
                                    star_hc_segments), StarSetPlan)
    mesh_exec.execute(star_hc_segments, STAR_HC_QUERY)
    mesh_exec.execute(star_hc_segments, STAR_HC_QUERY)
    star_hc_rate, star_hc_res = pipelined_rate(STAR_HC_QUERY,
                                               segs=star_hc_segments)
    # host star path on the same trees, for the device-vs-host comparison
    from pinot_tpu.query.executor import ServerQueryExecutor as _SQE
    host_exec = _SQE(use_device=False)
    host_exec.execute(star_hc_segments, STAR_HC_QUERY)
    t0 = time.perf_counter()
    host_exec.execute(star_hc_segments, STAR_HC_QUERY)
    star_hc_host_rate = ROWS / (time.perf_counter() - t0)

    # single-query latency at serving-sized row counts (1M rows after pruning)
    small_rows = 1024 * 1024
    small_segs = build_or_load_segments(schema, make_columns(small_rows),
                                        rows=small_rows,
                                        tag=f"r{small_rows}_s{SEGMENTS}_v1")
    mesh_exec.execute(small_segs, QUERY)
    mesh_exec.execute(small_segs, QUERY)
    p50_1m, _ = p50_latency(QUERY, segs=small_segs)
    floor_ms = relay_floor_ms()
    wire_gbps = wire_codec_bench()

    np_rows_per_sec, np_result = numpy_baseline(cols)
    ours = res.rows[0][0]
    if abs(ours - np_result) > 2e-3 * max(1.0, abs(np_result)):
        print(f"WARNING: result mismatch tpu={ours} numpy={np_result}", file=sys.stderr)

    # differential checks for the secondary configs (numpy ground truth)
    gmask = ((cols["lo_discount"] >= 1) & (cols["lo_discount"] <= 3)
             & (cols["lo_quantity"] < 25))
    for region, got_sum, got_cnt in grp_res.rows:
        m = gmask & (cols["lo_region"] == region)
        want = float(np.sum(cols["lo_revenue"][m]))
        if int(m.sum()) != got_cnt or abs(got_sum - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: group mismatch {region}: tpu=({got_sum},{got_cnt}) "
                  f"numpy=({want},{int(m.sum())})", file=sys.stderr)
    exact = len(np.unique(cols["lo_orderdate"][cols["lo_quantity"] < 25]))
    if abs(hll_res.rows[0][0] - exact) > 0.05 * exact:
        print(f"WARNING: HLL estimate {hll_res.rows[0][0]} vs exact {exact}",
              file=sys.stderr)
    if abs(theta_res.rows[0][0] - exact) > 0.05 * exact:
        print(f"WARNING: theta estimate {theta_res.rows[0][0]} vs {exact}",
              file=sys.stderr)
    # grouped-HLL differential: per-region exact distinct within theta/HLL error
    qmask = cols["lo_quantity"] < 25
    for region, got_cnt, got_sum, got_hll in hllg_res.rows:
        m = qmask & (cols["lo_region"] == region)
        want_d = len(np.unique(cols["lo_orderdate"][m]))
        if int(m.sum()) != got_cnt or abs(got_hll - want_d) > 0.05 * want_d:
            print(f"WARNING: hll-groupby mismatch {region}: "
                  f"cnt {got_cnt}/{int(m.sum())} hll {got_hll}/{want_d}",
                  file=sys.stderr)
    # high-card group-by differential: group count + sampled sums + count total
    hc_groups = {r[0]: (r[1], r[2]) for r in hc_res.rows}
    if len(hc_groups) != len(np.unique(cols["lo_suppkey"])):
        print(f"WARNING: high-card group count {len(hc_groups)}", file=sys.stderr)
    if sum(c for _, c in hc_groups.values()) != ROWS:
        print("WARNING: high-card counts do not sum to ROWS", file=sys.stderr)
    for sk in (0, 777, HIGH_CARD_SUPPKEYS - 1):
        m = cols["lo_suppkey"] == sk
        want = float(np.sum(cols["lo_revenue"][m]))
        got = hc_groups.get(sk, (0.0, 0))
        if got[1] != int(m.sum()) or abs(got[0] - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: high-card mismatch suppkey={sk}: {got} vs "
                  f"({want},{int(m.sum())})", file=sys.stderr)
    # 500k-key differential: group count + sampled sums
    vhc_groups = {r[0]: (r[1], r[2]) for r in vhc_res.rows}
    if len(vhc_groups) != len(np.unique(cols["lo_custkey"])):
        print(f"WARNING: 500k group count {len(vhc_groups)}", file=sys.stderr)
    if sum(c for _, c in vhc_groups.values()) != ROWS:
        print("WARNING: 500k counts do not sum to ROWS", file=sys.stderr)
    for ck in (0, 123_457, VERY_HIGH_CARD_KEYS - 1):
        m = cols["lo_custkey"] == ck
        want = float(np.sum(cols["lo_revenue"][m]))
        got = vhc_groups.get(ck, (0.0, 0))
        if got[1] != int(m.sum()) or abs(got[0] - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: 500k mismatch custkey={ck}: {got} vs "
                  f"({want},{int(m.sum())})", file=sys.stderr)
    # stacked-device star differential: sampled dates vs raw columns
    dmask = (cols["lo_discount"] >= 1) & (cols["lo_discount"] <= 3)
    star_hc_groups = {r[0]: r[1] for r in star_hc_res.rows}
    dates = np.unique(cols["lo_orderdate"])
    for d in (dates[0], dates[len(dates) // 2], dates[-1]):
        want = float(np.sum(cols["lo_revenue"][dmask
                                               & (cols["lo_orderdate"] == d)]))
        got = star_hc_groups.get(int(d), 0.0)
        if abs(got - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: star-hc mismatch {d}: {got} vs {want}",
                  file=sys.stderr)

    # realtime ingest + end-to-end serving stack: the JSON per-row lane, the
    # vectorized PCB1 block lane, and the 8-partition threaded pump lanes
    ingest_rate, ingest_np_rate = ingest_bench()
    ingest_vec_rate = ingest_vectorized_bench()
    ingest_agg_rate = ingest_multi_bench()
    e2e_qps, e2e_p50, e2e_qps_sampled = e2e_bench(measure_sampled=True)
    # device-backed serving (VERDICT r4 #1): same 100k-row data as the CPU
    # e2e for the stack-for-stack comparison, then a 4M-row head-to-head
    # where the engines (not the HTTP stack) dominate
    e2e_dev_qps, e2e_dev_p50, dev_stats, dev_loaded_100k = \
        e2e_device_bench(100_000)
    e2e_dev_qps_4m, e2e_dev_p50_4m, dev_stats_4m, dev_loaded_4m = \
        e2e_device_bench(4 * 1024 * 1024)
    e2e_cpu_qps_4m, e2e_cpu_p50_4m = e2e_bench(rows=4 * 1024 * 1024)
    # theta numpy baseline: filter + bulk sketch build, both timed — the
    # device query it is compared against pays for the filter too
    from pinot_tpu.query.sketches import ThetaSketch
    t0 = time.perf_counter()
    ThetaSketch.from_values(
        cols["lo_orderdate"][cols["lo_quantity"] < 25])
    theta_np_rate = ROWS / (time.perf_counter() - t0)
    # star-tree differential: same group-by truth, filter lo_discount in [1,3]
    smask = (cols["lo_discount"] >= 1) & (cols["lo_discount"] <= 3)
    for region, got_sum in star_res.rows:
        want = float(np.sum(cols["lo_revenue"][smask & (cols["lo_region"] == region)]))
        if abs(got_sum - want) > 2e-3 * max(1.0, abs(want)):
            print(f"WARNING: star-tree mismatch {region}: {got_sum} vs {want}",
                  file=sys.stderr)

    # per-config device time: pipelined wall = one relay round trip + the
    # serialized device executions -> device_time ~= (wall - floor) / iters.
    # Host-side dispatch/decode for the batch overlaps poorly on the relay,
    # so this is an UPPER bound on pure device time.
    def dev_ms(q):
        wall, iters = walls[q]
        return max(0.0, (wall - floor_ms / 1000) / iters) * 1000

    def dev_ms_slope(q):
        """Per-iteration device time from the two-depth slope: constant
        costs (round trip, dispatch warmup) cancel, so unlike dev_ms this
        cannot under-count when the round trip overlaps execution."""
        w1, n1 = walls[q]
        w2, n2 = walls_half[q]
        if n1 == n2:
            return dev_ms(q)
        return max(0.0, (w1 - w2) / (n1 - n2)) * 1000

    cal = platform_calibration()
    # scan roofline: Q1.1 touches 4 f32/i32 columns (orderdate ids, decoded
    # discount, quantity, extendedprice) = 16B/row of mandatory traffic —
    # the SAME 16B/row the calibration's fused_scan_gbps denominator counts
    scan_bytes = 16 * ROWS
    scan_dev_ms = dev_ms_slope(QUERY)
    scan_gbps = scan_bytes / max(scan_dev_ms, 1e-6) * 1e-6
    scan_pct = 100 * scan_gbps / cal["fused_scan_gbps"]
    # cap-check: a scan cannot beat the measured streaming ceiling on the
    # same device by more than timing jitter; >110% means the accounting
    # broke again (mismatched bytes/row or under-counted device time)
    scan_consistent = scan_pct <= 110.0
    if not scan_consistent:
        print(f"WARNING: scan roofline accounting inconsistent: "
              f"{scan_pct:.1f}% of measured ceiling", file=sys.stderr)
    detail = {
            "rows": ROWS, "segments": SEGMENTS, "devices": n_dev,
            "pipeline_depth": ITERS,
            "p50_query_latency_ms": round(q11_p50, 3),
            "p50_query_latency_1m_rows_ms": round(p50_1m, 3),
            "relay_roundtrip_floor_ms": round(floor_ms, 3),
            **wire_gbps,
            "platform_calibration": cal,
            "scan_device_time_ms": round(scan_dev_ms, 3),
            "scan_effective_gbps": round(scan_gbps, 1),
            "scan_pct_of_measured_roofline": round(scan_pct, 1),
            "scan_roofline_consistent": scan_consistent,
            "scan_pct_of_nominal_hbm": round(
                100 * scan_gbps / cal["nominal_hbm_gbps"], 1),
            "groupby_rows_per_sec": round(grp_rate / n_dev, 1),
            "groupby_p50_latency_ms": round(grp_p50, 3),
            "groupby_device_time_ms": round(dev_ms(GROUP_QUERY), 3),
            "hll_rows_per_sec": round(hll_rate / n_dev, 1),
            "hll_vs_numpy": round(hll_rate / n_dev / np_rows_per_sec, 3),
            "hll_groupby_rows_per_sec": round(hllg_rate / n_dev, 1),
            "hll_groupby_device_time_ms": round(dev_ms(HLL_GROUP_QUERY), 3),
            "high_card_groupby_rows_per_sec": round(hc_rate / n_dev, 1),
            "high_card_groupby_device_time_ms": round(
                dev_ms(HIGH_CARD_QUERY), 3),
            "high_card_groups": len(hc_groups),
            "very_high_card_groupby_rows_per_sec": round(vhc_rate / n_dev, 1),
            "very_high_card_groups": len(vhc_groups),
            "very_high_card_regime": _caps_mod.get_caps().high_card_regime,
            "very_high_card_sweep": vhc_sweep,
            "theta_rows_per_sec": round(theta_rate / n_dev, 1),
            "theta_vs_numpy": round(theta_rate / n_dev / theta_np_rate, 3),
            "startree_rows_per_sec": round(star_rate / n_dev, 1),
            "startree_p50_latency_ms": round(star_p50, 3),
            "startree_device_rows_per_sec": round(star_hc_rate / n_dev, 1),
            "startree_device_on_device": star_hc_on_device,
            "startree_device_vs_host": round(star_hc_rate / n_dev
                                             / max(star_hc_host_rate, 1.0), 3),
            "ingest_rows_per_sec": round(ingest_rate, 1),
            "ingest_vectorized_rows_per_sec": round(ingest_vec_rate, 1),
            # the headline ratio tracks the HOT lane (vectorized blocks);
            # the JSON per-row lane keeps its own ratio below
            "ingest_vs_numpy_append": round(ingest_vec_rate / ingest_np_rate,
                                            3),
            "ingest_json_vs_numpy_append": round(ingest_rate / ingest_np_rate,
                                                 3),
            "ingest_aggregate_rows_per_sec_8p": round(ingest_agg_rate, 1),
            # aggregate/single for the vectorized lane: 8 threaded pump
            # lanes time-share this host's single CPU core, so the ideal
            # here is 1.0 (no regression), not 8.0
            "ingest_partition_scaling_efficiency": round(
                ingest_agg_rate / ingest_vec_rate, 3),
            "host_cpu_cores": os.cpu_count(),
            "e2e_qps": round(e2e_qps, 1),
            "e2e_p50_ms": round(e2e_p50, 3),
            # same loop re-run at broker.trace.sample.rate=0.01: the always-on
            # tracing acceptance gate (sampled qps within 2% of unsampled)
            "e2e_qps_sampled": round(e2e_qps_sampled, 1),
            "trace_sample_overhead_pct": round(
                (1.0 - e2e_qps_sampled / e2e_qps) * 100.0, 2)
            if e2e_qps else None,
            "e2e_qps_device": round(e2e_dev_qps, 1)
            if dev_loaded_100k == 100_000 else None,
            "e2e_p50_device_ms": round(e2e_dev_p50, 3)
            if dev_loaded_100k == 100_000 else None,
            "e2e_device_loaded_rows": dev_loaded_100k,
            "e2e_p50_device_1client_ms": dev_stats.get("soloP50Ms"),
            "e2e_device_mean_batch": dev_stats.get("meanBatch", 0.0),
            # per-stage pipeline attribution (queue wait vs device dispatch
            # vs relay fetch vs host decode): where the relay floor actually
            # lands, in every future BENCH_*.json
            "e2e_device_pipeline_stage_ms": dev_stats.get("stageMs"),
            "e2e_device_launches": dev_stats.get("launches", 0),
            "e2e_device_dedupe_hits": dev_stats.get("dedupeHits", 0),
            "e2e_device_stacked_launches": dev_stats.get("stackedLaunches",
                                                         0),
            # guarded: a partially-loaded table would fake a huge QPS over
            # empty answers — emit null instead of a lie
            "e2e_qps_device_4m": round(e2e_dev_qps_4m, 1)
            if dev_loaded_4m == 4 * 1024 * 1024 else None,
            "e2e_p50_device_4m_ms": round(e2e_dev_p50_4m, 3)
            if dev_loaded_4m == 4 * 1024 * 1024 else None,
            "e2e_device_4m_loaded_rows": dev_loaded_4m,
            "e2e_device_4m_mean_batch": dev_stats_4m.get("meanBatch", 0.0),
            "e2e_device_4m_pipeline_stage_ms": dev_stats_4m.get("stageMs"),
            "e2e_qps_cpu_4m": round(e2e_cpu_qps_4m, 1),
            "e2e_p50_cpu_4m_ms": round(e2e_cpu_p50_4m, 3),
            "numpy_single_thread_rows_per_sec": round(np_rows_per_sec, 1),
            # vs_baseline divides by the numpy single-thread proxy: no JVM
            # exists in this image, so the reference Java engine cannot run
            # here (BASELINE.md) — the denominator is labeled, not implied
            "baseline_kind": "numpy_single_thread_proxy",
            "backend": jax.default_backend(),
    }
    detail.update(fused_bench())
    detail.update(join_bench())
    detail.update(chaos_bench())
    detail.update(pruning_bench())
    detail.update(soak_bench())
    detail.update(memory_bench())
    detail.update(tiering_bench())
    detail.update(events_bench())
    _update_baseline_published(detail, round(q11_rate / n_dev, 1))
    print(json.dumps({
        "metric": "ssb_q1.1_filter_agg_scan_rate",
        "value": round(q11_rate / n_dev, 1),
        "unit": "rows/s/chip",
        "vs_baseline": round(q11_rate / n_dev / np_rows_per_sec, 3),
        "detail": detail,
    }))


def _update_baseline_published(detail, headline_rate) -> None:
    """Record the measured proxy numbers per BASELINE config (VERDICT r4 #7:
    the vs_baseline denominator must be auditable)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            base = json.load(f)
        base["published"] = {
            "baseline_kind": "numpy_single_thread_proxy",
            "note": ("no JVM in this image: the reference Java engine cannot "
                     "run here, so configs are measured against a "
                     "single-thread vectorized numpy evaluation of the same "
                     "queries (BASELINE.md)"),
            "config1_ssb_q11_numpy_rows_per_sec":
                detail["numpy_single_thread_rows_per_sec"],
            "config1_ssb_q11_tpu_rows_per_sec_chip": headline_rate,
            "config5_high_card_tpu_rows_per_sec":
                detail["high_card_groupby_rows_per_sec"],
            "config5_hll_groupby_tpu_rows_per_sec":
                detail["hll_groupby_rows_per_sec"],
            "platform_calibration": detail["platform_calibration"],
        }
        with open(path, "w") as f:
            json.dump(base, f, indent=2)
    except Exception as e:  # never fail the bench over bookkeeping
        print(f"WARNING: BASELINE.json update failed: {e}", file=sys.stderr)


if __name__ == "__main__":
    if "--multichip-child" in sys.argv:
        _multichip_child(int(sys.argv[sys.argv.index("--multichip-child") + 1]))
    elif "--multichip" in sys.argv:
        run_multichip_lane()
    elif "--chaos" in sys.argv:
        print(json.dumps(chaos_bench(), indent=2))
    elif "--pruning" in sys.argv:
        print(json.dumps(pruning_bench(), indent=2))
    elif "--soak" in sys.argv:
        print(json.dumps(soak_bench(), indent=2))
    elif "--memory" in sys.argv:
        print(json.dumps(memory_bench(), indent=2))
    elif "--tiering" in sys.argv:
        print(json.dumps(tiering_bench(), indent=2))
    elif "--workload" in sys.argv:
        print(json.dumps(workload_bench(), indent=2))
    elif "--events" in sys.argv:
        print(json.dumps(events_bench(), indent=2))
    elif "--fused" in sys.argv:
        print(json.dumps(fused_bench(), indent=2))
    elif "--join" in sys.argv:
        print(json.dumps(join_bench(), indent=2))
    else:
        main()
