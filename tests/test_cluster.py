"""Cluster control-plane tests: upload->assign->load->route->query, replication,
failure handling, retention, rebalance.

Reference pattern: OfflineClusterIntegrationTest + ControllerTest suites (SURVEY.md §4.4)
run in one process via the enclosure.
"""

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.cluster.catalog import ONLINE
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import SegmentPartitionConfig, TableConfig

from conftest import make_ssb_columns


@pytest.fixture()
def cluster(tmp_path):
    return QuickCluster(num_servers=3, work_dir=str(tmp_path))


@pytest.fixture()
def lineorder_cluster(cluster, ssb_schema):
    rng = np.random.default_rng(5)
    cfg = TableConfig(ssb_schema.name, replication=2, time_column="lo_orderdate")
    cluster.create_table(ssb_schema, cfg)
    for i in range(4):
        cluster.ingest_columns(cfg, make_ssb_columns(rng, 1000))
    return cluster, cfg


def test_upload_assign_load_query(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    # ideal state has 4 segments x 2 replicas over 3 servers
    ist = cluster.catalog.ideal_state[table]
    assert len(ist) == 4
    assert all(len(a) == 2 for a in ist.values())
    # external view converged
    status = cluster.controller.table_status(table)
    assert status["converged"], status
    # queries work through the broker
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 4000
    assert res.stats["numServersResponded"] == res.stats["numServersQueried"]


def test_group_by_through_broker(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    res = cluster.query("SELECT lo_region, COUNT(*) FROM lineorder "
                        "GROUP BY lo_region ORDER BY lo_region LIMIT 10")
    assert sum(r[1] for r in res.rows) == 4000
    assert [r[0] for r in res.rows] == sorted(r[0] for r in res.rows)


def test_replica_failover(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    cluster.kill_server("server_0")
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    # replication=2: every segment still has a live replica
    assert res.rows[0][0] == 4000
    assert not res.stats["partialResult"]


def test_failed_server_produces_partial_result(lineorder_cluster):
    cluster, cfg = lineorder_cluster

    def broken(table, ctx, segments, time_filter=None):
        raise ConnectionError("boom")

    cluster.broker.register_server_handle("server_1", broken)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    if res.stats["partialResult"]:
        # second attempt routes around the unhealthy server (failure detector)
        res2 = cluster.query("SELECT COUNT(*) FROM lineorder")
        assert res2.rows[0][0] == 4000
        assert not res2.stats["partialResult"]


def test_segment_deletion(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    seg = next(iter(cluster.catalog.segments[table]))
    meta = cluster.catalog.segments[table][seg]
    assert cluster.deepstore.exists(meta.download_path)
    cluster.controller.delete_segment(table, seg)
    assert seg not in cluster.catalog.ideal_state[table]
    assert not cluster.deepstore.exists(meta.download_path)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 3000


def test_retention(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    cfg.retention_days = 1.0
    table = cfg.table_name_with_type
    # pretend every segment's data ended 2 days ago (time units: the table's raw time
    # values; retention compares in the same unit scaled to ms here)
    now_ms = 10_000_000
    for meta in cluster.catalog.segments[table].values():
        meta.end_time_ms = now_ms - 2 * 24 * 3600 * 1000
    deleted = cluster.controller.run_retention(now_ms=now_ms)
    assert len(deleted) == 4
    assert cluster.query("SELECT COUNT(*) FROM lineorder").rows[0][0] == 0


def test_rebalance_after_server_addition(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    from pinot_tpu.cluster.server import ServerNode
    import os
    new_server = ServerNode("server_3", cluster.catalog, cluster.deepstore,
                            os.path.join(cluster.work_dir, "server_3"))
    cluster.broker.register_server_handle("server_3", new_server.execute_partial)
    final = cluster.controller.rebalance(table)
    # the new server picked up work and every segment kept its replica count
    loads = {}
    for seg, assignment in final.items():
        assert len(assignment) == cfg.replication
        for s in assignment:
            loads[s] = loads.get(s, 0) + 1
    assert "server_3" in loads
    assert cluster.query("SELECT COUNT(*) FROM lineorder").rows[0][0] == 4000


def test_partition_pruned_routing(cluster):
    schema = Schema("events", [dimension("user", DataType.STRING),
                               metric("value", DataType.DOUBLE)])
    cfg = TableConfig("events", replication=1,
                      partition=SegmentPartitionConfig("user", "murmur", 4))
    cluster.create_table(schema, cfg)
    from pinot_tpu.cluster.routing import partition_for_value
    # build one segment per partition with matching users
    users = [f"user{i}" for i in range(40)]
    by_partition = {}
    for u in users:
        by_partition.setdefault(partition_for_value(u, "murmur", 4), []).append(u)
    for pid, us in sorted(by_partition.items()):
        cluster.ingest_columns(cfg, {"user": us * 5, "value": np.ones(len(us) * 5)})

    target_user = users[0]
    res = cluster.query(f"SELECT COUNT(*) FROM events WHERE user = '{target_user}'")
    assert res.rows[0][0] == 5
    # routing pruned to exactly the one partition's segment
    from pinot_tpu.query.context import compile_query
    ctx = compile_query(f"SELECT COUNT(*) FROM events WHERE user = '{target_user}'", schema)
    routed = cluster.broker.routing.route_query(cfg.table_name_with_type, ctx)
    assert sum(len(v) for v in routed.values()) == 1


def test_time_pruned_routing(cluster, ssb_schema):
    cfg = TableConfig(ssb_schema.name, replication=1, time_column="lo_orderdate")
    cluster.create_table(ssb_schema, cfg)
    rng = np.random.default_rng(3)
    for year in (1992, 1995):
        cols = make_ssb_columns(rng, 500)
        cols["lo_orderdate"] = (np.full(500, year * 10000 + 601)).astype(np.int32)
        cluster.ingest_columns(cfg, cols)
    from pinot_tpu.query.context import compile_query
    ctx = compile_query("SELECT COUNT(*) FROM lineorder "
                        "WHERE lo_orderdate BETWEEN 19950101 AND 19951231", ssb_schema)
    routed = cluster.broker.routing.route_query(cfg.table_name_with_type, ctx)
    assert sum(len(v) for v in routed.values()) == 1
    res = cluster.query("SELECT COUNT(*) FROM lineorder "
                        "WHERE lo_orderdate BETWEEN 19950101 AND 19951231")
    assert res.rows[0][0] == 500


def test_catalog_snapshot_restore(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    blob = cluster.catalog.snapshot()
    from pinot_tpu.cluster.catalog import Catalog
    fresh = Catalog()
    fresh.restore(blob)
    table = cfg.table_name_with_type
    assert set(fresh.segments[table]) == set(cluster.catalog.segments[table])
    assert fresh.ideal_state[table] == cluster.catalog.ideal_state[table]
    assert fresh.table_configs[table].replication == 2


def test_deleted_segment_parks_then_reaped(lineorder_cluster):
    """Reference: SegmentDeletionManager — deleted segments park under
    Deleted_Segments/ in the deep store and are reaped after retention."""
    import time as _t
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    seg = next(iter(cluster.catalog.ideal_state[table]))
    uri = cluster.catalog.segments[table][seg].download_path
    assert cluster.deepstore.exists(uri)

    cluster.controller.delete_segment(table, seg)
    parked = f"Deleted_Segments/{table}/{seg}.tar.gz"
    assert not cluster.deepstore.exists(uri)
    assert cluster.deepstore.exists(parked)
    note = cluster.catalog.get_property(f"deleted/{table}/{seg}")
    assert note and note["uri"] == parked

    # within retention: reaper leaves it
    cluster.controller.run_retention()
    assert cluster.deepstore.exists(parked)
    # past retention: reaped
    future = int(_t.time() * 1000) + 8 * 86_400_000
    out = cluster.controller.run_retention(now_ms=future)
    assert any(x == f"reaped:{parked}" for x in out), out
    assert not cluster.deepstore.exists(parked)
    assert cluster.catalog.get_property(f"deleted/{table}/{seg}") is None


def test_replica_group_selector_routes_one_replica_ordinal(tmp_path, ssb_schema):
    """replicaGroup/strictReplicaGroup: every segment of one query is served
    from the same replica ordinal (reference: ReplicaGroupInstanceSelector);
    upsert tables get strict routing automatically for valid-doc consistency."""
    from pinot_tpu.table import UpsertConfig
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    rng = np.random.default_rng(8)
    cfg = TableConfig(ssb_schema.name, replication=2,
                      routing_selector="replicaGroup")
    cluster.create_table(ssb_schema, cfg)
    for _ in range(4):
        cluster.ingest_columns(cfg, make_ssb_columns(rng, 200))

    rm = cluster.broker.routing
    for _ in range(6):
        plan = rm.route_query(cfg.table_name_with_type)
        # all four segments land on exactly one server per query
        assert len(plan) == 1, plan
        assert sum(len(v) for v in plan.values()) == 4

    # balanced (default) spreads segments across both servers
    cfg2 = TableConfig("spread", replication=2)
    schema2 = Schema("spread", list(ssb_schema.fields))
    cluster.create_table(schema2, cfg2)
    for _ in range(4):
        cluster.ingest_columns(cfg2, make_ssb_columns(rng, 100))
    seen = set()
    for _ in range(6):
        seen |= set(rm.route_query(cfg2.table_name_with_type))
    assert len(seen) == 2

    # upsert tables default to strict-replica-group behavior
    cfg3 = TableConfig("ups", replication=2, upsert=UpsertConfig())
    schema3 = Schema("ups", list(ssb_schema.fields), ["lo_orderkey"])
    assert cfg3.routing_selector == ""
    cluster.create_table(schema3, cfg3)
    cluster.ingest_columns(cfg3, make_ssb_columns(rng, 50))
    cluster.ingest_columns(cfg3, make_ssb_columns(rng, 50))
    for _ in range(4):
        plan = rm.route_query(cfg3.table_name_with_type)
        assert len(plan) == 1, plan


def test_group_selector_equal_candidate_sets_always_colocate():
    """The strict guarantee: segments with IDENTICAL candidate sets pick the
    same server on every rotation (per-segment modulo over different list
    lengths would scatter them — the upsert double-count hole)."""
    from pinot_tpu.cluster.routing import RoutingTable
    rt = RoutingTable("t")
    rt.segment_servers = {"a": ["s0", "s1"], "b": ["s0", "s1"], "c": ["s0", "s1"],
                          "d": ["s1", "s2"]}
    seen = set()
    for _ in range(7):
        plan = rt.route(selector="strictReplicaGroup")
        by_seg = {seg: srv for srv, segs in plan.items() for seg in segs}
        assert by_seg["a"] == by_seg["b"] == by_seg["c"]
        seen.add(by_seg["a"])
    assert len(seen) > 1  # rotation still spreads load across queries

    import pytest as _p
    with _p.raises(ValueError):
        rt.route(selector="bogus")


def test_unknown_routing_selector_rejected_at_create(tmp_path, ssb_schema):
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig(ssb_schema.name, routing_selector="strict")  # typo
    import pytest as _p
    with _p.raises(ValueError, match="routingSelector"):
        cluster.create_table(ssb_schema, cfg)


def test_uncovered_segments_surface_as_partial_result(lineorder_cluster):
    """A segment no replica can serve after the retry round must be SURFACED
    (partialResult + segmentsUnavailable), never silently short results."""
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    victim = sorted(cluster.catalog.segments[table])[0]
    victim_rows = 1000

    def drop_victim(orig):
        def handle(t, ctx, segments, tf=None):
            return orig(t, ctx, [s for s in segments if s != victim], tf)
        return handle

    for sid in list(cluster.broker._servers):
        cluster.broker.register_server_handle(
            sid, drop_victim(cluster.broker._servers[sid]))
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 4000 - victim_rows
    assert res.stats["partialResult"] is True
    assert res.stats["segmentsUnavailable"] == [f"{table}:{victim}"]


def test_retry_covers_single_flaky_replica(lineorder_cluster):
    """One replica briefly missing a segment mid-transition: the retry round
    fetches it from the other replica and the result stays complete."""
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    victim = sorted(cluster.catalog.segments[table])[0]
    flaky = "server_0"

    orig = cluster.broker._servers[flaky]

    def handle(t, ctx, segments, tf=None):
        return orig(t, ctx, [s for s in segments if s != victim], tf)

    cluster.broker.register_server_handle(flaky, handle)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 4000
    assert not res.stats["partialResult"]
    assert "segmentsUnavailable" not in res.stats


def test_strict_replica_group_never_retries_per_segment(lineorder_cluster):
    """strictReplicaGroup (upsert) tables must not serve one segment from a
    different replica than its partition peers — the retry round refuses and
    the segment is surfaced as uncovered instead."""
    cluster, cfg = lineorder_cluster
    table = cfg.table_name_with_type
    cluster.catalog.table_configs[table].routing_selector = "strictReplicaGroup"
    out, failed = cluster.broker._retry_missing(
        table, None, {"seg_x": {"server_0"}}, None, lambda h, s: h)
    assert out == [] and failed == 0


def test_query_error_raises_and_keeps_servers_routable(lineorder_cluster):
    """A deterministic query error (server evaluated and rejected the query)
    must RAISE to the caller and must NOT poison routing: before this guard a
    single malformed query marked every replica unhealthy and all later
    queries silently returned 0 rows."""
    cluster, cfg = lineorder_cluster
    with pytest.raises(Exception):
        # bad serialized id-set -> per-server QueryValidationError
        cluster.query("SELECT COUNT(*) FROM lineorder "
                      "WHERE IN_ID_SET(lo_custkey, 'not-a-valid-idset')")
    assert cluster.broker.routing.unhealthy_servers() == set()
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 4000 and not res.stats["partialResult"]


def test_bool_predicate_comparison_form(lineorder_cluster):
    """Reference syntax `IN_ID_SET(col, '...') = 1` / `= 0` (boolean transform
    compared to a literal) must compile like the bare predicate / negation."""
    cluster, cfg = lineorder_cluster
    ser = cluster.query(
        "SELECT IDSET(lo_region) FROM lineorder WHERE lo_region = 'ASIA'"
    ).rows[0][0]
    base = cluster.query("SELECT COUNT(*) FROM lineorder "
                         f"WHERE IN_ID_SET(lo_region, '{ser}')").rows[0][0]
    eq1 = cluster.query("SELECT COUNT(*) FROM lineorder "
                        f"WHERE IN_ID_SET(lo_region, '{ser}') = 1").rows[0][0]
    eq0 = cluster.query("SELECT COUNT(*) FROM lineorder "
                        f"WHERE IN_ID_SET(lo_region, '{ser}') = 0").rows[0][0]
    assert eq1 == base and eq0 == 4000 - base and 0 < base < 4000


def test_all_replicas_down_surfaces_unavailable(lineorder_cluster):
    """Every replica unhealthy: the query must flag the undispatchable
    segments (partialResult + segmentsUnavailable), not answer 0 cleanly."""
    cluster, cfg = lineorder_cluster
    for sid in list(cluster.broker._servers):
        cluster.broker.routing.mark_server_unhealthy(sid)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 0
    assert res.stats["partialResult"] is True
    assert len(res.stats["segmentsUnavailable"]) == 4


def test_crashed_server_segments_retried_in_buffered_path(lineorder_cluster):
    """A transport-failed server's segments enter the retry round: with a
    healthy replica available the FIRST query already returns complete
    results (servers_failed still marks it partial for visibility)."""
    cluster, cfg = lineorder_cluster

    def broken(table, ctx, segments, time_filter=None):
        raise ConnectionError("boom")

    cluster.broker.register_server_handle("server_1", broken)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 4000
    assert "segmentsUnavailable" not in res.stats


def test_backpressured_server_segments_retried(lineorder_cluster):
    """HTTP 429 / admission rejection on one replica: the segments retry on a
    DIFFERENT healthy replica and the result stays complete (the overloaded
    server keeps its routing slot — backpressure is the server working)."""
    cluster, cfg = lineorder_cluster
    from pinot_tpu.query.scheduler import QueryRejectedError

    def throttled(table, ctx, segments, time_filter=None):
        raise QueryRejectedError("admission queue full")

    cluster.broker.register_server_handle("server_2", throttled)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 4000
    assert "segmentsUnavailable" not in res.stats
    assert "server_2" not in cluster.broker.routing.unhealthy_servers()


def test_all_replicas_dead_segments_surface(lineorder_cluster):
    """Every server holding a segment leaves live_servers (process death, not
    just unhealthy-marking): the segment must still appear in the coverage
    audit — previously it vanished from the routing table entirely and the
    query returned short with partialResult=False."""
    cluster, cfg = lineorder_cluster
    for sid in ("server_0", "server_1", "server_2"):
        cluster.kill_server(sid)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 0
    assert res.stats["partialResult"] is True
    assert len(res.stats["segmentsUnavailable"]) == 4


def test_replica_local_error_fails_over(lineorder_cluster):
    """One replica raises a replica-LOCAL error (corrupt file): the segment
    retries on the healthy replica and the query completes; the error is only
    raised when EVERY replica fails (deterministic bad query)."""
    cluster, cfg = lineorder_cluster

    orig = cluster.broker._servers["server_0"]

    def corrupt(table, ctx, segments, time_filter=None):
        raise ValueError("segment file corrupt on this replica")

    cluster.broker.register_server_handle("server_0", corrupt)
    res = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert res.rows[0][0] == 4000  # replication=2 covered everything
    assert "server_0" not in cluster.broker.routing.unhealthy_servers()


def test_stream_query_replica_local_error_fails_over(lineorder_cluster):
    """Streaming export: a replica-local error retries on the healthy replica
    (same policy as the buffered path) instead of aborting the export."""
    cluster, cfg = lineorder_cluster

    def corrupt(table, ctx, segments, time_filter=None):
        raise ValueError("segment file corrupt on this replica")

    cluster.broker.register_server_handle("server_1", corrupt)
    rows = []
    for kind, payload in cluster.broker.stream_query(
            "SELECT lo_custkey FROM lineorder LIMIT 100000"):
        if kind == "rows":
            rows.extend(payload)
    assert len(rows) == 4000
