"""Randomized JOIN differential testing: multistage engine vs sqlite3 oracle.

Extends the single-table harness (test_differential.py) to the join engine:
random INNER/LEFT joins over two tables with WHERE pushdown, aggregations, and
group-bys, executed through `execute_multistage` (the same runtime the broker
dispatches) and compared row-for-row against sqlite.
"""

import sqlite3

import numpy as np
import pytest

from pinot_tpu.multistage import execute_multistage
from pinot_tpu.multistage.runtime import make_segment_scan
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder

RNG = np.random.default_rng(42)
N_ORDERS = 2000
N_CUST = 80   # some customers absent from orders; some orders dangling

ORDERS = {
    "cust_id": [f"c{i}" for i in RNG.integers(0, 100, N_ORDERS)],  # c80..c99 dangle
    "qty": RNG.integers(1, 20, N_ORDERS).astype(np.int32),
    "amount": np.round(RNG.uniform(1, 500, N_ORDERS), 2),
}
CUSTS = {
    "cust_id": [f"c{i}" for i in range(N_CUST)],
    "region": [["east", "west", "north"][i % 3] for i in range(N_CUST)],
    "tier": RNG.integers(1, 4, N_CUST).astype(np.int32),
}

ORDERS_SCHEMA = Schema("orders", [
    dimension("cust_id"), metric("qty", DataType.INT),
    metric("amount", DataType.DOUBLE)])
CUSTS_SCHEMA = Schema("custs", [
    dimension("cust_id"), dimension("region"), metric("tier", DataType.INT)])


@pytest.fixture(scope="module")
def engines(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("joins")
    o_seg = load_segment(SegmentBuilder(ORDERS_SCHEMA).build(
        {k: (v.copy() if isinstance(v, np.ndarray) else list(v))
         for k, v in ORDERS.items()}, str(tmp), "o_0"))
    c_seg = load_segment(SegmentBuilder(CUSTS_SCHEMA).build(
        {k: (v.copy() if isinstance(v, np.ndarray) else list(v))
         for k, v in CUSTS.items()}, str(tmp), "c_0"))
    scan = make_segment_scan({"orders": [o_seg], "custs": [c_seg]})
    schema_for = {"orders": ORDERS_SCHEMA, "custs": CUSTS_SCHEMA}.get

    db = sqlite3.connect(":memory:")
    db.execute("CREATE TABLE orders (cust_id TEXT, qty INTEGER, amount REAL)")
    db.execute("CREATE TABLE custs (cust_id TEXT, region TEXT, tier INTEGER)")
    db.executemany("INSERT INTO orders VALUES (?,?,?)",
                   list(zip(ORDERS["cust_id"], ORDERS["qty"].tolist(),
                            ORDERS["amount"].tolist())))
    db.executemany("INSERT INTO custs VALUES (?,?,?)",
                   list(zip(CUSTS["cust_id"], CUSTS["region"],
                            CUSTS["tier"].tolist())))
    return scan, schema_for, db


def gen_join_query(rng) -> str:
    join_type = ["JOIN", "LEFT JOIN"][rng.integers(0, 2)]
    conds = []
    if rng.random() < 0.5:
        conds.append(f"o.qty > {int(rng.integers(1, 15))}")
    if rng.random() < 0.5:
        conds.append(f"c.tier = {int(rng.integers(1, 4))}")
    if rng.random() < 0.3:
        conds.append(f"o.amount < {round(float(rng.uniform(50, 450)), 2)}")
    where = (" WHERE " + " AND ".join(conds)) if conds else ""
    shape = rng.integers(0, 3)
    if shape == 0:
        return (f"SELECT c.region, COUNT(*), SUM(o.amount) FROM orders o "
                f"{join_type} custs c ON o.cust_id = c.cust_id{where} "
                f"GROUP BY c.region LIMIT 100000")
    if shape == 1:
        return (f"SELECT c.region, c.tier, SUM(o.qty) FROM orders o "
                f"{join_type} custs c ON o.cust_id = c.cust_id{where} "
                f"GROUP BY c.region, c.tier LIMIT 100000")
    return (f"SELECT COUNT(*), SUM(o.amount), MIN(o.qty), MAX(o.qty) "
            f"FROM orders o {join_type} custs c ON o.cust_id = c.cust_id{where}")


# share the single-table harness's comparison helpers (no rounding: rounding
# before isclose() injects error the tolerance then has to absorb)
from test_differential import _rows_match, _sorted_rows


def _rows(rows):
    return _sorted_rows(rows)


@pytest.mark.parametrize("seed", range(6))
def test_join_differential_vs_sqlite(engines, seed):
    scan, schema_for, db = engines
    rng = np.random.default_rng(3000 + seed)
    for qi in range(15):
        sql = gen_join_query(rng)
        oracle = _rows(db.execute(sql.replace(" LIMIT 100000", "")).fetchall())
        got = _rows(execute_multistage(sql, scan, schema_for).rows)
        # _rows_match checks row count AND per-row column count (a dropped
        # trailing column must fail, not silently zip-truncate)
        assert _rows_match(got, oracle, 1e-6, 1e-4), (
            f"JOIN MISMATCH seed={seed} q={qi}\n{sql}\n"
            f"ours({len(got)}): {got[:4]}\noracle({len(oracle)}): {oracle[:4]}")


def test_join_differential_non_equi_residual(engines):
    """Inner joins with non-equi residual conditions on the ON clause."""
    scan, schema_for, db = engines
    sql = ("SELECT c.region, COUNT(*) FROM orders o JOIN custs c "
           "ON o.cust_id = c.cust_id AND o.qty > c.tier "
           "GROUP BY c.region LIMIT 1000")
    oracle = _rows(db.execute(sql.replace(" LIMIT 1000", "")).fetchall())
    got = _rows(execute_multistage(sql, scan, schema_for).rows)
    assert got == oracle
