"""Kafka binary wire format: golden frame bytes, CRC-32C vectors, batch
roundtrips. These pin the ENCODING itself (not just our client/server pair
agreeing with each other): header layout, zigzag varints, record batch v2
field order, and the checksum polynomial are each asserted against
spec-derived expected bytes, so a stock Kafka client would interoperate.
"""

import struct

import pytest

from pinot_tpu.ingest import kafka_wire as kw


def test_crc32c_standard_vectors():
    # the canonical CRC-32C (Castagnoli) check value
    assert kw.crc32c(b"123456789") == 0xE3069283
    assert kw.crc32c(b"") == 0
    # iSCSI test vector: 32 bytes of zeros
    assert kw.crc32c(bytes(32)) == 0x8A9136AA


def test_zigzag_varint():
    cases = {0: b"\x00", -1: b"\x01", 1: b"\x02", -2: b"\x03",
             7: b"\x0e", 63: b"\x7e", 64: b"\x80\x01", -64: b"\x7f"}
    for v, raw in cases.items():
        assert kw.varint(v) == raw, v
        assert kw.Reader(raw).varint() == v


def test_record_batch_v2_golden_bytes():
    """One record (no key, value b'x', ts 1000) at base offset 5 — every field
    hand-assembled from the v2 spec."""
    got = kw.encode_record_batch(5, [(None, b"x", 1000)])
    # record: attrs(0) tsDelta(0) offsetDelta(0) keyLen(-1) valueLen(1) 'x' headers(0)
    record_body = b"\x00" + b"\x00" + b"\x00" + b"\x01" + b"\x02" + b"x" + b"\x00"
    records = kw.varint(len(record_body)) + record_body
    crc_part = (struct.pack(">h", 0)            # attributes
                + struct.pack(">i", 0)          # lastOffsetDelta
                + struct.pack(">q", 1000)       # firstTimestamp
                + struct.pack(">q", 1000)       # maxTimestamp
                + struct.pack(">q", -1)         # producerId
                + struct.pack(">h", -1)         # producerEpoch
                + struct.pack(">i", -1)         # baseSequence
                + struct.pack(">i", 1)          # recordCount
                + records)
    inner = (struct.pack(">i", -1)              # partitionLeaderEpoch
             + b"\x02"                          # magic = 2
             + struct.pack(">I", kw.crc32c(crc_part)) + crc_part)
    want = struct.pack(">q", 5) + struct.pack(">i", len(inner)) + inner
    assert got == want


def test_record_batch_roundtrip_multi():
    recs = [(b"k0", b"value-zero", 1_700_000_000_000),
            (None, b"v1", 1_700_000_000_050),
            (b"k2", b"", 1_700_000_000_100)]
    data = kw.encode_record_batch(40, recs)
    out = kw.decode_record_batches(data)
    assert out == [(40, 1_700_000_000_000, b"k0", b"value-zero"),
                   (41, 1_700_000_000_050, None, b"v1"),
                   (42, 1_700_000_000_100, b"k2", b"")]
    # two appended batches decode as one stream (a fetch response's record set)
    data2 = data + kw.encode_record_batch(43, [(None, b"tail", 7)])
    assert [v for *_1, v in kw.decode_record_batches(data2)] == \
        [b"value-zero", b"v1", b"", b"tail"]


def test_record_batch_crc_detects_corruption():
    data = bytearray(kw.encode_record_batch(0, [(None, b"payload", 1)]))
    data[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        kw.decode_record_batches(bytes(data))


def test_request_frame_golden_bytes():
    """Produce v3 header for client 'pinot': length-prefixed int16/int16/int32
    + nullable string, exactly the Kafka request framing."""
    got = kw.encode_request(kw.API_PRODUCE, 3, 7, "pinot", b"BODY")
    payload = (struct.pack(">h", 0)        # api_key = Produce
               + struct.pack(">h", 3)      # api_version
               + struct.pack(">i", 7)      # correlation_id
               + struct.pack(">h", 5) + b"pinot"
               + b"BODY")
    assert got == struct.pack(">i", len(payload)) + payload
    api, version, cid, client, r = kw.decode_request_header(payload)
    assert (api, version, cid, client) == (0, 3, 7, "pinot")
    assert r.data[r.pos:] == b"BODY"


def test_api_bodies_roundtrip():
    # Metadata v1
    body = kw.encode_metadata_response(1, "127.0.0.1", 9092, {"t": 3})
    meta = kw.decode_metadata_response(1, kw.Reader(body))
    assert meta["brokers"][0]["port"] == 9092
    assert meta["topics"][0]["topic"] == "t"
    assert len(meta["topics"][0]["partitions"]) == 3
    # ListOffsets v1
    body = kw.encode_list_offsets_response([("t", 0, 0, -1, 42)])
    assert kw.decode_list_offsets_response(kw.Reader(body)) == [
        {"topic": "t", "partition": 0, "error": 0, "timestamp": -1, "offset": 42}]
    # Fetch v4 with a real record set
    rs = kw.encode_record_batch(10, [(None, b"a", 1), (None, b"b", 2)])
    body = kw.encode_fetch_response([("t", 1, 0, 12, rs)])
    out = kw.decode_fetch_response(kw.Reader(body))
    assert out[0]["highWatermark"] == 12
    assert [v for *_x, v in out[0]["records"]] == [b"a", b"b"]
    # Produce v3
    body = kw.encode_produce_response([("t", 0, 0, 99)])
    assert kw.decode_produce_response(kw.Reader(body))[0]["offset"] == 99
    # ApiVersions advertises every supported api
    vers = kw.decode_api_versions_response(
        kw.Reader(kw.encode_api_versions_response()))
    assert vers == kw.SUPPORTED


def test_fetch_request_decode_matches_encode():
    body = kw.encode_fetch_request("topic", 2, 1234, 500, 1 << 20)
    max_wait, max_bytes, parts = kw.decode_fetch_request(kw.Reader(body))
    assert (max_wait, max_bytes) == (500, 1 << 20)
    assert parts == [("topic", 2, 1234, 1 << 20)]


def test_unsupported_version_gets_downgrade_answer():
    """A too-new ApiVersions request is answered v0 with UNSUPPORTED_VERSION
    (the spec's downgrade path for old brokers)."""
    from pinot_tpu.ingest.kafkalite import LogBrokerServer, _recv_payload
    import socket
    srv = LogBrokerServer()
    try:
        s = socket.create_connection((srv.host, srv.port), timeout=5)
        s.sendall(kw.encode_request(kw.API_API_VERSIONS, 99, 1, "x", b""))
        payload = _recv_payload(s)
        r = kw.Reader(payload)
        assert r.i32() == 1  # correlation id
        assert r.i16() == kw.ERR_UNSUPPORTED_VERSION
        s.close()
    finally:
        srv.stop()


def test_list_offsets_by_timestamp():
    """ListOffsets v1 with a real timestamp returns the FIRST offset whose
    record timestamp >= T (offsetsForTimes semantics), not log end."""
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer
    srv = LogBrokerServer()
    try:
        c = LogBrokerClient(srv.bootstrap)
        c.create_topic("t", 1)
        for i, ts in enumerate((100, 200, 300)):
            c.produce("t", f"m{i}", partition=0, timestamp_ms=ts)
        assert c.list_offsets("t", 0, timestamp=-2) == 0   # earliest
        assert c.list_offsets("t", 0, timestamp=-1) == 3   # latest
        assert c.list_offsets("t", 0, timestamp=150) == 1
        assert c.list_offsets("t", 0, timestamp=300) == 2
        assert c.list_offsets("t", 0, timestamp=301) == -1  # past the end
        # explicit timestamp 0 is preserved verbatim (no wall-clock re-stamp)
        c.produce("t", "zero", partition=0, timestamp_ms=0)
        recs = c.fetch("t", 0, 3)
        assert recs[0][1] == 0
        c.close()
    finally:
        srv.stop()
