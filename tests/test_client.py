"""Python client + controller UI tests (reference: pinot-java-client / pinotdb
connect-and-execute surface, controller admin webapp)."""

import numpy as np
import pytest

from pinot_tpu.client import connect
from pinot_tpu.schema import Schema, dimension, metric
from pinot_tpu.table import TableConfig


@pytest.fixture()
def http_stack(tmp_path):
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    catalog = Catalog()
    ctrl = Controller("c0", catalog, LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / "c"))
    csvc = ControllerService(ctrl)
    cats = [RemoteCatalog(csvc.url, poll_timeout_s=1.0)]
    node = ServerNode("server_0", cats[0], ControllerDeepStore(csvc.url),
                      str(tmp_path / "s0"))
    ssvc = ServerService(node)
    cats.append(RemoteCatalog(csvc.url, poll_timeout_s=1.0))
    bsvc = BrokerService(Broker("b0", cats[1]))
    try:
        yield csvc, bsvc, node, tmp_path
    finally:
        for c in cats:
            c.close()
        for s in (csvc, ssvc, bsvc):
            s.stop()


def test_connect_and_execute(http_stack):
    csvc, bsvc, node, tmp = http_stack
    conn = connect(bsvc.url, controller=csvc.url)
    schema = Schema("trips", [dimension("city"), metric("fare")])
    conn.admin.add_schema(schema)
    conn.admin.add_table(TableConfig("trips"))
    from pinot_tpu.segment.writer import SegmentBuilder
    seg = SegmentBuilder(schema).build(
        {"city": ["nyc", "sf", "nyc"], "fare": np.array([1.0, 2.0, 3.0])},
        str(tmp / "b"), "trips_0")
    conn.admin.upload_segment("trips_OFFLINE", seg)
    from conftest import wait_until
    assert wait_until(   # broker catalog mirror converges via polls
        lambda: conn.execute("SELECT COUNT(*) FROM trips").scalar() == 3)

    rs = conn.execute("SELECT city, SUM(fare) FROM trips GROUP BY city "
                      "ORDER BY city LIMIT 5")
    assert rs.columns == ["city", "sum(fare)"]
    assert list(rs) == [["nyc", 4.0], ["sf", 2.0]]
    assert len(rs) == 2 and rs.first() == ["nyc", 4.0]
    assert conn.execute("SELECT COUNT(*) FROM trips").scalar() == 3
    assert "timeUsedMs" in rs.stats


def test_controller_ui(http_stack):
    csvc, bsvc, node, tmp = http_stack
    from pinot_tpu.cluster.http_service import http_call
    html = http_call("GET", f"{csvc.url}/").decode()
    assert "pinot-tpu controller" in html
    assert "server_0" in html and "b0" in html
