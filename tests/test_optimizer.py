"""Filter optimizer tests: EQ/IN merge, range tightening, dedupe, bloom fold.

Reference pattern: core/query/optimizer/filter/ optimizer unit tests
(MergeEqInFilterOptimizerTest, MergeRangeFilterOptimizerTest,
IdenticalPredicateFilterOptimizerTest) + BloomFilterSegmentPruner.
"""

import numpy as np
import pytest

from pinot_tpu.query.context import compile_query
from pinot_tpu.query.executor import ServerQueryExecutor, execute_query
from pinot_tpu.query.optimizer import optimize_filter
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.sql.ast import to_sql
from pinot_tpu.sql.parser import parse_query


OPT_SCHEMA = Schema("t", [dimension("c"), dimension("d", DataType.INT),
                          metric("v", DataType.DOUBLE)])


def opt(sql_where: str) -> str:
    stmt = parse_query(f"SELECT * FROM t WHERE {sql_where}")
    return to_sql(optimize_filter(stmt.where, OPT_SCHEMA))


# -- AST rewrites -------------------------------------------------------------

def test_merge_eq_or_to_in():
    out = opt("c = 'a' OR c = 'b' OR c = 'c'")
    assert "IN" in out and out.count("c") >= 1
    assert to_sql(parse_query(
        "SELECT * FROM t WHERE c IN ('a', 'b', 'c')").where) == out


def test_merge_eq_and_in_dedupes():
    out = opt("c IN ('a', 'b') OR c = 'b' OR c = 'd'")
    assert out == "(c IN ('a', 'b', 'd'))"


def test_merge_preserves_other_disjuncts():
    out = opt("c = 'a' OR d > 5 OR c = 'b'")
    assert "d > 5" in out and "IN" in out


def test_merge_ranges_tightest():
    # tightest combined range is the inclusive [5, 10]
    assert opt("v > 3 AND v >= 5 AND v < 20 AND v <= 10") == "(v BETWEEN 5 AND 10)"
    assert opt("v >= 5 AND v <= 10 AND v >= 2") == "(v BETWEEN 5 AND 10)"


def test_range_merge_exclusive_bounds():
    assert opt("v > 5 AND v >= 5") == "(v > 5)"
    assert opt("v < 9 AND v <= 9") == "(v < 9)"


def test_dedupe_identical():
    assert opt("c = 'a' AND c = 'a'") == "(c = 'a')"
    out = opt("(v > 1 AND c = 'x') OR (v > 1 AND c = 'x')")
    assert out == "((v > 1) AND (c = 'x'))" or out == "((c = 'x') AND (v > 1))"


def test_nested_flatten_enables_merge():
    out = opt("(c = 'a' OR (c = 'b' OR c = 'd'))")
    assert out == "(c IN ('a', 'b', 'd'))"


def test_mixed_type_range_not_merged():
    """`v > 5 AND v > '3'` must not merge (string vs number literals) — and
    must still compile/execute through the normal per-type normalization."""
    out = opt("v > 5 AND v > '3'")
    assert "AND" in out


def test_mv_range_not_merged(tmp_path):
    """ANY-value MV semantics: `tag >= 5 AND tag <= 10` is satisfiable by
    DIFFERENT values of one row; a merged BETWEEN would silently drop rows."""
    from pinot_tpu.schema import FieldSpec, FieldRole
    mv_schema = Schema("mvq", [
        FieldSpec("tag", DataType.INT, FieldRole.DIMENSION, single_value=False)])
    seg = load_segment(SegmentBuilder(mv_schema).build(
        {"tag": [[1, 20], [6, 7], [2, 3]]}, str(tmp_path), "mv_0"))
    res = execute_query([seg],
                        "SELECT COUNT(*) FROM mvq WHERE tag >= 5 AND tag <= 10")
    assert res.rows[0][0] == 2   # rows [1,20] (20>=5, 1<=10) and [6,7]


# -- behavior preserved end-to-end --------------------------------------------

SCHEMA = Schema("o", [dimension("c"), metric("v", DataType.DOUBLE)])


@pytest.fixture(scope="module")
def seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("opt")
    rng = np.random.default_rng(4)
    return load_segment(SegmentBuilder(SCHEMA, SegmentGeneratorConfig(
        bloom_filter_columns=["c"])).build(
        {"c": [f"c{i % 17}" for i in range(3000)],
         "v": rng.uniform(0, 100, 3000)}, str(tmp), "o_0"))


@pytest.mark.parametrize("where", [
    "c = 'c1' OR c = 'c2' OR c = 'c3'",
    "v > 10 AND v >= 20 AND v < 90",
    "(c = 'c1' OR c = 'c1') AND v BETWEEN 5 AND 95 AND v >= 10",
    "c IN ('c1', 'c5') OR c = 'c5' OR v < 2",
])
def test_optimized_results_match_brute_force(seg, where):
    sql = f"SELECT COUNT(*), SUM(v) FROM o WHERE {where}"
    got = execute_query([seg], sql).rows
    # brute force via host numpy on the RAW (unoptimized) predicate
    c = np.array([f"c{i % 17}" for i in range(3000)], dtype=object)
    v = seg.column("v").values()
    env = {"c": c, "v": v}
    from pinot_tpu.engine.expr import eval_expr
    mask = np.asarray(eval_expr(parse_query(
        f"SELECT * FROM t WHERE {where}").where, env, np), dtype=bool)
    assert got[0][0] == int(mask.sum())
    assert got[0][1] == pytest.approx(float(v[mask].sum()), rel=1e-6)


def test_eq_or_merge_gives_single_lut_leaf(seg):
    ctx = compile_query("SELECT COUNT(*) FROM o WHERE c = 'c1' OR c = 'c2'",
                        SCHEMA)
    from pinot_tpu.query.planner import plan_segment
    plan = plan_segment(ctx, seg)
    assert len(plan.filter_prog.leaves) == 1   # one LUT, not two ORed masks


def test_bloom_prunes_at_plan_time(seg):
    from pinot_tpu.query.planner import plan_segment
    # dict-encoded columns already fold on dictionary miss; the bloom path
    # matters for RAW (no-dictionary) columns, exercised below
    import tempfile
    with tempfile.TemporaryDirectory() as tmp:
        schema = Schema("b", [metric("x", DataType.LONG)])
        seg2 = load_segment(SegmentBuilder(schema, SegmentGeneratorConfig(
            no_dictionary_columns=["x"], bloom_filter_columns=["x"])).build(
            {"x": np.arange(0, 5000, 7, dtype=np.int64)}, tmp, "b_0"))
        ctx = compile_query("SELECT COUNT(*) FROM b WHERE x = 3", schema)
        plan = plan_segment(ctx, seg2)   # 3 not in range steps of 7... but
        # 3 < max and > min so min-max cannot fold; bloom proves absence
        assert plan.kind == "empty", (plan.kind, plan.fallback_reason)
        res = ServerQueryExecutor().execute([seg2],
                                            "SELECT COUNT(*) FROM b WHERE x = 3")
        assert res.rows[0][0] == 0
