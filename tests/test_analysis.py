"""graftcheck tests: fixture snippets per rule pack, the tier-1 package gate,
CLI exit codes, and threaded regressions for the lock-discipline fixes.

Fixture tests follow one shape per rule pack: a seeded true positive, a clean
negative, and an honored `# graftcheck: ignore[...] -- reason` suppression —
proving each rule both fires and can be silenced with a rationale.
"""

import textwrap
import threading
import time

import pytest

from pinot_tpu.analysis import (AnalysisContext, Module, load_baseline,
                                run_project, run_rules, unbaselined)
from pinot_tpu.analysis import (accumulation, admission_hygiene,
                                blocking_in_loop,
                                collective_hygiene, drift_guards,
                                events_drift, exception_hygiene, filter_path,
                                fused_path, ingest_hot_loop, jit_hygiene,
                                join_path, lock_discipline, memory_hygiene,
                                transport_bypass)
from pinot_tpu.analysis.__main__ import main as analysis_main
from pinot_tpu.analysis.core import BAD_SUPPRESSION


def _check(source, rules, rel="pinot_tpu/scratch/fixture.py", readme=""):
    """Run `rules` over one in-memory module; (active, suppressed)."""
    m = Module("/fixture.py", rel, textwrap.dedent(source))
    assert m.parse_error is None, m.parse_error
    ctx = AnalysisContext(repo_root="/nonexistent", modules=[m])
    ctx._readme = readme
    return run_rules(rules, [m], ctx)


def _ids(findings):
    return [f.rule for f in findings]


# -- jit-hygiene --------------------------------------------------------------

def test_jit_host_sync_true_positive():
    active, _ = _check("""
        import jax.numpy as jnp
        def f(a):
            x = jnp.sum(a)
            return float(x)
    """, jit_hygiene.rules())
    assert "jit-host-sync" in _ids(active)


def test_jit_hygiene_clean_negative():
    active, _ = _check("""
        import jax.numpy as jnp
        def f(a, n):
            x = jnp.sum(a)
            return x, float(n)
    """, jit_hygiene.rules())
    assert active == []


def test_jit_host_sync_suppression_honored():
    active, suppressed = _check("""
        import jax.numpy as jnp
        def f(a):
            x = jnp.sum(a)
            return float(x)  # graftcheck: ignore[jit-host-sync] -- fixture
    """, jit_hygiene.rules())
    assert "jit-host-sync" not in _ids(active)
    assert "jit-host-sync" in _ids(suppressed)


def test_jit_fetch_site_outside_sanctioned_files():
    src = """
        import jax
        def f(x):
            return jax.device_get(x)
    """
    active, _ = _check(src, jit_hygiene.rules())
    assert "jit-fetch-site" in _ids(active)
    # the same call in a sanctioned fetch site is the batched fetch path
    active, _ = _check(src, jit_hygiene.rules(),
                       rel="pinot_tpu/parallel/combine.py")
    assert active == []


def test_jit_literal_rebuild_and_cache_key():
    active, _ = _check("""
        import jax
        import jax.numpy as jnp
        @jax.jit
        def f(x):
            return x + jnp.array([1.0, 2.0])
        def kernel_for(arr):
            return _cached_kernel((arr.dtype,), arr)
    """, jit_hygiene.rules())
    assert "jit-literal-rebuild" in _ids(active)
    assert "jit-cache-key" in _ids(active)  # dtype keyed without shape


# -- lock-discipline ----------------------------------------------------------

def test_lock_unguarded_write_true_positive():
    active, _ = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def safe(self):
                with self._lock:
                    self.n += 1
            def racy(self):
                self.n += 1
    """, lock_discipline.rules())
    assert _ids(active) == ["lock-unguarded-write"]


def test_lock_discipline_clean_negative():
    active, _ = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def a(self):
                with self._lock:
                    self.n += 1
            def b(self):
                with self._lock:
                    self.n = 0
    """, lock_discipline.rules())
    assert active == []


def test_lock_unguarded_write_suppression_honored():
    active, suppressed = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def safe(self):
                with self._lock:
                    self.n += 1
            def racy(self):
                self.n += 1  # graftcheck: ignore[lock-unguarded-write] -- held by caller
    """, lock_discipline.rules())
    assert active == []
    assert "lock-unguarded-write" in _ids(suppressed)


def test_thread_no_join_variants():
    # fire-and-forget fires; a joined handle does not; the getattr-guarded
    # stop() idiom (stores/http_service) is recognized as a join path
    active, _ = _check("""
        import threading
        def go():
            threading.Thread(target=print, daemon=True).start()
    """, lock_discipline.rules())
    assert "thread-no-join" in _ids(active)
    active, _ = _check("""
        import threading
        class C:
            def start(self):
                self._thread = threading.Thread(target=print)
                self._thread.start()
            def stop(self):
                t = getattr(self, "_thread", None)
                if t is not None:
                    t.join(timeout=5.0)
    """, lock_discipline.rules())
    assert active == []


# -- blocking-in-loop ---------------------------------------------------------

def test_blocking_result_no_timeout_true_positive():
    active, _ = _check("""
        def gather(futs):
            return [f.result() for f in futs]
    """, blocking_in_loop.rules())
    assert "blocking-result-no-timeout" in _ids(active)


def test_blocking_clean_negative():
    # .result() on an as_completed-yielded future is already done (the
    # timeout on as_completed carries the bound) — not a finding
    active, _ = _check("""
        from concurrent.futures import as_completed
        def gather(futs, q):
            out = []
            for f in as_completed(futs, timeout=30.0):
                out.append(f.result())
            out.append(q.result(timeout=5.0))
            return out
    """, blocking_in_loop.rules())
    assert active == []


def test_blocking_as_completed_without_timeout():
    active, _ = _check("""
        from concurrent.futures import as_completed
        def gather(futs):
            return [f.result() for f in as_completed(futs)]
    """, blocking_in_loop.rules())
    assert _ids(active) == ["blocking-result-no-timeout"]
    assert "as_completed" in active[0].message


def test_blocking_queue_and_sleep_rules():
    active, _ = _check("""
        import time
        def _fetch_loop(self):
            while True:
                item = self._queue.get()
                time.sleep(0.1)
    """, blocking_in_loop.rules())
    assert sorted(_ids(active)) == ["blocking-queue-get",
                                    "blocking-sleep-in-loop"]


def test_blocking_suppression_honored():
    active, suppressed = _check("""
        def gather(futs):
            # graftcheck: ignore[blocking-result-no-timeout] -- fixture
            return [f.result() for f in futs]
    """, blocking_in_loop.rules())
    assert active == []
    assert "blocking-result-no-timeout" in _ids(suppressed)


# -- drift-guards -------------------------------------------------------------

_OBS_README = """
## Observability

| metric | meaning |
|---|---|
| `pinot_documented_total` | documented |

## Layout
"""


def test_drift_metric_glossary_true_positive():
    active, _ = _check("""
        def report(reg):
            reg.counter("pinot_documented_total").inc()
            reg.counter("pinot_mystery_total").inc()
    """, drift_guards.rules(), readme=_OBS_README)
    assert _ids(active) == ["drift-metric-glossary"]
    assert "pinot_mystery_total" in active[0].message


def test_drift_metric_glossary_clean_negative():
    active, _ = _check("""
        def report(reg):
            reg.counter("pinot_documented_total").inc()
    """, drift_guards.rules(), readme=_OBS_README)
    assert active == []


def test_drift_metric_glossary_suppression_honored():
    active, suppressed = _check("""
        def report(reg):
            reg.counter("pinot_mystery_total").inc()  # graftcheck: ignore[drift-metric-glossary] -- fixture
    """, drift_guards.rules(), readme=_OBS_README)
    assert active == []
    assert "drift-metric-glossary" in _ids(suppressed)


def test_drift_cluster_config_rule():
    src = """
        def knob(catalog):
            return catalog.get_property("clusterConfig/broker.mystery.knob")
    """
    active, _ = _check(src, drift_guards.rules(), readme=_OBS_README)
    assert _ids(active) == ["drift-cluster-config"]
    documented = _OBS_README + "\n`broker.mystery.knob` does a thing\n"
    active, _ = _check(src, drift_guards.rules(), readme=documented)
    assert active == []


def test_label_cardinality_true_positive():
    # a per-user label value is unbounded: every distinct user mints a series
    active, _ = _check("""
        def report(reg, user_id):
            reg.counter("pinot_documented_total", {"user": user_id}).inc()
    """, drift_guards.rules(), readme=_OBS_README)
    assert _ids(active) == ["metric-label-cardinality"]
    assert "'user'" in active[0].message


def test_label_cardinality_kwarg_and_dynamic_key_flagged():
    active, _ = _check("""
        def report(reg, sql, key):
            reg.histogram("pinot_documented_total",
                          labels={"query": sql, key: sql}).observe(1.0)
    """, drift_guards.rules(), readme=_OBS_README)
    assert _ids(active) == ["metric-label-cardinality"] * 2
    assert any("'query'" in f.message for f in active)
    assert any("<dynamic>" in f.message for f in active)


def test_label_cardinality_clean_negative():
    # bounded keys (table/task/...) may take dynamic values; unknown keys are
    # fine with CONSTANT values; a labels VARIABLE is out of scope (only a
    # dict literal is judgeable)
    active, _ = _check("""
        def report(reg, table, labels):
            reg.counter("pinot_documented_total", {"table": table}).inc()
            reg.gauge("pinot_documented_total", {"source": "broker"}).set(1)
            reg.timer("pinot_documented_total", labels).update(2.0)
    """, drift_guards.rules(), readme=_OBS_README)
    assert active == []


def test_label_cardinality_suppression_honored():
    active, suppressed = _check("""
        def report(reg, shard):
            reg.counter("pinot_documented_total",
                        {"shard": shard}).inc()  # graftcheck: ignore[metric-label-cardinality] -- fixture
    """, drift_guards.rules(), readme=_OBS_README)
    assert active == []
    assert "metric-label-cardinality" in _ids(suppressed)


# -- event-kind-drift ---------------------------------------------------------

# one fixture module standing in for utils/events.py: it carries the KINDS
# registry AND the call sites (the rel= makes ctx.module() resolve it)
_EVENTS_REL = "pinot_tpu/utils/events.py"

_EVENTS_README = """
## Observability

Event kinds: `segment.online` means the segment went queryable.

## Layout
"""


def test_event_kind_drift_unregistered_kind():
    active, _ = _check("""
        from pinot_tpu.utils.events import emit as emit_event
        KINDS = {"segment.online": ("INFO", "segment went queryable")}
        def fire():
            emit_event("segment.mystery")
    """, events_drift.rules(), rel=_EVENTS_REL, readme=_EVENTS_README)
    assert _ids(active) == ["event-kind-drift"]
    assert "segment.mystery" in active[0].message


def test_event_kind_drift_undocumented_kind():
    active, _ = _check("""
        KINDS = {"segment.online": ("INFO", "documented"),
                 "segment.shadow": ("WARN", "registered, never documented")}
    """, events_drift.rules(), rel=_EVENTS_REL, readme=_EVENTS_README)
    assert _ids(active) == ["event-kind-drift"]
    assert "segment.shadow" in active[0].message


def test_event_kind_drift_clean_negative():
    # a registered+documented kind passes; journal-attribute emits are in
    # scope; an unrelated local emit() helper is NOT (no events import)
    active, _ = _check("""
        from pinot_tpu.utils.events import emit as emit_event
        KINDS = {"segment.online": ("INFO", "documented")}
        def fire(journal):
            emit_event("segment.online")
            journal.emit("segment.online")
        def unrelated_tree_walk():
            def emit(label):
                return label
            emit("not.an.event.kind")
    """, events_drift.rules(), rel=_EVENTS_REL, readme=_EVENTS_README)
    assert active == []


def test_event_kind_drift_suppression_honored():
    active, suppressed = _check("""
        from pinot_tpu.utils.events import emit as emit_event
        KINDS = {"segment.online": ("INFO", "documented")}
        def fire():
            emit_event("segment.mystery")  # graftcheck: ignore[event-kind-drift] -- fixture
    """, events_drift.rules(), rel=_EVENTS_REL, readme=_EVENTS_README)
    assert active == []
    assert "event-kind-drift" in _ids(suppressed)


# -- transport-bypass ---------------------------------------------------------

def test_transport_bypass_true_positive():
    active, _ = _check("""
        import urllib.request

        def fetch(url):
            from http.client import HTTPConnection
            return urllib.request.urlopen(url).read()
    """, transport_bypass.rules())
    assert _ids(active) == ["transport-bypass"] * 2


def test_transport_bypass_sanctioned_in_http_service():
    active, _ = _check("""
        import http.client
        import urllib.request
    """, transport_bypass.rules(),
        rel="pinot_tpu/cluster/http_service.py")
    assert active == []


def test_transport_bypass_urllib_parse_is_clean():
    # urllib.parse/error are string handling, not transport; the pooled
    # helpers themselves are obviously fine
    active, _ = _check("""
        import urllib.parse
        from urllib.parse import urlencode
        from pinot_tpu.cluster.http_service import http_call, http_stream

        def q(d):
            return urllib.parse.urlencode(d)
    """, transport_bypass.rules())
    assert active == []


def test_transport_bypass_from_import_forms_flagged():
    active, _ = _check("""
        from urllib import request
        from http import client
        from urllib.request import urlopen
    """, transport_bypass.rules())
    assert _ids(active) == ["transport-bypass"] * 3


def test_transport_bypass_suppression_honored():
    active, suppressed = _check("""
        # graftcheck: ignore[transport-bypass] -- external S3 endpoint
        import urllib.request
    """, transport_bypass.rules())
    assert active == []
    assert _ids(suppressed) == ["transport-bypass"]


# -- memory-hygiene -----------------------------------------------------------

def test_untracked_staging_true_positive():
    active, _ = _check("""
        import jax
        import jax.numpy as jnp

        def load(host):
            a = jnp.asarray(host)
            b = jax.device_put(host)
            return a, b
    """, memory_hygiene.rules(), rel="pinot_tpu/engine/fixture.py")
    assert _ids(active) == ["memory-untracked-staging"] * 2


def test_untracked_staging_clean_when_wrapped():
    # staged() registers the allocation in the ledger — the sanctioned form
    active, _ = _check("""
        import jax.numpy as jnp
        from pinot_tpu.utils.memledger import staged

        def load(host, seg):
            return staged(jnp.asarray(host), seg, "raw")
    """, memory_hygiene.rules(), rel="pinot_tpu/segment/fixture.py")
    assert active == []


def test_untracked_staging_scoped_to_device_residency_packages():
    # tools/analysis/bench code doesn't hold serving residency: out of scope
    active, _ = _check("""
        import jax.numpy as jnp

        def load(host):
            return jnp.asarray(host)
    """, memory_hygiene.rules(), rel="pinot_tpu/tools/fixture.py")
    assert active == []


def test_untracked_staging_jit_traced_is_exempt():
    # inside a jit trace, asarray is math on tracers — not device staging
    active, _ = _check("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x):
            return jnp.asarray(x) + 1
    """, memory_hygiene.rules(), rel="pinot_tpu/engine/fixture.py")
    assert active == []


def test_untracked_staging_suppression_honored():
    active, suppressed = _check("""
        import jax.numpy as jnp

        def bench(host):
            return jnp.asarray(host)  # graftcheck: ignore[memory-untracked-staging] -- bench-only data
    """, memory_hygiene.rules(), rel="pinot_tpu/engine/fixture.py")
    assert active == []
    assert _ids(suppressed) == ["memory-untracked-staging"]


# -- collective-hygiene --------------------------------------------------------

def test_collective_axis_scope_true_positive():
    active, _ = _check("""
        import jax
        def merge(parts):
            return jax.lax.psum(parts, "seg")
    """, collective_hygiene.rules())
    assert _ids(active) == ["collective-axis-scope"]
    assert "psum" in active[0].message and "'seg'" in active[0].message


def test_collective_axis_scope_bare_import_flagged():
    active, _ = _check("""
        from jax.lax import psum_scatter
        def merge(parts):
            return psum_scatter(parts, "seg", tiled=True)
    """, collective_hygiene.rules())
    assert _ids(active) == ["collective-axis-scope"]


def test_collective_under_shard_map_clean():
    active, _ = _check("""
        import jax
        from jax.experimental.shard_map import shard_map
        def body(x):
            return jax.lax.psum(x, "seg")
        fn = jax.jit(shard_map(body, mesh=None, in_specs=None,
                               out_specs=None))
    """, collective_hygiene.rules())
    assert active == []


def test_collective_lambda_inside_shard_map_clean():
    active, _ = _check("""
        import jax
        from jax.experimental.shard_map import shard_map
        AX = "seg"
        fn = shard_map(lambda x: jax.lax.psum(x, AX), mesh=None,
                       in_specs=None, out_specs=None)
    """, collective_hygiene.rules())
    assert active == []


def test_collective_param_axis_exempt():
    # the combine_collective(name, v, axis) shape: the caller owns the binding
    active, _ = _check("""
        import jax
        def combine(name, v, axis):
            if name.endswith(".min"):
                return jax.lax.pmin(v, axis)
            return jax.lax.psum(v, axis)
    """, collective_hygiene.rules())
    assert active == []


def test_collective_unrelated_psum_name_not_flagged():
    active, _ = _check("""
        def f(table):
            return table.psum("seg")
    """, collective_hygiene.rules())
    assert active == []


def test_collective_axis_scope_suppression_honored():
    active, suppressed = _check("""
        import jax
        def merge(parts):
            # trace-checked by test_multichip fixture
            return jax.lax.psum(parts, "seg")  # graftcheck: ignore[collective-axis-scope] -- fixture
    """, collective_hygiene.rules())
    assert active == []
    assert _ids(suppressed) == ["collective-axis-scope"]


# -- row-loop-in-ingest -------------------------------------------------------

_HOT_REL = "pinot_tpu/ingest/vectorized.py"


def test_row_loop_append_true_positive():
    active, _ = _check("""
        def decode(rows):
            out = []
            for row in rows:
                out.append(int(row))
            return out
    """, ingest_hot_loop.rules(), rel=_HOT_REL)
    assert _ids(active) == ["row-loop-in-ingest"]


def test_row_loop_nested_dict_iteration_flagged():
    active, _ = _check("""
        def index(rows, cols):
            for row in rows:
                for k, v in row.items():
                    cols[k] = v
    """, ingest_hot_loop.rules(), rel=_HOT_REL)
    assert _ids(active) == ["row-loop-in-ingest"]


def test_row_loop_per_column_iteration_clean():
    # per-COLUMN loops are O(schema width): not the smell this rule hunts
    active, _ = _check("""
        def encode(schema, cols):
            parts = []
            for spec in schema.fields:
                parts.append(cols[spec.name])
            for name, chunk in cols.items():
                parts.append(chunk)
            return parts
    """, ingest_hot_loop.rules(), rel=_HOT_REL)
    assert active == []


def test_row_loop_outside_hot_modules_ignored():
    active, _ = _check("""
        def decode(rows):
            out = []
            for row in rows:
                out.append(int(row))
            return out
    """, ingest_hot_loop.rules(), rel="pinot_tpu/server/admin.py")
    assert active == []


def test_row_loop_slow_path_declaration_exempts():
    active, _ = _check("""
        __graft_slow_paths__ = ("decode_fallback",)

        def decode_fallback(rows):
            out = []
            for row in rows:
                out.append(int(row))
            return out
    """, ingest_hot_loop.rules(), rel=_HOT_REL)
    assert active == []


def test_row_loop_suppression_honored():
    active, suppressed = _check("""
        def walk(msgs):
            out = []
            # graftcheck: ignore[row-loop-in-ingest] -- per-block, not per-row
            for m in msgs:
                out.append(m)
            return out
    """, ingest_hot_loop.rules(), rel=_HOT_REL)
    assert active == []
    assert _ids(suppressed) == ["row-loop-in-ingest"]


# -- filter-path-host-materialization -----------------------------------------

_FILTER_REL = "pinot_tpu/query/executor.py"


def test_filter_path_nonzero_true_positive():
    active, _ = _check("""
        import numpy as np
        def fast_mask(lut, ids):
            return np.nonzero(lut[ids])[0]
    """, filter_path.rules(), rel=_FILTER_REL)
    assert _ids(active) == ["filter-path-host-materialization"]


def test_filter_path_postings_loop_flagged():
    active, _ = _check("""
        def collect(inv, match_ids, n):
            mask = [False] * n
            for doc in inv.doc_ids_for(match_ids):
                mask[doc] = True
            return mask
    """, filter_path.rules(), rel=_FILTER_REL)
    assert "filter-path-host-materialization" in _ids(active)


def test_filter_path_slow_path_declaration_exempts():
    active, _ = _check("""
        import numpy as np
        __graft_slow_paths__ = ("host_filter_mask",)

        def host_filter_mask(lut, ids):
            def leaf_mask(i):
                return np.nonzero(lut[ids])[0]
            return leaf_mask(0)
    """, filter_path.rules(), rel=_FILTER_REL)
    assert active == []


def test_filter_path_outside_hot_modules_ignored():
    active, _ = _check("""
        import numpy as np
        def route(lut):
            return np.flatnonzero(lut)
    """, filter_path.rules(), rel="pinot_tpu/query/planner.py")
    assert active == []


def test_filter_path_clean_negative():
    active, _ = _check("""
        import jax.numpy as jnp
        def word_mask(words, sel):
            return jnp.sum(jnp.where(sel[:, None], words, jnp.uint32(0)),
                           axis=0, dtype=jnp.uint32)
    """, filter_path.rules(), rel=_FILTER_REL)
    assert active == []


def test_filter_path_suppression_honored():
    active, suppressed = _check("""
        import numpy as np
        def probe(lut):
            # graftcheck: ignore[filter-path-host-materialization] -- fixture
            return np.nonzero(lut)[0]
    """, filter_path.rules(), rel=_FILTER_REL)
    assert active == []
    assert _ids(suppressed) == ["filter-path-host-materialization"]


# -- fused-path-materialization -----------------------------------------------

_FUSED_REL = "pinot_tpu/engine/kernels.py"


def test_fused_path_take_gather_flagged():
    active, _ = _check("""
        import jax.numpy as jnp
        def build_env(lut, ids):
            return jnp.take(lut, ids)
    """, fused_path.rules(), rel=_FUSED_REL)
    assert _ids(active) == ["fused-path-materialization"]


def test_fused_path_staged_surface_call_flagged():
    active, _ = _check("""
        def gather_inputs(block, cols):
            return {c: block.values(c) for c in cols}
    """, fused_path.rules(), rel=_FUSED_REL)
    assert _ids(active) == ["fused-path-materialization"]


def test_fused_path_decoded_call_flagged():
    active, _ = _check("""
        def gather_inputs(block, c):
            return block.decoded(c)
    """, fused_path.rules(), rel="pinot_tpu/engine/datablock.py")
    assert _ids(active) == ["fused-path-materialization"]


def test_fused_path_take_along_axis_is_sanctioned():
    active, _ = _check("""
        import jax.numpy as jnp
        def fused_env(lut, idx):
            return jnp.take_along_axis(lut, idx, axis=1)
    """, fused_path.rules(), rel=_FUSED_REL)
    assert active == []


def test_fused_path_slow_path_declaration_exempts():
    active, _ = _check("""
        import jax.numpy as jnp
        __graft_slow_paths__ = ("staged_decode",)

        def staged_decode(block, lut, ids, c):
            full = jnp.take(lut, ids)
            return full, block.values(c)
    """, fused_path.rules(), rel=_FUSED_REL)
    assert active == []


def test_fused_path_outside_hot_modules_ignored():
    active, _ = _check("""
        import jax.numpy as jnp
        def inputs(block, lut, ids, c):
            return jnp.take(lut, ids), block.values(c)
    """, fused_path.rules(), rel="pinot_tpu/query/executor.py")
    assert active == []


def test_fused_path_suppression_honored():
    active, suppressed = _check("""
        import jax.numpy as jnp
        def probe(lut, ids):
            # graftcheck: ignore[fused-path-materialization] -- fixture
            return jnp.take(lut, ids)
    """, fused_path.rules(), rel=_FUSED_REL)
    assert active == []
    assert _ids(suppressed) == ["fused-path-materialization"]


# -- join-path-host-materialization -------------------------------------------

_JOIN_REL = "pinot_tpu/engine/join_kernels.py"


def test_join_path_fromiter_flagged():
    active, _ = _check("""
        import numpy as np
        def codes_for(col):
            return np.fromiter((hash(v) for v in col), dtype=np.uint64)
    """, join_path.rules(), rel=_JOIN_REL)
    assert _ids(active) == ["join-path-host-materialization"]


def test_join_path_tolist_flagged():
    active, _ = _check("""
        def probe_candidates(cand):
            return cand.tolist()
    """, join_path.rules(), rel="pinot_tpu/multistage/runtime.py")
    assert _ids(active) == ["join-path-host-materialization"]


def test_join_path_device_get_flagged():
    active, _ = _check("""
        import jax
        def fetch_mid_pipeline(buf):
            return jax.device_get(buf)
    """, join_path.rules(), rel=_JOIN_REL)
    assert _ids(active) == ["join-path-host-materialization"]


def test_join_path_vectorized_staging_is_clean():
    active, _ = _check("""
        import numpy as np
        def fold_codes(codes):
            return (codes ^ (codes >> np.uint64(33))).astype(np.uint32)
    """, join_path.rules(), rel=_JOIN_REL)
    assert active == []


def test_join_path_slow_path_declaration_exempts():
    active, _ = _check("""
        import numpy as np
        __graft_slow_paths__ = ("_hash_obj_rows",)

        def _hash_obj_rows(arr):
            return np.fromiter((hash(v) for v in arr), dtype=np.uint64)
    """, join_path.rules(), rel="pinot_tpu/multistage/runtime.py")
    assert active == []


def test_join_path_outside_hot_modules_ignored():
    active, _ = _check("""
        import numpy as np
        def frame_rows(arr):
            return arr.tolist()
    """, join_path.rules(), rel="pinot_tpu/multistage/shuffle.py")
    assert active == []


def test_join_path_suppression_honored():
    active, suppressed = _check("""
        def probe(cand):
            # graftcheck: ignore[join-path-host-materialization] -- fixture
            return cand.tolist()
    """, join_path.rules(), rel=_JOIN_REL)
    assert active == []
    assert _ids(suppressed) == ["join-path-host-materialization"]


# -- exception-hygiene --------------------------------------------------------

def test_exception_hygiene_true_positives():
    active, _ = _check("""
        def f(items):
            for item in items:
                try:
                    item.close()
                except Exception:
                    continue
            try:
                risky()
            except:
                pass
            try:
                other()
            except BaseException:
                ...
    """, exception_hygiene.rules())
    assert _ids(active) == ["exception-hygiene"] * 3


def test_exception_hygiene_broad_member_of_tuple():
    active, _ = _check("""
        def f():
            try:
                risky()
            except (ValueError, Exception):
                pass
    """, exception_hygiene.rules())
    assert _ids(active) == ["exception-hygiene"]


def test_exception_hygiene_clean_negatives():
    # narrow types, observed failures, and re-raises are all fine
    active, _ = _check("""
        import logging
        def f():
            try:
                risky()
            except ValueError:
                pass                 # narrow: the one expected failure
            try:
                risky()
            except Exception:
                logging.exception("risky failed")
            try:
                risky()
            except Exception:
                count_failure()
                raise
            try:
                risky()
            except Exception:
                out = FALLBACK       # the fallback IS the observation
    """, exception_hygiene.rules())
    assert active == []


def test_exception_hygiene_suppression_honored():
    active, suppressed = _check("""
        def f():
            try:
                risky()
            # graftcheck: ignore[exception-hygiene] -- teardown best-effort
            except Exception:
                pass
    """, exception_hygiene.rules())
    assert active == []
    assert _ids(suppressed) == ["exception-hygiene"]


# -- admission-bypass ---------------------------------------------------------

_CLUSTER_REL = "pinot_tpu/cluster/fixture.py"


def test_admission_bypass_unbounded_queue_true_positive():
    active, _ = _check("""
        import queue
        class Dispatcher:
            def __init__(self):
                self._q = queue.Queue()
                self._lifo = queue.LifoQueue(maxsize=0)
    """, admission_hygiene.rules(), rel=_CLUSTER_REL)
    assert _ids(active) == ["admission-bypass"] * 2


def test_admission_bypass_looped_submit_true_positive():
    active, _ = _check("""
        from concurrent.futures import ThreadPoolExecutor
        class Broker:
            def __init__(self):
                self._scatter = ThreadPoolExecutor(max_workers=4)
            def fan_out(self, units):
                for u in units:
                    self._scatter.submit(u.run)
            def comprehension(self, units, pool):
                return [pool.submit(u.run) for u in units]
    """, admission_hygiene.rules(), rel=_CLUSTER_REL)
    assert _ids(active) == ["admission-bypass"] * 2


def test_admission_bypass_clean_negatives():
    # bounded queues, non-loop submits, and non-executor .submit receivers
    active, _ = _check("""
        import queue
        from concurrent.futures import ThreadPoolExecutor
        class Dispatcher:
            def __init__(self, scheduler):
                self._q = queue.Queue(maxsize=64)
                self._prio = queue.PriorityQueue(128)
                self._pool = ThreadPoolExecutor(max_workers=4)
                self.scheduler = scheduler
            def one_shot(self, task):
                return self._pool.submit(task)          # not fanned out
            def gated(self, tasks):
                for t in tasks:
                    self.scheduler.submit("tbl", t)     # the admission gate
    """, admission_hygiene.rules(), rel=_CLUSTER_REL)
    assert active == []


def test_admission_bypass_scoped_to_cluster_modules():
    active, _ = _check("""
        import queue
        q = queue.Queue()
    """, admission_hygiene.rules())                      # default scratch rel
    assert active == []


def test_admission_bypass_suppression_honored():
    active, suppressed = _check("""
        import queue
        class Dispatcher:
            def __init__(self):
                # graftcheck: ignore[admission-bypass] -- drained by a bounded
                # flow-control window downstream
                self._q = queue.Queue()
    """, admission_hygiene.rules(), rel=_CLUSTER_REL)
    assert active == []
    assert _ids(suppressed) == ["admission-bypass"]


# -- suppression mechanics ----------------------------------------------------

def test_suppression_without_reason_is_a_finding():
    active, _ = _check("""
        def gather(futs):
            return [f.result() for f in futs]  # graftcheck: ignore[blocking-result-no-timeout]
    """, blocking_in_loop.rules())
    assert BAD_SUPPRESSION in _ids(active)
    # the reason-less suppression does NOT silence the rule either
    assert "blocking-result-no-timeout" in _ids(active)


def test_standalone_suppression_covers_wrapped_comment():
    active, suppressed = _check("""
        def gather(futs):
            # graftcheck: ignore[blocking-result-no-timeout] -- a two-line
            # rationale wrapping onto a second comment line
            return [f.result() for f in futs]
    """, blocking_in_loop.rules())
    assert active == []
    assert _ids(suppressed) == ["blocking-result-no-timeout"]


# -- tier-1 gate + CLI exit codes ---------------------------------------------

def test_package_clean_against_committed_baseline():
    """THE tier-1 gate: zero non-baselined findings over the live package."""
    findings, _suppressed, _ctx = run_project()
    new = unbaselined(findings, load_baseline())
    assert not new, "new graftcheck findings:\n" + \
        "\n".join(f.render() for f in new)


def test_cli_exits_nonzero_on_seeded_violation(tmp_path, capsys):
    bad = tmp_path / "seeded.py"
    bad.write_text(textwrap.dedent("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def safe(self):
                with self._lock:
                    self.n += 1
            def racy(self):
                self.n += 1
    """))
    assert analysis_main([str(bad), "--no-baseline"]) == 1
    assert "lock-unguarded-write" in capsys.readouterr().out
    # and the same file with the violation fixed exits 0
    ok = tmp_path / "clean.py"
    ok.write_text("x = 1\n")
    assert analysis_main([str(ok), "--no-baseline"]) == 0


def test_cli_json_format(tmp_path, capsys):
    import json
    bad = tmp_path / "seeded.py"
    bad.write_text("def g(futs):\n    return [f.result() for f in futs]\n")
    assert analysis_main([str(bad), "--no-baseline", "--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["new"][0]["rule"] == "blocking-result-no-timeout"


def test_update_baseline_round_trip(tmp_path, capsys):
    """--update-baseline accepts today's findings, the next run is clean, and
    a NEW violation still fails against the updated baseline."""
    fixture_dir = tmp_path / "corpus"
    fixture_dir.mkdir()
    (fixture_dir / "racy.py").write_text(textwrap.dedent("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def safe(self):
                with self._lock:
                    self.n += 1
            def racy(self):
                self.n += 1
    """))
    bl = str(tmp_path / "baseline.json")
    corpus = str(fixture_dir)
    # seeded violation fails against an empty baseline...
    assert analysis_main([corpus, "--baseline", bl]) == 1
    # ...--update-baseline accepts it and reports what it wrote...
    assert analysis_main([corpus, "--update-baseline", "--baseline", bl]) == 0
    assert "baseline updated" in capsys.readouterr().out
    # ...after which the same corpus is clean, but --no-baseline still sees it
    assert analysis_main([corpus, "--baseline", bl]) == 0
    assert analysis_main([corpus, "--no-baseline"]) == 1
    capsys.readouterr()
    # a NEW violation (unbounded metric label) is not masked by the baseline
    (fixture_dir / "labels.py").write_text(textwrap.dedent("""
        def report(reg, user_id):
            reg.counter("pinot_x_total", {"user": user_id}).inc()
    """))
    assert analysis_main([corpus, "--baseline", bl]) == 1
    assert "metric-label-cardinality" in capsys.readouterr().out


# -- threaded regressions for the lock-discipline sweep fixes -----------------

def test_upsert_concurrent_add_record_stays_consistent():
    """Regression for the upsert _bitmap/_bump lock fix: hammer add_record
    from many threads; exactly one live row per primary key must survive and
    the winner must carry the globally largest comparison value."""
    from pinot_tpu.upsert import PartitionUpsertMetadataManager
    mgr = PartitionUpsertMetadataManager(comparison_enabled=True)
    NKEYS, NTHREADS, NITER = 32, 8, 25
    ndocs = NITER * NKEYS
    barrier = threading.Barrier(NTHREADS)

    def worker(tid):
        barrier.wait()
        for i in range(NITER):
            for k in range(NKEYS):
                mgr.add_record(f"seg{tid}", i * NKEYS + k, (k,),
                               comparison_value=tid * NITER + i)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(NTHREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30.0)
    assert not any(t.is_alive() for t in threads)

    assert mgr.num_primary_keys == NKEYS
    live = 0
    for tid in range(NTHREADS):
        mask = mgr.valid_mask(f"seg{tid}", ndocs)
        if mask is not None:
            live += int(mask.sum())
    assert live == NKEYS
    # the comparison contract: no add_record with a smaller value may have
    # displaced the largest one
    best = (NTHREADS - 1) * NITER + (NITER - 1)
    with mgr._lock:
        for k in range(NKEYS):
            assert mgr._primary_keys[(k,)][2] == best


def test_stub_store_stop_joins_serving_thread():
    """Regression for the stop()-joins sweep: the stub deep stores must fence
    their serving thread on stop, not orphan it."""
    from pinot_tpu.cluster.s3store import S3StubServer
    srv = S3StubServer()
    assert srv._thread.is_alive()
    srv.stop()
    assert not srv._thread.is_alive()


def test_kafkalite_concurrent_topic_creation():
    """Regression for the kafkalite topic-map locking: concurrent
    create_topic calls must collapse to one partition list."""
    from pinot_tpu.ingest.kafkalite import LogBrokerServer
    srv = LogBrokerServer()
    try:
        barrier = threading.Barrier(6)

        def mk():
            barrier.wait()
            for _ in range(20):
                srv.create_topic("events", 4)

        threads = [threading.Thread(target=mk) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=10.0)
        assert len(srv._topics["events"]) == 4
    finally:
        srv.stop()


def test_failure_detector_tick_survives_wedged_probe():
    """Regression for the probe timeout: a probe stuck past probe_timeout_s
    counts as failed and the tick returns instead of wedging."""
    from pinot_tpu.cluster.broker import FailureDetector

    class _Routing:
        def __init__(self):
            self.healthy = []

        def mark_server_healthy(self, sid):
            self.healthy.append(sid)

    routing = _Routing()
    fd = FailureDetector(routing, initial_interval_s=0.0,
                         probe_timeout_s=0.2)
    release = threading.Event()
    fd.register_probe("stuck", lambda: release.wait(30.0))
    fd.register_probe("fine", lambda: True)
    fd.notify_unhealthy("stuck")
    fd.notify_unhealthy("fine")
    t0 = time.monotonic()
    fd.tick(now=time.time() + 1.0)
    elapsed = time.monotonic() - t0
    release.set()  # unblock the abandoned probe thread
    assert elapsed < 5.0, "tick wedged behind a stuck probe"
    assert routing.healthy == ["fine"]
    with fd._lock:
        assert "stuck" in fd._pending  # still unhealthy, backoff rescheduled


# -- interprocedural: call graph, cross-function taint, cross-method races ----

def _project(files, rules, readme=""):
    """Run `rules` over an in-memory multi-module package; (active, supp)."""
    mods = [Module(f"/{rel}", rel, textwrap.dedent(src))
            for rel, src in files.items()]
    for m in mods:
        assert m.parse_error is None, m.parse_error
    ctx = AnalysisContext(repo_root="/nonexistent", modules=mods)
    ctx._readme = readme
    return run_rules(rules, mods, ctx)


_DEVICE_HELPER = """
    import jax.numpy as jnp
    def make_scores(a):
        return jnp.sum(a)
"""


def test_cross_module_host_sync_with_chain():
    active, _ = _project({
        "pkg/helper.py": _DEVICE_HELPER,
        "pkg/caller.py": """
            from pkg.helper import make_scores
            def report(a):
                x = make_scores(a)
                return float(x)
        """,
    }, jit_hygiene.rules())
    syncs = [f for f in active if f.rule == "jit-host-sync"]
    assert [f.path for f in syncs] == ["pkg/caller.py"]
    assert "make_scores" in syncs[0].chain and "float(x)" in syncs[0].chain
    assert "[via " in syncs[0].render()


def test_cross_module_host_sync_negative_on_host_helper():
    active, _ = _project({
        "pkg/helper.py": """
            import jax.numpy as jnp
            def count(a):
                return len(a)
        """,
        "pkg/caller.py": """
            from pkg.helper import count
            def report(a):
                return float(count(a))
        """,
    }, jit_hygiene.rules())
    assert "jit-host-sync" not in _ids(active)


def test_cross_module_host_sync_suppression_honored():
    active, suppressed = _project({
        "pkg/helper.py": _DEVICE_HELPER,
        "pkg/caller.py": """
            from pkg.helper import make_scores
            def report(a):
                x = make_scores(a)
                return float(x)  # graftcheck: ignore[jit-host-sync] -- fixture
        """,
    }, jit_hygiene.rules())
    assert "jit-host-sync" not in _ids(active)
    assert "jit-host-sync" in _ids(suppressed)


def test_self_attr_device_taint_crosses_methods():
    active, _ = _project({
        "pkg/holder.py": """
            import jax.numpy as jnp
            class Holder:
                def put(self, a):
                    self._val = jnp.sum(a)
                def read(self):
                    return float(self._val)
        """,
    }, jit_hygiene.rules())
    syncs = [f for f in active if f.rule == "jit-host-sync"]
    assert len(syncs) == 1 and "stores self._val" in syncs[0].chain


_RACE_STATE = """
    import threading
    from pkg.util import drain
    class Consumer:
        def __init__(self):
            self._lock = threading.Lock()
            self._buf = []
            self._t = threading.Thread(target=self._loop)
        def put(self, x):
            with self._lock:
                self._buf.append(x)
        def _loop(self):
            return drain(self)
        def stop(self):
            self._t.join()
"""


def test_race_cross_method_through_other_module():
    active, _ = _project({
        "pkg/state.py": _RACE_STATE,
        "pkg/util.py": """
            def drain(c):
                return list(c._buf)
        """,
    }, lock_discipline.rules())
    races = [f for f in active if f.rule == "race-cross-method"]
    assert [f.path for f in races] == ["pkg/util.py"]
    assert "Thread(target=self._loop)" in races[0].chain
    assert "drain" in races[0].chain and "read self._buf" in races[0].chain


def test_race_cross_method_negative_when_helper_locks():
    active, _ = _project({
        "pkg/state.py": _RACE_STATE,
        "pkg/util.py": """
            def drain(c):
                with c._lock:
                    return list(c._buf)
        """,
    }, lock_discipline.rules())
    assert "race-cross-method" not in _ids(active)


def test_race_cross_method_suppression_in_helper_module():
    active, suppressed = _project({
        "pkg/state.py": _RACE_STATE,
        "pkg/util.py": """
            def drain(c):
                return list(c._buf)  # graftcheck: ignore[race-cross-method] -- fixture
        """,
    }, lock_discipline.rules())
    assert "race-cross-method" not in _ids(active)
    assert "race-cross-method" in _ids(suppressed)


def test_fixpoint_terminates_on_mutually_recursive_helpers():
    active, _ = _project({
        "pkg/a.py": """
            import jax.numpy as jnp
            from pkg.b import pong
            def ping(n, x):
                if n <= 0:
                    return jnp.sum(x)
                return pong(n - 1, x)
        """,
        "pkg/b.py": """
            from pkg.a import ping
            def pong(n, x):
                return ping(n - 1, x)
        """,
        "pkg/c.py": """
            from pkg.a import ping
            def use(x):
                return float(ping(3, x))
        """,
    }, jit_hygiene.rules())
    syncs = [f for f in active if f.rule == "jit-host-sync"]
    assert [f.path for f in syncs] == ["pkg/c.py"]


def test_chain_carrying_fingerprints_survive_rename_and_shift():
    """Renaming the device-returning helper and shifting the caller's lines
    must not churn the baseline fingerprint — only the chain may change."""
    before_active, _ = _project({
        "pkg/helper.py": _DEVICE_HELPER,
        "pkg/caller.py": """
            from pkg.helper import make_scores
            def report(a):
                x = make_scores(a)
                return float(x)
        """,
    }, jit_hygiene.rules())
    after_active, _ = _project({
        "pkg/helper.py": """
            import jax.numpy as jnp
            def compute_scores(a):
                return jnp.sum(a)
        """,
        "pkg/caller.py": """
            from pkg.helper import compute_scores


            def report(a):
                x = compute_scores(a)
                return float(x)
        """,
    }, jit_hygiene.rules())
    before = {f.fingerprint() for f in before_active
              if f.rule == "jit-host-sync"}
    after = {f.fingerprint() for f in after_active
             if f.rule == "jit-host-sync"}
    assert before and before == after
    chains = {f.chain for f in before_active + after_active
              if f.rule == "jit-host-sync"}
    assert len(chains) == 2  # the chain reflects the rename; the id does not


def test_run_rules_targets_narrow_the_scan():
    files = {
        "pkg/clean.py": "x = 1\n",
        "pkg/bad.py": "def g(futs):\n    return [f.result() for f in futs]\n",
    }
    mods = [Module(f"/{rel}", rel, src) for rel, src in files.items()]
    ctx = AnalysisContext(repo_root="/nonexistent", modules=mods)
    ctx._readme = ""
    rules = blocking_in_loop.rules()
    active, _ = run_rules(rules, mods, ctx, targets=[mods[0]])
    assert active == []
    active, _ = run_rules(rules, mods, ctx, targets=[mods[1]])
    assert _ids(active) == ["blocking-result-no-timeout"]


def test_changed_only_fallbacks(monkeypatch, tmp_path):
    import pinot_tpu.analysis.__main__ as cli
    # a directory with no git repo anywhere above it -> git cannot answer
    assert cli._changed_files("/nonexistent-graftcheck-dir") is None
    monkeypatch.setattr(cli, "_changed_files",
                        lambda root: ["pinot_tpu/analysis/core.py"])
    rels, note = cli._changed_only_rels("/x")
    assert rels is None and "analyzer" in note
    monkeypatch.setattr(cli, "_changed_files", lambda root: ["README.md"])
    assert cli._changed_only_rels("/x")[0] is None
    monkeypatch.setattr(
        cli, "_changed_files",
        lambda root: ["pinot_tpu/cluster/broker.py", "notes.md"])
    rels, note = cli._changed_only_rels("/x")
    assert rels == ["pinot_tpu/cluster/broker.py"] and note == ""
    monkeypatch.setattr(
        cli, "_changed_files",
        lambda root: [f"pinot_tpu/m{i}.py" for i in range(40)])
    assert cli._changed_only_rels("/x")[0] is None


def test_cli_seeded_interprocedural_package(tmp_path, capsys):
    """The acceptance fixture: both new rules firing across module
    boundaries through the CLI, exit 1, chain-annotated messages."""
    (tmp_path / "helper.py").write_text(textwrap.dedent("""
        import jax.numpy as jnp
        def make_scores(a):
            return jnp.sum(a)
    """))
    (tmp_path / "caller.py").write_text(textwrap.dedent("""
        from helper import make_scores
        def report(a):
            x = make_scores(a)
            return float(x)
    """))
    (tmp_path / "state.py").write_text(textwrap.dedent("""
        import threading
        from util import drain
        class Consumer:
            def __init__(self):
                self._lock = threading.Lock()
                self._buf = []
                self._t = threading.Thread(target=self._loop)
            def put(self, x):
                with self._lock:
                    self._buf.append(x)
            def _loop(self):
                return drain(self)
            def stop(self):
                self._t.join()
    """))
    (tmp_path / "util.py").write_text(textwrap.dedent("""
        def drain(c):
            return list(c._buf)
    """))
    assert analysis_main([str(tmp_path), "--no-baseline"]) == 1
    out = capsys.readouterr().out
    assert "jit-host-sync" in out and "race-cross-method" in out
    assert "[via " in out and "make_scores" in out
    assert "Thread(target=self._loop)" in out


# -- unbounded-keyed-accumulation ---------------------------------------------

def test_unbounded_accumulation_true_positive():
    # a query-keyed dict with growth sites and no shrink/bound anywhere:
    # exactly the grow-forever registry bug class
    active, _ = _check("""
        class Registry:
            def __init__(self):
                self.profiles = {}
                self.recent = []

            def observe(self, fingerprint, row):
                self.profiles[fingerprint] = row
                self.recent.append(row)
    """, accumulation.rules(), rel="pinot_tpu/cluster/fixture.py")
    assert _ids(active) == ["unbounded-keyed-accumulation"] * 2
    assert {"self.profiles", "self.recent"} <= {
        a for f in active for a in f.message.split("`")[1::2]}


def test_unbounded_accumulation_clean_negatives():
    # every bounding idiom the rule recognizes: an LRU evict loop (pop),
    # a len() bound check, a deque(maxlen=), and construction-time fill
    active, _ = _check("""
        from collections import OrderedDict, deque

        class Bounded:
            def __init__(self, rows):
                self.lru = OrderedDict()
                self.capped = {}
                self.window = deque(maxlen=256)
                self.index = {r: i for i, r in enumerate(rows)}

            def observe(self, key, row):
                self.lru[key] = row
                while len(self.lru) > 512:
                    self.lru.popitem(last=False)
                if len(self.capped) < 100:
                    self.capped[key] = row
                self.window.append(row)
    """, accumulation.rules(), rel="pinot_tpu/cluster/fixture.py")
    assert active == []


def test_unbounded_accumulation_replace_rebuild_exempt():
    # snapshot-replace idiom: the attr is reassigned wholesale outside its
    # defining method, so each generation's size is the rebuild's concern
    active, _ = _check("""
        class View:
            def __init__(self):
                self.by_table = {}

            def refresh(self, rows):
                self.by_table = {}
                for r in rows:
                    self.by_table[r.table] = r
    """, accumulation.rules(), rel="pinot_tpu/cluster/fixture.py")
    assert active == []


def test_unbounded_accumulation_scoped_to_serving_layers():
    # tools/analysis/bench code is process-short: out of scope
    active, _ = _check("""
        class Collector:
            def __init__(self):
                self.rows = {}

            def add(self, k, v):
                self.rows[k] = v
    """, accumulation.rules(), rel="pinot_tpu/tools/fixture.py")
    assert active == []


def test_unbounded_accumulation_suppression_honored():
    active, suppressed = _check("""
        class Topology:
            def __init__(self):
                self.per_server = {}

            def admit(self, server, row):
                # graftcheck: ignore[unbounded-keyed-accumulation] -- keyed by cluster topology, not query text
                self.per_server[server] = row
    """, accumulation.rules(), rel="pinot_tpu/cluster/fixture.py")
    assert active == []
    assert _ids(suppressed) == ["unbounded-keyed-accumulation"]


def test_full_package_run_within_time_budget():
    """Tier-1 perf guard: the full-package run (call-graph build, fixpoint
    and all rule packs) stays under the 15s budget and exits 0 against the
    committed baseline."""
    t0 = time.perf_counter()
    assert analysis_main([]) == 0
    assert time.perf_counter() - t0 < 15.0


# -- CFG + forward dataflow (the flow-sensitive layer) ------------------------

def _fn(src):
    import ast
    return ast.parse(textwrap.dedent(src)).body[0]


def test_cfg_try_finally_edges():
    """An unmatched exception runs the finally (dispatch -> finally.unwind);
    a catch-all handler removes the unmatched path entirely."""
    from pinot_tpu.analysis.cfg import build_cfg
    g = build_cfg(_fn("""
        def f(self):
            try:
                self.work()
            except ValueError:
                self.log()
            finally:
                self.cleanup()
    """))
    labels = {b.label for b in g.blocks}
    assert {"try.dispatch", "finally.unwind", "finally"} <= labels
    dispatch = next(b for b in g.blocks if b.label == "try.dispatch")
    unwind = next(b for b in g.blocks if b.label == "finally.unwind")
    assert unwind.idx in dispatch.succs

    g2 = build_cfg(_fn("""
        def f(self):
            try:
                self.work()
            except BaseException:
                self.log()
                raise
    """))
    dispatch2 = next(b for b in g2.blocks if b.label == "try.dispatch")
    assert g2.raise_exit not in dispatch2.succs  # catch-all: no unmatched path
    # ...but the handler's own `raise` still reaches raise_exit
    raising = [b for b in g2.blocks if g2.raise_exit in b.succs]
    assert raising


def test_cfg_loop_edges():
    """break exits to loop.after, continue re-enters loop.head, and the
    body's fall-through is the back edge."""
    import ast
    from pinot_tpu.analysis.cfg import build_cfg
    g = build_cfg(_fn("""
        def f(xs):
            for x in xs:
                if x > 9:
                    break
                if x < 0:
                    continue
                handle(x)
    """))
    head = next(b for b in g.blocks if b.label == "loop.head")
    after = next(b for b in g.blocks if b.label == "loop.after")
    assert any(isinstance(s, ast.expr) for s in head.stmts)  # the iterable
    break_blocks = [b for b in g.blocks
                    if any(isinstance(s, ast.Break) for s in b.stmts)]
    assert break_blocks and all(after.idx in b.succs for b in break_blocks)
    cont_blocks = [b for b in g.blocks
                   if any(isinstance(s, ast.Continue) for s in b.stmts)]
    assert cont_blocks and all(head.idx in b.succs for b in cont_blocks)
    back = [b for b in g.blocks
            if head.idx in b.succs and b.idx != g.entry
            and b not in cont_blocks]
    assert back  # the body fall-through back edge


def test_dataflow_fixpoint_terminates_on_cyclic_cfg():
    """A while-True loop with a conditional acquire cycles the lattice
    through {held, free}; the worklist must still reach a fixpoint."""
    from pinot_tpu.analysis.cfg import build_cfg, run_forward
    from pinot_tpu.analysis.lock_discipline import _LockFlow
    fn = _fn("""
        def f(self):
            while True:
                got = self._lock.acquire(timeout=0.1)
                if got:
                    self._lock.release()
                if self.done:
                    break
    """)
    g = build_cfg(fn)
    states = run_forward(g, _LockFlow({"_lock"}))
    assert set(states) == {b.idx for b in g.blocks}
    head = next(b for b in g.blocks if b.label == "loop.head")
    assert states[head.idx] is not None  # the cycle converged, not skipped


# -- lock-manual-acquire (flow-sensitive) -------------------------------------

def test_manual_acquire_exception_leak_true_positive():
    active, _ = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def leak(self):
                self._lock.acquire()
                self.flush()
                self._lock.release()
    """, lock_discipline.rules())
    leaks = [f for f in active if f.rule == "lock-manual-acquire"]
    assert len(leaks) == 1 and "exception path" in leaks[0].message


def test_manual_acquire_try_finally_is_clean():
    active, _ = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def safe(self):
                self._lock.acquire()
                try:
                    self.flush()
                finally:
                    self._lock.release()
    """, lock_discipline.rules())
    assert "lock-manual-acquire" not in _ids(active)


def test_manual_acquire_return_while_held():
    active, _ = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def grab(self):
                self._lock.acquire()
                return self.value
    """, lock_discipline.rules())
    leaks = [f for f in active if f.rule == "lock-manual-acquire"]
    assert len(leaks) == 1 and "still" in leaks[0].message


def test_manual_acquire_semaphore_permit_leak():
    """The mux.py bug class: a factory-bound LOCAL semaphore whose permit
    leaks when a call between acquire and the next iteration raises."""
    active, _ = _check("""
        import threading
        def pump(jobs, run):
            window = threading.Semaphore(4)
            for j in jobs:
                window.acquire()
                run(j)
    """, lock_discipline.rules())
    leaks = [f for f in active if f.rule == "lock-manual-acquire"]
    assert len(leaks) == 1 and "window.acquire()" in leaks[0].message


def test_manual_acquire_suppression_honored():
    active, suppressed = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def leak(self):
                self._lock.acquire()  # graftcheck: ignore[lock-manual-acquire] -- fixture
                self.flush()
                self._lock.release()
    """, lock_discipline.rules())
    assert "lock-manual-acquire" not in _ids(active)
    assert "lock-manual-acquire" in _ids(suppressed)


# -- lock-state-flow ----------------------------------------------------------

_STATE_FLOW_BAD = """
    import threading
    class C:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
        def ok(self):
            with self._lock:
                self._n += 1
        def bad(self):
            self._lock.acquire()
            try:
                self._n += 1
            finally:
                self._lock.release()
            self._n += 2{suffix}
"""


def test_lock_state_flow_write_after_release():
    active, _ = _check(_STATE_FLOW_BAD.format(suffix=""),
                       lock_discipline.rules())
    flows = [f for f in active if f.rule == "lock-state-flow"]
    assert len(flows) == 1
    assert "after self._lock.release()" in flows[0].message
    # the definitely-held write inside the try does NOT double-report as
    # lock-unguarded-write: the flow state credits it as guarded
    assert "lock-unguarded-write" not in _ids(active)


def test_lock_state_flow_conditional_acquire():
    active, _ = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
            def ok(self):
                with self._lock:
                    self._n += 1
            def maybe(self):
                got = self._lock.acquire(timeout=0.1)
                self._n += 1
                if got:
                    self._lock.release()
    """, lock_discipline.rules())
    flows = [f for f in active if f.rule == "lock-state-flow"]
    assert len(flows) == 1
    assert "both with and without" in flows[0].message


def test_lock_state_flow_clean_negative():
    active, _ = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
            def ok(self):
                with self._lock:
                    self._n += 1
            def manual_but_correct(self):
                self._lock.acquire()
                try:
                    self._n += 1
                finally:
                    self._lock.release()
    """, lock_discipline.rules())
    assert "lock-state-flow" not in _ids(active)
    assert "lock-unguarded-write" not in _ids(active)


def test_lock_state_flow_suppression_honored():
    active, suppressed = _check(
        _STATE_FLOW_BAD.format(
            suffix="  # graftcheck: ignore[lock-state-flow] -- fixture"),
        lock_discipline.rules())
    assert "lock-state-flow" not in _ids(active)
    assert "lock-state-flow" in _ids(suppressed)


# -- lock-order-inversion -----------------------------------------------------

def test_lock_order_inversion_direct():
    from pinot_tpu.analysis import lock_order
    active, _ = _check("""
        import threading
        class Broker:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()
            def forward(self):
                with self._alock:
                    with self._block:
                        pass
            def reverse(self):
                with self._block:
                    with self._alock:
                        pass
    """, lock_order.rules())
    inv = [f for f in active if f.rule == "lock-order-inversion"]
    assert len(inv) == 1
    assert "Broker._alock" in inv[0].message
    assert "Broker._block" in inv[0].message
    assert "->" in inv[0].chain  # witness edges ride in the chain


def test_lock_order_inversion_through_call_graph():
    """The two halves of the inversion live in different modules and only
    meet through the call graph's transitive acquisition sets."""
    from pinot_tpu.analysis import lock_order
    active, _ = _project({
        "pkg/a.py": """
            import threading
            from pkg.b import with_b
            _A_LOCK = threading.Lock()
            def with_a_then_b():
                with _A_LOCK:
                    with_b()
            def take_a():
                with _A_LOCK:
                    pass
        """,
        "pkg/b.py": """
            import threading
            from pkg.a import take_a
            _B_LOCK = threading.Lock()
            def with_b():
                with _B_LOCK:
                    pass
            def with_b_then_a():
                with _B_LOCK:
                    take_a()
        """,
    }, lock_order.rules())
    inv = [f for f in active if f.rule == "lock-order-inversion"]
    assert len(inv) == 1
    assert "pkg.a._A_LOCK" in inv[0].message
    assert "pkg.b._B_LOCK" in inv[0].message


def test_lock_order_consistent_is_clean():
    from pinot_tpu.analysis import lock_order
    active, _ = _check("""
        import threading
        class Broker:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()
            def one(self):
                with self._alock:
                    with self._block:
                        pass
            def two(self):
                with self._alock:
                    with self._block:
                        pass
    """, lock_order.rules())
    assert "lock-order-inversion" not in _ids(active)


def test_lock_order_suppression_honored():
    from pinot_tpu.analysis import lock_order
    active, suppressed = _check("""
        import threading
        class Broker:
            def __init__(self):
                self._alock = threading.Lock()
                self._block = threading.Lock()
            def forward(self):
                with self._alock:
                    with self._block:  # graftcheck: ignore[lock-order-inversion] -- fixture
                        pass
            def reverse(self):
                with self._block:
                    with self._alock:
                        pass
    """, lock_order.rules())
    assert "lock-order-inversion" not in _ids(active)
    assert "lock-order-inversion" in _ids(suppressed)


# -- container-element device taint -------------------------------------------

def test_container_element_taint_subscript_store():
    active, _ = _check("""
        import jax.numpy as jnp
        class Cache:
            def __init__(self):
                self._vals = {}
            def put(self, k, a):
                self._vals[k] = jnp.sum(a)
            def read(self, k):
                return float(self._vals[k])
    """, jit_hygiene.rules())
    syncs = [f for f in active if f.rule == "jit-host-sync"]
    assert len(syncs) == 1
    assert "_vals" in syncs[0].message or "_vals" in syncs[0].chain


def test_container_element_taint_append_and_pop():
    active, _ = _check("""
        import jax.numpy as jnp
        class Buf:
            def __init__(self):
                self._parts = []
            def add(self, a):
                self._parts.append(jnp.dot(a, a))
            def head(self):
                return float(self._parts.pop())
    """, jit_hygiene.rules())
    assert "jit-host-sync" in _ids(active)


def test_container_element_taint_setdefault_and_get():
    active, _ = _check("""
        import jax.numpy as jnp
        class Memo:
            def __init__(self):
                self._vals = {}
            def memo(self, k, a):
                self._vals.setdefault(k, jnp.sum(a))
            def peek(self, k):
                return float(self._vals.get(k))
    """, jit_hygiene.rules())
    assert "jit-host-sync" in _ids(active)


def test_container_element_host_values_are_clean():
    active, _ = _check("""
        class Counts:
            def __init__(self):
                self._vals = {}
            def put(self, k, n):
                self._vals[k] = n + 1
            def read(self, k):
                return float(self._vals[k])
    """, jit_hygiene.rules())
    assert "jit-host-sync" not in _ids(active)


def test_container_element_taint_suppression_honored():
    active, suppressed = _check("""
        import jax.numpy as jnp
        class Cache:
            def __init__(self):
                self._vals = {}
            def put(self, k, a):
                self._vals[k] = jnp.sum(a)
            def read(self, k):
                return float(self._vals[k])  # graftcheck: ignore[jit-host-sync] -- fixture
    """, jit_hygiene.rules())
    assert "jit-host-sync" not in _ids(active)
    assert "jit-host-sync" in _ids(suppressed)


# -- SARIF output -------------------------------------------------------------

def test_cli_sarif_round_trip(tmp_path, capsys):
    """--format sarif carries the same findings as --format json: same rules,
    same lines, and the canonical fingerprint under partialFingerprints."""
    import json
    from pinot_tpu.analysis.core import Finding
    bad = tmp_path / "seeded.py"
    bad.write_text(textwrap.dedent("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
            def leak(self):
                self._lock.acquire()
                self.flush()
                self._lock.release()
        def g(futs):
            return [f.result() for f in futs]
    """))
    assert analysis_main([str(bad), "--no-baseline", "--format", "json"]) == 1
    jnew = json.loads(capsys.readouterr().out)["new"]
    assert analysis_main([str(bad), "--no-baseline", "--format", "sarif"]) == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    results = sarif["runs"][0]["results"]
    assert [r["ruleId"] for r in results] == [f["rule"] for f in jnew]
    assert [r["locations"][0]["physicalLocation"]["region"]["startLine"]
            for r in results] == [f["line"] for f in jnew]
    want = [Finding(f["rule"], f["path"], f["line"], f["message"],
                    chain=f.get("chain", "")).fingerprint() for f in jnew]
    assert [r["partialFingerprints"]["graftcheck/v1"]
            for r in results] == want
    driver_rules = {r["id"] for r in sarif["runs"][0]["tool"]["driver"]["rules"]}
    assert {"lock-manual-acquire", "lock-state-flow",
            "lock-order-inversion"} <= driver_rules
    # a clean file: exit 0, zero results, rule metadata still present
    ok = tmp_path / "clean.py"
    ok.write_text("x = 1\n")
    assert analysis_main([str(ok), "--no-baseline", "--format", "sarif"]) == 0
    empty = json.loads(capsys.readouterr().out)
    assert empty["runs"][0]["results"] == []
    assert empty["runs"][0]["tool"]["driver"]["rules"]


# -- --changed-only closure reaches flow-sensitive dependents -----------------

def test_changed_only_closure_reaches_lock_flow_dependents(tmp_path):
    """Editing a helper must re-fire its importer's lock-flow finding via the
    reverse import closure; restricting to an unrelated module must not."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "helper.py").write_text(textwrap.dedent("""
        def compute(x):
            return x + 1
    """))
    (pkg / "consumer.py").write_text(textwrap.dedent("""
        import threading
        from pkg.helper import compute
        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
            def step(self, x):
                self._lock.acquire()
                y = compute(x)
                self._lock.release()
                return y
    """))
    (pkg / "unrelated.py").write_text("z = 3\n")
    root = str(tmp_path)
    findings, _, _ = run_project(paths=[root], repo_root=root,
                                 restrict_rels=["pkg/helper.py"])
    assert "lock-manual-acquire" in _ids(findings)
    assert all(f.path == "pkg/consumer.py" for f in findings
               if f.rule == "lock-manual-acquire")
    findings, _, _ = run_project(paths=[root], repo_root=root,
                                 restrict_rels=["pkg/unrelated.py"])
    assert "lock-manual-acquire" not in _ids(findings)


# -- fingerprint stability for the flow-sensitive rules -----------------------

def test_lock_state_flow_fingerprint_survives_rename_and_shift():
    """Line shifts and renaming an uninvolved helper must not churn the
    lock-state-flow fingerprint (message is line-free)."""
    before_active, _ = _check("""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0
            def helper_one(self):
                return 1
            def ok(self):
                with self._lock:
                    self._n += 1
            def bad(self):
                self._lock.acquire()
                try:
                    self._n += 1
                finally:
                    self._lock.release()
                self._n += 2
    """, lock_discipline.rules())
    after_active, _ = _check("""
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def renamed_helper(self):
                return 1

            def ok(self):
                with self._lock:
                    self._n += 1

            def bad(self):
                self._lock.acquire()
                try:
                    self._n += 1
                finally:
                    self._lock.release()
                self._n += 2
    """, lock_discipline.rules())
    before = {f.fingerprint() for f in before_active
              if f.rule == "lock-state-flow"}
    after = {f.fingerprint() for f in after_active
             if f.rule == "lock-state-flow"}
    assert before and before == after
    lines = {f.line for f in before_active + after_active
             if f.rule == "lock-state-flow"}
    assert len(lines) == 2  # the site moved; the identity did not


# -- threaded regression: mux flow-control window under submit failure --------

def test_mux_submit_failure_releases_window_permit():
    """Regression for the demux window-permit leak: when executor.submit
    raises (shutdown mid-stream), the permit and the inflight count must be
    rolled back so the stream still drains instead of hanging forever."""
    import io
    from concurrent.futures import ThreadPoolExecutor
    from pinot_tpu.cluster.mux import (serve_mux_stream, _HEADER,
                                       KIND_REQUEST)
    ex = ThreadPoolExecutor(1)
    ex.shutdown()
    body = io.BytesIO(_HEADER.pack(7, KIND_REQUEST, 0))
    frames = serve_mux_stream(body, lambda p, w: (200, [b"x"]), ex,
                              max_inflight=2)
    out = []
    consumer = threading.Thread(target=lambda: out.extend(frames))
    consumer.start()
    consumer.join(timeout=10.0)
    assert not consumer.is_alive(), \
        "mux stream failed to drain after submit() raised"
    assert out == []  # the failed frame produced no response
