"""Observability tests: metrics registry, per-phase timers, request tracing.

Reference pattern: the metrics stack (`pinot-common/.../metrics/`, AbstractMetrics +
meter catalogs), per-phase timings (`ServerQueryPhase`/`BrokerQueryPhase`) and the
trace SPI (`pinot-spi/.../trace/Tracing.java`) exercised via OPTION(trace=true).
"""

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.query.executor import execute_query
from pinot_tpu.schema import DataType, FieldSpec, Schema
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig
from pinot_tpu.table import TableConfig
from pinot_tpu.utils.metrics import MetricsRegistry, get_registry
from pinot_tpu.utils.trace import Trace, request_trace, span

from conftest import make_ssb_columns


# -- registry primitives -----------------------------------------------------

def test_counter_gauge_timer():
    reg = MetricsRegistry()
    reg.counter("q").inc()
    reg.counter("q").inc(2)
    assert reg.counter_value("q") == 3
    # labels split the series
    reg.counter("q", {"table": "a"}).inc()
    assert reg.counter_value("q", {"table": "a"}) == 1
    assert reg.counter_value("q") == 3
    reg.gauge("g").set(7.5)
    t = reg.timer("lat")
    with t.time():
        pass
    t.update(10.0)
    assert t.count == 2 and t.max_ms >= 10.0
    snap = reg.snapshot()
    assert snap["q"] == 3 and snap["q{table=a}"] == 1 and snap["g"] == 7.5
    assert snap["lat_count"] == 2


def test_prometheus_render():
    reg = MetricsRegistry()
    reg.counter("pinot_server_queries", {"table": "t1"}).inc(5)
    reg.counter("pinot_server_queries", {"table": "t2"}).inc(1)
    reg.gauge("pinot_up").set(1)
    reg.timer("lat").update(3.0)
    text = reg.render_prometheus()
    assert 'pinot_server_queries{table="t1"} 5.0' in text
    assert 'pinot_server_queries{table="t2"} 1.0' in text
    # exactly ONE TYPE line per family even with multiple labeled series —
    # Prometheus rejects an exposition with duplicate TYPE lines
    assert text.count("# TYPE pinot_server_queries counter") == 1
    assert "pinot_up 1.0" in text
    assert "lat_count 1" in text and "lat_sum 3.0" in text
    # label values escape quotes/backslashes/newlines
    reg.counter("esc", {"q": 'a"b\\c\nd'}).inc()
    assert 'esc{q="a\\"b\\\\c\\nd"} 1.0' in reg.render_prometheus()


# -- trace primitives ---------------------------------------------------------

def test_trace_spans_nest_and_cross_threads():
    import threading
    with request_trace(True) as tr:
        with span("outer"):
            with span("inner"):
                pass

        def worker():
            with tr.activate(), span("thread-side"):
                pass
        th = threading.Thread(target=worker)
        th.start()
        th.join()
    rows = tr.to_rows()
    names = {s["name"]: s for s in rows}
    assert set(names) == {"outer", "inner", "thread-side"}
    assert names["outer"]["depth"] == 0
    assert names["inner"]["depth"] == 1
    assert names["thread-side"]["depth"] == 0


def test_disabled_trace_is_noop():
    with request_trace(False) as tr:
        assert tr is None
        with span("ignored"):
            pass


# -- executor phase timers -----------------------------------------------------

SCHEMA = Schema("obs", [
    FieldSpec("k", DataType.STRING),
    FieldSpec("v", DataType.DOUBLE),
])


@pytest.fixture(scope="module")
def seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("obs")
    builder = SegmentBuilder(SCHEMA, SegmentGeneratorConfig())
    d = builder.build({"k": np.array(["a", "b", "a", "c"], dtype=object),
                       "v": np.array([1.0, 2.0, 3.0, 4.0])}, str(tmp), "obs_0")
    return load_segment(d)


def test_executor_phase_times(seg):
    res = execute_query([seg], "SELECT k, SUM(v) FROM obs GROUP BY k")
    pt = res.stats["phaseTimesMs"]
    assert set(pt) == {"compile", "scan", "reduce"}
    assert all(v >= 0 for v in pt.values())


# -- cluster wiring -------------------------------------------------------------

@pytest.fixture()
def lineorder_cluster(tmp_path, ssb_schema):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    rng = np.random.default_rng(11)
    cfg = TableConfig(ssb_schema.name, replication=1, time_column="lo_orderdate")
    cluster.create_table(ssb_schema, cfg)
    for _ in range(2):
        cluster.ingest_columns(cfg, make_ssb_columns(rng, 500))
    return cluster, cfg


def test_broker_and_server_meters(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    reg = get_registry()
    q0 = reg.counter_value("pinot_broker_queries")
    d0 = reg.counter_value("pinot_server_docs_scanned")
    e0 = reg.counter_value("pinot_broker_query_exceptions")

    # group-by: a bare COUNT(*) (even with a foldable filter) answers from
    # metadata and scans 0 docs, which would not move the docs-scanned meter
    res = cluster.query("SELECT lo_region, COUNT(*) FROM lineorder "
                        "GROUP BY lo_region")
    assert sum(r[1] for r in res.rows) == 1000
    assert reg.counter_value("pinot_broker_queries") == q0 + 1
    assert reg.counter_value("pinot_server_docs_scanned") >= d0 + 1000
    assert reg.counter_value(
        "pinot_server_queries", {"table": cfg.table_name_with_type}) >= 1
    assert "phaseTimesMs" in res.stats
    assert set(res.stats["phaseTimesMs"]) == {"compile", "scatter", "reduce"}

    with pytest.raises(Exception):
        cluster.query("SELECT COUNT(*) FROM no_such_table")
    assert reg.counter_value("pinot_broker_query_exceptions") == e0 + 1
    # latency timer observed every successful query
    assert reg.timer("pinot_broker_query_latency_ms").count >= 1


def test_trace_through_broker(lineorder_cluster):
    cluster, cfg = lineorder_cluster
    res = cluster.query("SELECT lo_region, COUNT(*) FROM lineorder "
                        "GROUP BY lo_region OPTION(trace=true)")
    spans = res.stats["traceInfo"]
    names = [s["name"] for s in spans]
    assert "compile" in names and "reduce" in names
    assert any(n.startswith("server:") for n in names)
    assert any(n.startswith("segment:") for n in names)
    # untraced query carries no traceInfo
    res2 = cluster.query("SELECT COUNT(*) FROM lineorder")
    assert "traceInfo" not in res2.stats


def test_segment_status_checker_and_task_metrics(tmp_path):
    """Reference: SegmentStatusChecker / TaskMetricsEmitter /
    MinionInstancesCleanupTask periodic controller tasks."""
    import numpy as np
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.cluster.catalog import InstanceInfo
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import TableConfig
    from pinot_tpu.utils.metrics import get_registry

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = Schema("m1", [dimension("k"), metric("v", DataType.DOUBLE)])
    cfg = TableConfig("m1")
    cluster.create_table(schema, cfg)
    cluster.ingest_columns(cfg, {"k": ["a", "b"], "v": np.array([1.0, 2.0])})

    st = cluster.controller.run_segment_status_check()
    assert st["m1_OFFLINE"]["segments"] == 1
    assert st["m1_OFFLINE"]["online"] == 1
    reg = get_registry()
    assert reg.gauge("pinot_controller_segments_total",
                     {"table": "m1_OFFLINE"}).value == 1
    assert reg.gauge("pinot_controller_table_converged",
                     {"table": "m1_OFFLINE"}).value == 1

    # dead minion cleanup
    cluster.catalog.register_instance(InstanceInfo("minion_9", "minion"))
    cluster.catalog.set_instance_alive("minion_9", False)
    assert cluster.controller.cleanup_dead_minions() == ["minion_9"]
    assert "minion_9" not in cluster.catalog.instances
    assert cluster.controller.cleanup_dead_minions() == []

    # task metrics over the queue (generate_all may enqueue nothing here;
    # emit must not fail on an empty queue either way)
    counts = cluster.controller.emit_task_metrics()
    assert isinstance(counts, dict)
