"""Geospatial tests: ST_* functions, device haversine rewrite, geo cell index.

Reference patterns: StDistanceFunctionTest / StContainsFunctionTest +
H3IndexFilterOperator (coarse cell cover + exact refine).
"""

import numpy as np
import pytest

from pinot_tpu.engine.geo_fns import (GeoPolygon, haversine_m, parse_wkt,
                                      rewrite_geo)
from pinot_tpu.query.executor import ServerQueryExecutor, execute_query
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.reader import load_segment
from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

# well-known distances: SFO (-122.375, 37.619), LAX (-118.408, 33.9425)
SFO = (-122.375, 37.619)
LAX = (-118.408, 33.9425)
SFO_LAX_M = 543_000  # ~543 km


N = 2000
RNG = np.random.default_rng(9)
LNG = RNG.uniform(-123.0, -118.0, N)
LAT = RNG.uniform(33.0, 38.5, N)

SCHEMA = Schema("places", [
    dimension("name", DataType.STRING),
    metric("lng", DataType.DOUBLE),
    metric("lat", DataType.DOUBLE),
])
COLS = {"name": [f"p{i}" for i in range(N)], "lng": LNG, "lat": LAT}


@pytest.fixture(scope="module")
def seg(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("geo")
    return load_segment(SegmentBuilder(SCHEMA, SegmentGeneratorConfig())
                        .build(dict(COLS), str(tmp), "places_0"))


@pytest.fixture(scope="module")
def seg_indexed(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("geoidx")
    cfg = SegmentGeneratorConfig(geo_index_pairs=["lng,lat"])
    return load_segment(SegmentBuilder(SCHEMA, cfg)
                        .build(dict(COLS), str(tmp), "places_idx"))


# -- function library ---------------------------------------------------------

def test_wkt_roundtrip():
    p = parse_wkt("POINT (-122.375 37.619)")
    assert p == complex(-122.375, 37.619)
    poly = parse_wkt("POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))")
    assert isinstance(poly, GeoPolygon)
    assert poly.contains(2, 2) and not poly.contains(5, 1)


def test_haversine_known_distance():
    d = haversine_m(*SFO, *LAX)
    assert d == pytest.approx(SFO_LAX_M, rel=0.01)


def test_st_functions_in_selection(seg):
    res = execute_query(
        [seg], "SELECT name, ST_DISTANCE(ST_POINT(lng, lat), "
               "ST_GEOGFROMTEXT('POINT (-122.375 37.619)')) FROM places "
               "ORDER BY name LIMIT 3")
    exp = haversine_m(LNG, LAT, *SFO)
    by_name = {f"p{i}": exp[i] for i in range(N)}
    for name, d in res.rows:
        assert d == pytest.approx(by_name[name], rel=1e-6)
    res = execute_query(
        [seg], "SELECT ST_ASTEXT(ST_POINT(lng, lat)), ST_X(ST_POINT(lng, lat)) "
               "FROM places LIMIT 1")
    assert res.rows[0][0].startswith("POINT (")
    assert res.rows[0][1] == pytest.approx(LNG[0])


def test_rewrite_produces_device_plan(seg):
    """The distance predicate must compile onto the fused device kernel."""
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import plan_segment
    sql = ("SELECT COUNT(*) FROM places WHERE "
           "ST_DISTANCE(ST_POINT(lng, lat), ST_POINT(-122.375, 37.619)) < 100000")
    ctx = compile_query(sql, SCHEMA)
    plan = plan_segment(ctx, seg)
    assert plan.kind == "device", plan.fallback_reason


@pytest.mark.parametrize("radius", [50_000, 200_000, 500_000])
def test_distance_filter_device_host_parity(seg, radius):
    sql = (f"SELECT COUNT(*) FROM places WHERE ST_DISTANCE(ST_POINT(lng, lat), "
           f"ST_GEOGFROMTEXT('POINT (-122.375 37.619)')) < {radius}")
    dev = ServerQueryExecutor(use_device=True).execute([seg], sql).rows[0][0]
    host = ServerQueryExecutor(use_device=False).execute([seg], sql).rows[0][0]
    exact = int((haversine_m(LNG, LAT, *SFO) < radius).sum())
    assert host == exact
    # f32 trig on device may flip docs within ~1e-4 relative of the boundary
    assert abs(dev - exact) <= max(2, int(0.002 * exact))


def test_polygon_contains_filter(seg):
    sql = ("SELECT COUNT(*) FROM places WHERE ST_CONTAINS("
           "ST_GEOGFROMTEXT('POLYGON ((-123 36, -120 36, -120 38, -123 38, -123 36))'), "
           "ST_POINT(lng, lat))")
    got = execute_query([seg], sql).rows[0][0]
    exact = int(((LNG >= -123) & (LNG <= -120) & (LAT >= 36) & (LAT <= 38)).sum())
    assert got == exact
    # ST_WITHIN is the flipped-argument equivalent
    sql2 = ("SELECT COUNT(*) FROM places WHERE ST_WITHIN(ST_POINT(lng, lat), "
            "ST_GEOGFROMTEXT('POLYGON ((-123 36, -120 36, -120 38, -123 38, -123 36))'))")
    assert execute_query([seg], sql2).rows[0][0] == exact


# -- geo cell index -----------------------------------------------------------

def test_geo_index_candidates_superset(seg_indexed):
    idx = seg_indexed.geo_index("lng", "lat")
    assert idx is not None
    for radius in (20_000, 100_000):
        mask = idx.candidate_mask(*SFO, radius, N)
        exact = haversine_m(LNG, LAT, *SFO) < radius
        assert (mask | ~exact).all(), "candidates must be a superset"
        assert mask.sum() < N, "cover must actually prune"


def test_geo_index_query_matches_unindexed(seg, seg_indexed):
    sql = ("SELECT COUNT(*) FROM places WHERE ST_DISTANCE(ST_POINT(lng, lat), "
           "ST_POINT(-122.375, 37.619)) < 150000")
    host_plain = ServerQueryExecutor(use_device=False).execute([seg], sql).rows
    host_idx = ServerQueryExecutor(use_device=False).execute([seg_indexed], sql).rows
    assert host_plain == host_idx
    dev_idx = ServerQueryExecutor(use_device=True).execute([seg_indexed], sql).rows
    assert abs(dev_idx[0][0] - host_idx[0][0]) <= 2


def test_geo_index_in_explain(seg_indexed):
    res = execute_query(
        [seg_indexed], "EXPLAIN PLAN FOR SELECT COUNT(*) FROM places WHERE "
        "ST_DISTANCE(ST_POINT(lng, lat), ST_POINT(-122.375, 37.619)) < 50000")
    ls = [r[0] for r in res.rows]
    assert any("FILTER_DOCSET" in l and "geo cells" in l for l in ls)
    assert any("FILTER_EXPR" in l for l in ls)


def test_geo_index_antimeridian_and_poles(tmp_path):
    """Cells wrap at lng ±180 and clamp at lat ±90 — the superset invariant
    must hold at the globe's seams."""
    lng = np.array([-179.95, 179.95, 10.0, 0.0])
    lat = np.array([0.0, 0.0, 90.0, -90.0])
    cols = {"name": ["a", "b", "c", "d"], "lng": lng, "lat": lat}
    cfg = SegmentGeneratorConfig(geo_index_pairs=["lng,lat"])
    seg = load_segment(SegmentBuilder(SCHEMA, cfg).build(
        cols, str(tmp_path), "seam_0"))
    idx = seg.geo_index("lng", "lat")
    # circle centered just east of the date line must reach the western doc
    mask = idx.candidate_mask(179.95, 0.0, 30_000, 4)
    exact = haversine_m(lng, lat, 179.95, 0.0) < 30_000
    assert (mask | ~exact).all()
    assert mask[0] and mask[1]
    # pole doc reachable from a near-pole center
    mask = idx.candidate_mask(10.0, 89.99, 50_000, 4)
    exact = haversine_m(lng, lat, 10.0, 89.99) < 50_000
    assert (mask | ~exact).all() and mask[2]


def test_flipped_distance_predicate_uses_index_and_device(seg, seg_indexed):
    """`r > ST_DISTANCE(...)` is the same predicate: device plan + geo docset."""
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import plan_segment
    sql = ("SELECT COUNT(*) FROM places WHERE 100000 > "
           "ST_DISTANCE(ST_POINT(lng, lat), ST_POINT(-122.375, 37.619))")
    ctx = compile_query(sql, SCHEMA)
    assert plan_segment(ctx, seg).kind == "device"
    res = execute_query([seg_indexed], "EXPLAIN PLAN FOR " + sql)
    assert any("geo cells" in r[0] for r in res.rows)
    straight = execute_query(
        [seg_indexed], sql.replace("100000 > ST_DISTANCE", "ST_DISTANCE")
        .replace("37.619))", "37.619)) < 100000")).rows
    assert execute_query([seg_indexed], sql).rows == straight


def test_geo_index_null_coordinates(tmp_path):
    """Null coordinates index under the stored null-fill values, keeping the
    index consistent with the column (no dropped or phantom rows)."""
    cols = {"name": ["a", "b"], "lng": [-122.0, None], "lat": [37.0, None]}
    cfg = SegmentGeneratorConfig(geo_index_pairs=["lng,lat"])
    seg = load_segment(SegmentBuilder(SCHEMA, cfg).build(
        cols, str(tmp_path), "nulls_0"))
    sql = ("SELECT COUNT(*) FROM places WHERE ST_DISTANCE(ST_POINT(lng, lat), "
           "ST_POINT(-122.0, 37.0)) < 1000")
    assert ServerQueryExecutor(use_device=False).execute([seg], sql).rows[0][0] == 1


def test_geo_index_built_by_every_ingestion_path(tmp_path):
    """Batch ingestion and realtime flush honor geo_index_pairs like quickstart."""
    from pinot_tpu.segment.writer import SegmentGeneratorConfig as SGC
    from pinot_tpu.table import IndexingConfig
    idx = IndexingConfig(geo_index_pairs=["lng,lat"])
    gen = SGC.from_indexing(idx)
    assert gen.geo_index_pairs == ["lng,lat"]


def test_geo_cluster_path(tmp_path):
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.table import IndexingConfig, TableConfig
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    cfg = TableConfig("places",
                      indexing=IndexingConfig(geo_index_pairs=["lng,lat"]))
    cluster.create_table(SCHEMA, cfg)
    cluster.ingest_columns(cfg, dict(COLS))
    res = cluster.query(
        "SELECT COUNT(*) FROM places WHERE ST_DISTANCE(ST_POINT(lng, lat), "
        "ST_POINT(-122.375, 37.619)) < 100000")
    exact = int((haversine_m(LNG, LAT, *SFO) < 100_000).sum())
    assert abs(res.rows[0][0] - exact) <= 2


def test_stunion_aggregation(tmp_path):
    """STUNION: distinct-point union serialized as MULTIPOINT WKT
    (reference: StUnionAggregationFunction)."""
    import numpy as np
    from pinot_tpu.query.executor import execute_query
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, load_segment
    schema = Schema("pts", [dimension("city"),
                            metric("lng", DataType.DOUBLE),
                            metric("lat", DataType.DOUBLE)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"city": ["a", "b", "a"],
         "lng": np.array([1.0, 2.0, 1.0]),
         "lat": np.array([3.0, 4.0, 3.0])}, str(tmp_path), "pts_0"))
    res = execute_query([seg],
                        "SELECT STUNION(ST_POINT(lng, lat)) FROM pts")
    assert res.rows[0][0] == "MULTIPOINT (1 3, 2 4)"
    res = execute_query([seg], "SELECT STUNION(ST_POINT(lng, lat)) FROM pts "
                               "WHERE city = 'nope'")
    assert res.rows[0][0] == "MULTIPOINT EMPTY"
    res = execute_query([seg], "SELECT city, STUNION(ST_POINT(lng, lat)) FROM pts "
                               "GROUP BY city ORDER BY city LIMIT 5")
    assert res.rows == [["a", "MULTIPOINT (1 3)"], ["b", "MULTIPOINT (2 4)"]]
