"""Ingestion tests: readers, transforms, batch jobs, mutable segments, realtime
consumption + segment completion protocol.

Reference patterns: record-transformer unit tests, LLCRealtimeClusterIntegrationTest and
SegmentCompletionIntegrationTest (FSM driving) — all in-process (SURVEY.md §4).
"""

import json
import os

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.cluster.catalog import CONSUMING, ONLINE, STATUS_DONE, STATUS_IN_PROGRESS
from pinot_tpu.ingest.batch import BatchIngestionJobSpec, run_batch_ingestion
from pinot_tpu.ingest.stream import MemoryStream
from pinot_tpu.ingest.transform import TransformPipeline
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.segment.mutable import MutableSegment
from pinot_tpu.table import StreamConfig, TableConfig, TableType


@pytest.fixture(autouse=True)
def _reset_streams():
    MemoryStream.reset_all()
    yield
    MemoryStream.reset_all()


@pytest.fixture()
def events_schema():
    return Schema("events", [
        dimension("user", DataType.STRING),
        dimension("country", DataType.STRING),
        metric("value", DataType.DOUBLE),
        metric("clicks", DataType.INT),
    ])


# -- transforms --------------------------------------------------------------

def test_transform_pipeline(events_schema):
    p = TransformPipeline(events_schema,
                          filter_expr="value < 0",
                          column_transforms={"clicks": "clicks * 2"})
    cols = p.apply({"user": ["a", "b", "c"], "country": ["US", "DE", "US"],
                    "value": [1.0, -5.0, 2.0], "clicks": [1, 2, 3]})
    assert cols["user"] == ["a", "c"]
    assert cols["clicks"] == [2, 6]
    assert cols["value"] == [1.0, 2.0]


def test_transform_missing_column_defaults(events_schema):
    p = TransformPipeline(events_schema)
    cols = p.apply({"user": ["a"], "value": [1.5]})
    assert cols["country"] == [None]
    assert cols["clicks"] == [None]


# -- readers + batch job -----------------------------------------------------

def test_batch_ingestion_job(tmp_path, events_schema):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path / "cluster"))
    cfg = TableConfig("events")
    cluster.create_table(events_schema, cfg)

    csv_path = tmp_path / "in.csv"
    csv_path.write_text("user,country,value,clicks\n" +
                        "".join(f"u{i % 7},C{i % 3},{i}.5,{i}\n" for i in range(100)))
    jsonl_path = tmp_path / "in.jsonl"
    jsonl_path.write_text("".join(
        json.dumps({"user": f"u{i}", "country": "JP", "value": i, "clicks": 1}) + "\n"
        for i in range(20)))

    spec = BatchIngestionJobSpec(
        input_paths=[str(csv_path), str(jsonl_path)],
        table=cfg.table_name_with_type,
        segment_rows=50,
        filter_expr="clicks > 90",
    )
    pushed = run_batch_ingestion(spec, cluster.controller, work_dir=str(tmp_path))
    assert len(pushed) == 3  # 111 rows kept / 50 per segment
    res = cluster.query("SELECT COUNT(*), SUM(value) FROM events")
    assert res.rows[0][0] == 111  # 100 - 9 filtered + 20


# -- mutable segment ---------------------------------------------------------

def test_mutable_segment_query(events_schema):
    from pinot_tpu.query.executor import ServerQueryExecutor
    seg = MutableSegment("events__0__0__x", events_schema)
    for i in range(50):
        seg.index({"user": f"u{i % 5}", "country": "US" if i % 2 else "DE",
                   "value": float(i), "clicks": i})
    ex = ServerQueryExecutor()
    res = ex.execute([seg], "SELECT COUNT(*), SUM(value) FROM events "
                            "WHERE country = 'US'", events_schema)
    assert res.rows[0][0] == 25
    res2 = ex.execute([seg], "SELECT user, COUNT(*) FROM events GROUP BY user LIMIT 10",
                      events_schema)
    assert sum(r[1] for r in res2.rows) == 50


# -- realtime end-to-end -----------------------------------------------------

def realtime_cluster(tmp_path, events_schema, replication=2, flush_rows=40,
                     num_partitions=2):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig("events", table_type=TableType.REALTIME, replication=replication,
                      stream=StreamConfig(stream_type="memory", topic="events_topic",
                                          decoder="json",
                                          flush_threshold_rows=flush_rows))
    cluster.create_realtime_table(events_schema, cfg, num_partitions)
    return cluster, cfg


def produce(topic, partition, rows):
    stream = MemoryStream.get(topic)
    for row in rows:
        stream.produce(json.dumps(row), partition=partition)


def test_realtime_consume_query_commit(tmp_path, events_schema):
    cluster, cfg = realtime_cluster(tmp_path, events_schema)
    table = cfg.table_name_with_type

    # initial CONSUMING segments exist and are routable
    ist = cluster.catalog.ideal_state[table]
    assert len(ist) == 2 and all(set(a.values()) == {CONSUMING} for a in ist.values())

    produce("events_topic", 0, [{"user": f"u{i}", "country": "US", "value": i,
                                 "clicks": 1} for i in range(30)])
    produce("events_topic", 1, [{"user": f"v{i}", "country": "DE", "value": i,
                                 "clicks": 1} for i in range(10)])
    cluster.pump_realtime(table)

    # rows visible before any commit (consuming segments are queryable)
    res = cluster.query("SELECT COUNT(*) FROM events")
    assert res.rows[0][0] == 40

    # cross the flush threshold on partition 0 -> completion protocol runs
    produce("events_topic", 0, [{"user": "x", "country": "US", "value": 1,
                                 "clicks": 2} for _ in range(15)])
    cluster.pump_realtime(table)   # consume; first consumed report HOLDs
    cluster.pump_realtime(table)   # re-report -> elect committer -> COMMIT round
    cluster.pump_realtime(table)

    metas = cluster.catalog.segments[table]
    done = [m for m in metas.values() if m.status == STATUS_DONE]
    assert len(done) == 1
    committed = done[0]
    assert committed.partition_group == 0
    assert int(committed.end_offset) == 45
    assert committed.num_docs == 45
    # successor consuming segment created from the end offset
    successors = [m for m in metas.values()
                  if m.partition_group == 0 and m.sequence_number == 1]
    assert len(successors) == 1
    assert successors[0].status == STATUS_IN_PROGRESS
    assert int(successors[0].start_offset) == 45

    # committed segment serves ONLINE replicas; data still complete
    res = cluster.query("SELECT COUNT(*) FROM events")
    assert res.rows[0][0] == 55
    ev = cluster.catalog.external_view[table]
    assert set(ev[committed.name].values()) == {ONLINE}


def test_realtime_data_survives_commit_plus_new_rows(tmp_path, events_schema):
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=20,
                                    num_partitions=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "country": "US", "value": 1,
                                 "clicks": 1} for i in range(25)])
    for _ in range(4):
        cluster.pump_realtime(table)
    # post-commit rows land in the successor consuming segment
    produce("events_topic", 0, [{"user": "z", "country": "JP", "value": 2,
                                 "clicks": 1} for _ in range(5)])
    cluster.pump_realtime(table)
    res = cluster.query("SELECT COUNT(*), SUM(value) FROM events")
    assert res.rows[0][0] == 30
    assert res.rows[0][1] == pytest.approx(25 + 10)


def test_drop_realtime_table_stops_consumers(tmp_path, events_schema):
    """Dropping a realtime table must stop + forget its realtime manager — a
    stale handler would keep consuming and shadow a recreated table's config."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, num_partitions=1,
                                    replication=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": "a", "country": "US", "value": 1,
                                 "clicks": 1} for _ in range(5)])
    cluster.pump_realtime(table)
    mgrs = [s.realtime_manager(table) for s in cluster.servers
            if s.realtime_manager(table) is not None]
    assert mgrs, "a consuming manager must exist before the drop"

    cluster.controller.drop_table(table)
    for s in cluster.servers:
        assert s.realtime_manager(table) is None, "manager must be forgotten"
    for m in mgrs:
        assert m._stop.is_set(), "consume loop must be stopped"


def test_completion_fsm_edges():
    from pinot_tpu.cluster.completion import CompletionFSM, HOLD, CATCHUP, COMMIT, KEEP, DISCARD
    fsm = CompletionFSM("seg", num_replicas=2)
    # first reporter holds until all replicas report
    assert fsm.on_consumed("s1", 100)["status"] == HOLD
    # second reporter at lower offset: election happens; s1 wins; s2 must catch up
    r = fsm.on_consumed("s2", 90)
    assert r["status"] == CATCHUP and r["offset"] == 100
    # winner gets COMMIT
    assert fsm.on_consumed("s1", 100)["status"] == COMMIT
    assert fsm.on_commit_start("s2") == "FAILED"      # only the committer may commit
    assert fsm.on_commit_start("s1") == "COMMIT_CONTINUE"
    assert fsm.on_commit_end("s1", 100) == "COMMIT_SUCCESS"
    # post-commit reports: caught-up replica keeps local build, laggard discards
    assert fsm.on_consumed("s2", 100)["status"] == KEEP
    assert fsm.on_consumed("s3", 90)["status"] == DISCARD


def test_repair_missing_consuming_segment(tmp_path, events_schema):
    cluster, cfg = realtime_cluster(tmp_path, events_schema, flush_rows=10,
                                    num_partitions=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": "a", "country": "US", "value": 1, "clicks": 1}
                                for _ in range(12)])
    for _ in range(4):
        cluster.pump_realtime(table)
    metas = cluster.catalog.segments[table]
    # simulate controller crash after commit: delete the successor's metadata + IS
    succ = next(m for m in metas.values() if m.sequence_number == 1)
    cluster.controller.llc.fsms.pop(succ.name, None)
    cluster.catalog.update_ideal_state(table, {succ.name: None})
    cluster.catalog.drop_segment_meta(table, succ.name)

    created = cluster.controller.llc.repair_missing_consuming_segments()
    assert len(created) == 1
    new_meta = cluster.catalog.segments[table][created[0]]
    assert new_meta.sequence_number == 1
    assert int(new_meta.start_offset) == 12


def test_pause_resume_consumption(tmp_path, events_schema):
    """Reference: PinotRealtimeTableResource pauseConsumption/resumeConsumption —
    pause force-commits consuming rows and stops successors; resume restarts
    consumption from the committed offsets."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, replication=1,
                                    flush_rows=1000, num_partitions=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "country": "US", "value": 1,
                                 "clicks": 1} for i in range(12)])
    cluster.pump_realtime(table)
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 12

    # pause: held rows force-commit (well under the 1000-row flush threshold),
    # no successor is created
    resp = cluster.controller.pause_consumption(table)
    assert resp["paused"] and resp["consumingSegments"]
    for _ in range(3):
        cluster.pump_realtime(table)
    metas = cluster.catalog.segments[table]
    done = [m for m in metas.values() if m.status == STATUS_DONE]
    assert len(done) == 1 and done[0].num_docs == 12
    assert all(m.status == STATUS_DONE for m in metas.values())  # no successor

    # rows produced while paused are NOT consumed
    produce("events_topic", 0, [{"user": "p", "country": "DE", "value": 2,
                                 "clicks": 1} for _ in range(5)])
    for _ in range(3):
        cluster.pump_realtime(table)
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 12

    # resume: successor created from offset 12, catches up on the backlog
    resp = cluster.controller.resume_consumption(table)
    assert resp["created"]
    successors = [m for m in cluster.catalog.segments[table].values()
                  if m.status == STATUS_IN_PROGRESS]
    assert len(successors) == 1 and int(successors[0].start_offset) == 12
    for _ in range(3):
        cluster.pump_realtime(table)
    res = cluster.query("SELECT COUNT(*), SUM(value) FROM events")
    assert res.rows[0][0] == 17
    assert res.rows[0][1] == pytest.approx(12 + 10)


def test_pause_with_empty_consuming_segment(tmp_path, events_schema):
    """Pausing a partition with zero consumed rows: nothing to commit, the
    consuming segment idles, resume simply restarts fetching."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, replication=1,
                                    flush_rows=1000, num_partitions=1)
    table = cfg.table_name_with_type
    cluster.controller.pause_consumption(table)
    produce("events_topic", 0, [{"user": "a", "country": "US", "value": 1,
                                 "clicks": 1} for _ in range(4)])
    for _ in range(2):
        cluster.pump_realtime(table)
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 0
    metas = cluster.catalog.segments[table]
    assert all(m.status == STATUS_IN_PROGRESS for m in metas.values())

    cluster.controller.resume_consumption(table)
    for _ in range(2):
        cluster.pump_realtime(table)
    assert cluster.query("SELECT COUNT(*) FROM events").rows[0][0] == 4


def test_successor_consuming_segment_inherits_replica_set(tmp_path, events_schema):
    """Partition-consistent realtime assignment (reference:
    RealtimeSegmentAssignment): the successor CONSUMING segment is placed on
    the same servers as its committed predecessor, so replica-group routing
    can serve the whole partition from one server."""
    cluster, cfg = realtime_cluster(tmp_path, events_schema, replication=1,
                                    flush_rows=10, num_partitions=1)
    table = cfg.table_name_with_type
    produce("events_topic", 0, [{"user": f"u{i}", "country": "US", "value": 1,
                                 "clicks": 1} for i in range(12)])
    for _ in range(4):
        cluster.pump_realtime(table)
    ist = cluster.catalog.ideal_state[table]
    by_seq = {}
    for seg, assignment in ist.items():
        meta = cluster.catalog.segments[table][seg]
        by_seq[meta.sequence_number] = set(assignment)
    assert len(by_seq) >= 2  # committed seq 0 + consuming seq 1
    assert by_seq[0] == by_seq[1]


def test_batch_ingestion_streams_with_bounded_memory(tmp_path, events_schema):
    """VERDICT r4 item 7: a job 10x one segment's size must peak at O(segment)
    runner memory, not O(job) — the streaming two-pass driver cuts and pushes
    segments incrementally (reference: SegmentIndexCreationDriverImpl's
    stats-then-write record streaming)."""
    import tracemalloc

    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.ingest.readers import reader_for

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path / "cluster"))
    cfg = TableConfig("events")
    cluster.create_table(events_schema, cfg)

    n, seg_rows = 40_000, 4_000   # 10 segments per job
    csv_path = tmp_path / "big.csv"
    csv_path.write_text("user,country,value,clicks\n" + "".join(
        f"user_{i % 997},C{i % 13},{i}.25,{i % 51}\n" for i in range(n)))

    # baseline: what materializing ALL rows (the pre-r4 runner) costs
    tracemalloc.start()
    reader = reader_for(str(csv_path), None)
    all_rows = list(reader.rows())
    reader.close()
    _, full_peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert len(all_rows) == n
    del all_rows

    spec = BatchIngestionJobSpec(
        input_paths=[str(csv_path)],
        table=cfg.table_name_with_type,
        segment_rows=seg_rows,
    )
    tracemalloc.start()
    pushed = run_batch_ingestion(spec, cluster.controller,
                                 work_dir=str(tmp_path))
    _, stream_peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()

    assert len(pushed) == 10
    res = cluster.query("SELECT COUNT(*), MAX(clicks) FROM events")
    assert res.rows[0] == [n, 50]
    # O(segment), not O(job): the streaming run must peak well below the cost
    # of materializing the whole input (10 segments' worth) at once
    assert stream_peak < 0.55 * full_peak, (stream_peak, full_peak)


def test_orc_reader_batch_ingest(tmp_path, events_schema):
    """ORC files ingest through the reader SPI (pyarrow-backed), matching the
    same rows via jsonl."""
    pa = pytest.importorskip("pyarrow")
    orc = pytest.importorskip("pyarrow.orc")
    rows = [{"user": f"u{i % 9}", "country": ["US", "DE"][i % 2],
             "value": i * 0.5, "clicks": i} for i in range(300)]
    table = pa.table({k: [r[k] for r in rows]
                      for k in ("user", "country", "value", "clicks")})
    path = tmp_path / "ev.orc"
    orc.write_table(table, str(path))

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path / "c"))
    cfg = TableConfig("events")
    cluster.create_table(events_schema, cfg)
    pushed = run_batch_ingestion(
        BatchIngestionJobSpec(input_paths=[str(path)],
                              table=cfg.table_name_with_type,
                              segment_rows=100),
        cluster.controller, work_dir=str(tmp_path / "w"))
    assert len(pushed) == 3
    res = cluster.query("SELECT COUNT(*), SUM(clicks), MAX(value) FROM events")
    assert res.rows[0] == [300, sum(range(300)), 299 * 0.5]
