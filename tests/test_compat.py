"""Compatibility verifier: YAML-driven ops against a live HTTP cluster.

Reference: pinot-compatibility-verifier (CompatibilityOpsRunner + TableOp /
SegmentOp / QueryOp / StreamOp YAML ops).
"""

import json
import textwrap

import pytest

from pinot_tpu.ingest.stream import MemoryStream
from pinot_tpu.tools.compat import CompatibilityOpsRunner


@pytest.fixture(autouse=True)
def _reset_streams():
    MemoryStream.reset_all()
    yield
    MemoryStream.reset_all()


@pytest.fixture()
def http_cluster(tmp_path):
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    catalog = Catalog()
    ctrl = Controller("c0", catalog, LocalDeepStore(str(tmp_path / "ds")),
                      str(tmp_path / "c"))
    csvc = ControllerService(ctrl)
    cats = [RemoteCatalog(csvc.url, poll_timeout_s=1.0)]
    node = ServerNode("server_0", cats[0], ControllerDeepStore(csvc.url),
                      str(tmp_path / "s0"), auto_consume=True,
                      completion=ctrl.llc)
    ssvc = ServerService(node)
    cats.append(RemoteCatalog(csvc.url, poll_timeout_s=1.0))
    bsvc = BrokerService(Broker("b0", cats[1]))
    try:
        yield csvc, bsvc
    finally:
        for c in cats:
            c.close()
        for s in (csvc, ssvc, bsvc):
            s.stop()


def _write(p, text):
    p.write_text(textwrap.dedent(text))
    return p.name


def test_offline_roundtrip_ops(tmp_path, http_cluster):
    csvc, bsvc = http_cluster
    d = tmp_path / "ops"
    d.mkdir()
    (d / "schema.json").write_text(json.dumps({
        "schemaName": "trips",
        "dimensionFieldSpecs": [{"name": "city", "dataType": "STRING"}],
        "metricFieldSpecs": [{"name": "fare", "dataType": "DOUBLE"}],
    }))
    (d / "table.json").write_text(json.dumps({"tableName": "trips"}))
    (d / "rows.csv").write_text("city,fare\nnyc,1.5\nsf,2.0\nnyc,3.0\n")
    _write(d / "queries.sql", """\
        SELECT COUNT(*) FROM trips
        SELECT city, SUM(fare) FROM trips GROUP BY city ORDER BY city LIMIT 5
    """)
    (d / "results.jsonl").write_text(
        json.dumps({"rows": [[3]]}) + "\n" +
        json.dumps({"rows": [["nyc", 4.5], ["sf", 2.0]]}) + "\n")
    _write(d / "ops.yaml", """\
        description: offline round-trip
        operations:
          - type: tableOp
            op: CREATE
            schemaFile: schema.json
            tableConfigFile: table.json
          - type: segmentOp
            op: UPLOAD
            tableName: trips_OFFLINE
            segmentName: trips_c0
            inputDataFile: rows.csv
          - type: queryOp
            queryFile: queries.sql
            expectedResultsFile: results.jsonl
    """)
    runner = CompatibilityOpsRunner(csvc.url, bsvc.url,
                                    work_dir=str(tmp_path / "work"))
    ok = runner.run(str(d / "ops.yaml"))
    assert ok, runner.log


def test_query_mismatch_fails(tmp_path, http_cluster):
    csvc, bsvc = http_cluster
    d = tmp_path / "ops2"
    d.mkdir()
    (d / "schema.json").write_text(json.dumps({
        "schemaName": "miss",
        "dimensionFieldSpecs": [{"name": "k", "dataType": "STRING"}],
        "metricFieldSpecs": [{"name": "v", "dataType": "DOUBLE"}],
    }))
    (d / "table.json").write_text(json.dumps({"tableName": "miss"}))
    (d / "rows.csv").write_text("k,v\na,1.0\n")
    (d / "queries.sql").write_text("SELECT COUNT(*) FROM miss\n")
    (d / "results.jsonl").write_text(json.dumps({"rows": [[999]]}) + "\n")
    _write(d / "ops.yaml", """\
        operations:
          - type: tableOp
            op: CREATE
            schemaFile: schema.json
            tableConfigFile: table.json
          - type: segmentOp
            op: UPLOAD
            tableName: miss_OFFLINE
            segmentName: miss_0
            inputDataFile: rows.csv
          - type: queryOp
            queryFile: queries.sql
            expectedResultsFile: results.jsonl
    """)
    runner = CompatibilityOpsRunner(csvc.url, bsvc.url,
                                    work_dir=str(tmp_path / "work"),
                                    query_timeout_s=3.0)
    assert not runner.run(str(d / "ops.yaml"))
    assert any("FAILED" in line for line in runner.log)


def test_stream_op_realtime(tmp_path, http_cluster):
    csvc, bsvc = http_cluster
    d = tmp_path / "ops3"
    d.mkdir()
    (d / "schema.json").write_text(json.dumps({
        "schemaName": "events",
        "dimensionFieldSpecs": [{"name": "u", "dataType": "STRING"}],
        "metricFieldSpecs": [{"name": "m", "dataType": "DOUBLE"}],
    }))
    (d / "table.json").write_text(json.dumps({
        "tableName": "events", "tableType": "REALTIME",
        "streamConfig": {"streamType": "memory", "topic": "compat_topic",
                         "decoder": "json", "flushThresholdRows": 1000},
    }))
    (d / "rows.jsonl").write_text(
        "".join(json.dumps({"u": f"u{i}", "m": 1.0}) + "\n" for i in range(8)))
    _write(d / "ops.yaml", """\
        operations:
          - type: tableOp
            op: CREATE
            schemaFile: schema.json
            tableConfigFile: table.json
          - type: streamOp
            op: PRODUCE
            streamTopic: compat_topic
            partition: 0
            inputDataFile: rows.jsonl
            tableName: events_REALTIME
            recordCount: 8
    """)
    runner = CompatibilityOpsRunner(csvc.url, bsvc.url,
                                    work_dir=str(tmp_path / "work"))
    ok = runner.run(str(d / "ops.yaml"))
    assert ok, runner.log
