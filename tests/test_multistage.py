"""Multistage engine: join queries checked against sqlite3 as oracle.

Reference pattern: `QueryRunnerTest`/`QueryDispatcherTest` run a multi-server mailbox
topology in one process and `MultiStageEngineIntegrationTest` checks join SQL against
H2. Here identical rows live in segments and a sqlite mirror; every query runs through
both engines.
"""

import sqlite3

import numpy as np
import pytest

from pinot_tpu.multistage import execute_multistage, make_segment_scan, plan_multistage
from pinot_tpu.query.context import QueryValidationError
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.schema import DataType, Schema, dimension, metric


@pytest.fixture(scope="module")
def jenv(tmp_path_factory):
    """orders (2 segments) + customers + regions, mirrored into sqlite."""
    rng = np.random.default_rng(5)
    out = tmp_path_factory.mktemp("join")

    n_cust = 40
    customers = {
        "cust_id": np.arange(1, n_cust + 1, dtype=np.int64),
        "cust_name": [f"cust{i}" for i in range(1, n_cust + 1)],
        "region_id": rng.integers(0, 6, n_cust).astype(np.int32),  # 5 exists, 5 doesn't
    }
    regions = {
        "region_id": np.arange(0, 5, dtype=np.int32),
        "region_name": ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MEA"],
    }
    n_ord = 800
    orders_all = {
        # some orders reference missing customers (id up to 45) for outer-join tests
        "cust_id": rng.integers(1, 46, n_ord).astype(np.int64),
        "amount": np.round(rng.uniform(1, 1000, n_ord), 2),
        "qty": rng.integers(1, 20, n_ord).astype(np.int32),
    }

    cust_schema = Schema("customers", [dimension("cust_id", DataType.LONG),
                                       dimension("cust_name", DataType.STRING),
                                       dimension("region_id", DataType.INT)])
    reg_schema = Schema("regions", [dimension("region_id", DataType.INT),
                                    dimension("region_name", DataType.STRING)])
    ord_schema = Schema("orders", [dimension("cust_id", DataType.LONG),
                                   metric("amount", DataType.DOUBLE),
                                   metric("qty", DataType.INT)])

    half = n_ord // 2
    orders_a = {k: v[:half] for k, v in orders_all.items()}
    orders_b = {k: v[half:] for k, v in orders_all.items()}

    tables = {
        "customers": [load_segment(SegmentBuilder(cust_schema).build(
            customers, str(out), "cust_0"))],
        "regions": [load_segment(SegmentBuilder(reg_schema).build(
            regions, str(out), "reg_0"))],
        "orders": [load_segment(SegmentBuilder(ord_schema).build(
            orders_a, str(out), "ord_0")),
                   load_segment(SegmentBuilder(ord_schema).build(
            orders_b, str(out), "ord_1"))],
    }
    schemas = {"customers": cust_schema, "regions": reg_schema, "orders": ord_schema}

    db = sqlite3.connect(":memory:")
    db.execute("CREATE TABLE customers (cust_id, cust_name, region_id)")
    db.executemany("INSERT INTO customers VALUES (?,?,?)",
                   list(zip(customers["cust_id"].tolist(), customers["cust_name"],
                            customers["region_id"].tolist())))
    db.execute("CREATE TABLE regions (region_id, region_name)")
    db.executemany("INSERT INTO regions VALUES (?,?)",
                   list(zip(regions["region_id"].tolist(), regions["region_name"])))
    db.execute("CREATE TABLE orders (cust_id, amount, qty)")
    db.executemany("INSERT INTO orders VALUES (?,?,?)",
                   list(zip(orders_all["cust_id"].tolist(),
                            orders_all["amount"].tolist(),
                            orders_all["qty"].tolist())))
    db.commit()
    return tables, schemas, db


def run_both(jenv, sql, sqlite_sql=None, ordered=False):
    tables, schemas, db = jenv
    ours = execute_multistage(sql, make_segment_scan(tables), schemas.get)
    oracle = db.execute(sqlite_sql or sql).fetchall()
    got = [tuple(r) for r in ours.rows]
    want = [tuple(r) for r in oracle]
    if not ordered:
        # sort on rounded values so float noise cannot reorder; compare approx below
        keyfn = lambda r: repr(tuple(_norm(v) for v in r))
        got, want = sorted(got, key=keyfn), sorted(want, key=keyfn)
    assert len(got) == len(want), f"{len(got)} rows != {len(want)}\n{got[:5]}\n{want[:5]}"
    for g, w in zip(got, want):
        for gv, wv in zip(g, w):
            if isinstance(gv, float) or isinstance(wv, float):
                assert gv == pytest.approx(wv, rel=1e-6, abs=1e-6), f"{g} != {w}"
            else:
                assert gv == wv, f"{g} != {w}"
    return ours


def _norm(v):
    if isinstance(v, float):
        return round(v, 2)
    return v


INNER_QUERIES = [
    # plain inner join, selection
    "SELECT o.cust_id, c.cust_name, o.amount FROM orders o "
    "JOIN customers c ON o.cust_id = c.cust_id LIMIT 100000",
    # join + group-by + aggregates
    "SELECT c.cust_name, COUNT(*), SUM(o.amount) FROM orders o "
    "JOIN customers c ON o.cust_id = c.cust_id GROUP BY c.cust_name LIMIT 100000",
    # three-way join
    "SELECT r.region_name, SUM(o.amount) FROM orders o "
    "JOIN customers c ON o.cust_id = c.cust_id "
    "JOIN regions r ON c.region_id = r.region_id GROUP BY r.region_name LIMIT 100000",
    # WHERE pushdown both sides + post-join condition
    "SELECT c.cust_name, SUM(o.amount) FROM orders o "
    "JOIN customers c ON o.cust_id = c.cust_id "
    "WHERE o.qty > 5 AND c.region_id <= 3 GROUP BY c.cust_name LIMIT 100000",
    # unqualified columns resolved by uniqueness
    "SELECT cust_name, SUM(amount) FROM orders o "
    "JOIN customers c ON o.cust_id = c.cust_id GROUP BY cust_name LIMIT 100000",
    # non-equi residual ON condition (inner only)
    "SELECT COUNT(*) FROM orders o JOIN customers c "
    "ON o.cust_id = c.cust_id AND o.qty > c.region_id",
    # HAVING + ORDER BY + LIMIT on joined aggregate
    "SELECT c.cust_name, SUM(o.amount) AS total FROM orders o "
    "JOIN customers c ON o.cust_id = c.cust_id GROUP BY c.cust_name "
    "HAVING SUM(o.amount) > 2000 ORDER BY total DESC LIMIT 5",
    # expression select items over both tables
    "SELECT o.cust_id + c.region_id, AVG(o.amount) FROM orders o "
    "JOIN customers c ON o.cust_id = c.cust_id "
    "GROUP BY o.cust_id + c.region_id LIMIT 100000",
    # DISTINCT over joined columns
    "SELECT DISTINCT c.region_id FROM orders o "
    "JOIN customers c ON o.cust_id = c.cust_id LIMIT 100000",
]


@pytest.mark.parametrize("sql", INNER_QUERIES)
def test_inner_joins(jenv, sql):
    run_both(jenv, sql)


def test_left_join(jenv):
    # orders with missing customers survive with null cust_name
    run_both(jenv,
             "SELECT o.cust_id, c.cust_name FROM orders o "
             "LEFT JOIN customers c ON o.cust_id = c.cust_id LIMIT 100000")
    # aggregation over the null-extended side skips nulls like SQL
    run_both(jenv,
             "SELECT o.cust_id, COUNT(c.cust_name) FROM orders o "
             "LEFT JOIN customers c ON o.cust_id = c.cust_id "
             "GROUP BY o.cust_id LIMIT 100000")


def test_left_join_where_not_pushed(jenv):
    # WHERE on the null-extended side must apply after the join
    run_both(jenv,
             "SELECT o.cust_id, c.cust_name FROM orders o "
             "LEFT JOIN customers c ON o.cust_id = c.cust_id "
             "WHERE c.region_id <= 2 LIMIT 100000")


def test_right_and_full_join(jenv):
    # customers with no orders (sqlite supports RIGHT/FULL from 3.39; emulate)
    ours = execute_multistage(
        "SELECT c.cust_id, COUNT(o.amount) FROM orders o "
        "RIGHT JOIN customers c ON o.cust_id = c.cust_id "
        "GROUP BY c.cust_id LIMIT 100000",
        make_segment_scan(jenv[0]), jenv[1].get)
    oracle = jenv[2].execute(
        "SELECT c.cust_id, COUNT(o.amount) FROM customers c "
        "LEFT JOIN orders o ON o.cust_id = c.cust_id GROUP BY c.cust_id").fetchall()
    assert sorted(map(tuple, ours.rows)) == sorted(map(tuple, oracle))

    full = execute_multistage(
        "SELECT o.cust_id, c.cust_id FROM orders o "
        "FULL JOIN customers c ON o.cust_id = c.cust_id LIMIT 100000",
        make_segment_scan(jenv[0]), jenv[1].get)
    # full join row count = inner matches + unmatched left + unmatched right
    inner = jenv[2].execute(
        "SELECT COUNT(*) FROM orders o JOIN customers c "
        "ON o.cust_id = c.cust_id").fetchone()[0]
    left_un = jenv[2].execute(
        "SELECT COUNT(*) FROM orders o WHERE cust_id NOT IN "
        "(SELECT cust_id FROM customers)").fetchone()[0]
    right_un = jenv[2].execute(
        "SELECT COUNT(*) FROM customers c WHERE cust_id NOT IN "
        "(SELECT cust_id FROM orders)").fetchone()[0]
    assert len(full.rows) == inner + left_un + right_un


def test_plan_shapes(jenv):
    _, schemas, _ = jenv
    plan = plan_multistage(
        "SELECT c.cust_name, SUM(o.amount) FROM orders o "
        "JOIN customers c ON o.cust_id = c.cust_id "
        "WHERE o.qty > 5 AND c.region_id = 2 GROUP BY c.cust_name",
        schemas.get)
    assert plan.scans["o"].filter is not None      # o.qty > 5 pushed down
    assert plan.scans["c"].filter is not None      # c.region_id = 2 pushed down
    assert plan.post_filter is None
    assert plan.joins[0].left_keys == ["o.cust_id"]
    assert plan.joins[0].right_keys == ["c.cust_id"]
    # pushdown is disabled for the null-extended side of an outer join
    plan2 = plan_multistage(
        "SELECT o.cust_id FROM orders o LEFT JOIN customers c "
        "ON o.cust_id = c.cust_id WHERE c.region_id = 2 AND o.qty > 5",
        schemas.get)
    assert plan2.scans["c"].filter is None
    assert plan2.post_filter is not None
    assert plan2.scans["o"].filter is not None


def test_errors(jenv):
    _, schemas, _ = jenv
    with pytest.raises(QueryValidationError, match="equality key"):
        plan_multistage("SELECT 1 FROM orders o JOIN customers c ON o.qty > c.region_id",
                        schemas.get)
    with pytest.raises(QueryValidationError, match="ambiguous"):
        plan_multistage("SELECT cust_id FROM orders o JOIN customers c "
                        "ON o.cust_id = c.cust_id", schemas.get)
    with pytest.raises(QueryValidationError, match="multistage"):
        from pinot_tpu.query.context import compile_query
        compile_query("SELECT 1 FROM a JOIN b ON a.x = b.x")


def test_cluster_join_query(tmp_path):
    """Join query through the full broker path (reference:
    MultiStageEngineIntegrationTest via BrokerRequestHandlerDelegate)."""
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.table import TableConfig

    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    dim_schema = Schema("dim", [dimension("k", DataType.INT),
                                dimension("label", DataType.STRING)])
    fact_schema = Schema("fact", [dimension("k", DataType.INT),
                                  metric("v", DataType.DOUBLE)])
    dim_cfg = cluster.create_table(dim_schema, TableConfig("dim"))
    fact_cfg = cluster.create_table(fact_schema, TableConfig("fact"))
    cluster.ingest_columns(dim_cfg, {"k": np.arange(5, dtype=np.int32),
                                     "label": [f"L{i}" for i in range(5)]})
    rng = np.random.default_rng(1)
    ks = rng.integers(0, 5, 200).astype(np.int32)
    vs = np.round(rng.uniform(0, 10, 200), 2)
    cluster.ingest_columns(fact_cfg, {"k": ks[:100], "v": vs[:100]})
    cluster.ingest_columns(fact_cfg, {"k": ks[100:], "v": vs[100:]})

    res = cluster.query(
        "SELECT d.label, SUM(f.v), COUNT(*) FROM fact f "
        "JOIN dim d ON f.k = d.k GROUP BY d.label ORDER BY d.label LIMIT 100")
    assert res.stats.get("multistage") is True
    want = {}
    for k, v in zip(ks.tolist(), vs.tolist()):
        s, c = want.get(f"L{k}", (0.0, 0))
        want[f"L{k}"] = (s + v, c + 1)
    assert [r[0] for r in res.rows] == sorted(want)
    for label, s, c in res.rows:
        assert s == pytest.approx(want[label][0], rel=1e-6)
        assert c == want[label][1]


def test_num_partitions_query_option(tmp_path):
    """OPTION(numPartitions=N) tunes the join shuffle width per query."""
    import numpy as np
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import TableConfig
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    sa = Schema("pa", [dimension("k"), metric("x", DataType.DOUBLE)])
    sb = Schema("pb", [dimension("k"), dimension("g")])
    cluster.create_table(sa, TableConfig("pa"))
    cluster.create_table(sb, TableConfig("pb"))
    cluster.ingest_columns(TableConfig("pa"),
                           {"k": [f"k{i % 7}" for i in range(50)],
                            "x": np.arange(50, dtype=np.float64)})
    cluster.ingest_columns(TableConfig("pb"),
                           {"k": [f"k{i}" for i in range(7)],
                            "g": [f"g{i % 2}" for i in range(7)]})
    base = cluster.query("SELECT pb.g, SUM(pa.x) FROM pa JOIN pb ON pa.k = pb.k "
                         "GROUP BY pb.g ORDER BY pb.g LIMIT 10").rows
    for n in (1, 3, 16):
        got = cluster.query(
            "SELECT pb.g, SUM(pa.x) FROM pa JOIN pb ON pa.k = pb.k "
            f"GROUP BY pb.g ORDER BY pb.g LIMIT 10 OPTION(numPartitions={n})").rows
        assert got == base, (n, got, base)
