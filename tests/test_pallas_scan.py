"""Pallas scan kernel: parity with the XLA-fused scan (interpret mode on CPU;
the module's _bench reproduces the TPU measurement that keeps XLA default)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from pinot_tpu.engine.pallas_scan import (masked_sums_pallas,  # noqa: E402
                                          masked_sums_xla)


def _data(n=1 << 16, seed=3):
    rng = np.random.default_rng(seed)
    od = jnp.asarray(rng.integers(19920101, 19990101, n), dtype=jnp.int32)
    disc = jnp.asarray(rng.integers(0, 11, n), dtype=jnp.int32)
    qty = jnp.asarray(rng.integers(1, 51, n), dtype=jnp.int32)
    price = jnp.asarray(rng.uniform(1, 10000, n), dtype=jnp.float32)
    rev = jnp.asarray(rng.uniform(1, 60000, n), dtype=jnp.float32)
    return (od, disc, qty), (price, rev)


BANDS = [(19930101, 19931231), (1, 3), (-(1 << 31), 24)]


def test_pallas_matches_xla_and_numpy():
    cols, rows = _data()
    want = np.asarray(masked_sums_xla(cols, BANDS, rows))
    got = np.asarray(masked_sums_pallas(cols, BANDS, rows,
                                        block_rows=1 << 13, interpret=True))
    assert np.allclose(got, want, rtol=1e-4), (got, want)
    # independent numpy truth
    od, disc, qty = (np.asarray(c) for c in cols)
    m = ((od >= 19930101) & (od <= 19931231) & (disc >= 1) & (disc <= 3)
         & (qty <= 24))
    assert got[-1] == m.sum()
    assert got[0] == pytest.approx(float(np.asarray(rows[0])[m].sum()),
                                   rel=1e-4)


def test_pallas_rejects_unpadded_rows():
    cols, rows = _data(n=1000)
    with pytest.raises(ValueError, match="multiple"):
        masked_sums_pallas(cols, BANDS, rows, block_rows=1 << 13,
                           interpret=True)


def test_pallas_one_sided_bands_and_empty_mask():
    cols, rows = _data()
    none = [(1, 0)] * 3   # impossible band: empty mask
    out = np.asarray(masked_sums_pallas(cols, none, rows,
                                        block_rows=1 << 13, interpret=True))
    assert np.allclose(out, 0.0)
