"""Auth SPI + HTTP access control tests.

Reference pattern: BasicAuth access-control tests — principals with table ACLs
and permissions enforced at the controller/broker/server HTTP surfaces.
"""

import numpy as np
import pytest

from pinot_tpu.auth import (ADMIN, READ, WRITE, Principal,
                            StaticTokenAccessControl)
from pinot_tpu.config import Configuration


# -- principal semantics ------------------------------------------------------

def test_permission_implication():
    admin = Principal("a", frozenset({ADMIN}))
    writer = Principal("w", frozenset({WRITE}))
    reader = Principal("r", frozenset({READ}))
    assert admin.allows(READ) and admin.allows(WRITE) and admin.allows(ADMIN)
    assert writer.allows(READ) and writer.allows(WRITE)
    assert not writer.allows(ADMIN)
    assert reader.allows(READ) and not reader.allows(WRITE)


def test_table_scoping_matches_physical_names():
    p = Principal("r", frozenset({READ}), frozenset({"trips"}))
    assert p.allows(READ, "trips")
    assert p.allows(READ, "trips_OFFLINE")
    assert p.allows(READ, "trips_REALTIME")
    assert not p.allows(READ, "other")
    unscoped = Principal("r", frozenset({READ}), None)
    assert unscoped.allows(READ, "anything")


def test_static_tokens_from_config():
    ac = StaticTokenAccessControl.from_config(Configuration({
        "auth.tokens": "tokA=admin:*:ADMIN, tokB=bob:trips|users:READ"}))
    a = ac.authenticate("tokA")
    assert a.name == "admin" and a.allows(ADMIN) and a.tables is None
    b = ac.authenticate("tokB")
    assert b.allows(READ, "trips_OFFLINE") and not b.allows(READ, "secret")
    assert not b.allows(WRITE)
    assert ac.authenticate("nope") is None
    assert ac.authenticate(None) is None
    assert StaticTokenAccessControl.from_config(Configuration({})) is None


# -- HTTP enforcement ---------------------------------------------------------

@pytest.fixture()
def secured_cluster(tmp_path):
    """Controller + server + broker over HTTP with token auth; the service
    identity uses an admin token (reference: per-service auth tokens)."""
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.http_service import set_default_token
    from pinot_tpu.cluster.remote import (ControllerDeepStore, RemoteCatalog,
                                          RemoteServerHandle)
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)

    ac = StaticTokenAccessControl.from_config(Configuration({
        "auth.tokens": ("svc=service:*:ADMIN, admin=root:*:ADMIN, "
                        "reader=alice:trips:READ")}))
    set_default_token("svc")   # this process's outgoing identity
    services, catalogs = [], []
    try:
        catalog = Catalog()
        ctrl = Controller("c0", catalog, LocalDeepStore(str(tmp_path / "ds")),
                          str(tmp_path / "c"))
        csvc = ControllerService(ctrl, access_control=ac)
        services.append(csvc)
        rc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(rc)
        node = ServerNode("server_0", rc, ControllerDeepStore(csvc.url),
                          str(tmp_path / "s0"))
        ssvc = ServerService(node, access_control=ac)
        services.append(ssvc)
        brc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(brc)
        broker = Broker("b0", brc)
        bsvc = BrokerService(broker, access_control=ac)
        services.append(bsvc)
        yield {"csvc": csvc, "bsvc": bsvc, "node": node, "tmp": tmp_path}
    finally:
        set_default_token(None)
        for c in catalogs:
            c.close()
        for s in services:
            s.stop()


def _setup_table(cluster):
    import time
    from pinot_tpu.cluster.process import ControllerClient
    from pinot_tpu.schema import Schema, dimension, metric
    from pinot_tpu.segment.writer import SegmentBuilder
    from pinot_tpu.table import TableConfig
    schema = Schema("trips", [dimension("city"), metric("fare")])
    c = ControllerClient(cluster["csvc"].url)
    c.add_schema(schema)
    c.add_table(TableConfig("trips"))
    seg = SegmentBuilder(schema).build(
        {"city": ["nyc", "sf"], "fare": np.array([1.0, 2.0])},
        str(cluster["tmp"] / "b"), "trips_0")
    c.upload_segment("trips_OFFLINE", seg)
    deadline = time.time() + 15
    while time.time() < deadline and \
            len(cluster["node"].segments_served("trips_OFFLINE")) < 1:
        time.sleep(0.05)


def test_allow_all_access_control(tmp_path):
    """AllowAllAccessControl: auth machinery on, everyone is anonymous admin."""
    from pinot_tpu.auth import AllowAllAccessControl
    from pinot_tpu.cluster.http_service import HttpService, http_call, json_response
    svc = HttpService(access_control=AllowAllAccessControl())
    svc.route("GET", "whoami", lambda p, q, b: json_response(
        {"name": __import__("pinot_tpu.auth", fromlist=["auth"])
         .current_principal().name}), action="ADMIN")
    svc.start()
    try:
        import json
        out = json.loads(http_call("GET", f"{svc.url}/whoami", token="").decode())
        assert out["name"] == "anonymous"
    finally:
        svc.stop()


def test_health_is_exempt_from_auth(secured_cluster):
    """Liveness probes carry no credentials; /health must answer without auth."""
    from pinot_tpu.cluster.http_service import http_call
    import json
    out = json.loads(http_call(
        "GET", f"{secured_cluster['csvc'].url}/health", token="").decode())
    assert out["status"] == "OK"


def test_segment_download_respects_table_acl(secured_cluster):
    """Raw segment/deep-store downloads enforce the same table ACL as queries —
    a scoped reader cannot exfiltrate denied tables' data."""
    from pinot_tpu.cluster.http_service import HttpError, http_call
    _setup_table(secured_cluster)
    url = secured_cluster["csvc"].url
    # allowed table: download works for the scoped reader
    data = http_call("GET", f"{url}/segments/trips_OFFLINE/trips_0", token="reader")
    assert len(data) > 0
    # denied table: 403 on both download surfaces
    with pytest.raises(HttpError) as ei:
        http_call("GET", f"{url}/segments/secrets_OFFLINE/s_0", token="reader")
    assert ei.value.status == 403
    with pytest.raises(HttpError) as ei:
        http_call("GET", f"{url}/deepstore/secrets_OFFLINE/s_0.tar.gz",
                  token="reader")
    assert ei.value.status == 403


def test_two_client_connections_with_different_tokens(secured_cluster):
    """Per-connection credentials: one process, two identities, no clobbering
    (the client must not route tokens through process-global state)."""
    from pinot_tpu.client import connect
    from pinot_tpu.cluster.http_service import HttpError
    _setup_table(secured_cluster)
    admin = connect(secured_cluster["bsvc"].url, token="admin")
    reader = connect(secured_cluster["bsvc"].url, token="reader")
    from conftest import wait_until
    assert wait_until(   # broker catalog mirror converges via polls
        lambda: admin.execute("SELECT COUNT(*) FROM trips").scalar() == 2)
    assert admin.execute("SELECT COUNT(*) FROM trips").scalar() == 2
    assert reader.execute("SELECT COUNT(*) FROM trips").scalar() == 2
    # reader stays scoped even after the admin connection was created LAST-ish
    with pytest.raises(HttpError) as ei:
        reader.execute("SELECT COUNT(*) FROM secrets")
    assert ei.value.status == 403
    # and the admin connection still carries ITS token afterwards
    assert admin.execute("SELECT COUNT(*) FROM trips").scalar() == 2


def test_missing_token_is_401(secured_cluster):
    from pinot_tpu.cluster.http_service import HttpError, http_call
    with pytest.raises(HttpError) as ei:
        http_call("GET", f"{secured_cluster['csvc'].url}/tables", token="")
    assert ei.value.status == 401
    with pytest.raises(HttpError) as ei:
        http_call("GET", f"{secured_cluster['csvc'].url}/tables", token="bogus")
    assert ei.value.status == 401


def test_reader_cannot_write(secured_cluster):
    from pinot_tpu.cluster.http_service import HttpError, http_call
    url = secured_cluster["csvc"].url
    # reads allowed
    http_call("GET", f"{url}/tables", token="reader")
    # writes rejected with 403
    with pytest.raises(HttpError) as ei:
        http_call("POST", f"{url}/schemas", b"{}", token="reader")
    assert ei.value.status == 403
    with pytest.raises(HttpError) as ei:
        http_call("DELETE", f"{url}/tables/trips_OFFLINE", token="reader")
    assert ei.value.status == 403


def test_table_scoped_query_acl(secured_cluster):
    import json
    from pinot_tpu.cluster.http_service import HttpError, http_call
    _setup_table(secured_cluster)
    url = secured_cluster["bsvc"].url

    def query(sql, token):
        resp = http_call("POST", f"{url}/query",
                         json.dumps({"sql": sql}).encode(), token=token)
        return json.loads(resp.decode())

    # service/admin identity works end-to-end (segment upload above used it);
    # retry through the broker catalog-mirror convergence window
    from conftest import wait_until
    assert wait_until(lambda: query("SELECT SUM(fare) FROM trips",
                                    "admin")["resultTable"]["rows"][0][0] == 3.0)
    out = query("SELECT SUM(fare) FROM trips", "admin")
    assert out["resultTable"]["rows"][0][0] == 3.0
    # reader is scoped to `trips`: allowed there...
    out = query("SELECT COUNT(*) FROM trips", "reader")
    assert out["resultTable"]["rows"][0][0] == 2
    # ...and denied on other tables BEFORE any execution happens
    with pytest.raises(HttpError) as ei:
        query("SELECT COUNT(*) FROM secrets", "reader")
    assert ei.value.status == 403
