"""Theta sketch and t-digest: unit accuracy + merge associativity + e2e query paths.

Reference analogs: DistinctCountThetaSketchQueriesTest, PercentileTDigestQueriesTest
(pinot-core/src/test/.../queries/)."""

import numpy as np
import pytest

from pinot_tpu.query.executor import execute_query
from pinot_tpu.query.sketches import TDigest, ThetaSketch

from conftest import make_ssb_columns


def test_theta_exact_below_k():
    v = np.arange(1000)
    sk = ThetaSketch.from_values(v, k=4096)
    assert sk.estimate() == 1000


def test_theta_approx_above_k():
    rng = np.random.default_rng(0)
    v = rng.integers(0, 10**12, 200_000)
    true = len(np.unique(v))
    sk = ThetaSketch.from_values(v, k=4096)
    assert sk.estimate() == pytest.approx(true, rel=0.05)


def test_theta_merge_matches_bulk():
    rng = np.random.default_rng(1)
    a = rng.integers(0, 10**9, 50_000)
    b = rng.integers(0, 10**9, 50_000)
    merged = ThetaSketch.from_values(a, 2048).union(ThetaSketch.from_values(b, 2048))
    true = len(np.unique(np.concatenate([a, b])))
    assert merged.estimate() == pytest.approx(true, rel=0.08)


def test_theta_set_operations():
    a = ThetaSketch.from_values(np.arange(0, 1000), 4096)
    b = ThetaSketch.from_values(np.arange(500, 1500), 4096)
    assert a.intersect(b).estimate() == pytest.approx(500, rel=0.01)
    assert a.a_not_b(b).estimate() == pytest.approx(500, rel=0.01)
    assert a.union(b).estimate() == pytest.approx(1500, rel=0.01)


def test_theta_serialization_roundtrip():
    sk = ThetaSketch.from_values(np.arange(10_000), 1024)
    back = ThetaSketch.from_bytes(sk.to_bytes())
    assert back.estimate() == pytest.approx(sk.estimate())
    assert back.theta == sk.theta


def test_tdigest_quantiles():
    rng = np.random.default_rng(2)
    v = rng.normal(100, 15, 100_000)
    td = TDigest.from_values(v)
    for q in (0.01, 0.25, 0.5, 0.75, 0.99):
        assert td.quantile(q) == pytest.approx(np.quantile(v, q), abs=1.0)


def test_tdigest_merge():
    rng = np.random.default_rng(3)
    parts = [rng.uniform(0, 1000, 20_000) for _ in range(5)]
    td = TDigest.from_values(parts[0])
    for p in parts[1:]:
        td = td.merge(TDigest.from_values(p))
    allv = np.concatenate(parts)
    assert td.quantile(0.5) == pytest.approx(np.quantile(allv, 0.5), rel=0.02)
    assert td.quantile(0.95) == pytest.approx(np.quantile(allv, 0.95), rel=0.02)


def test_tdigest_serialization_roundtrip():
    td = TDigest.from_values(np.arange(1000, dtype=float))
    back = TDigest.from_bytes(td.to_bytes())
    assert back.quantile(0.5) == td.quantile(0.5)


def test_tdigest_bounded_size():
    td = TDigest.from_values(np.random.default_rng(4).uniform(0, 1, 500_000))
    assert len(td.means) < 200  # compression=100 keeps ~O(compression) centroids


# -- end-to-end through the engine --------------------------------------------

@pytest.fixture(scope="module")
def senv(tmp_path_factory):
    from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig, load_segment
    rng = np.random.default_rng(9)
    out = tmp_path_factory.mktemp("sketchseg")
    from conftest import make_ssb_columns
    schema = Schema("lineorder", [
        dimension("lo_orderkey", DataType.LONG),
        dimension("lo_custkey", DataType.INT),
        dimension("lo_region", DataType.STRING),
        dimension("lo_category", DataType.STRING),
        dimension("lo_brand", DataType.STRING),
        date_time("lo_orderdate", DataType.INT),
        metric("lo_quantity", DataType.INT),
        metric("lo_extendedprice", DataType.DOUBLE),
        metric("lo_discount", DataType.INT),
        metric("lo_revenue", DataType.DOUBLE),
    ])
    builder = SegmentBuilder(schema, SegmentGeneratorConfig())
    cols_a = make_ssb_columns(rng, 3000)
    cols_b = make_ssb_columns(rng, 3000)
    segs = [
        __import__("pinot_tpu.segment", fromlist=["load_segment"]).load_segment(
            builder.build(c, str(out), f"lineorder_{i}"))
        for i, c in enumerate((cols_a, cols_b))]
    allcols = {k: np.concatenate([np.asarray(cols_a[k]), np.asarray(cols_b[k])])
               for k in cols_a}
    return segs, allcols


def test_theta_query_vs_exact(senv):
    segs, cols = senv
    res = execute_query(segs, "SELECT DISTINCTCOUNTTHETASKETCH(lo_custkey) FROM lineorder")
    true = len(np.unique(cols["lo_custkey"]))
    assert int(res.rows[0][0]) == pytest.approx(true, rel=0.05)


def test_theta_query_string_column(senv):
    segs, cols = senv
    res = execute_query(segs, "SELECT DISTINCTCOUNTTHETASKETCH(lo_brand) FROM lineorder "
                        "WHERE lo_quantity > 25")
    mask = cols["lo_quantity"] > 25
    true = len(set(np.asarray(cols["lo_brand"])[mask]))
    assert int(res.rows[0][0]) == true  # below k -> exact


def test_raw_theta_query_returns_sketch(senv):
    from pinot_tpu.query.sketches import ThetaSketch
    segs, cols = senv
    res = execute_query(segs,
                        "SELECT DISTINCTCOUNTRAWTHETASKETCH(lo_custkey) FROM lineorder")
    sk = ThetaSketch.from_bytes(bytes.fromhex(res.rows[0][0]))
    true = len(np.unique(cols["lo_custkey"]))
    assert sk.estimate() == pytest.approx(true, rel=0.05)


def test_percentile_tdigest_query(senv):
    segs, cols = senv
    res = execute_query(
        segs, "SELECT PERCENTILETDIGEST(lo_extendedprice, 95), "
              "PERCENTILETDIGEST50(lo_extendedprice) FROM lineorder")
    v = cols["lo_extendedprice"]
    assert res.rows[0][0] == pytest.approx(np.percentile(v, 95), rel=0.02)
    assert res.rows[0][1] == pytest.approx(np.percentile(v, 50), rel=0.02)


def test_percentile_est_query(senv):
    segs, cols = senv
    res = execute_query(segs, "SELECT PERCENTILEEST90(lo_quantity) FROM lineorder")
    assert res.rows[0][0] == pytest.approx(np.percentile(cols["lo_quantity"], 90), abs=2)


def test_tdigest_group_by(senv):
    segs, cols = senv
    res = execute_query(
        segs, "SELECT lo_region, PERCENTILETDIGEST(lo_revenue, 50) FROM lineorder "
              "GROUP BY lo_region ORDER BY lo_region")
    regions = np.asarray(cols["lo_region"])
    for region, got in res.rows:
        want = np.percentile(cols["lo_revenue"][regions == region], 50)
        assert got == pytest.approx(want, rel=0.05)


# -- filtered theta set operations (reference:
# DistinctCountThetaSketchAggregationFunction postAggregationExpression) ------

def test_theta_filtered_set_ops(tmp_path):
    from pinot_tpu.query.executor import execute_query
    from pinot_tpu.schema import DataType, Schema, dimension
    from pinot_tpu.segment import SegmentBuilder, load_segment
    rng = np.random.default_rng(4)
    n = 3000
    users = [f"u{i % 800}" for i in range(n)]
    device = [("mobile" if i % 3 else "desktop") for i in range(n)]
    country = [("US" if i % 2 else "DE") for i in range(n)]
    schema = Schema("events", [dimension("user"), dimension("device"),
                               dimension("country", DataType.STRING)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"user": users, "device": device, "country": country},
        str(tmp_path), "ev_0"))

    def exact(pred):
        return len({u for u, d, c in zip(users, device, country) if pred(d, c)})

    # users seen on mobile AND on desktop (intersection across predicates)
    q = ("SELECT DISTINCTCOUNTTHETASKETCH(user, 'nominalEntries=8192', "
         "'device = ''mobile''', 'device = ''desktop''', "
         "'SET_INTERSECT($1, $2)') FROM events")
    got = execute_query([seg], q).rows[0][0]
    mob = {u for u, d in zip(users, device) if d == "mobile"}
    desk = {u for u, d in zip(users, device) if d == "desktop"}
    want = len(mob & desk)
    assert got == pytest.approx(want, rel=0.06), (got, want)

    # union and diff
    q2 = ("SELECT DISTINCTCOUNTTHETASKETCH(user, 'nominalEntries=8192', "
          "'country = ''US''', 'country = ''DE''', "
          "'SET_UNION($1, $2)') FROM events")
    got2 = execute_query([seg], q2).rows[0][0]
    assert got2 == pytest.approx(800, rel=0.06)
    q3 = ("SELECT DISTINCTCOUNTTHETASKETCH(user, 'nominalEntries=8192', "
          "'device = ''mobile''', 'device = ''desktop''', "
          "'SET_DIFF($1, $2)') FROM events")
    got3 = execute_query([seg], q3).rows[0][0]
    assert got3 == pytest.approx(len(mob - desk), rel=0.25) or \
        abs(got3 - len(mob - desk)) <= 30

    # main WHERE composes with the per-predicate filters
    q4 = ("SELECT DISTINCTCOUNTTHETASKETCH(user, 'nominalEntries=8192', "
          "'device = ''mobile''', 'device = ''desktop''', "
          "'SET_INTERSECT($1, $2)') FROM events WHERE country = 'US'")
    got4 = execute_query([seg], q4).rows[0][0]
    mob_us = {u for u, d, c in zip(users, device, country)
              if d == "mobile" and c == "US"}
    desk_us = {u for u, d, c in zip(users, device, country)
               if d == "desktop" and c == "US"}
    assert got4 == pytest.approx(len(mob_us & desk_us), rel=0.1)


def test_theta_setop_errors(tmp_path):
    from pinot_tpu.query.context import QueryValidationError
    from pinot_tpu.query.executor import execute_query
    from pinot_tpu.schema import Schema, dimension
    from pinot_tpu.segment import SegmentBuilder, load_segment
    schema = Schema("e2", [dimension("u"), dimension("d")])
    seg = load_segment(SegmentBuilder(schema).build(
        {"u": ["a"], "d": ["x"]}, str(tmp_path), "e2_0"))
    with pytest.raises(QueryValidationError):
        execute_query([seg], "SELECT DISTINCTCOUNTTHETASKETCH(u, 'x=1', "
                             "'d = ''x''', 'SET_BOGUS($1)') FROM e2")
    with pytest.raises(QueryValidationError):
        execute_query([seg], "SELECT DISTINCTCOUNTTHETASKETCH(u, 'x=1', "
                             "'d = ''x''', 'SET_UNION($1, $9)') FROM e2")


def test_theta_three_arg_form_rejected(tmp_path):
    from pinot_tpu.query.context import QueryValidationError
    from pinot_tpu.query.executor import execute_query
    from pinot_tpu.schema import Schema, dimension
    from pinot_tpu.segment import SegmentBuilder, load_segment
    schema = Schema("e3", [dimension("u"), dimension("d")])
    seg = load_segment(SegmentBuilder(schema).build(
        {"u": ["a"], "d": ["x"]}, str(tmp_path), "e3_0"))
    with pytest.raises(QueryValidationError):
        execute_query([seg], "SELECT DISTINCTCOUNTTHETASKETCH(u, 'x=1', "
                             "'d = ''x''') FROM e3")


def test_theta_setop_rejects_unknown_chars():
    from pinot_tpu.query.aggregates import _eval_theta_setop
    from pinot_tpu.query.context import QueryValidationError
    from pinot_tpu.query.sketches import ThetaSketch
    s = [ThetaSketch(), ThetaSketch()]
    with pytest.raises(QueryValidationError):
        _eval_theta_setop("SET_DIFF($1,$2)*2", s)


def test_theta_filtered_numeric_hash_domain_matches_unfiltered(tmp_path):
    """Raw sketches from filtered and unfiltered queries over the same int
    column must share a hash domain (clients intersect them)."""
    import numpy as np
    from pinot_tpu.query.executor import execute_query
    from pinot_tpu.query.sketches import ThetaSketch
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, load_segment
    schema = Schema("n1", [metric("k", DataType.LONG), dimension("d")])
    seg = load_segment(SegmentBuilder(schema).build(
        {"k": np.arange(100, dtype=np.int64), "d": ["x"] * 100},
        str(tmp_path), "n1_0"))
    raw_all = execute_query(
        [seg], "SELECT DISTINCTCOUNTRAWTHETASKETCH(k) FROM n1").rows[0][0]
    raw_filt = execute_query(
        [seg], "SELECT DISTINCTCOUNTRAWTHETASKETCH(k, 'nominalEntries=4096', "
               "'d = ''x''', 'SET_UNION($1)') FROM n1").rows[0][0]
    a = ThetaSketch.from_bytes(bytes.fromhex(raw_all))
    b = ThetaSketch.from_bytes(bytes.fromhex(raw_filt))
    inter = a.intersect(b).estimate()
    assert inter == pytest.approx(100, rel=0.05), inter


def test_hll_device_state_is_registers_not_value_set(tmp_path):
    """A single-segment server ships the HLL partial over the wire without any
    merge; the state must already be the bounded register array, not the exact
    value set the device decode produces."""
    import numpy as np
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, load_segment
    schema = Schema("w1", [dimension("k"), metric("v", DataType.INT)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"k": [f"k{i % 50}" for i in range(500)],
         "v": np.arange(500, dtype=np.int32)}, str(tmp_path), "w1_0"))
    ctx = compile_query("SELECT DISTINCTCOUNTHLL(k), "
                        "DISTINCTCOUNTTHETASKETCH(k) FROM w1", schema)
    res = ServerQueryExecutor(use_device=True).execute_segment(ctx, seg)
    hll_state, theta_state = res.scalar[0], res.scalar[1]
    assert isinstance(hll_state, np.ndarray) and hll_state.dtype == np.int8, \
        type(hll_state)
    from pinot_tpu.query.sketches import ThetaSketch
    assert isinstance(theta_state, ThetaSketch), type(theta_state)


def test_theta_device_cached_hashes_match_host_exactly(tmp_path):
    """r4: the device presence path builds the sketch from a per-dictionary
    cached hash table (vectorized k-min) — its hashes must be IDENTICAL to
    the host from_values path, or cross-segment/cross-path merges would
    double-count (same invariant HLL's bucket/rank cache keeps)."""
    import numpy as np
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.query.sketches import ThetaSketch, hash64
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, load_segment

    rng = np.random.default_rng(3)
    n = 20_000
    ks = [f"user_{i}" for i in rng.integers(0, 9000, n)]       # > k=4096
    iv = rng.integers(0, 7000, n).astype(np.int64)
    schema = Schema("w2", [dimension("k"), dimension("ki", DataType.LONG),
                           metric("v", DataType.INT)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"k": ks, "ki": iv, "v": np.arange(n, dtype=np.int32)},
        str(tmp_path), "w2_0"))
    ctx = compile_query("SELECT DISTINCTCOUNTTHETASKETCH(k), "
                        "DISTINCTCOUNTTHETASKETCH(ki) FROM w2", schema)
    dev = ServerQueryExecutor(use_device=True).execute_segment(ctx, seg)
    host = ServerQueryExecutor(use_device=False).execute_segment(ctx, seg)
    for got, want, col in [(dev.scalar[0], host.scalar[0], "k"),
                           (dev.scalar[1], host.scalar[1], "ki")]:
        assert isinstance(got, ThetaSketch), type(got)
        assert got.theta == pytest.approx(want.theta)
        assert np.array_equal(got.hashes, want.hashes), col
    # the dictionary-level cache is populated (the device fast path ran)
    assert getattr(seg.column("k").dictionary, "_theta_h64", None) is not None
    # estimates agree with the truth within theta error
    est = int(round(dev.scalar[0].estimate()))
    assert est == pytest.approx(len(set(ks)), rel=0.05)


def test_grouped_distinct_family_device_matches_host(tmp_path):
    """r4 (BASELINE config 5): GROUP BY + DISTINCTCOUNT/HLL/THETA runs ON
    DEVICE via the per-group presence matrix and matches the host path
    exactly (HLL registers and theta hashes are value-deterministic)."""
    import numpy as np
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.query.planner import plan_segment
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, load_segment

    rng = np.random.default_rng(5)
    n = 30_000
    schema = Schema("g1", [dimension("g"), dimension("u"),
                           metric("v", DataType.INT)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"g": [f"grp{i % 6}" for i in range(n)],
         "u": [f"user_{x}" for x in rng.integers(0, 800, n)],
         "v": np.arange(n, dtype=np.int32)}, str(tmp_path), "g1_0"))
    sql = ("SELECT g, DISTINCTCOUNT(u), DISTINCTCOUNTHLL(u), "
           "DISTINCTCOUNTTHETASKETCH(u), COUNT(*) FROM g1 "
           "WHERE v < 25000 GROUP BY g ORDER BY g LIMIT 10")
    ctx = compile_query(sql, schema)
    # the plan must actually take the device path (not a silent host fallback)
    plan = plan_segment(ctx, seg)
    assert plan.kind == "device", plan.reason if hasattr(plan, "reason") else plan.kind
    dev_rows = execute_query([seg], sql).rows
    host = ServerQueryExecutor(use_device=False)
    from pinot_tpu.query.reduce import merge_segment_results, reduce_to_result
    from pinot_tpu.query.aggregates import make_agg
    aggs = [make_agg(f) for f in ctx.aggregations]
    merged = merge_segment_results([host.execute_segment(ctx, seg)], aggs)
    host_rows = reduce_to_result(ctx, merged, aggs, list(ctx.group_by)).rows
    assert dev_rows == host_rows


def test_tdigest_device_counts_path(tmp_path):
    """r4: PERCENTILETDIGEST over a dict column rides the per-id COUNT vector
    (weighted digest over the sorted dictionary at cardinality cost) — device
    plan verified, quantiles match numpy and the host path within digest
    error, scalar + grouped + mesh."""
    from pinot_tpu.parallel import MeshQueryExecutor, default_mesh
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.query.planner import plan_segment
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import load_segment
    from pinot_tpu.segment.writer import build_aligned_segments

    rng = np.random.default_rng(8)
    n = 40_000
    # bounded-cardinality numeric: stays dictionary-encoded
    vals = np.round(rng.normal(500, 120, n)).astype(np.int32)
    cols = {"g": [f"g{i % 4}" for i in range(n)],
            "price": vals, "pad": np.arange(n, dtype=np.int32)}
    schema = Schema("td", [dimension("g"),
                           metric("price", DataType.INT),
                           metric("pad", DataType.INT)])
    paths = build_aligned_segments(schema, cols, str(tmp_path), "td", 8)
    segs = [load_segment(p) for p in paths]

    sql = ("SELECT PERCENTILETDIGEST(price, 95), PERCENTILETDIGEST50(price) "
           "FROM td WHERE pad < 30000")
    ctx = compile_query(sql, segs[0].schema)
    plan = plan_segment(ctx, segs[0])
    assert plan.kind == "device", plan.fallback_reason

    res = execute_query(segs, sql)
    m = cols["pad"] < 30000
    assert res.rows[0][0] == pytest.approx(np.percentile(vals[m], 95), rel=0.02)
    assert res.rows[0][1] == pytest.approx(np.percentile(vals[m], 50), rel=0.02)

    # host path agrees (same merge chain, different state construction)
    host = ServerQueryExecutor(use_device=False).execute(segs, sql)
    assert res.rows[0][0] == pytest.approx(host.rows[0][0], rel=0.02)

    # grouped on the mesh: per-group count matrices psum across devices
    gsql = ("SELECT g, PERCENTILETDIGEST(price, 50) FROM td "
            "GROUP BY g ORDER BY g LIMIT 10")
    mesh = MeshQueryExecutor(default_mesh(8)).execute(segs, gsql)
    garr = np.array(cols["g"], dtype=object)
    for g, got in mesh.rows:
        want = np.percentile(vals[garr == g], 50)
        assert got == pytest.approx(want, rel=0.03), (g, got, want)


def test_smart_tdigest_stays_on_host(tmp_path):
    """Review round: PERCENTILESMARTTDIGEST keeps its tuple state + exact-
    below-threshold contract — it must NOT inherit the device counts path."""
    import numpy as np
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.planner import plan_segment
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, load_segment

    rng = np.random.default_rng(2)
    n = 20_000
    vals = rng.integers(0, 500, n).astype(np.int32)
    schema = Schema("sm", [dimension("g"), metric("p", DataType.INT)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"g": ["a"] * n, "p": vals}, str(tmp_path), "sm_0"))
    sql = "SELECT PERCENTILESMARTTDIGEST(p, 50) FROM sm"
    ctx = compile_query(sql, schema)
    plan = plan_segment(ctx, seg)
    assert plan.kind != "device" or all(
        a.name != "percentilesmarttdigest" or not a.device_outputs
        for a in plan.aggs)
    res = execute_query([seg], sql)
    assert res.rows[0][0] == pytest.approx(np.percentile(vals, 50), abs=1.0)


def test_est_on_device_and_raw_variants_on_host(tmp_path):
    """Review round: PERCENTILEEST inherits the device counts path (audited:
    int finalize of the same digest quantile); the RAW serialized variants
    stay host-only so their hex payloads are execution-path-independent."""
    import numpy as np
    from pinot_tpu.query.aggregates import make_agg
    from pinot_tpu.query.context import compile_query
    from pinot_tpu.query.executor import ServerQueryExecutor
    from pinot_tpu.query.planner import plan_segment
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, load_segment

    rng = np.random.default_rng(6)
    n = 30_000
    vals = rng.integers(0, 800, n).astype(np.int32)
    schema = Schema("pe", [dimension("g"), metric("p", DataType.INT)])
    seg = load_segment(SegmentBuilder(schema).build(
        {"g": ["a"] * n, "p": vals}, str(tmp_path), "pe_0"))

    ctx = compile_query("SELECT PERCENTILEEST90(p) FROM pe", schema)
    assert plan_segment(ctx, seg).kind == "device"
    res = execute_query([seg], "SELECT PERCENTILEEST90(p) FROM pe")
    assert res.rows[0][0] == pytest.approx(np.percentile(vals, 90), abs=3)

    for fn in ("PERCENTILERAWTDIGEST(p, 50)", "PERCENTILERAWEST50(p)"):
        sql = f"SELECT {fn} FROM pe"
        ctx2 = compile_query(sql, schema)
        plan2 = plan_segment(ctx2, seg)
        raw_aggs = [a for a in plan2.aggs if a.name.startswith("percentileraw")]
        assert all(not a.device_outputs for a in raw_aggs), fn
        # identical hex regardless of use_device flag
        a = execute_query([seg], sql).rows[0][0]
        b = ServerQueryExecutor(use_device=False).execute([seg], sql).rows[0][0]
        assert a == b, fn
