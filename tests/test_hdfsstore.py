"""WebHDFS deep store: REST client (incl. the 307 redirect dance) + stub,
native rename, cluster chaos (VERDICT r4 #6).

Mirrors the reference's HDFS plugin coverage
(`pinot-plugins/pinot-file-system/pinot-hdfs/...HadoopPinotFS.java`) with
the same proof pattern as test_s3store.py / test_gcsstore.py."""

import json

import pytest

from pinot_tpu.cluster.deepstore import create_fs
from pinot_tpu.cluster.hdfsstore import HdfsDeepStoreFS, HdfsStub
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.table import StreamConfig, TableConfig, TableType

from conftest import wait_until


@pytest.fixture
def stub():
    s = HdfsStub()
    yield s
    s.stop()


def test_hdfs_fs_contract(stub, tmp_path):
    fs = create_fs(stub.spec())
    assert isinstance(fs, HdfsDeepStoreFS)
    fs.put_bytes(b"hello", "t/seg0.tar.gz")
    assert fs.get_bytes("t/seg0.tar.gz") == b"hello"
    assert fs.exists("t/seg0.tar.gz") and fs.exists("t")
    assert not fs.exists("t/nope")
    src = tmp_path / "blob"
    src.write_bytes(b"\x00\x01" * 500)
    fs.upload(str(src), "t/seg1.tar.gz")
    dst = tmp_path / "out" / "blob"
    fs.download("t/seg1.tar.gz", str(dst))
    assert dst.read_bytes() == src.read_bytes()
    fs.put_bytes(b"x", "t/sub/inner.bin")
    assert fs.listdir("t") == ["seg0.tar.gz", "seg1.tar.gz", "sub"]
    fs.move("t/seg0.tar.gz", "moved/seg0.tar.gz")
    assert not fs.exists("t/seg0.tar.gz")
    assert fs.get_bytes("moved/seg0.tar.gz") == b"hello"
    fs.delete("t")
    assert not fs.exists("t/seg1.tar.gz") and not fs.exists("t/sub/inner.bin")
    with pytest.raises(FileNotFoundError):
        fs.get_bytes("t/seg1.tar.gz")


def test_hdfs_redirect_dance_is_real(stub):
    """CREATE and OPEN must traverse the namenode->datanode 307 redirect;
    the stub only stores/serves data on the step2 leg."""
    fs = create_fs(stub.spec())
    fs.put_bytes(b"abc", "r/x.bin")
    # the stored path exists (write went through the redirect target)
    assert any(k.endswith("/r/x.bin") for k in stub.files)
    assert fs.get_bytes("r/x.bin") == b"abc"
    # direct un-redirected PUT against the namenode leg stores nothing
    import http.client
    import urllib.parse
    conn = http.client.HTTPConnection(stub.host, stub.port, timeout=5)
    conn.request("PUT", "/webhdfs/v1/deepstore/raw.bin?op=CREATE",
                 body=b"zz", headers={"Content-Length": "2"})
    resp = conn.getresponse()
    resp.read()
    assert resp.status == 307 and resp.getheader("Location")
    conn.close()
    assert not any(k.endswith("/raw.bin") for k in stub.files)


def test_hdfs_native_rename_is_metadata_move(stub):
    fs = create_fs(stub.spec())
    fs.put_bytes(b"payload", "a/b/seg.tar.gz")
    before = dict(stub.files)
    fs.move("a/b/seg.tar.gz", "c/d/seg.tar.gz")
    assert fs.get_bytes("c/d/seg.tar.gz") == b"payload"
    assert not fs.exists("a/b/seg.tar.gz")
    # same bytes object moved, never re-uploaded (metadata rename)
    new_key = [k for k in stub.files if k.endswith("/c/d/seg.tar.gz")][0]
    old_key = [k for k in before if k.endswith("/a/b/seg.tar.gz")][0]
    assert stub.files[new_key] is before[old_key]


def test_process_cluster_on_hdfs_with_outage_heals(tmp_path):
    """ProcessCluster storing realtime segments through hdfs://; an HDFS
    outage mid-stream commits via peer download and heals after recovery
    (the same chaos flow the s3/gcs schemes pass — one deep-store SPI)."""
    from pinot_tpu.cluster.process import ProcessCluster
    from pinot_tpu.ingest.kafkalite import LogBrokerClient, LogBrokerServer

    stub = HdfsStub()
    srv = LogBrokerServer()
    try:
        client = LogBrokerClient(srv.bootstrap)
        client.create_topic("ht", 1)
        cfg_path = tmp_path / "cluster.conf"
        cfg_path.write_text(f"controller.deepstore={stub.spec('deepstore')}\n")
        schema = Schema("ht", [
            dimension("u", DataType.STRING), metric("v", DataType.LONG),
            date_time("ts", DataType.LONG)])
        with ProcessCluster(num_servers=2, work_dir=str(tmp_path),
                            config_path=str(cfg_path)) as cluster:
            cluster.controller.add_schema(schema)
            cfg = TableConfig(
                "ht", table_type=TableType.REALTIME, time_column="ts",
                replication=2,
                stream=StreamConfig(stream_type="kafkalite", topic="ht",
                                    properties={"bootstrap": srv.bootstrap},
                                    flush_threshold_rows=25))
            cluster.controller.add_table(cfg, num_partitions=1)
            table = cfg.table_name_with_type

            def count():
                rows = cluster.query(
                    "SELECT COUNT(*) FROM ht")["resultTable"]["rows"]
                return rows[0][0] if rows else 0

            for i in range(30):
                client.produce("ht", json.dumps(
                    {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
            assert wait_until(lambda: count() == 30, timeout=60)

            def done_segments():
                metas = cluster.controller.segments_meta(table)["segments"]
                return {n: m for n, m in metas.items()
                        if m.get("status") == "DONE"}
            assert wait_until(lambda: len(done_segments()) >= 1, timeout=60)
            assert any(k.endswith(".tar.gz") for k in stub.files)

            stub.outage = True
            try:
                for i in range(30, 60):
                    client.produce("ht", json.dumps(
                        {"u": f"u{i % 3}", "v": i, "ts": 1700000000000 + i}))
                assert wait_until(
                    lambda: any(str(m.get("download_path", "")).startswith(
                        "peer://") for m in done_segments().values()),
                    timeout=90), "commit must survive the HDFS outage"
                assert wait_until(lambda: count() == 60, timeout=60)
            finally:
                stub.outage = False
            # healing: the repair task re-uploads the peer segment to hdfs
            assert wait_until(
                lambda: all(not str(m.get("download_path", "")).startswith(
                    "peer://") for m in done_segments().values()),
                timeout=120), "deep-store healing did not run"
    finally:
        srv.stop()
        stub.stop()
