"""Avro tests: golden bytes, container round-trips, schema resolution, and
end-to-end batch + realtime ingestion of avro data.

Mirrors the reference's avro plugin coverage
(`pinot-plugins/pinot-input-format/pinot-avro/src/test/...`,
`pinot-confluent-avro/.../KafkaConfluentSchemaRegistryAvroMessageDecoderTest`)
plus spec-level golden-byte vectors in the style of test_kafka_wire.py.
"""

import json
import struct

import numpy as np
import pytest

from pinot_tpu.ingest.avro import (AvroError, AvroFileReader, AvroFileWriter,
                                   BinaryDecoder, BinaryEncoder,
                                   DEFAULT_REGISTRY, LocalSchemaRegistry,
                                   confluent_avro_decoder, encode_confluent,
                                   make_simple_avro_decoder, parse_schema,
                                   read_datum, write_datum)
from pinot_tpu.schema import DataType, Schema, dimension, metric


def enc(schema, value) -> bytes:
    e = BinaryEncoder()
    write_datum(e, parse_schema(schema), value)
    return e.getvalue()


def dec(schema, data: bytes, reader=None):
    return read_datum(BinaryDecoder(data), parse_schema(schema),
                      parse_schema(reader) if reader is not None else None)


# -- golden bytes (Avro 1.11 spec examples) ----------------------------------

def test_golden_zigzag_longs():
    # spec table: 0->00, -1->01, 1->02, -2->03, 2->04; varint: 64->80 01
    for v, raw in [(0, b"\x00"), (-1, b"\x01"), (1, b"\x02"), (-2, b"\x03"),
                   (2, b"\x04"), (64, b"\x80\x01"), (-64, b"\x7f"),
                   (100, b"\xc8\x01"), (-(1 << 63), b"\xff" * 9 + b"\x01")]:
        assert enc('"long"', v) == raw, v
        assert dec('"long"', raw) == v


def test_golden_string_and_primitives():
    assert enc('"string"', "foo") == b"\x06foo"          # len 3 zigzag=06
    assert dec('"string"', b"\x06foo") == "foo"
    assert enc('"boolean"', True) == b"\x01"
    assert enc('"null"', None) == b""
    assert enc('"float"', 1.5) == struct.pack("<f", 1.5)
    assert enc('"double"', -2.25) == struct.pack("<d", -2.25)
    assert enc('"bytes"', b"\x00\xff") == b"\x04\x00\xff"


def test_golden_record():
    # spec's canonical example: {"a": 27, "b": "foo"} -> 36 06 66 6f 6f
    schema = {"type": "record", "name": "test", "fields": [
        {"name": "a", "type": "long"}, {"name": "b", "type": "string"}]}
    assert enc(schema, {"a": 27, "b": "foo"}) == b"\x36\x06foo"
    assert dec(schema, b"\x36\x06foo") == {"a": 27, "b": "foo"}


def test_golden_array_and_union():
    # spec: array of longs [3, 27] -> 04 06 36 00
    assert enc({"type": "array", "items": "long"}, [3, 27]) == b"\x04\x06\x36\x00"
    assert dec({"type": "array", "items": "long"}, b"\x04\x06\x36\x00") == [3, 27]
    # spec: union ["null","string"]: null -> 02? no: index 0 -> 00; "a" -> 02 02 61
    assert enc(["null", "string"], None) == b"\x00"
    assert enc(["null", "string"], "a") == b"\x02\x02a"
    assert dec(["null", "string"], b"\x02\x02a") == "a"
    assert dec(["null", "string"], b"\x00") is None


def test_negative_array_block_count_with_size():
    # writers may emit a negative count followed by the block byte size
    data = b"\x03\x04\x06\x36\x00"  # count=-2, size=2, items 3,27, end
    assert dec({"type": "array", "items": "long"}, data) == [3, 27]


# -- round-trips over the full supported subset ------------------------------

COMPLEX = {
    "type": "record", "name": "Event", "fields": [
        {"name": "id", "type": "long"},
        {"name": "name", "type": ["null", "string"], "default": None},
        {"name": "tags", "type": {"type": "array", "items": "string"}},
        {"name": "props", "type": {"type": "map", "values": "double"}},
        {"name": "kind", "type": {"type": "enum", "name": "Kind",
                                  "symbols": ["A", "B", "C"]}},
        {"name": "sig", "type": {"type": "fixed", "name": "Sig", "size": 4}},
        {"name": "nested", "type": {"type": "record", "name": "Inner",
                                    "fields": [{"name": "x", "type": "double"}]}},
    ]}

ROWS = [
    {"id": 1, "name": "alpha", "tags": ["x", "y"], "props": {"p": 1.5},
     "kind": "A", "sig": b"\x01\x02\x03\x04", "nested": {"x": 0.5}},
    {"id": -7, "name": None, "tags": [], "props": {},
     "kind": "C", "sig": b"\xff\xfe\xfd\xfc", "nested": {"x": -1.25}},
]


def test_complex_record_roundtrip():
    for row in ROWS:
        assert dec(COMPLEX, enc(COMPLEX, row)) == row


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_container_file_roundtrip(tmp_path, codec):
    path = str(tmp_path / f"events_{codec}.avro")
    with AvroFileWriter(path, COMPLEX, codec=codec, sync_interval=1) as w:
        for row in ROWS * 5:
            w.append(row)
    r = AvroFileReader(path)
    assert r.codec == codec
    out = list(r)
    r.close()
    assert out == ROWS * 5


def test_container_detects_corrupt_sync(tmp_path):
    path = str(tmp_path / "bad.avro")
    with AvroFileWriter(path, COMPLEX, sync_interval=1) as w:
        w.append(ROWS[0])
    raw = bytearray(open(path, "rb").read())
    raw[-1] ^= 0xFF  # flip a sync byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(AvroError, match="sync marker"):
        list(AvroFileReader(path))


def test_container_rejects_snappy(tmp_path):
    with pytest.raises(AvroError, match="codec"):
        AvroFileWriter(str(tmp_path / "x.avro"), COMPLEX, codec="snappy")


# -- schema resolution -------------------------------------------------------

def test_resolution_defaults_skips_and_promotions():
    writer = {"type": "record", "name": "R", "fields": [
        {"name": "a", "type": "int"},
        {"name": "dropped", "type": "string"},
        {"name": "raw", "type": "bytes"}]}
    reader = {"type": "record", "name": "R", "fields": [
        {"name": "a", "type": "double"},               # int -> double
        {"name": "raw", "type": "string"},             # bytes -> string
        {"name": "added", "type": "long", "default": 42}]}
    data = enc(writer, {"a": 3, "dropped": "gone", "raw": b"hi"})
    out = dec(writer, data, reader=reader)
    assert out == {"a": 3.0, "raw": "hi", "added": 42}
    assert isinstance(out["a"], float)


def test_resolution_union_reader_for_plain_writer():
    out = dec('"string"', enc('"string"', "v"), reader=["null", "string"])
    assert out == "v"


def test_resolution_missing_default_errors():
    writer = {"type": "record", "name": "R",
              "fields": [{"name": "a", "type": "int"}]}
    reader = {"type": "record", "name": "R", "fields": [
        {"name": "a", "type": "int"}, {"name": "b", "type": "int"}]}
    with pytest.raises(AvroError, match="default"):
        dec(writer, enc(writer, {"a": 1}), reader=reader)


# -- confluent stream wire ---------------------------------------------------

def test_confluent_wire_golden_and_decoder():
    reg = LocalSchemaRegistry()
    schema = {"type": "record", "name": "test", "fields": [
        {"name": "a", "type": "long"}, {"name": "b", "type": "string"}]}
    sid = reg.register(schema)
    msg = encode_confluent(sid, schema, {"a": 27, "b": "foo"})
    assert msg == b"\x00" + struct.pack(">I", sid) + b"\x36\x06foo"
    assert confluent_avro_decoder(msg, reg) == {"a": 27, "b": "foo"}
    with pytest.raises(AvroError, match="magic"):
        confluent_avro_decoder(b"\x01junk", reg)


def test_simple_avro_decoder():
    schema = {"type": "record", "name": "t", "fields": [
        {"name": "v", "type": "double"}]}
    decode = make_simple_avro_decoder(schema)
    assert decode(enc(schema, {"v": 2.5})) == {"v": 2.5}


# -- end-to-end: batch ingest of .avro + realtime avro stream ----------------

EVENTS_AVRO_SCHEMA = {
    "type": "record", "name": "events", "fields": [
        {"name": "user", "type": "string"},
        {"name": "country", "type": ["null", "string"], "default": None},
        {"name": "value", "type": "double"},
        {"name": "clicks", "type": "long"}]}


def _events_schema():
    return Schema("events", [
        dimension("user"), dimension("country"),
        metric("value", DataType.DOUBLE), metric("clicks", DataType.LONG)])


def test_batch_ingestion_of_avro_file_differential(tmp_path):
    """Same rows through .avro and .jsonl must produce identical query
    results (the reader is just another SPI plugin)."""
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.ingest.batch import BatchIngestionJobSpec, run_batch_ingestion
    from pinot_tpu.table import TableConfig

    rng = np.random.default_rng(11)
    rows = [{"user": f"u{int(i)}", "country": ["US", "DE", None][int(i) % 3],
             "value": round(float(v), 3), "clicks": int(i)}
            for i, v in zip(rng.integers(0, 50, 500), rng.uniform(0, 9, 500))]
    avro_path = str(tmp_path / "events.avro")
    with AvroFileWriter(avro_path, EVENTS_AVRO_SCHEMA, codec="deflate",
                        sync_interval=128) as w:
        for r in rows:
            w.append(r)
    jsonl_path = tmp_path / "events.jsonl"
    jsonl_path.write_text("".join(json.dumps(r) + "\n" for r in rows))

    results = {}
    for fmt, path in [("avro", avro_path), ("jsonl", str(jsonl_path))]:
        cluster = QuickCluster(num_servers=1,
                               work_dir=str(tmp_path / f"c_{fmt}"))
        cfg = TableConfig("events")
        cluster.create_table(_events_schema(), cfg)
        pushed = run_batch_ingestion(
            BatchIngestionJobSpec(input_paths=[path],
                                  table=cfg.table_name_with_type,
                                  segment_rows=200),
            cluster.controller, work_dir=str(tmp_path / f"w_{fmt}"))
        assert len(pushed) == 3
        res = cluster.query(
            "SELECT user, COUNT(*), SUM(value), MAX(clicks) FROM events "
            "GROUP BY user ORDER BY user LIMIT 1000")
        results[fmt] = res.rows
    assert results["avro"] == results["jsonl"]


def test_realtime_table_consumes_confluent_avro(tmp_path):
    """A realtime table with decoder='avro' consumes confluent-framed binary
    messages; totals match the produced rows exactly (reference:
    KafkaConfluentSchemaRegistryAvroMessageDecoder in a realtime table)."""
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.ingest.stream import MemoryStream
    from pinot_tpu.table import StreamConfig, TableConfig, TableType

    MemoryStream.reset_all()
    sid = DEFAULT_REGISTRY.register(EVENTS_AVRO_SCHEMA)
    try:
        cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
        cfg = TableConfig("events", table_type=TableType.REALTIME,
                          replication=1,
                          stream=StreamConfig(stream_type="memory",
                                              topic="avro_topic",
                                              decoder="avro",
                                              flush_threshold_rows=1000))
        cluster.create_realtime_table(_events_schema(), cfg, 1)
        stream = MemoryStream.get("avro_topic")
        total_clicks = 0
        for i in range(120):
            row = {"user": f"u{i % 9}", "country": "JP" if i % 2 else None,
                   "value": i * 0.5, "clicks": i}
            total_clicks += i
            stream.produce(encode_confluent(sid, EVENTS_AVRO_SCHEMA, row),
                           partition=0)
        cluster.pump_realtime(cfg.table_name_with_type)
        res = cluster.query("SELECT COUNT(*), SUM(clicks) FROM events")
        assert res.rows[0] == [120, total_clicks]
        res2 = cluster.query("SELECT COUNT(*) FROM events WHERE country = 'JP'")
        assert res2.rows[0][0] == 60
    finally:
        MemoryStream.reset_all()


def test_review_fixes_lenient_schema_attrs_and_promotion(tmp_path):
    """Review round: Java-written schemas with extra attributes parse; ints
    encode into double-only unions; truncated confluent headers raise
    AvroError; AvroRecordReader restarts cleanly."""
    assert parse_schema({"type": "string", "avro.java.string": "String"}) \
        == "string"
    assert parse_schema({"type": "long", "extra": 1}) == "long"
    # int into ["null","double"] promotes on write like it does on read
    assert dec(["null", "double"], enc(["null", "double"], 3)) == 3.0
    with pytest.raises(AvroError, match="truncated"):
        confluent_avro_decoder(b"\x00\x01\x02")
    path = str(tmp_path / "r.avro")
    with AvroFileWriter(path, EVENTS_AVRO_SCHEMA) as w:
        w.append({"user": "u", "country": None, "value": 1.0, "clicks": 2})
    from pinot_tpu.ingest.readers import reader_for
    rdr = reader_for(path)
    assert len(list(rdr.rows())) == 1
    assert len(list(rdr.rows())) == 1   # second pass: restartable
    rdr.close()
