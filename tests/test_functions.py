"""Transform/scalar function library correctness.

Datetime functions are differential-tested against python's datetime module (UTC) over
random epochs including pre-1970; string functions against straight python. End-to-end
queries exercise the device kernel path for calendar math (reference analog:
DateTimeFunctionsTest / StringFunctionsTest in pinot-common, and the transform-function
suites in pinot-core).
"""

import datetime as dt

import numpy as np
import pytest

from pinot_tpu.engine.expr import eval_expr
from pinot_tpu.sql.parser import Parser


def expr(sql_expr):
    return Parser(f"SELECT {sql_expr} FROM t").parse().select[0][0]


def ev(sql_expr, env=None, xp=np):
    return eval_expr(expr(sql_expr), env or {}, xp)


@pytest.fixture(scope="module")
def epochs():
    rng = np.random.default_rng(3)
    ms = rng.integers(-5_000_000_000_000, 5_000_000_000_000, 500).astype(np.int64)
    fixed = np.array([0, 1, -1, 86_399_999, 86_400_000, -86_400_000,
                      951_782_400_000,   # 2000-02-29
                      4_107_542_400_000  # 2100-02-28 (non-leap century)
                      ], dtype=np.int64)
    return np.concatenate([fixed, ms])


def utc(ms):
    return dt.datetime(1970, 1, 1, tzinfo=dt.timezone.utc) + dt.timedelta(milliseconds=int(ms))


def test_calendar_fields(epochs):
    env = {"ts": epochs}
    got = {name: np.asarray(ev(f"{name}(ts)", env))
           for name in ("year", "month", "dayofmonth", "dayofyear", "dayofweek",
                        "hour", "minute", "second", "millisecond", "quarter", "week")}
    for i, ms in enumerate(epochs):
        d = utc(ms)
        iso = d.isocalendar()
        assert got["year"][i] == d.year, ms
        assert got["month"][i] == d.month, ms
        assert got["dayofmonth"][i] == d.day, ms
        assert got["dayofyear"][i] == d.timetuple().tm_yday, ms
        assert got["dayofweek"][i] == d.isoweekday(), ms
        assert got["hour"][i] == d.hour, ms
        assert got["minute"][i] == d.minute, ms
        assert got["second"][i] == d.second, ms
        assert got["millisecond"][i] == int(ms) % 1000, ms
        assert got["quarter"][i] == (d.month - 1) // 3 + 1, ms
        assert got["week"][i] == iso[1], ms


def test_calendar_fields_on_jax(epochs):
    # The scan path ships 64-bit epochs to the device decomposed (or falls back to host —
    # planner rejects >int32 columns); under x64 the traced math must match numpy exactly.
    import jax.numpy as jnp
    from jax.experimental import enable_x64
    with enable_x64():
        host = np.asarray(ev("year(ts)", {"ts": epochs}))
        dev = np.asarray(ev("year(ts)", {"ts": jnp.asarray(epochs)}, xp=jnp))
        np.testing.assert_array_equal(host, dev)
        np.testing.assert_array_equal(
            np.asarray(ev("week(ts)", {"ts": epochs})),
            np.asarray(ev("week(ts)", {"ts": jnp.asarray(epochs)}, xp=jnp)))


def test_datetrunc(epochs):
    env = {"ts": epochs}
    for unit, fn in [
        ("day", lambda d: d.replace(hour=0, minute=0, second=0, microsecond=0)),
        ("month", lambda d: d.replace(day=1, hour=0, minute=0, second=0, microsecond=0)),
        ("year", lambda d: d.replace(month=1, day=1, hour=0, minute=0, second=0,
                                     microsecond=0)),
    ]:
        got = np.asarray(ev(f"datetrunc('{unit}', ts)", env))
        for i, ms in enumerate(epochs):
            want = int(fn(utc(ms)).timestamp() * 1000)
            assert got[i] == want, (unit, ms)


def test_datetrunc_week_is_monday(epochs):
    got = np.asarray(ev("datetrunc('week', ts)", {"ts": epochs}))
    for i, ms in enumerate(epochs):
        d = utc(got[i])
        assert d.isoweekday() == 1 and d.hour == 0 and d.minute == 0
        assert got[i] <= ms < got[i] + 7 * 86_400_000


def test_epoch_conversions():
    assert ev("toepochdays(ts)", {"ts": np.int64(86_400_000 * 3 + 5)}) == 3
    assert ev("fromepochhours(ts)", {"ts": np.int64(2)}) == 7_200_000
    assert ev("toepochminutesbucket(ts, 10)", {"ts": np.int64(60_000 * 25)}) == 2
    assert ev("timeconvert(ts, 'MILLISECONDS', 'SECONDS')", {"ts": np.int64(5999)}) == 5


def test_datetimeconvert_epoch_roundtrip():
    ts = np.array([1_577_836_800_000, 1_577_923_200_123], dtype=np.int64)  # 2020-01-01/02
    days = np.asarray(ev("datetimeconvert(ts, '1:MILLISECONDS:EPOCH', '1:DAYS:EPOCH', '1:DAYS')",
                         {"ts": ts}))
    np.testing.assert_array_equal(days, [18262, 18263])
    sdf = ev("datetimeconvert(ts, '1:MILLISECONDS:EPOCH', "
             "'1:DAYS:SIMPLE_DATE_FORMAT:yyyy-MM-dd', '1:DAYS')", {"ts": ts})
    assert list(sdf) == ["2020-01-01", "2020-01-02"]


def test_todatetime_fromdatetime_roundtrip():
    ts = np.array([1_577_836_800_000, 1_609_459_199_000], dtype=np.int64)
    s = ev("todatetime(ts, 'yyyy-MM-dd HH:mm:ss')", {"ts": ts})
    back = np.asarray(ev("fromdatetime(s, 'yyyy-MM-dd HH:mm:ss')", {"s": s}))
    np.testing.assert_array_equal(back, ts)


def test_string_functions():
    v = np.asarray(["Hello World", "  pad  ", "abc", ""], dtype=object)
    env = {"s": v}
    assert list(ev("upper(s)", env)) == ["HELLO WORLD", "  PAD  ", "ABC", ""]
    assert list(ev("lower(s)", env)) == ["hello world", "  pad  ", "abc", ""]
    assert list(ev("reverse(s)", env)) == ["dlroW olleH", "  dap  ", "cba", ""]
    assert list(ev("length(s)", env)) == [11, 7, 3, 0]
    assert list(ev("trim(s)", env)) == ["Hello World", "pad", "abc", ""]
    assert list(ev("substr(s, 0, 5)", env)) == ["Hello", "  pad", "abc", ""]
    assert list(ev("substr(s, 6)", env)) == ["World", " ", "", ""]
    assert list(ev("replace(s, 'l', 'L')", env)) == ["HeLLo WorLd", "  pad  ", "abc", ""]
    assert list(ev("startswith(s, 'He')", env)) == [True, False, False, False]
    assert list(ev("contains(s, 'o')", env)) == [True, False, False, False]
    assert list(ev("strpos(s, 'o')", env)) == [4, -1, -1, -1]
    assert list(ev("strpos(s, 'o', 2)", env)) == [7, -1, -1, -1]
    assert list(ev("lpad(s, 5, '*')", env)) == ["Hello", "  pad", "**abc", "*****"]
    assert list(ev("rpad(s, 4, '-')", env)) == ["Hell", "  pa", "abc-", "----"]
    assert list(ev("splitpart(s, ' ', 1)", env)) == ["World", "", "null", "null"]


def test_concat_and_codepoints():
    a = np.asarray(["x", "y"], dtype=object)
    b = np.asarray(["1", "2"], dtype=object)
    assert list(ev("concat(a, b)", {"a": a, "b": b})) == ["x1", "y2"]
    assert list(ev("concat(a, b, '-')", {"a": a, "b": b})) == ["x-1", "y-2"]
    assert list(ev("concat_ws('-', a, b)", {"a": a, "b": b})) == ["x-1", "y-2"]
    assert ev("codepoint(s)", {"s": "A"}) == 65
    assert ev("chr(n)", {"n": 66}) == "B"


def test_regexp_functions():
    v = np.asarray(["foo123bar", "nope"], dtype=object)
    assert list(ev("regexp_extract(s, '[0-9]+')", {"s": v})) == ["123", ""]
    assert list(ev("regexp_replace(s, '[0-9]+', '#')", {"s": v})) == ["foo#bar", "nope"]


def test_hash_functions():
    import hashlib
    v = np.asarray(["abc"], dtype=object)
    assert ev("md5(s)", {"s": v})[0] == hashlib.md5(b"abc").hexdigest()
    assert ev("sha256(s)", {"s": v})[0] == hashlib.sha256(b"abc").hexdigest()


def test_null_functions():
    a = np.array([1.0, np.nan, 3.0])
    b = np.array([9.0, 8.0, 7.0])
    np.testing.assert_array_equal(ev("coalesce(a, b)", {"a": a, "b": b}), [1.0, 8.0, 3.0])
    got = ev("nullif(a, 1.0)", {"a": a})
    assert np.isnan(got[0]) and np.isnan(got[1]) and got[2] == 3.0


def test_arith_extras():
    v = np.array([-2.5, 0.0, 3.7])
    np.testing.assert_array_equal(ev("sign(v)", {"v": v}), [-1.0, 0.0, 1.0])
    np.testing.assert_allclose(ev("truncate(v, 0)", {"v": v}), [-2.0, 0.0, 3.0])
    np.testing.assert_allclose(ev("atan2(v, v)", {"v": np.array([1.0])}), [np.pi / 4])
    np.testing.assert_allclose(ev("degrees(v)", {"v": np.array([np.pi])}), [180.0])


# -- end-to-end through the query engine -------------------------------------

@pytest.fixture(scope="module")
def time_env(tmp_path_factory):
    from pinot_tpu.query.executor import execute_query
    from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
    from pinot_tpu.segment import SegmentBuilder, SegmentGeneratorConfig, load_segment

    rng = np.random.default_rng(11)
    n = 2000
    start = 1_560_000_000_000
    cols = {
        "ts": (start + rng.integers(0, 400 * 86_400_000, n)).astype(np.int64),
        "site": [f"site{i}" for i in rng.integers(0, 4, n)],
        "clicks": rng.integers(0, 100, n).astype(np.int32),
    }
    schema = Schema("events", [
        date_time("ts", DataType.TIMESTAMP),
        dimension("site", DataType.STRING),
        metric("clicks", DataType.INT),
    ])
    out = tmp_path_factory.mktemp("timeseg")
    seg = load_segment(SegmentBuilder(schema, SegmentGeneratorConfig()).build(
        cols, str(out), "events_0"))
    return [seg], cols, execute_query


def test_group_by_year(time_env):
    segments, cols, execute_query = time_env
    res = execute_query(segments, "SELECT YEAR(ts), COUNT(*) FROM events GROUP BY YEAR(ts)")
    want = {}
    for ms in cols["ts"]:
        y = utc(ms).year
        want[y] = want.get(y, 0) + 1
    got = {int(r[0]): int(r[1]) for r in res.rows}
    assert got == want


def test_filter_on_datetrunc(time_env):
    segments, cols, execute_query = time_env
    res = execute_query(
        segments,
        "SELECT COUNT(*) FROM events WHERE DATETRUNC('year', ts) = 1577836800000")
    want = sum(1 for ms in cols["ts"] if utc(ms).year == 2020)
    assert int(res.rows[0][0]) == want


def test_select_todatetime(time_env):
    segments, cols, execute_query = time_env
    res = execute_query(segments,
                        "SELECT TODATETIME(ts, 'yyyy-MM-dd') FROM events LIMIT 5")
    for row in res.rows:
        assert len(row[0]) == 10 and row[0][4] == "-"


# -- new breadth: MV reductions, codecs, cot ----------------------------------

def _mv_env():
    return {"a": np.array([np.array([1.0, 2.0, 3.0]), np.array([5.0]),
                           np.array([])], dtype=object),
            "s": np.array(["café com leite", "a&b=c", None], dtype=object)}


def test_array_reductions():
    env = _mv_env()
    assert ev("arraysum(a)", env).tolist() == [6.0, 5.0, 0.0]
    assert ev("arraymax(a)", env)[:2].tolist() == [3.0, 5.0]
    assert ev("arraymin(a)", env)[:2].tolist() == [1.0, 5.0]
    assert ev("arrayaverage(a)", env)[0] == pytest.approx(2.0)
    assert np.isnan(ev("arrayaverage(a)", env)[2])


def test_array_distinct_sort_index():
    env = {"a": np.array([np.array([3, 1, 3, 2]), np.array([7])], dtype=object)}
    d = ev("arraydistinct(a)", env)
    assert d[0].tolist() == [3, 1, 2]
    assert ev("arraysortasc(a)", env)[0].tolist() == [1, 2, 3, 3]
    assert ev("arraysortdesc(a)", env)[0].tolist() == [3, 3, 2, 1]
    assert ev("arrayindexof(a, 2)", env).tolist() == [3, -1]
    assert ev("arraycontains(a, 7)", env).tolist() == [False, True]


def test_base64_and_url_codecs():
    import base64
    import urllib.parse
    env = _mv_env()
    enc = ev("tobase64(s)", env)
    assert enc[0] == base64.b64encode("café com leite".encode()).decode()
    assert enc[2] is None
    back = ev("frombase64(tobase64(s))", env)
    assert back[0] == "café com leite"
    u = ev("encodeurl(s)", env)
    assert u[1] == urllib.parse.quote("a&b=c", safe="")
    assert ev("decodeurl(encodeurl(s))", env)[1] == "a&b=c"


def test_cot():
    assert ev("cot(x)", {"x": np.array([1.0])})[0] == pytest.approx(1 / np.tan(1.0))


def test_codecs_on_scalar_literals():
    assert ev("tobase64('hello')", {}) == "aGVsbG8="
    assert ev("frombase64('aGVsbG8=')", {}) == "hello"
    assert ev("encodeurl('a b')", {}) == "a%20b"


def test_string_breadth_batch():
    env = {"s": np.array(["hello", "world"], dtype=object)}
    assert ev("repeat(s, 2)", env).tolist() == ["hellohello", "worldworld"]
    assert ev("remove(s, 'l')", env).tolist() == ["heo", "word"]
    assert ev("leftsubstr(s, 3)", env).tolist() == ["hel", "wor"]
    assert ev("rightsubstr(s, 3)", env).tolist() == ["llo", "rld"]
    assert ev("strcmp(s, 'hello')", env).tolist() == [0, 1]
    assert ev("strrpos(s, 'l')", env).tolist() == [3, 3]
    assert ev("hammingdistance(s, 'hella')", env).tolist() == [1, 4]
    assert ev("toascii(s)", env).tolist() == ["hello", "world"]
    assert ev("base64encode(s)", env)[0] == "aGVsbG8="
    assert ev("bytestohex(toutf8(s))", env)[0] == "68656c6c6f"
    assert ev("fromutf8(hextobytes('68656c6c6f'))", {}) == "hello"


def test_timestamp_add_diff():
    ts = 1_700_000_000_000  # 2023-11-14
    env = {"t": np.array([ts], dtype=np.int64)}
    plus_day = ev("timestampadd('DAY', 3, t)", env)
    assert int(plus_day[0]) == ts + 3 * 86_400_000
    plus_month = ev("timestampadd('MONTH', 2, t)", env)
    import datetime as dt
    d0 = dt.datetime.fromtimestamp(ts / 1000, dt.timezone.utc)
    d1 = dt.datetime.fromtimestamp(int(plus_month[0]) / 1000, dt.timezone.utc)
    assert (d1.year, d1.month, d1.day) == (2024, 1, d0.day)
    assert ev("timestampdiff('HOUR', t, timestampadd('HOUR', 7, t))", env)[0] == 7
    assert ev("datediff('MONTH', t, dateadd('MONTH', 5, t))", env)[0] == 5
    # month-end clamping: Jan 31 + 1 month -> Feb 29 (2024 leap)
    jan31 = int(dt.datetime(2024, 1, 31, tzinfo=dt.timezone.utc).timestamp() * 1000)
    feb = ev("timestampadd('MONTH', 1, t2)", {"t2": np.array([jan31], dtype=np.int64)})
    d2 = dt.datetime.fromtimestamp(int(feb[0]) / 1000, dt.timezone.utc)
    assert (d2.month, d2.day) == (2, 29)


def test_array_breadth_batch():
    env = {"a": np.array([np.array([3, 1, 3]), np.array([7, 8])], dtype=object),
           "b": np.array([np.array([1, 9]), np.array([8])], dtype=object)}
    assert ev("arrayreverse(a)", env)[0].tolist() == [3, 1, 3][::-1]
    assert ev("arrayslice(a, 0, 2)", env)[0].tolist() == [3, 1]
    assert ev("arrayremove(a, 3)", env)[0].tolist() == [1, 3]  # first occurrence only
    assert ev("arrayunion(a, b)", env)[0].tolist() == [3, 1, 9]
    assert ev("arrayconcat(a, b)", env)[1].tolist() == [7, 8, 8]
    assert ev("arraysortint(a)", env)[0].tolist() == [1, 3, 3]


def test_jsonpath_aliases():
    env = {"j": np.array(['{"a": {"b": 7, "s": "x"}}'], dtype=object)}
    assert ev("jsonpathlong(j, '$.a.b')", env).tolist() == [7]
    assert ev("jsonpathstring(j, '$.a.s')", env).tolist() == ["x"]
    assert ev("jsonpathdouble(j, '$.a.b')", env).tolist() == [7.0]


def test_function_review_fixes():
    import math
    env = {"s": np.array(["abcabc"], dtype=object)}
    assert ev("repeat(s, '-', 3)", env)[0] == "abcabc-abcabc-abcabc"
    assert ev("strrpos(s, 'bc', 4)", env)[0] == 4  # match may START at fromIndex
    assert ev("timezonehour('America/New_York')", {}) == -5    # at epoch 0, no DST
    assert ev("timezonehour('America/St_Johns')", {}) == -3    # truncate toward zero
    assert ev("timezoneminute('America/St_Johns')", {}) == -30
    j = {"j": np.array(['{"a": 1}'], dtype=object)}
    assert ev("jsonpathlong(j, '$.missing')", j).tolist() == [-(1 << 63)]
    assert math.isnan(ev("jsonpathdouble(j, '$.missing')", j)[0])
