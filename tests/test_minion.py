"""Minion tests: segment-processing framework, MergeRollup, RealtimeToOffline,
scheduled retention, lineage-protected replace.

Reference scenarios: MergeRollupTaskExecutor/Generator tests, RealtimeToOffline
integration tests, RetentionManager tests (SURVEY.md §2.8).
"""

import time

import numpy as np
import pytest

from pinot_tpu.cluster.enclosure import QuickCluster
from pinot_tpu.minion import ProcessorConfig, process_segments
from pinot_tpu.minion.framework import CONCAT, DEDUP, ROLLUP
from pinot_tpu.minion.tasks import COMPLETED, MERGE_ROLLUP, REALTIME_TO_OFFLINE
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.segment import SegmentBuilder, load_segment
from pinot_tpu.table import StreamConfig, TableConfig, TableType

DAY = 24 * 3600 * 1000


def event_schema(name="events"):
    return Schema(name, [
        dimension("site", DataType.STRING),
        date_time("ts", DataType.LONG),
        metric("clicks", DataType.LONG),
        metric("cost", DataType.DOUBLE),
    ])


def make_cols(rng, n, day_ms, sites=("a", "b", "c")):
    return {
        "site": [sites[i] for i in rng.integers(0, len(sites), n)],
        "ts": day_ms + rng.integers(0, DAY, n, dtype=np.int64),
        "clicks": rng.integers(1, 10, n, dtype=np.int64),
        "cost": np.round(rng.uniform(0.1, 5.0, n), 4),
    }


# ---------------------------------------------------------------------------
# Framework
# ---------------------------------------------------------------------------

class TestFramework:
    def _segments(self, tmp_path, n_segs=3, rows=100, day=0):
        rng = np.random.default_rng(5)
        schema = event_schema()
        builder = SegmentBuilder(schema)
        segs = []
        for i in range(n_segs):
            cols = make_cols(rng, rows, day * DAY)
            segs.append(load_segment(builder.build(cols, str(tmp_path), f"in_{i}")))
        return schema, segs

    def test_concat_preserves_rows(self, tmp_path):
        schema, segs = self._segments(tmp_path / "in")
        built = process_segments(segs, schema, ProcessorConfig(
            merge_type=CONCAT, segment_prefix="m"), str(tmp_path / "out"))
        assert len(built) == 1
        merged = load_segment(built[0])
        assert merged.num_docs == sum(s.num_docs for s in segs)
        want = sum(int(v) for s in segs for v in s.column("clicks").values())
        assert sum(int(v) for v in merged.column("clicks").values()) == want

    def test_rollup_aggregates_metrics(self, tmp_path):
        schema, segs = self._segments(tmp_path / "in")
        built = process_segments(segs, schema, ProcessorConfig(
            merge_type=ROLLUP, time_column="ts", round_time_to=DAY,
            aggregations={"cost": "sum"}, segment_prefix="m"), str(tmp_path / "out"))
        merged = load_segment(built[0])
        # after rounding ts to the day, keys collapse to (site, day): <= 3 sites
        assert merged.num_docs <= 3
        total = sum(float(v) for s in segs for v in s.column("cost").values())
        assert sum(float(v) for v in merged.column("cost").values()) == pytest.approx(
            total, rel=1e-9)
        want_clicks = sum(int(v) for s in segs for v in s.column("clicks").values())
        assert sum(int(v) for v in merged.column("clicks").values()) == want_clicks

    def test_rollup_min_max(self, tmp_path):
        schema, segs = self._segments(tmp_path / "in")
        built = process_segments(segs, schema, ProcessorConfig(
            merge_type=ROLLUP, time_column="ts", round_time_to=DAY,
            aggregations={"cost": "max", "clicks": "min"}, segment_prefix="m"),
            str(tmp_path / "out"))
        merged = load_segment(built[0])
        want_max = max(float(v) for s in segs for v in s.column("cost").values())
        assert max(float(v) for v in merged.column("cost").values()) == pytest.approx(want_max)

    def test_dedup_drops_identical_rows(self, tmp_path):
        schema = event_schema()
        cols = {"site": ["x", "x", "y"], "ts": np.array([1, 1, 2], dtype=np.int64),
                "clicks": np.array([5, 5, 6], dtype=np.int64),
                "cost": np.array([1.0, 1.0, 2.0])}
        seg = load_segment(SegmentBuilder(schema).build(cols, str(tmp_path / "in"), "d0"))
        built = process_segments([seg, seg], schema, ProcessorConfig(
            merge_type=DEDUP, segment_prefix="m"), str(tmp_path / "out"))
        assert load_segment(built[0]).num_docs == 2

    def test_time_window_and_buckets(self, tmp_path):
        schema = event_schema()
        rng = np.random.default_rng(9)
        cols = make_cols(rng, 200, 0)
        cols["ts"] = rng.integers(0, 3 * DAY, 200, dtype=np.int64)  # spans 3 days
        seg = load_segment(SegmentBuilder(schema).build(cols, str(tmp_path / "in"), "w0"))
        built = process_segments([seg], schema, ProcessorConfig(
            merge_type=CONCAT, time_column="ts", bucket_ms=DAY,
            window_start=0, window_end=2 * DAY, segment_prefix="m"),
            str(tmp_path / "out"))
        assert len(built) == 2  # one per day bucket inside the window
        total = sum(load_segment(b).num_docs for b in built)
        assert total == int((cols["ts"] < 2 * DAY).sum())

    def test_split_by_max_rows(self, tmp_path):
        schema, segs = self._segments(tmp_path / "in", n_segs=2, rows=150)
        built = process_segments(segs, schema, ProcessorConfig(
            merge_type=CONCAT, max_rows_per_segment=100, segment_prefix="m"),
            str(tmp_path / "out"))
        assert len(built) == 3
        assert sum(load_segment(b).num_docs for b in built) == 300


# ---------------------------------------------------------------------------
# MergeRollupTask end-to-end
# ---------------------------------------------------------------------------

def test_merge_rollup_task(tmp_path):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    schema = event_schema()
    yesterday = (int(time.time() * 1000) // DAY - 1) * DAY
    cfg = TableConfig(schema.name, time_column="ts",
                      task_configs={MERGE_ROLLUP: {
                          "bucketMs": DAY, "mergeType": "ROLLUP",
                          "roundTimeTo": DAY, "aggregations": {"cost": "sum"}}})
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(17)
    for i in range(3):
        cluster.ingest_columns(cfg, make_cols(rng, 120, yesterday))
    before = cluster.query("SELECT SUM(clicks), SUM(cost), COUNT(*) FROM events LIMIT 5")
    assert len(cluster.catalog.segments[cfg.table_name_with_type]) == 3

    done = cluster.run_minion_round()
    assert [t.state for t in done] == [COMPLETED], [t.error for t in done]

    segs = cluster.catalog.segments[cfg.table_name_with_type]
    assert len(segs) == 1 and next(iter(segs)).startswith("merged_")
    after = cluster.query("SELECT SUM(clicks), SUM(cost), COUNT(*) FROM events LIMIT 5")
    assert after.rows[0][0] == before.rows[0][0]
    assert after.rows[0][1] == pytest.approx(before.rows[0][1], rel=1e-5)
    assert after.rows[0][2] <= before.rows[0][2]  # rollup shrank the row count
    # idempotent: merged outputs are not re-merged
    assert cluster.run_minion_round() == []


def test_merge_rollup_concat_preserves_queries(tmp_path):
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = event_schema()
    yesterday = (int(time.time() * 1000) // DAY - 1) * DAY
    cfg = TableConfig(schema.name, time_column="ts",
                      task_configs={MERGE_ROLLUP: {"bucketMs": DAY}})
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(19)
    for i in range(2):
        cluster.ingest_columns(cfg, make_cols(rng, 80, yesterday))
    before = cluster.query(
        "SELECT site, COUNT(*), SUM(cost) FROM events GROUP BY site ORDER BY site LIMIT 10")
    done = cluster.run_minion_round()
    assert [t.state for t in done] == [COMPLETED], [t.error for t in done]
    after = cluster.query(
        "SELECT site, COUNT(*), SUM(cost) FROM events GROUP BY site ORDER BY site LIMIT 10")
    assert [(r[0], r[1]) for r in after.rows] == [(r[0], r[1]) for r in before.rows]
    for a, b in zip(after.rows, before.rows):
        assert a[2] == pytest.approx(b[2], rel=1e-5)


# ---------------------------------------------------------------------------
# RealtimeToOfflineSegmentsTask end-to-end (hybrid table)
# ---------------------------------------------------------------------------

def _ingest_realtime_window(cluster, cfg, schema, rng, day_ms, rows=60):
    import json
    from pinot_tpu.ingest.stream import MemoryStream
    topic = MemoryStream.get(cfg.stream.topic)
    cols = make_cols(rng, rows, day_ms)
    for i in range(rows):
        row = {k: (v[i].item() if isinstance(v[i], np.generic) else v[i])
               for k, v in cols.items()}
        topic.produce(json.dumps(row), partition=0)
    cluster.pump_realtime(cfg.table_name_with_type)


def test_realtime_to_offline_task(tmp_path):
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = event_schema()
    day0 = (int(time.time() * 1000) // DAY - 3) * DAY
    rt_cfg = TableConfig(schema.name, table_type=TableType.REALTIME,
                         time_column="ts",
                         stream=StreamConfig(topic="events_topic",
                                             flush_threshold_rows=50),
                         task_configs={REALTIME_TO_OFFLINE: {"bucketMs": DAY}})
    off_cfg = TableConfig(schema.name, table_type=TableType.OFFLINE, time_column="ts")
    cluster.controller.add_schema(schema)
    cluster.controller.add_table(off_cfg)
    cluster.create_realtime_table(schema, rt_cfg, num_partitions=1)

    rng = np.random.default_rng(23)
    # two committed windows + rows still consuming in a later window
    _ingest_realtime_window(cluster, rt_cfg, schema, rng, day0, rows=60)
    _ingest_realtime_window(cluster, rt_cfg, schema, rng, day0 + DAY, rows=60)
    _ingest_realtime_window(cluster, rt_cfg, schema, rng, day0 + 2 * DAY, rows=20)

    before = cluster.query("SELECT COUNT(*), SUM(clicks) FROM events LIMIT 5")

    done = cluster.run_minion_round()
    assert done and all(t.state == COMPLETED for t in done), [t.error for t in done]
    off_table = off_cfg.table_name_with_type
    assert cluster.catalog.segments[off_table], "offline segments must exist"

    # hybrid query must not double count (time boundary split)
    after = cluster.query("SELECT COUNT(*), SUM(clicks) FROM events LIMIT 5")
    assert after.rows[0] == before.rows[0]

    wm = cluster.catalog.get_property(
        f"rtToOffline/{rt_cfg.table_name_with_type}/watermark")
    assert wm is not None and wm >= day0 + DAY


# ---------------------------------------------------------------------------
# Scheduled retention + lineage
# ---------------------------------------------------------------------------

def test_retention_scheduled(tmp_path):
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = event_schema()
    cfg = TableConfig(schema.name, time_column="ts", retention_days=2)
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(29)
    now = int(time.time() * 1000)
    cluster.ingest_columns(cfg, make_cols(rng, 50, now - 10 * DAY))  # expired
    cluster.ingest_columns(cfg, make_cols(rng, 50, now - DAY // 2))  # fresh
    # the registered periodic task runs retention (deterministic tick)
    cluster.controller.scheduler.task("RetentionManager").run_once()
    segs = cluster.catalog.segments[cfg.table_name_with_type]
    assert len(segs) == 1
    assert cluster.query("SELECT COUNT(*) FROM events LIMIT 5").rows[0][0] == 50


def test_replace_segments_lineage_hides_both_sides(tmp_path):
    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = event_schema()
    cfg = TableConfig(schema.name, time_column="ts")
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(31)
    cluster.ingest_columns(cfg, make_cols(rng, 40, 0))
    table = cfg.table_name_with_type
    # IN_PROGRESS lineage hides the replacement outputs from routing
    cluster.catalog.put_property(f"lineage/{table}", [
        {"id": "x", "from": [], "to": ["events_0"], "state": "IN_PROGRESS"}])
    assert cluster.query("SELECT COUNT(*) FROM events LIMIT 5").rows[0][0] == 0
    # COMPLETED lineage hides the replaced inputs
    cluster.catalog.put_property(f"lineage/{table}", [
        {"id": "x", "from": ["events_0"], "to": [], "state": "COMPLETED"}])
    assert cluster.query("SELECT COUNT(*) FROM events LIMIT 5").rows[0][0] == 0
    cluster.catalog.put_property(f"lineage/{table}", None)
    assert cluster.query("SELECT COUNT(*) FROM events LIMIT 5").rows[0][0] == 40


def test_convert_to_raw_rewrite_preserves_nulls(tmp_path):
    """Segment-rewrite null preservation: a minion rewrite reads columns back
    through read_columns, which must restore None at null-bitmap positions —
    a rewrite that materializes default-value fills would silently turn
    `cost IS NULL` rows into zeros in the replacement segment. The conversion
    targets `clicks`; the nulls live in `cost`, which merely rides along
    through the rebuild (a null-carrying column is raw-encoded from birth,
    so it can never be the conversion target itself)."""
    from pinot_tpu.minion.tasks import CONVERT_TO_RAW_INDEX

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = event_schema()
    cfg = TableConfig(
        schema.name,
        task_configs={CONVERT_TO_RAW_INDEX: {"columnsToConvert": ["clicks"]}})
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(11)
    cols = make_cols(rng, 100, 0)
    cost = np.asarray(cols["cost"]).astype(object)
    cost[::7] = None                      # 15 null cells at known positions
    cols["cost"] = cost
    cluster.ingest_columns(cfg, cols)
    table = cfg.table_name_with_type
    (old_name,) = cluster.catalog.segments[table]
    n_null = cluster.query(
        "SELECT COUNT(*) FROM events WHERE cost IS NULL LIMIT 5").rows[0][0]
    assert n_null == 15

    done = cluster.run_minion_round()
    assert [t.state for t in done] == [COMPLETED], [t.error for t in done]
    (new_name,) = cluster.catalog.segments[table]
    assert new_name != old_name           # the segment really was rewritten
    assert cluster.query(
        "SELECT COUNT(*) FROM events WHERE cost IS NULL LIMIT 5"
    ).rows[0][0] == 15
    assert cluster.query(
        "SELECT COUNT(*) FROM events LIMIT 5").rows[0][0] == 100


def test_convert_to_raw_index_noop_does_not_churn(tmp_path):
    """A segment whose target columns are ALREADY raw gets one no-op task,
    lands in the done-set, and is never generated again (an unmarked no-op
    would re-download the inputs every controller tick forever)."""
    from pinot_tpu.minion.tasks import CONVERT_TO_RAW_INDEX
    from pinot_tpu.segment.writer import SegmentGeneratorConfig
    from pinot_tpu.table import IndexingConfig

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = event_schema()
    cfg = TableConfig(
        schema.name,
        indexing=IndexingConfig(no_dictionary_columns=["cost"]),
        task_configs={CONVERT_TO_RAW_INDEX: {"columnsToConvert": ["cost"]}})
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(3)
    cluster.ingest_columns(cfg, make_cols(rng, 100, 0))
    table = cfg.table_name_with_type
    (name,) = cluster.catalog.segments[table]

    done = cluster.run_minion_round()
    assert [t.state for t in done] == [COMPLETED], [t.error for t in done]
    # no replacement happened (it was already raw) and the done-set holds it
    assert set(cluster.catalog.segments[table]) == {name}
    assert name in (cluster.catalog.get_property(
        f"convertRawDone/{table}") or [])
    # the generator is now quiescent
    assert cluster.run_minion_round() == []
    assert cluster.run_minion_round() == []
