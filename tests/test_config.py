"""Config system, plugin manager, and service lifecycle tests.

Reference patterns: PinotConfiguration precedence tests (pinot-spi env),
PluginManager registration, ServiceStatus/readiness gating
(BaseServerStarter.startupServiceStatusCheck).
"""

import sys

import numpy as np
import pytest

from pinot_tpu import plugins
from pinot_tpu.config import Configuration, read_config_file


# -- configuration layering ----------------------------------------------------

def test_precedence_defaults_file_env_overrides(tmp_path):
    f = tmp_path / "server.properties"
    f.write_text("# comment\nserver.port=9000\nserver.tenant.tags=a,b\n"
                 "query.timeout.ms=5000\n")
    cfg = Configuration.load(
        str(f),
        defaults={"server.port": 8000, "only.default": "d"},
        env={"PINOT_TPU_QUERY_TIMEOUT_MS": "7000", "UNRELATED": "x"},
        overrides={"server.tenant.tags": "c"},
    )
    assert cfg.get_int("server.port") == 9000          # file beats default
    assert cfg.get_int("query.timeout.ms") == 7000     # env beats file
    assert cfg.get_list("server.tenant.tags") == ["c"]  # override beats file
    assert cfg.get("only.default") == "d"
    assert "unrelated" not in cfg


def test_json_config_flattens(tmp_path):
    f = tmp_path / "cfg.json"
    f.write_text('{"server": {"scheduler": {"enabled": true, "max": {"concurrent": 8}}}}')
    cfg = Configuration.load(str(f))
    assert cfg.get_bool("server.scheduler.enabled") is True
    assert cfg.get_int("server.scheduler.max.concurrent") == 8


def test_typed_getters_and_subset():
    cfg = Configuration({"a.x": "10", "a.y": "true", "a.z": "1.5",
                         "a.list": "p, q ,r", "b.k": "v"})
    sub = cfg.subset("a")
    assert sub.get_int("x") == 10
    assert sub.get_bool("y") is True
    assert sub.get_float("z") == 1.5
    assert sub.get_list("list") == ["p", "q", "r"]
    assert "k" not in sub
    assert cfg.get_bool("missing", True) is True
    assert cfg.get_int("missing", 3) == 3


def test_properties_parse_errors(tmp_path):
    f = tmp_path / "bad.properties"
    f.write_text("no_equals_sign_here\n")
    with pytest.raises(ValueError):
        read_config_file(str(f))


def test_scheduler_from_config():
    from pinot_tpu.query.scheduler import scheduler_from_config
    assert scheduler_from_config(Configuration({})) is None
    s = scheduler_from_config(Configuration({
        "server.scheduler.enabled": "true",
        "server.scheduler.max.concurrent": "2",
        "server.scheduler.max.pending": "5",
    }))
    assert s is not None and s.max_concurrent == 2 and s.max_pending == 5
    s.stop()


# -- plugin manager ------------------------------------------------------------

def test_plugin_inventory_covers_builtins():
    inv = plugins.inventory()
    assert "memory" in inv[plugins.STREAM]
    assert "kafkalite" in inv[plugins.STREAM]   # lazily imported builtin
    assert "json" in inv[plugins.DECODER]
    assert "csv" in inv[plugins.READER]
    assert "local" in inv[plugins.FS]


def test_plugin_get_and_errors():
    factory = plugins.get(plugins.STREAM, "memory")
    assert callable(factory)
    with pytest.raises(KeyError, match="no stream plugin"):
        plugins.get(plugins.STREAM, "nope")
    with pytest.raises(KeyError, match="unknown plugin kind"):
        plugins.get("bogus", "x")


def test_plugin_module_loading(tmp_path):
    """An external module registers its plugin at import (the reference's
    plugin-dir classloading analog)."""
    mod = tmp_path / "my_decoder_plugin.py"
    mod.write_text(
        "from pinot_tpu.ingest.stream import register_decoder\n"
        "register_decoder('upper_json', lambda b: {'v': b.decode().upper()})\n")
    sys.path.insert(0, str(tmp_path))
    try:
        cfg = Configuration({"plugins.modules": "my_decoder_plugin"})
        assert plugins.load_from_config(cfg) == ["my_decoder_plugin"]
        assert "upper_json" in plugins.available(plugins.DECODER)
    finally:
        sys.path.remove(str(tmp_path))


# -- service lifecycle ---------------------------------------------------------

def test_server_lifecycle_and_readiness(tmp_path):
    from pinot_tpu.cluster import QuickCluster
    from pinot_tpu.cluster.services import ServerService
    from pinot_tpu.cluster.http_service import HttpError, get_json, http_call
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import TableConfig

    cluster = QuickCluster(num_servers=1, work_dir=str(tmp_path))
    schema = Schema("t", [dimension("s"), metric("v", DataType.DOUBLE)])
    cfg = cluster.create_table(schema, TableConfig("t"))
    cluster.ingest_columns(cfg, {"s": ["a"], "v": np.array([1.0])})
    node = cluster.servers[0]
    st = node.startup_status()
    assert st == {"status": "UP", "assignedSegments": 1, "loadedSegments": 1,
                  "ready": True}

    svc = ServerService(node)
    try:
        health = get_json(f"{svc.url}/health")
        assert health["ready"] is True and health["status"] == "UP"
        assert get_json(f"{svc.url}/health/readiness")["ready"] is True
        # a not-yet-started server answers 503 to READINESS probes, while the
        # bare liveness probe stays 200 (the process is up, just not ready)
        node.status = "STARTING"
        with pytest.raises(HttpError) as ei:
            http_call("GET", f"{svc.url}/health/readiness")
        assert ei.value.status == 503
        assert get_json(f"{svc.url}/health")["status"] == "STARTING"
        node.status = "UP"
    finally:
        svc.stop()

    # graceful shutdown flips liveness + state
    node.shutdown()
    assert node.status == "SHUTTING_DOWN"
    assert cluster.catalog.instances[node.instance_id].alive is False
