"""Overload-robustness plane tests: adaptive broker admission (shed-state
machine, deadline-budget shed), the weighted-fair per-tenant scheduler, and
the Retry-After plumbing that turns sheds into typed, retryable backpressure.

Reference scenarios: the broker-side admission gates in front of
BaseBrokerRequestHandler, per-query-group fair scheduling in
QuerySchedulerFactory, and 429/Retry-After semantics on the server APIs.
"""

import json
import threading
import time

import pytest

from pinot_tpu.cluster.admission import (HEALTHY, SATURATED, SHEDDING,
                                         AdmissionController)
from pinot_tpu.cluster.http_service import HttpError
from pinot_tpu.query.scheduler import (QueryQuotaManager, QueryRejectedError,
                                       QueryScheduler, QueryTimeoutError,
                                       TokenBucket, scheduler_from_config)


class _Catalog:
    """clusterConfig stub for exercising AdmissionController knobs."""

    def __init__(self, **props):
        self.props = {f"clusterConfig/{k}": v for k, v in props.items()}

    def get_property(self, key, default=None):
        return self.props.get(key, default)


class _Ctx:
    """QueryContext stub: just the fields the expensive classifier reads."""

    def __init__(self, agg=False, group_by=(), limit=10, options=None):
        self.is_aggregation_query = agg
        self.group_by = list(group_by)
        self.limit = limit
        self.options = dict(options or {})


def _cheap():
    return _Ctx(agg=True, limit=10)


def _expensive():
    return _Ctx(agg=False, limit=100_000)


# -- admission state machine --------------------------------------------------

def test_admission_disabled_is_noop():
    ac = AdmissionController(_Catalog())
    for _ in range(100):
        ac.begin()
    ac.admit("t", _expensive())   # never sheds while the knob is off
    assert ac.state() == HEALTHY
    assert not ac.overloaded()


def test_admission_shed_states_and_hysteresis():
    ac = AdmissionController(_Catalog(**{
        "broker.admission.enabled": "true",
        "broker.admission.queue.high": "2",
        "broker.admission.queue.max": "4"}))
    ac.admit("t", _cheap())
    assert ac.state() == HEALTHY

    ac.begin()
    ac.begin()                     # depth 2 >= high -> SHEDDING
    with pytest.raises(QueryRejectedError) as ei:
        ac.admit("hog", _expensive())
    assert "query shed (expensive)" in str(ei.value)
    assert ei.value.retry_after_ms is not None
    ac.admit("good", _cheap())     # cheap served path keeps admitting
    assert ac.state() == SHEDDING
    assert ac.overloaded()

    ac.begin()
    ac.begin()                     # depth 4 >= max -> SATURATED sheds all
    with pytest.raises(QueryRejectedError) as ei:
        ac.admit("good", _cheap())
    assert "query shed (saturated)" in str(ei.value)
    assert ei.value.retry_after_ms is not None
    assert ac.state() == SATURATED

    ac.end()
    ac.end()                       # depth 2 > high/2: hysteresis holds SHEDDING
    with pytest.raises(QueryRejectedError):
        ac.admit("hog", _expensive())
    assert ac.state() == SHEDDING

    ac.end()                       # depth 1 <= high/2: recovered
    ac.admit("hog", _expensive())
    assert ac.state() == HEALTHY

    snap = ac.snapshot()
    assert snap["enabled"] is True
    assert snap["sheds"] == 3
    assert snap["shedByReason"] == {"expensive": 2, "saturated": 1}
    assert snap["shedByTable"] == {"hog": 2, "good": 1}
    assert snap["admitted"] == 3
    assert snap["queueHigh"] == 2.0 and snap["queueMax"] == 4.0


def test_admission_deadline_shed_uses_predicted_service_time():
    ac = AdmissionController(_Catalog(**{"broker.admission.enabled": "true"}))
    ac.predicted_service_ms = lambda: (500.0, 64)   # p99 500ms, confident
    doomed = _Ctx(agg=True, options={
        "deadlineEpochMs": time.time() * 1000.0 + 50.0})
    with pytest.raises(QueryRejectedError) as ei:
        ac.admit("t", doomed)
    assert "query shed (deadline)" in str(ei.value)
    # ample budget admits even with the same p99
    ac.admit("t", _Ctx(agg=True, options={
        "deadlineEpochMs": time.time() * 1000.0 + 60_000.0}))
    # thin budget but too few samples: the estimate is not trusted yet
    ac.predicted_service_ms = lambda: (500.0, 3)
    ac.admit("t", _Ctx(agg=True, options={
        "deadlineEpochMs": time.time() * 1000.0 + 50.0}))
    assert ac.snapshot()["shedByReason"] == {"deadline": 1}


def test_admission_latency_signal_joins_when_configured():
    ac = AdmissionController(_Catalog(**{
        "broker.admission.enabled": "true",
        "broker.admission.latency.ms": "100"}))
    # p99 past the threshold with confidence -> SHEDDING at zero depth
    ac.predicted_service_ms = lambda: (150.0, 20)
    with pytest.raises(QueryRejectedError):
        ac.admit("t", _expensive())
    assert ac.state() == SHEDDING
    # same p99 without enough samples: stays depth-driven -> recovers
    ac.predicted_service_ms = lambda: (150.0, 2)
    ac.admit("t", _expensive())
    assert ac.state() == HEALTHY


def test_admission_expensive_classifier():
    ac = AdmissionController(_Catalog(**{"broker.admission.enabled": "true"}))
    assert ac.is_expensive(_Ctx(agg=False, limit=100_000))
    assert ac.is_expensive(_Ctx(agg=False, limit=None))     # unbounded scan
    assert not ac.is_expensive(_Ctx(agg=False, limit=100))
    assert not ac.is_expensive(_Ctx(agg=True, limit=100_000))
    assert not ac.is_expensive(_Ctx(agg=False, group_by=["d"],
                                    limit=100_000))


# -- the rotating recent-latency window behind the p99 signal -----------------

def test_histogram_recent_percentile_window_rotation():
    from pinot_tpu.utils.metrics import Histogram
    h = Histogram()
    for _ in range(4):
        h.observe(10.0)
    val, n = h.recent_percentile(0.99)
    assert (val, n) == (10.0, 4)
    # age the window past WINDOW_S: current becomes "previous", and a fresh
    # spike joins it in the recent view
    h._win_started -= h.WINDOW_S + 1
    h.observe(100.0)
    val, n = h.recent_percentile(0.99)
    assert (val, n) == (100.0, 5)
    # both windows stale: the recent view empties and falls back to lifetime
    h._win_started -= 2 * h.WINDOW_S + 1
    val, n = h.recent_percentile(0.99)
    assert n == h.count == 5
    assert val == 100.0


# -- weighted-fair scheduler --------------------------------------------------

def _drive(sched, plan, release):
    """Enqueue `plan` tables one by one (each submit blocks its own thread)
    behind a held worker; returns (threads, executed-order list)."""
    order = []
    olock = threading.Lock()

    def runner(table):
        def fn():
            with olock:
                order.append(table)
        try:
            sched.submit(table, fn, timeout_s=10.0)
        except QueryRejectedError:
            pass

    threads = []
    for i, table in enumerate(plan):
        t = threading.Thread(target=runner, args=(table,))
        t.start()
        threads.append(t)
        deadline = time.monotonic() + 5.0
        while sched.stats.queued < i + 1 and time.monotonic() < deadline:
            time.sleep(0.002)
        assert sched.stats.queued == i + 1, \
            f"query {i} for {table!r} never queued"
    release.set()
    for t in threads:
        t.join(10.0)
    return order


def _hold_worker(sched):
    """Occupy the single worker so later submits queue up; returns the
    release event and the holder thread."""
    release = threading.Event()
    started = threading.Event()

    def blocker():
        started.set()
        release.wait(10.0)

    holder = threading.Thread(target=lambda: sched.submit("hold", blocker,
                                                          timeout_s=15.0))
    holder.start()
    assert started.wait(5.0)
    return release, holder


def test_fair_queue_light_tenant_not_starved():
    """FIFO would run all four hog queries first; the fair queue dispatches
    the light tenant right after the first hog query."""
    sched = QueryScheduler(max_concurrent=1, max_pending=16)
    release, holder = _hold_worker(sched)
    order = _drive(sched, ["hog", "hog", "hog", "hog", "good"], release)
    holder.join(10.0)
    assert sorted(order) == ["good", "hog", "hog", "hog", "hog"]
    assert order.index("good") <= 1, f"light tenant starved: {order}"
    sched.stop()


def test_fair_queue_weights_bias_the_split():
    sched = QueryScheduler(max_concurrent=1, max_pending=16,
                           tenant_weights={"heavy": 4.0})
    release, holder = _hold_worker(sched)
    order = _drive(sched, ["heavy"] * 4 + ["light"] * 4, release)
    holder.join(10.0)
    # weight 4 buys ~4 dispatches per light dispatch in the contended prefix
    assert order[:5].count("heavy") == 4, order
    sched.stop()


def test_byte_budget_bounds_concurrent_bytes_but_never_wedges():
    sched = QueryScheduler(max_concurrent=2, max_pending=8,
                           max_table_bytes=1000.0)
    # an idle tenant may always run one query, however oversized
    assert sched.submit("t", lambda: 42, cost_bytes=5000.0) == 42

    release = threading.Event()
    started = threading.Event()

    def slow():
        started.set()
        release.wait(5.0)

    holder = threading.Thread(
        target=lambda: sched.submit("t", slow, cost_bytes=800.0))
    holder.start()
    assert started.wait(5.0)
    with pytest.raises(QueryRejectedError) as ei:
        sched.submit("t", lambda: None, cost_bytes=300.0)
    assert "byte budget" in str(ei.value)
    assert ei.value.retry_after_ms is not None
    # another table is unaffected by t's budget
    assert sched.submit("u", lambda: "ok", cost_bytes=300.0) == "ok"
    release.set()
    holder.join(5.0)
    assert sched.submit("t", lambda: "ok", cost_bytes=300.0) == "ok"
    sched.stop()


def test_capacity_reject_carries_retry_after_hint():
    sched = QueryScheduler(max_concurrent=1, max_pending=1)
    release, holder = _hold_worker(sched)
    queued = threading.Thread(
        target=lambda: sched.submit("t", lambda: None, timeout_s=10.0))
    queued.start()
    deadline = time.monotonic() + 5.0
    while sched.stats.queued < 1 and time.monotonic() < deadline:
        time.sleep(0.002)
    with pytest.raises(QueryRejectedError) as ei:
        sched.submit("t", lambda: None)
    assert ei.value.retry_after_ms is not None
    assert ei.value.retry_after_ms > 0
    # the standalone drain estimate agrees in shape: positive milliseconds
    assert sched.retry_after_ms() >= 1.0
    release.set()
    holder.join(5.0)
    queued.join(5.0)
    sched.stop()


def test_scheduler_from_config_fair_knobs():
    from pinot_tpu.config import Configuration
    cfg = Configuration({
        "server.scheduler.enabled": "true",
        "server.scheduler.max.concurrent": "3",
        "server.scheduler.fair.weights": json.dumps({"gold": 4, "bronze": 1}),
        "server.scheduler.fair.tenant.bytes": "2048"})
    sched = scheduler_from_config(cfg)
    assert sched is not None
    assert sched.tenant_weights == {"gold": 4.0, "bronze": 1.0}
    assert sched.max_table_bytes == 2048.0
    sched.stop()
    # malformed weights JSON degrades to unweighted, not a crash
    sched2 = scheduler_from_config(Configuration({
        "server.scheduler.enabled": "true",
        "server.scheduler.fair.weights": "{not json"}))
    assert sched2.tenant_weights == {}
    sched2.stop()


# -- Retry-After plumbing -----------------------------------------------------

def test_retry_after_helper_reads_attr_then_json_body():
    from pinot_tpu.cluster.broker import _retry_after_ms
    e = HttpError(429, '{"error": "busy", "retryAfterMs": 12.5}')
    assert _retry_after_ms(e) == 12.5
    tagged = HttpError(429, "busy")
    tagged.retry_after_ms = 7
    assert _retry_after_ms(tagged) == 7.0
    assert _retry_after_ms(HttpError(429, "no body")) is None
    assert _retry_after_ms(ValueError("not http")) is None


def test_services_reject_body_hint_and_timeout_body_deadline():
    from pinot_tpu.cluster.services import ServerService

    class _Srv:
        scheduler = None

    class _Handler:
        server = _Srv()

    h = _Handler()
    body = ServerService._reject_body(h, QueryRejectedError(
        "shed", retry_after_ms=12.5))
    assert body == {"error": "shed", "retryAfterMs": 12.5}
    # no hint on the error: the handler asks the scheduler's drain estimate
    h.server.scheduler = QueryScheduler(max_concurrent=2)
    body = ServerService._reject_body(h, QueryRejectedError("shed"))
    assert body["retryAfterMs"] > 0
    h.server.scheduler.stop()

    body = ServerService._timeout_body(QueryTimeoutError(
        "late", deadline_epoch_ms=1234.5))
    assert body == {"error": "late", "deadlineEpochMs": 1234.5}
    assert "deadlineEpochMs" not in ServerService._timeout_body(
        QueryTimeoutError("late"))


def test_remote_handle_defers_by_retry_after_then_retries():
    from pinot_tpu.cluster.remote import RemoteServerHandle

    h = RemoteServerHandle.__new__(RemoteServerHandle)
    calls = []

    def once_then_ok(table, ctx, segs, time_filter=None):
        calls.append(table)
        if len(calls) == 1:
            e = HttpError(429, "busy")
            e.retry_after_ms = 5.0
            raise e
        return "ok"

    h._call_once = once_then_ok
    t0 = time.monotonic()
    assert h("t", None, []) == "ok"
    assert len(calls) == 2
    assert time.monotonic() - t0 < h.RETRY_AFTER_CAP_S + 1.0

    # legacy transport: the hint rides the JSON error body in the message
    calls.clear()

    def json_hint(table, ctx, segs, time_filter=None):
        calls.append(table)
        if len(calls) == 1:
            raise HttpError(429, '{"error": "busy", "retryAfterMs": 2.0}')
        return "ok"

    h._call_once = json_hint
    assert h("t", None, []) == "ok"
    assert len(calls) == 2

    # a 429 with NO hint propagates: no blind hammering
    def no_hint(table, ctx, segs, time_filter=None):
        raise HttpError(429, "busy, no body")

    h._call_once = no_hint
    with pytest.raises(HttpError):
        h("t", None, [])

    # non-backpressure statuses are untouched
    def server_fault(table, ctx, segs, time_filter=None):
        raise HttpError(500, "boom")

    h._call_once = server_fault
    with pytest.raises(HttpError):
        h("t", None, [])


# -- broker integration: sheds are typed and counted --------------------------

def _overload_cluster(tmp_path, num_servers=1, replication=1):
    from pinot_tpu.cluster.enclosure import QuickCluster
    from pinot_tpu.schema import DataType, Schema, dimension, metric
    from pinot_tpu.table import TableConfig

    cluster = QuickCluster(num_servers=num_servers, work_dir=str(tmp_path))
    schema = Schema("ov", [dimension("user", DataType.STRING),
                           metric("value", DataType.DOUBLE)])
    cfg = cluster.create_table(schema, TableConfig("ov",
                                                   replication=replication))
    cluster.ingest_columns(cfg, {"user": [f"u{i}" for i in range(40)],
                                 "value": [1.0] * 40})
    return cluster


def test_broker_sheds_expensive_scan_typed_and_counted(tmp_path):
    cluster = _overload_cluster(tmp_path)
    # queue.high=1: the query's own begin() tips the depth signal, so the
    # machine is SHEDDING for every admit decision — deterministic overload
    cluster.catalog.put_property("clusterConfig/broker.admission.enabled",
                                 "true")
    cluster.catalog.put_property("clusterConfig/broker.admission.queue.high",
                                 "1")
    with pytest.raises(QueryRejectedError) as ei:
        cluster.query("SELECT user, value FROM ov LIMIT 20000")
    assert "query shed (expensive)" in str(ei.value)
    # the cheap served path still answers while shedding
    assert cluster.query("SELECT COUNT(*) FROM ov").rows[0][0] == 40
    snap = cluster.broker.admission.snapshot()
    assert snap["sheds"] == 1
    assert snap["shedByTable"] == {"ov": 1}
    assert snap["shedByReason"] == {"expensive": 1}
    assert snap["state"] == SHEDDING
    # the shed surfaced in the broker's debug plane for cluster_top
    assert cluster.broker.debug_stats()["admission"]["sheds"] == 1


def test_broker_backpressure_bookkeeping_expires(tmp_path):
    cluster = _overload_cluster(tmp_path)
    broker = cluster.broker
    broker._note_backpressure("s_slow", 60_000.0)   # capped at BACKPRESSURE_MAX_S
    broker._note_backpressure("s_quick", 1.0)
    assert "s_slow" in broker._backpressured_servers()
    time.sleep(0.02)
    held = broker._backpressured_servers()
    assert "s_quick" not in held and "s_slow" in held
    # no hint falls back to the default hold, not an infinite one
    broker._note_backpressure("s_default", None)
    assert "s_default" in broker._backpressured_servers()
    assert broker._backpressure_until["s_default"] - time.monotonic() \
        <= broker.BACKPRESSURE_DEFAULT_S + 0.01


def test_hedges_suppressed_while_broker_overloaded(tmp_path):
    from pinot_tpu.utils import faults
    from pinot_tpu.utils.faults import FaultSchedule
    from pinot_tpu.utils.metrics import get_registry

    cluster = _overload_cluster(tmp_path, num_servers=2, replication=2)
    cluster.catalog.put_property("clusterConfig/broker.hedge.enabled", "true")
    cluster.catalog.put_property("clusterConfig/broker.hedge.delay.ms", "20")
    cluster.broker.admission.overloaded = lambda: True
    before = get_registry().counter_value("pinot_broker_hedges_suppressed")
    sched = FaultSchedule({"server.slow": {"latencyMs": 100, "count": 1}},
                          seed=3)
    with faults.active(sched):
        res = cluster.query("SELECT COUNT(*) FROM ov")
    faults.deactivate()
    assert res.rows[0][0] == 40
    # the straggler was waited out, not hedged: degradation over amplification
    assert res.stats["hedgedRequests"] == 0
    after = get_registry().counter_value("pinot_broker_hedges_suppressed")
    assert after == before + 1


# -- satellite: server-side expired-deadline rejection ------------------------

def test_server_rejects_expired_deadline_with_stamped_deadline(tmp_path):
    from pinot_tpu.cluster.services import ServerService

    cluster = _overload_cluster(tmp_path)
    server = cluster.servers[0]
    past = int(time.time() * 1000.0) - 500
    with pytest.raises(QueryTimeoutError) as ei:
        server.execute_partial(
            "ov_OFFLINE",
            f"SELECT COUNT(*) FROM ov OPTION(deadlineEpochMs={past})", None)
    assert "deadline budget exhausted" in str(ei.value)
    assert ei.value.deadline_epoch_ms == float(past)
    # and the 408 body carries the stamped deadline back to the caller
    body = ServerService._timeout_body(ei.value)
    assert body["deadlineEpochMs"] == float(past)


# -- satellite: quota refund + scheduler stats consistency --------------------

class _QuotaCatalog:
    def __init__(self, configs):
        self.table_configs = configs
        self.instances = {}

    def subscribe(self, fn):
        pass


def test_quota_try_acquire_all_refunds_under_concurrency():
    from pinot_tpu.table import QuotaConfig, TableConfig

    cat = _QuotaCatalog({
        "a": TableConfig("a", quota=QuotaConfig(max_qps=4)),
        "b": TableConfig("b"),                              # unlimited
        "z": TableConfig("z", quota=QuotaConfig(max_qps=1))})
    qm = QueryQuotaManager(cat, broker_count_fn=lambda: 1)
    # frozen clocks: no refill mid-test, so token counts are exact
    qm._buckets["a"] = TokenBucket(4.0, burst=4.0, clock=lambda: 0.0)
    qm._buckets["z"] = TokenBucket(1.0, burst=1.0, clock=lambda: 0.0)
    qm._buckets["b"] = None
    assert qm.try_acquire("z")          # drain z: later hybrid admits fail

    results = []
    rlock = threading.Lock()

    def storm():
        for _ in range(25):
            ok = qm.try_acquire_all(["a", "b", "z"])
            with rlock:
                results.append(ok)

    threads = [threading.Thread(target=storm) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    # every admission failed on z — and every one refunded a's token, so the
    # losing tenant's quota never leaked
    assert not any(results)
    assert qm._buckets["a"]._tokens == pytest.approx(4.0)

    # the success path is all-or-nothing too: exactly burst admissions win
    wins = []

    def racer():
        ok = qm.try_acquire_all(["a", "b"])
        with rlock:
            wins.append(ok)

    threads = [threading.Thread(target=racer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert sum(wins) == 4
    assert qm._buckets["a"]._tokens == pytest.approx(0.0)


def test_scheduler_stats_consistent_under_parallel_churn():
    sched = QueryScheduler(max_concurrent=2, max_pending=4,
                           default_timeout_s=5.0)

    def boom():
        raise ValueError("query error")

    def worker(i):
        table = f"t{i % 3}"
        for j in range(12):
            kind = (i + j) % 4
            try:
                if kind == 0:
                    sched.submit(table, lambda: None)
                elif kind == 1:
                    sched.submit(table, lambda: time.sleep(0.002),
                                 cost_bytes=512.0)
                elif kind == 2:
                    sched.submit(table, boom)
                else:
                    sched.submit(table, lambda: time.sleep(0.05),
                                 timeout_s=0.01)
            except (QueryRejectedError, QueryTimeoutError, ValueError):
                pass

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    # abandoned timed-out queries finish in the background; wait for drain
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        with sched._lock:
            if sched.stats.running == 0 and sched.stats.queued == 0:
                break
        time.sleep(0.01)
    snap = sched.stats.snapshot()
    # conservation: every submitted query resolved exactly one way
    assert snap["submitted"] == (snap["completed"] + snap["timed_out"]
                                 + snap["failed"]), snap
    assert snap["submitted"] + snap["rejected"] == 6 * 12
    assert snap["running"] == 0 and snap["queued"] == 0
    assert snap["per_table_running"] == {}
    assert snap["per_table_queued"] == {}
    assert snap["per_table_bytes"] == {}
    sched.stop()


# -- satellite: cluster_top admission panel -----------------------------------

def test_cluster_top_admission_panel():
    from pinot_tpu.tools.cluster_top import render, snapshot

    admission = {"enabled": True, "state": "SHEDDING", "inflight": 7,
                 "queueHigh": 6.0, "queueMax": 48.0, "admitted": 100,
                 "sheds": 40, "predictedServiceMs": 12.5,
                 "predictionSamples": 64,
                 "shedByTable": {"hog": 39, "good": 1},
                 "shedByReason": {"expensive": 39, "saturated": 1}}
    pages = {
        "http://c/tables": {"tables": []},
        "http://c/debug": {"periodicTasks": {}},
        "http://b/debug": {"queryStats": {"numQueries": 5, "avgTimeMs": 1.0,
                                          "numSlowQueries": 0},
                           "admission": admission},
    }
    snap = snapshot("http://c", "http://b", pages.__getitem__)
    assert snap["admission"]["state"] == "SHEDDING"
    out = render(snap)
    assert "admission: SHEDDING" in out
    assert "inflight=7/6.0/48.0" in out
    assert "sheds=40" in out
    assert "hog=39" in out
    assert "expensive=39" in out and "saturated=1" in out
    # disabled controllers render flagged, absent ones render nothing
    snap["admission"] = dict(admission, enabled=False)
    assert "admission (disabled): SHEDDING" in render(snap)
    snap["admission"] = {}
    assert "admission" not in render(snap)
