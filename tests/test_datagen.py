"""Data generator + anonymizer tools (reference: GenerateDataCommand /
AnonymizeDataCommand in pinot-tools)."""

import json

import numpy as np
import pytest

from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.tools.datagen import (ColumnAnonymizer, anonymize_columns,
                                     anonymize_file, generate_columns,
                                     write_csv, write_jsonl)


@pytest.fixture()
def schema():
    return Schema("gen", [dimension("city", DataType.STRING),
                          dimension("code", DataType.INT),
                          metric("fare", DataType.DOUBLE),
                          date_time("ts", DataType.LONG)])


def test_generate_columns_shapes_and_cardinality(schema):
    cols = generate_columns(schema, 500, seed=3, cardinalities={"city": 7})
    assert set(cols) == {"city", "code", "fare", "ts"}
    assert all(len(v) == 500 for v in cols.values())
    assert len(set(cols["city"])) == 7
    assert all(isinstance(v, float) for v in cols["fare"])
    ts = cols["ts"]
    assert all(b >= a for a, b in zip(ts, ts[1:]))  # time column increases


def test_generate_deterministic(schema):
    a = generate_columns(schema, 50, seed=9)
    b = generate_columns(schema, 50, seed=9)
    assert a == b
    c = generate_columns(schema, 50, seed=10)
    assert a != c


def test_generated_data_builds_segment_and_queries(tmp_path, schema):
    from pinot_tpu.ingest.transform import TransformPipeline
    from pinot_tpu.query.executor import execute_query
    from pinot_tpu.segment import SegmentBuilder, load_segment
    cols = generate_columns(schema, 300, seed=1, cardinalities={"city": 5})
    cols = TransformPipeline(schema).apply(cols)
    seg = load_segment(SegmentBuilder(schema).build(cols, str(tmp_path), "gen_0"))
    res = execute_query([seg], "SELECT city, COUNT(*) FROM gen GROUP BY city "
                               "ORDER BY city LIMIT 10")
    assert sum(r[1] for r in res.rows) == 300
    assert len(res.rows) == 5


def test_anonymizer_preserves_equality_and_order():
    vals = ["delta", "alpha", "delta", None, "bravo"]
    anon = ColumnAnonymizer("c").fit(vals)
    out = anon.apply(vals)
    assert out[0] == out[2]             # equality kept
    assert out[3] is None               # nulls kept
    assert (out[1] < out[4] < out[0]) == ("alpha" < "bravo" < "delta")  # order kept
    assert not set(out) - {None} & set(vals)  # no original leaks


def test_anonymizer_numeric_rank():
    vals = [30, 10, 20, 10]
    out = ColumnAnonymizer("n").fit(vals).apply(vals)
    assert out == [2, 0, 1, 0]


def test_anonymize_consistent_across_files():
    shared = {}
    a = anonymize_columns({"k": ["x", "y"], "v": [1, 2]}, ["k"], shared)
    b = anonymize_columns({"k": ["y", "z"], "v": [3, 4]}, ["k"], shared)
    assert a["k"][1] == b["k"][0]       # same token for "y" in both files
    assert a["v"] == [1, 2]             # untouched column passes through


def test_anonymize_file_roundtrip_csv_and_jsonl(tmp_path):
    csv_in = tmp_path / "in.csv"
    csv_in.write_text("user,city,n\nalice,nyc,1\nbob,sf,2\nalice,nyc,3\n")
    csv_out = tmp_path / "out.csv"
    anonymize_file(str(csv_in), str(csv_out), ["user"])
    lines = csv_out.read_text().strip().splitlines()
    assert lines[0] == "user,city,n"
    u1, u2, u3 = (ln.split(",")[0] for ln in lines[1:])
    assert u1 == u3 != u2 and "alice" not in {u1, u2}
    assert lines[1].split(",")[1] == "nyc"  # untouched column survives

    j_in = tmp_path / "in.jsonl"
    j_in.write_text(json.dumps({"user": "alice", "n": 1}) + "\n"
                    + json.dumps({"user": "bob", "n": 2}) + "\n")
    j_out = tmp_path / "out.jsonl"
    anonymize_file(str(j_in), str(j_out), ["user"])
    rows = [json.loads(x) for x in j_out.read_text().splitlines()]
    assert rows[0]["user"] != "alice" and rows[0]["n"] == 1


def test_cli_generate_and_anonymize(tmp_path, schema):
    from pinot_tpu.tools.admin import main
    sf = tmp_path / "schema.json"
    sf.write_text(json.dumps(schema.to_json()))
    out = tmp_path / "data.csv"
    rc = main(["generate-data", "--schema-file", str(sf), "--rows", "40",
               "--out", str(out), "--cardinality", "city=3"])
    assert rc == 0
    lines = out.read_text().strip().splitlines()
    assert len(lines) == 41
    anon_out = tmp_path / "anon.csv"
    rc = main(["anonymize-data", "--input", str(out), "--out", str(anon_out),
               "--columns", "city"])
    assert rc == 0
    assert len(anon_out.read_text().strip().splitlines()) == 41


def test_anonymize_csv_numeric_rank_preserved(tmp_path):
    p = tmp_path / "n.csv"
    p.write_text("fare,k\n9,a\n10,b\n9,c\n")
    out = tmp_path / "n_out.csv"
    anonymize_file(str(p), str(out), ["fare"])
    lines = out.read_text().strip().splitlines()
    fares = [ln.split(",")[0] for ln in lines[1:]]
    # numeric rank mapping: 9 -> 0, 10 -> 1 (not lexicographic string tokens)
    assert fares == ["0", "1", "0"]
