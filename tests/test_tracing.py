"""Always-on sampled tracing: the sampler/ring primitives, the Chrome
trace-event export, and the distributed span tree over BOTH transports.

Acceptance shape (ISSUE 6): every slow-query log line carries a trace id that
resolves at GET /debug/traces, whose spans decompose the broker<->server HTTP
hop (serialize / send / queue_wait / deserialize / device exec); the Chrome
export of a sampled multi-server query loads as a valid timeline; the in-proc
transport produces the SAME server-execution span tree as HTTP.
"""

import json
import random
import re
import threading

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.query.scheduler import QueryScheduler
from pinot_tpu.schema import DataType, Schema, dimension, metric
from pinot_tpu.table import TableConfig
from pinot_tpu.utils.trace import (Trace, TraceRing, TraceSampler,
                                   request_trace, span, to_chrome_trace)

# broker-side wire spans + scheduler admission: transport mechanics, not
# server execution — excluded from the dual-transport differential (the mux
# transport adds frame-queue / flow-control decomposition to the same hop)
WIRE_SPANS = frozenset(("serialize", "send", "deserialize", "queue_wait",
                        "mux:frame_queue", "mux:flow_control"))


# -- satellite: sampler determinism ------------------------------------------

def test_sampler_seeded_rng_is_deterministic():
    a = TraceSampler(rng=random.Random(42))
    b = TraceSampler(rng=random.Random(42))
    decisions_a = [a.sample(0.3) for _ in range(200)]
    decisions_b = [b.sample(0.3) for _ in range(200)]
    assert decisions_a == decisions_b
    assert any(decisions_a) and not all(decisions_a)


def test_sampler_rate_edges_never_consult_rng():
    class Boom:
        def random(self):
            raise AssertionError("rng consulted for a 0/1 rate")

    s = TraceSampler(rng=Boom())
    assert s.sample(0.0) is False
    assert s.sample(-1.0) is False
    assert s.sample(1.0) is True
    assert s.sample(2.0) is True


# -- satellite: ring bounds under concurrency --------------------------------

def test_trace_ring_bounded_under_concurrent_admits():
    ring = TraceRing(capacity=8)
    per_thread = 100
    admitted = [[] for _ in range(4)]

    def admit(i):
        for j in range(per_thread):
            tr = Trace(f"req-{i}-{j}")
            tr.sampled = True
            ring.admit(tr, sql=f"SELECT {i * per_thread + j}")
            admitted[i].append(tr.trace_id)

    threads = [threading.Thread(target=admit, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(ring) == 8
    entries = ring.entries()
    assert len(entries) == 8
    # every retained entry resolves by id; evicted ids return None
    for e in entries:
        assert ring.get(e["traceId"]) is e
    retained = {e["traceId"] for e in entries}
    for ids in admitted:
        for tid in ids:
            if tid not in retained:
                assert ring.get(tid) is None
    # the globally newest admit survived (eviction is strictly oldest-first),
    # and it was some thread's final admit
    assert any(entries[0]["traceId"] == ids[-1] for ids in admitted)


def test_trace_ring_entries_newest_first_with_limit():
    ring = TraceRing(capacity=4)
    ids = []
    for i in range(6):
        tr = Trace(f"r{i}")
        ring.admit(tr, seq=i)
        ids.append(tr.trace_id)
    assert [e["seq"] for e in ring.entries()] == [5, 4, 3, 2]
    assert [e["seq"] for e in ring.entries(limit=2)] == [5, 4]
    assert ring.get(ids[0]) is None     # evicted
    assert ring.get(ids[-1])["seq"] == 5


# -- satellite: error spans ---------------------------------------------------

def test_span_marks_error_and_reraises():
    with request_trace(True) as tr:
        with pytest.raises(ValueError):
            with span("explode"):
                raise ValueError("boom")
        with span("fine"):
            pass
    rows = {s["name"]: s for s in tr.to_rows()}
    assert rows["explode"]["error"] is True
    assert "error" not in rows["fine"]


# -- tentpole: Chrome trace-event export --------------------------------------

def _assert_valid_chrome_doc(doc):
    """Schema-check a Chrome trace-event document (the subset Perfetto and
    chrome://tracing require of the JSON object format)."""
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = doc["traceEvents"]
    assert isinstance(events, list) and events
    json.loads(json.dumps(doc))        # round-trips as pure JSON
    for ev in events:
        assert ev["ph"] in ("M", "X", "C")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name")
            assert isinstance(ev["args"]["name"], str)
        elif ev["ph"] == "C":
            # HBM residency counter track (the device-memory plane)
            assert ev["cat"] == "memory"
            assert ev["ts"] >= 0
            assert isinstance(ev["args"]["bytes"], (int, float))
        else:
            assert ev["ts"] >= 0 and ev["dur"] >= 0
            assert isinstance(ev["args"]["depth"], int)


def test_chrome_export_splits_tracks_per_server_hop():
    tr = Trace("q1")
    tr.sampled = True
    tr.record("compile", 0.0, 1.0)
    tr.record("server:server_0", 1.0, 5.0, depth=1)
    tr.record("server:server_0/segment:ev_0", 2.0, 3.0, depth=2)
    tr.record("server:server_1/segment:ev_1", 2.0, 3.0, depth=2,
              error=True)
    # a clock-skewed negative start must clamp, not corrupt the timeline
    tr.record("server:server_0/deserialize", -0.4, 0.4, depth=2)
    ring = TraceRing()
    ring.admit(tr, sql="SELECT 1")
    doc = to_chrome_trace(ring.entries())
    _assert_valid_chrome_doc(doc)
    events = doc["traceEvents"]
    names = {ev["args"]["name"] for ev in events if ev["ph"] == "M"}
    assert {"broker", "server:server_0", "server:server_1"} <= names
    proc = next(ev for ev in events
                if ev["ph"] == "M" and ev["name"] == "process_name")
    assert tr.trace_id in proc["args"]["name"]
    assert "SELECT 1" in proc["args"]["name"]
    # per-hop tracks: broker spans and each server's spans get distinct tids
    tid_of = {ev["args"]["name"]: ev["tid"] for ev in events
              if ev["ph"] == "M" and ev["name"] == "thread_name"}
    x_events = {ev["name"]: ev for ev in events if ev["ph"] == "X"}
    assert x_events["compile"]["tid"] == tid_of["broker"]
    assert x_events["server:server_0"]["tid"] == tid_of["broker"]
    assert x_events["server:server_0/segment:ev_0"]["tid"] == \
        tid_of["server:server_0"]
    assert x_events["server:server_1/segment:ev_1"]["tid"] == \
        tid_of["server:server_1"]
    assert x_events["server:server_1/segment:ev_1"]["args"]["error"] is True
    assert x_events["server:server_0/deserialize"]["ts"] == 0.0


# -- tentpole: dual-transport span-tree differential + HTTP acceptance -------

@pytest.fixture
def inproc_traced(tmp_path):
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    # same admission control as the HTTP fixture so queue_wait appears on
    # both transports
    for s in cluster.servers:
        s.scheduler = QueryScheduler(max_concurrent=2)
    schema = Schema("ev", [dimension("site", DataType.STRING),
                           metric("v", DataType.LONG)])
    cfg = TableConfig("ev", replication=1)
    cluster.create_table(schema, cfg)
    for i in range(2):
        cluster.ingest_columns(cfg, {
            "site": np.array(["a", "b"] * 10),
            "v": np.arange(20, dtype=np.int64) + i,
        })
    return cluster


@pytest.fixture
def http_traced(tmp_path):
    """A real HTTP cluster (controller + 2 scheduled servers + broker), torn
    down after the test. Yields (broker_service_url, broker, controller
    catalog, query client)."""
    from conftest import wait_until
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.catalog import Catalog
    from pinot_tpu.cluster.controller import Controller
    from pinot_tpu.cluster.deepstore import LocalDeepStore
    from pinot_tpu.cluster.process import BrokerClient, ControllerClient
    from pinot_tpu.cluster.remote import ControllerDeepStore, RemoteCatalog
    from pinot_tpu.cluster.server import ServerNode
    from pinot_tpu.cluster.services import (BrokerService, ControllerService,
                                            ServerService)
    from pinot_tpu.segment.writer import SegmentBuilder, SegmentGeneratorConfig

    schema = Schema("ev", [dimension("site", DataType.STRING),
                           metric("v", DataType.LONG)])
    catalog = Catalog()
    controller = Controller("controller_0", catalog,
                            LocalDeepStore(str(tmp_path / "ds")),
                            str(tmp_path / "ctrl"))
    csvc = ControllerService(controller)
    services, catalogs, nodes = [csvc], [], []
    try:
        for i in range(2):
            rc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
            catalogs.append(rc)
            node = ServerNode(f"server_{i}", rc, ControllerDeepStore(csvc.url),
                              str(tmp_path / f"server_{i}"),
                              scheduler=QueryScheduler(max_concurrent=2))
            nodes.append(node)
            services.append(ServerService(node))
        brc = RemoteCatalog(csvc.url, poll_timeout_s=1.0)
        catalogs.append(brc)
        broker = Broker("broker_http", brc)
        bsvc = BrokerService(broker)
        services.append(bsvc)

        cc = ControllerClient(csvc.url)
        cc.add_schema(schema)
        cfg = TableConfig("ev", replication=1)
        cc.add_table(cfg)
        b = SegmentBuilder(schema, SegmentGeneratorConfig())
        for i in range(2):
            seg = b.build({"site": np.array(["a", "b"] * 10, dtype=object),
                           "v": np.arange(20, dtype=np.int64) + i},
                          str(tmp_path / "b"), f"ev_{i}")
            cc.upload_segment(cfg.table_name_with_type, seg)
        assert wait_until(
            lambda: sum(len(n.segments_served(cfg.table_name_with_type))
                        for n in nodes) == 2,
            timeout=15.0, interval=0.05, swallow=())
        bc = BrokerClient(bsvc.url)

        def query(sql):
            return bc.query(sql)

        assert wait_until(
            lambda: _try(lambda: query("SELECT COUNT(*) FROM ev")) is not None,
            timeout=15.0, interval=0.1, swallow=())
        yield bsvc.url, broker, catalog, query
    finally:
        for c in catalogs:
            c.close()
        for s in services:
            s.stop()


def _try(fn):
    try:
        return fn()
    except Exception:
        return None


def _server_exec_shape(spans):
    """Normalize one transport's server-execution spans to a comparable
    shape: {(basename, depth relative to its dispatch span)}. HTTP spans are
    spliced in as `server:<id>/<name>`; in-proc spans run under the dispatch
    span directly."""
    dispatch_depth = {s["name"]: s["depth"] for s in spans
                      if re.fullmatch(r"server:server_\d+", s["name"])}
    shape = set()
    for s in spans:
        name, depth = s["name"], s["depth"]
        m = re.match(r"(server:server_\d+)/(.+)", name)
        if m:                                   # HTTP: spliced + prefixed
            base, rel = m.group(2), depth - dispatch_depth[m.group(1)]
        elif name in dispatch_depth or name in ("compile", "reduce"):
            continue                            # broker-side spans
        else:                                   # in-proc: shared trace
            base, rel = name, depth - min(dispatch_depth.values())
        base = re.sub(r"^segment:ev_\d+$", "segment:*", base)
        if base in WIRE_SPANS or base.startswith("pipeline:"):
            continue
        shape.add((base, rel))
    return shape


def test_dual_transport_span_tree_differential(inproc_traced, http_traced):
    sql = "SELECT site, SUM(v) FROM ev GROUP BY site OPTION(trace=true)"
    inproc_spans = inproc_traced.query(sql).stats["traceInfo"]
    _url, _broker, _catalog, query = http_traced
    http_spans = query(sql)["traceInfo"]
    # both transports dispatched to real servers under a dispatch span
    for spans in (inproc_spans, http_spans):
        assert any(re.fullmatch(r"server:server_\d+", s["name"])
                   for s in spans), [s["name"] for s in spans]
    # HTTP decomposes the hop with wire spans the in-proc transport never pays
    http_names = {s["name"] for s in http_spans}
    assert {"serialize", "send", "deserialize"} <= http_names
    assert any(n.endswith("/queue_wait") for n in http_names)
    # ... but the server-execution tree (what ran, nested where) is IDENTICAL
    assert _server_exec_shape(inproc_spans) == _server_exec_shape(http_spans)


def test_http_slow_query_resolves_at_debug_traces(http_traced):
    """The acceptance path: slow log line -> traceId -> GET /debug/traces?id=
    -> spans decomposing the broker<->server hop; plus the Chrome export."""
    import logging

    from conftest import wait_until
    from pinot_tpu.cluster.broker import Broker
    from pinot_tpu.cluster.http_service import HttpError, get_json

    url, broker, catalog, query = http_traced
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    h = Capture()
    logger = logging.getLogger(Broker.SLOW_QUERY_LOGGER)
    logger.addHandler(h)
    catalog.put_property("clusterConfig/broker.slow.query.ms", "0")
    try:
        # the broker reads its RemoteCatalog MIRROR; wait for the watch loop
        assert wait_until(
            lambda: broker.catalog.get_property(
                "clusterConfig/broker.slow.query.ms") == "0",
            timeout=10.0, interval=0.05, swallow=())
        query("SELECT COUNT(*) FROM ev")
    finally:
        catalog.put_property("clusterConfig/broker.slow.query.ms", None)
        logger.removeHandler(h)
    entry = json.loads(records[-1].getMessage())
    trace_id = entry["stats"]["traceId"]
    assert re.fullmatch(r"[0-9a-f]{16}", trace_id)

    got = get_json(f"{url}/debug/traces?id={trace_id}")
    assert got["traceId"] == trace_id
    assert got["slow"] is True
    names = {s["name"] for s in got["spans"]}
    # the 110ms-floor decomposition: wire + admission + server execution
    assert {"serialize", "send", "deserialize"} <= names
    assert any(n.endswith("/deserialize") for n in names)
    assert any(n.endswith("/queue_wait") for n in names)
    assert any(re.match(r"server:server_\d+/(segment:|device)", n)
               for n in names), sorted(names)

    # the listing carries it too, and unknown ids 404
    listing = get_json(f"{url}/debug/traces")
    assert any(e["traceId"] == trace_id for e in listing["traces"])
    assert listing["capacity"] >= listing["retained"] >= 1
    with pytest.raises(HttpError):
        get_json(f"{url}/debug/traces?id=deadbeefdeadbeef")

    # Chrome export of the retained trace is a loadable timeline
    doc = get_json(f"{url}/debug/traces?id={trace_id}&format=chrome")
    _assert_valid_chrome_doc(doc)


def test_http_sampled_multi_server_chrome_export(http_traced):
    """sample.rate=1 through clusterConfig: a multi-server query lands in the
    ring WITHOUT OPTION(trace=true), and its Chrome export carries one track
    per server hop."""
    from conftest import wait_until
    from pinot_tpu.cluster.http_service import get_json

    url, broker, catalog, query = http_traced
    catalog.put_property("clusterConfig/broker.trace.sample.rate", "1")
    try:
        assert wait_until(
            lambda: broker.catalog.get_property(
                "clusterConfig/broker.trace.sample.rate") == "1",
            timeout=10.0, interval=0.05, swallow=())
        resp = query("SELECT site, SUM(v) FROM ev GROUP BY site")
    finally:
        catalog.put_property("clusterConfig/broker.trace.sample.rate", None)
    assert "traceInfo" not in resp          # sampling retains, never inlines
    trace_id = resp["traceId"]
    entry = get_json(f"{url}/debug/traces?id={trace_id}")
    assert entry["sampled"] is True
    doc = get_json(f"{url}/debug/traces?id={trace_id}&format=chrome")
    _assert_valid_chrome_doc(doc)
    tracks = {ev["args"]["name"] for ev in doc["traceEvents"]
              if ev["ph"] == "M" and ev["name"] == "thread_name"}
    # both servers held a segment, so both hops get their own track
    assert {"broker", "server:server_0", "server:server_1"} <= tracks


def test_query_report_renders_exported_traces(http_traced, capsys):
    """Satellite: saved /debug/traces output analyzes offline."""
    from pinot_tpu.cluster.http_service import get_json
    from pinot_tpu.tools.query_report import _trace_entries, render_trace

    url, _broker, _catalog, query = http_traced
    query("SELECT COUNT(*) FROM ev OPTION(trace=true)")
    listing = get_json(f"{url}/debug/traces")
    entries = _trace_entries(listing)
    assert entries
    body = render_trace(entries[0])
    assert body.startswith("trace: ")
    assert "serialize" in body
    # the chrome form folds back into the same waterfall
    chrome = _trace_entries(get_json(f"{url}/debug/traces?format=chrome"))
    assert chrome and any("serialize" in s["name"]
                          for e in chrome for s in e["spans"])
