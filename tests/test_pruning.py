"""Broker-side metadata pruning (PR 12): commit-time per-column min/max +
bloom stats in SegmentMeta, range/bloom pruners in routing, per-pruner-kind
ExecutionStats counters, and the BROKER_PRUNE EXPLAIN ANALYZE row.

Reference: ColumnValueSegmentPruner — the broker rejects segments from
metadata alone, without ever opening them.
"""

import numpy as np
import pytest

from pinot_tpu.cluster import QuickCluster
from pinot_tpu.cluster.catalog import COLUMN_STATS_KEY, SegmentMeta
from pinot_tpu.cluster.routing import (PRUNE_ROWS_AVOIDED, PRUNER_KINDS,
                                       _count_prune, _prune_reason)
from pinot_tpu.query import stats as qstats
from pinot_tpu.schema import DataType, Schema, date_time, dimension, metric
from pinot_tpu.segment.indexes.bloom import bloom_hex
from pinot_tpu.sql.parser import parse_query
from pinot_tpu.table import TableConfig


def _filter_of(sql_where: str):
    stmt = parse_query(f"SELECT COUNT(*) FROM t WHERE {sql_where}")
    return stmt.where


def _meta(col_stats=None, **kw) -> SegmentMeta:
    meta = SegmentMeta("seg_0", "t_OFFLINE", num_docs=1000, **kw)
    if col_stats is not None:
        meta.custom[COLUMN_STATS_KEY] = col_stats
    return meta


CFG = TableConfig("t")


# -- _prune_reason: range -----------------------------------------------------

@pytest.mark.parametrize("where,reason", [
    ("v > 50", "range"), ("v >= 11", "range"), ("v < 0", "range"),
    ("v <= -1", "range"), ("v = 42", "range"), ("v IN (40, 50)", "range"),
    ("v BETWEEN 20 AND 30", "range"),
    # may-match forms: the range overlaps [0, 10]
    ("v > 5", None), ("v >= 10", None), ("v < 1", None), ("v <= 0", None),
    ("v = 7", None), ("v IN (40, 7)", None), ("v BETWEEN 5 AND 30", None),
])
def test_range_pruning(where, reason):
    meta = _meta({"v": {"min": 0, "max": 10}})
    assert _prune_reason(_filter_of(where), CFG, meta) == reason


def test_range_pruning_cross_type_degrades_to_may_match():
    # columnStats round-trip through JSON: a str-vs-int comparison must keep
    # the segment, never throw
    meta = _meta({"v": {"min": "a", "max": "z"}})
    assert _prune_reason(_filter_of("v > 50"), CFG, meta) is None


def test_range_pruning_without_stats_keeps_segment():
    assert _prune_reason(_filter_of("v > 50"), CFG, _meta()) is None
    assert _prune_reason(_filter_of("v > 50"), CFG, _meta({})) is None


# -- _prune_reason: bloom -----------------------------------------------------

def test_bloom_pruning_eq_and_in():
    hx = bloom_hex(["asia", "europe"], 0.01)
    meta = _meta({"region": {"bloom": hx}})
    assert _prune_reason(_filter_of("region = 'mars'"), CFG, meta) == "bloom"
    assert _prune_reason(_filter_of("region = 'asia'"), CFG, meta) is None
    assert _prune_reason(
        _filter_of("region IN ('mars', 'pluto')"), CFG, meta) == "bloom"
    # one possibly-present member keeps the segment
    assert _prune_reason(
        _filter_of("region IN ('mars', 'europe')"), CFG, meta) is None


def test_bloom_never_applies_to_ranges():
    hx = bloom_hex(["asia"], 0.01)
    meta = _meta({"region": {"bloom": hx}})
    assert _prune_reason(_filter_of("region > 'mars'"), CFG, meta) is None


# -- _prune_reason: tree logic ------------------------------------------------

def test_and_prunes_when_any_conjunct_misses():
    meta = _meta({"v": {"min": 0, "max": 10}})
    assert _prune_reason(
        _filter_of("v > 50 AND region = 'x'"), CFG, meta) == "range"
    assert _prune_reason(
        _filter_of("region = 'x' AND v > 5"), CFG, meta) is None


def test_or_prunes_only_when_all_branches_miss():
    hx = bloom_hex(["asia"], 0.01)
    meta = _meta({"v": {"min": 0, "max": 10}, "region": {"bloom": hx}})
    assert _prune_reason(
        _filter_of("v > 50 OR region = 'mars'"), CFG, meta) == "range"
    assert _prune_reason(
        _filter_of("v > 50 OR region = 'asia'"), CFG, meta) is None


# -- _count_prune -------------------------------------------------------------

def test_count_prune_accumulates_kind_and_rows():
    stats = {}
    _count_prune(stats, "range", _meta())
    _count_prune(stats, "range", _meta())
    _count_prune(stats, "bloom", _meta())
    _count_prune(None, "range", _meta())   # no-op without a sink
    assert stats["range"] == 2 and stats["bloom"] == 1
    assert stats[PRUNE_ROWS_AVOIDED] == 3000
    assert set(stats) - {PRUNE_ROWS_AVOIDED} <= set(PRUNER_KINDS)


def test_pruned_by_kind_key_table_covers_every_pruner():
    assert set(qstats.PRUNED_BY_KIND) == set(PRUNER_KINDS)
    for key in qstats.PRUNED_BY_KIND.values():
        assert key in qstats.COUNTER_KEYS


# -- end-to-end through the in-proc broker ------------------------------------

@pytest.fixture
def cluster(tmp_path):
    schema = Schema("ev", [
        dimension("site", DataType.STRING),
        metric("v", DataType.LONG),
        date_time("ts", DataType.LONG),
    ])
    cluster = QuickCluster(num_servers=2, work_dir=str(tmp_path))
    cfg = TableConfig("ev", replication=1)
    cluster.create_table(schema, cfg)
    rng = np.random.default_rng(0)
    for i in range(3):
        cluster.ingest_columns(cfg, {
            "site": np.array(["a", "b", "c", "d"] * 25),
            "v": rng.integers(i * 100, (i + 1) * 100, 100),
            "ts": np.full(100, 1_700_000_000_000 + i),
        })
    return cluster


def test_commit_lifts_column_stats_into_segment_meta(cluster):
    metas = cluster.catalog.segments["ev_OFFLINE"]
    assert metas
    for meta in metas.values():
        cs = meta.custom.get(COLUMN_STATS_KEY)
        assert cs and "v" in cs and "site" in cs
        assert cs["v"]["min"] is not None and cs["v"]["max"] is not None
        assert cs["site"].get("bloom")          # low-card string: bloom rides


def test_range_prune_counted_per_kind_end_to_end(cluster):
    # segment i holds v in [i*100, (i+1)*100): v >= 250 range-prunes 0 and 1
    res = cluster.query("SELECT COUNT(*) FROM ev WHERE v >= 250")
    assert res.stats["numSegmentsPrunedByRange"] == 2
    assert res.stats["numSegmentsPruned"] >= 2
    assert res.stats["numSegmentsQueried"] == 1
    assert res.stats["scanRowsAvoided"] >= 200
    # the answer itself stays correct
    full = cluster.query("SELECT COUNT(*) FROM ev").rows[0][0]
    kept = cluster.query("SELECT COUNT(*) FROM ev WHERE v < 250").rows[0][0]
    assert res.rows[0][0] + kept == full


def test_bloom_prune_counted_end_to_end(cluster):
    # 'bb' falls INSIDE [min='a', max='d'] so the range pruner keeps the
    # segment; only the bloom probe can prove absence
    res = cluster.query("SELECT COUNT(*) FROM ev WHERE site = 'bb'")
    assert res.stats["numSegmentsPrunedByBloom"] == 3
    assert res.stats["numSegmentsQueried"] == 0
    assert res.stats["scanRowsAvoided"] == 300
    assert res.rows[0][0] == 0
    # a literal beyond max attributes to the range pruner instead
    res = cluster.query("SELECT COUNT(*) FROM ev WHERE site = 'nope'")
    assert res.stats["numSegmentsPrunedByRange"] == 3
    assert res.stats["numSegmentsPrunedByBloom"] == 0
    # a present value is never bloom-pruned (no false negatives)
    hit = cluster.query("SELECT COUNT(*) FROM ev WHERE site = 'a'")
    assert hit.stats["numSegmentsPrunedByBloom"] == 0
    assert hit.rows[0][0] == 75


def test_prune_invariant_pruned_plus_queried_is_total(cluster):
    for sql in ("SELECT COUNT(*) FROM ev WHERE site = 'bb'",
                "SELECT COUNT(*) FROM ev WHERE v >= 250",
                "SELECT COUNT(*) FROM ev WHERE site = 'a' AND v < 150"):
        res = cluster.query(sql)
        assert (res.stats["numSegmentsPruned"]
                + res.stats["numSegmentsQueried"]) == 3, sql
        by_kind = sum(res.stats[k] for k in qstats.PRUNED_BY_KIND.values())
        assert by_kind <= res.stats["numSegmentsPruned"], sql


def test_broker_prune_row_in_explain_analyze(cluster):
    res = cluster.query(
        "EXPLAIN ANALYZE SELECT COUNT(*) FROM ev WHERE v >= 250")
    prune_rows = [r for r in res.rows if r[0].startswith("BROKER_PRUNE")]
    assert len(prune_rows) == 1
    row = prune_rows[0]
    assert "range:2" in row[0]
    assert row[2] == 0 and row[3] == 2      # child of root, Rows = pruned segs
    # an unpruned query renders NO broker prune row
    res2 = cluster.query("EXPLAIN ANALYZE SELECT COUNT(*) FROM ev")
    assert not [r for r in res2.rows if r[0].startswith("BROKER_PRUNE")]
